"""Training-throughput benchmark vs the reference's HIGGS baseline.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "platform",
"device", ...} — ALWAYS, even when the device backend is down (structured
failure record instead of a traceback).

Reference anchor (BASELINE.md): LightGBM CPU trains HIGGS — 10.5M rows x 28
features, 500 iterations, 255 leaves — in 130.094 s (docs/Experiments.rst:113),
i.e. 10.5e6 * 500 / 130.094 = 40.36M row-iterations/second. HIGGS itself
cannot be downloaded in this sandbox (zero egress), so the bench trains on a
synthetic dataset with the HIGGS shape profile (28 dense numerical features,
binary labels, max_bin=255, num_leaves=255) and reports the same
row-iterations/second measure; vs_baseline = ours / 40.36e6 (>1 is faster).

Resilience: the TPU backend arrives via a tunnel that has failed twice at
round-end capture (BENCH_r01/r02: backend init + remote-compile connection
refused), so before building any data we probe the backend in a SUBPROCESS
with retry/backoff — a probe crash cannot poison this process's JAX — and
fall back to the CPU backend (clearly labelled) if the TPU never comes up.
OOM on device falls back to smaller row counts.
"""
import json
import os
import subprocess
import sys
import time

N_ROWS = int(os.environ.get("BENCH_ROWS", 10_500_000))  # true HIGGS rows
N_FEATURES = 28
N_ITERS = int(os.environ.get("BENCH_ITERS", 5))
WARMUP_ITERS = 2
BASELINE_ROW_ITERS_PER_SEC = 10_500_000 * 500 / 130.094
PROBE_RETRIES = int(os.environ.get("BENCH_PROBE_RETRIES", 4))
PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT", 180))

_PROBE_SRC = (
    "import jax, json; d = jax.devices()[0]; "
    "x = (jax.numpy.ones(()) + 1).block_until_ready(); "
    "print(json.dumps({'platform': d.platform, 'device': str(d)}))"
)


def emit(record: dict) -> None:
    sys.stdout.flush()
    print(json.dumps(record), flush=True)


def probe_backend() -> dict:
    """Probe the default JAX backend in a subprocess with retry/backoff.

    Returns {"platform", "device"}; falls back to the CPU backend (and says
    so) when the accelerator tunnel never answers.
    """
    forced = os.environ.get("BENCH_PLATFORM")
    if forced:
        return {"platform": forced, "device": f"forced:{forced}",
                "fallback": forced == "cpu"}
    last_err = ""
    for attempt in range(PROBE_RETRIES):
        try:
            out = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                capture_output=True, text=True, timeout=PROBE_TIMEOUT_S)
            if out.returncode == 0 and out.stdout.strip():
                info = json.loads(out.stdout.strip().splitlines()[-1])
                info["fallback"] = False
                return info
            last_err = (out.stderr or out.stdout).strip()[-400:]
        except subprocess.TimeoutExpired:
            last_err = f"probe timeout after {PROBE_TIMEOUT_S}s"
        except Exception as e:  # noqa: BLE001 - structured failure record
            last_err = repr(e)
        if attempt + 1 < PROBE_RETRIES:
            time.sleep(min(5 * 2 ** attempt, 30))
    return {"platform": "cpu", "device": "cpu (accelerator probe failed)",
            "fallback": True, "probe_error": last_err}


def make_data(n_rows: int):
    import numpy as np

    rng = np.random.default_rng(42)
    X = rng.standard_normal((n_rows, N_FEATURES), dtype=np.float32)
    w = rng.standard_normal(N_FEATURES, dtype=np.float32)
    logit = X[:5_000_000] @ w  # cap the label-gen matmul cost
    if n_rows > logit.shape[0]:
        logit = np.concatenate([logit, X[5_000_000:] @ w])
    noise = rng.standard_normal(n_rows, dtype=np.float32)
    y = (logit + noise > 0).astype(np.float64)
    return X, y


def _auc(y, score) -> float:
    """Mann-Whitney AUC with midranks for ties (tree scores tie often;
    ordinal ranks would make the number order-dependent)."""
    import numpy as np

    score = np.asarray(score)
    order = np.argsort(score, kind="stable")
    sorted_s = score[order]
    ranks = np.empty(len(score))
    # average rank within each tied group
    uniq, start, counts = np.unique(sorted_s, return_index=True,
                                    return_counts=True)
    del uniq
    group_mid = start + (counts + 1) / 2.0  # 1-based midrank per group
    grp = np.repeat(np.arange(len(start)), counts)
    ranks[order] = group_mid[grp]
    pos = y > 0.5
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    if n_pos == 0 or n_neg == 0:
        return 0.5
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2)
                 / (n_pos * n_neg))


def _wave_traffic_fields(ds) -> dict:
    """HBM-traffic instrumentation for the bandwidth model in
    docs/PERF_NOTES.md: rows actually histogrammed (a counter the device
    learner publishes) and the bytes of loop carry each wave drags through
    HBM. Both fields are ALWAYS present — when the run never dispatched the
    device learner (CPU fallback benches use the serial learner), the
    carry estimate is recomputed from the dataset shape with the same
    formula DeviceTreeLearner._record_carry_bytes uses, and the row
    counter reports 0.
    """
    from lightgbm_tpu.utils.timer import global_timer

    fields = {"device_hist_rows":
              int(global_timer.counters.get("device_hist_rows", 0))}
    carry = global_timer.counters.get("device_carry_bytes_per_wave")
    if carry is None:
        from lightgbm_tpu import perfmodel
        from lightgbm_tpu.ops.compact_pallas import COMPACT_TILE
        from lightgbm_tpu.ops.hist_pallas import DEFAULT_TILE_ROWS

        core = ds._handle
        unit = max(DEFAULT_TILE_ROWS, COMPACT_TILE)
        plane_b = 1 if core.bins.dtype.itemsize == 1 else 4
        carry = perfmodel.carry_bytes_per_wave(
            core.num_data, core.bins.shape[0], plane_b, unit)
    fields["est_carried_bytes_per_wave"] = int(carry)
    return fields


def _kernel_micro_fields(ds, n_rows: int) -> dict:
    """Per-dispatch microlatency of the round-8 kernels, measured with the
    session's real dataset shapes on this backend so kernel-on/off ledger
    rows attribute the fused-scan and device-GOSS wins directly:

    * scan_kernel_ms: one `find_best_split` dispatch (the same call the
      serial learner's per-leaf scan makes; routed through the fused
      Pallas kernel or the XLA path by LGBM_TPU_SCAN_PALLAS);
    * goss_device_gather_ms: one jitted GOSS select (score + stable
      argsort + top-rate mask + small-gradient rescale) at the training
      row count — the work the device bag keeps off the host.
    """
    import numpy as np

    out = {}
    rng = np.random.default_rng(7)
    try:
        import jax.numpy as jnp

        from lightgbm_tpu.ops.histogram import build_histogram
        from lightgbm_tpu.ops.split import find_best_split, make_feature_meta

        core = ds._handle
        s = min(core.num_data, 100_000)
        g = rng.standard_normal(s, dtype=np.float32)
        h = np.abs(rng.standard_normal(s, dtype=np.float32)) + 0.1
        gh = jnp.asarray(np.stack([g, h, np.ones(s, np.float32)], axis=1))
        B = int(core.group_bin_counts().max())
        hist = build_histogram(jnp.asarray(core.bins[:, :s]), gh, B)
        meta = make_feature_meta(core, B)
        pvec = jnp.asarray([0, 0, 20, 1e-3, 0, 0], dtype=jnp.float32)
        totals = hist[0].sum(axis=0).astype(jnp.float32)
        find_best_split(hist, totals, meta, pvec).block_until_ready()
        reps = 20
        t0 = time.perf_counter()
        for _ in range(reps):
            rec = find_best_split(hist, totals, meta, pvec)
        rec.block_until_ready()
        out["scan_kernel_ms"] = round(
            (time.perf_counter() - t0) / reps * 1e3, 3)
    except Exception as e:  # noqa: BLE001 - secondary must not kill primary
        out["scan_kernel_error"] = repr(e)[:200]
    try:
        import jax
        import jax.numpy as jnp

        from lightgbm_tpu.models import sample_strategy as ss

        n = min(n_rows, 1_000_000)
        gd = jnp.asarray(rng.standard_normal(n, dtype=np.float32))
        hd = jnp.asarray(
            np.abs(rng.standard_normal(n, dtype=np.float32)) + 0.1)
        top_k = max(int(np.ceil(n * 0.2)), 1)
        n_sampled = min(int(np.ceil(n * 0.1)), n - top_k)
        pos = jnp.asarray(rng.choice(
            n - top_k, n_sampled, replace=False).astype(np.int32))
        from functools import partial

        select = jax.jit(partial(ss._goss_select, top_k=top_k))
        mult = jnp.float32(8.0)
        select(gd, hd, pos, mult)[1].block_until_ready()
        reps = 20
        t0 = time.perf_counter()
        for _ in range(reps):
            _, gr, _ = select(gd, hd, pos, mult)
        gr.block_until_ready()
        out["goss_device_gather_ms"] = round(
            (time.perf_counter() - t0) / reps * 1e3, 3)
    except Exception as e:  # noqa: BLE001 - secondary must not kill primary
        out["goss_kernel_error"] = repr(e)[:200]
    return out


def _bench_gang_recovery() -> dict:
    """Measure one detect -> reap -> respawn cycle of the elastic gang
    supervisor on stub workers (rank 1 exits nonzero on attempt 0; the
    relaunched gang exits clean). Stubs keep the number a pure supervisor
    latency — no JAX startup, no coordinator barrier — so regressions in
    the watch/reap loop itself are visible under the ledger gate."""
    import subprocess as sp

    from lightgbm_tpu.parallel.elastic import GangSupervisor

    code = ("import sys, time\n"
            "rank, attempt = int(sys.argv[1]), int(sys.argv[2])\n"
            "if attempt == 0 and rank == 1:\n"
            "    sys.exit(7)\n"
            "time.sleep(0.05)\n")

    def spawn(world, rank, attempt):
        return sp.Popen([sys.executable, "-c", code, str(rank), str(attempt)])

    try:
        sup = GangSupervisor(spawn, 4, elastic=True, max_restarts=1,
                             poll_s=0.02)
        rc = sup.run()
        if rc == 0 and sup.last_recovery_ms is not None:
            return {"gang_recovery_ms": round(sup.last_recovery_ms, 2)}
        return {"gang_error": f"supervisor rc={rc}, "
                              f"recovery_ms={sup.last_recovery_ms}"}
    except Exception as e:  # noqa: BLE001 - secondary must not kill primary
        return {"gang_error": repr(e)[:200]}


def _bench_voting_fields() -> dict:
    """Pod-scale learner comm capture (docs/PERF_NOTES.md round-9): grow
    trees over the same wide dataset (F=256 — the regime the PV-Tree
    voting scheme is priced for) with the data-parallel, voting-parallel
    and feature-parallel device learners plus the single-device baseline,
    and record

    * the per-wave ICI gauges each learner publishes — the three-way comm
      model: full-histogram psum_scatter (data) vs nomination gather +
      elected-slice psum (voting) vs best-record all_gather (feature);
    * device_ici_overlap_pct — the share of the elected-slice reduction
      the double-buffered dispatch hides behind partition/commit;
    * voting_miss_total under LGBM_TPU_VOTING_EXACT_CHECK=1: elections
      where the full reduction disagreed with the committed split (0 on a
      single shard, where the local argmax is always nominated);
    * scaling_efficiency_{data,voting,feature}: measured rows/s against
      D x the single-device learner's.

    Smoke-asserted on the spot: voting must move strictly fewer bytes per
    wave than data-parallel, and feature-parallel fewer than voting — the
    ordering the round-9 model predicts at F=256, top_k=20.
    """
    import jax.numpy as jnp
    import numpy as np

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import Dataset as CoreDataset
    from lightgbm_tpu.parallel.learners import (
        DeviceDataParallelTreeLearner, DeviceFeatureParallelTreeLearner,
        VotingDataParallelTreeLearner)
    from lightgbm_tpu.treelearner.device import DeviceTreeLearner
    from lightgbm_tpu.utils.timer import global_timer

    n, f = 4096, 256
    rng = np.random.default_rng(11)
    X = rng.standard_normal((n, f)).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] + 0.25 * X[:, 2] > 0).astype(np.float32)
    g = (0.5 - y + 0.1 * rng.standard_normal(n)).astype(np.float32)
    gh = np.stack([g, np.full(n, 0.25, np.float32),
                   np.ones(n, np.float32)], axis=1)
    gh_ext = jnp.asarray(
        np.concatenate([gh, np.zeros((1, 3), np.float32)]))
    params = {"objective": "binary", "num_leaves": 15, "max_bin": 64,
              "min_data_in_leaf": 20, "top_k": 20, "verbosity": -1}

    def _train(cls):
        cfg = Config(params)
        ds = CoreDataset.from_matrix(X, label=y, config=cfg)
        learner = cls(cfg, ds)
        learner.finalize(learner.train_async(gh_ext))  # compile warmup
        t0 = time.perf_counter()
        learner.finalize(learner.train_async(gh_ext))
        return learner, time.perf_counter() - t0

    _, single_s = _train(DeviceTreeLearner)
    _GAUGES = ("device_ici_bytes_per_wave", "voting_ici_bytes_per_wave",
               "feature_ici_bytes_per_wave", "device_ici_overlap_pct",
               "voting_miss_total")
    out, ici = {}, {}
    for key, cls in (("data", DeviceDataParallelTreeLearner),
                     ("voting", VotingDataParallelTreeLearner),
                     ("feature", DeviceFeatureParallelTreeLearner)):
        for c in _GAUGES:
            global_timer.counters.pop(c, None)
        saved = os.environ.get("LGBM_TPU_VOTING_EXACT_CHECK")
        if key == "voting":
            os.environ["LGBM_TPU_VOTING_EXACT_CHECK"] = "1"
        try:
            learner, el = _train(cls)
        finally:
            if key == "voting":
                if saved is None:
                    os.environ.pop("LGBM_TPU_VOTING_EXACT_CHECK", None)
                else:
                    os.environ["LGBM_TPU_VOTING_EXACT_CHECK"] = saved
        ici[key] = int(global_timer.counters["device_ici_bytes_per_wave"])
        out[f"scaling_efficiency_{key}"] = round(
            single_s / (learner.D * el), 4) if el > 0 else 0.0
        if key == "voting":
            out["voting_ici_bytes_per_wave"] = int(
                global_timer.counters["voting_ici_bytes_per_wave"])
            out["device_ici_overlap_pct"] = int(
                global_timer.counters["device_ici_overlap_pct"])
            out["voting_miss_total"] = int(
                global_timer.counters.get("voting_miss_total", 0))
        elif key == "feature":
            out["feature_ici_bytes_per_wave"] = int(
                global_timer.counters["feature_ici_bytes_per_wave"])
    assert out["voting_ici_bytes_per_wave"] < ici["data"], (
        "voting moved more ICI bytes than the full reduction", out, ici)
    assert out["feature_ici_bytes_per_wave"] < out[
        "voting_ici_bytes_per_wave"], (
        "feature-parallel should be the cheapest wire", out)
    return out


def run_bench(n_rows: int) -> dict:
    import lightgbm_tpu as lgb
    from lightgbm_tpu import telemetry

    holdout = min(200_000, n_rows // 5)
    Xall, yall = make_data(n_rows + holdout)
    # true holdout: rows NEVER seen by training
    Xh, yh = Xall[:holdout], yall[:holdout]
    X, y = Xall[holdout:], yall[holdout:]
    params = {
        "objective": "binary",
        "num_leaves": 255,
        "learning_rate": 0.1,
        "max_bin": 255,
        "min_data_in_leaf": 100,
        "verbosity": -1,
    }
    # aggregate-only telemetry session (no files): counts jit compiles and
    # samples HBM high-water so the capture record attributes regressions
    # (recompile churn vs memory pressure) instead of just restating them
    telemetry.start(None, label="bench")
    try:
        ds = lgb.Dataset(X, label=y)
        bst = lgb.Booster(params=params, train_set=ds)
        for _ in range(WARMUP_ITERS):  # compile + cache warmup, not timed
            bst.update()
        t0 = time.perf_counter()
        for _ in range(N_ITERS):
            bst.update()
        elapsed = time.perf_counter() - t0
        rips = n_rows * N_ITERS / elapsed
        out = {"row_iters_per_sec": rips, "elapsed_s": elapsed,
               "rows": n_rows, "iters": N_ITERS,
               "auc": round(_auc(yh, bst.predict(Xh)), 4)}
        out.update(_wave_traffic_fields(ds))

        # cost-model attribution (perfmodel.py): measured per-stage walls
        # from the timer, the analytic byte model from the published
        # gauges, and XLA's own cost_analysis() for each captured dispatch
        # — taken NOW, before the guardrail/telemetry short trains below
        # pollute the timer totals with their own boosting scopes
        from lightgbm_tpu import perfmodel
        from lightgbm_tpu.utils.timer import global_timer

        # round-8 wave controller + kernel instrumentation: the observed
        # commit rate and the K the adaptive controller settled on (both 0
        # when the run never dispatched the device learner), plus the
        # per-dispatch microlatency of the fused scan and the device GOSS
        # select at this session's shapes
        spec = int(global_timer.counters.get("wave_splits_speculated", 0))
        out["wave_commit_rate"] = round(
            int(global_timer.counters.get("wave_splits_committed", 0))
            / spec, 4) if spec else 0.0
        out["adaptive_k_final"] = int(
            global_timer.counters.get("wave_k", 0))
        out.update(_kernel_micro_fields(ds, n_rows))

        try:
            import jax

            devs = jax.devices()
            kind = str(devs[0].device_kind) if devs else ""
        except Exception:  # noqa: BLE001 - attribution is best-effort
            kind = ""
        out["attribution"] = perfmodel.attribution(
            dict(global_timer.totals), dict(global_timer.counters),
            device_kind=kind, include_static=True)

        # inference throughput: chunked streaming predict over the train
        # matrix (the serving configuration — double-buffered
        # H2D/compute/D2H overlap)
        from lightgbm_tpu.ops.partition import bucket_size

        pred_chunk = min(1 << 20, bucket_size(max(n_rows // 4, 1), 1024))
        bst.predict(X, raw_score=True, pred_chunk_rows=pred_chunk)  # warmup
        t0 = time.perf_counter()
        bst.predict(X, raw_score=True, pred_chunk_rows=pred_chunk)
        pe = time.perf_counter() - t0
        out["predict_rows_per_sec"] = round(n_rows / pe, 1)
        out["predict_chunk_rows"] = pred_chunk

        # serving-layer throughput: an open-loop generator firing fixed-size
        # requests over HTTP at the hardened prediction service
        # (docs/SERVING.md) — the full request path, so the tracing stage
        # histograms decompose the serve-vs-direct gap into named numbers
        # (parse / queue_wait / assembly / device / d2h / serialize)
        import json as json_mod
        import threading
        import urllib.request

        import numpy as np

        from lightgbm_tpu import tracing
        from lightgbm_tpu.serving import PredictionService
        from lightgbm_tpu.serving.http import serve as serve_http

        serve_rows = 64
        serve_requests = int(os.environ.get("BENCH_SERVE_REQUESTS", 300))
        tracing.reset_stats()  # this section owns the stage quantiles
        # min_bucket matches the 64-row request size: the coalescing beat
        # only helps when a batch is still below one bucket, and a 256-row
        # floor made every ~3-request batch pay the full window
        svc = PredictionService(max_batch_rows=4096, min_bucket=64,
                                batch_window_s=0.001)
        server = None
        try:
            svc.load_model("bench", booster=bst)
            server, _ = serve_http(svc, port=0)
            url = f"http://127.0.0.1:{server.port}/predict"
            span = max(X.shape[0] - serve_rows, 1)
            # request bodies built outside the timed loop: client-side
            # encoding is the generator's cost, not the service's
            bodies = [json_mod.dumps(
                {"model": "bench", "raw_score": True,
                 "rows": X[(i * serve_rows) % span:
                           (i * serve_rows) % span + serve_rows].tolist()}
            ).encode() for i in range(serve_requests)]
            served = []

            def fire(i):
                req = urllib.request.Request(
                    url, data=bodies[i],
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=60) as resp:
                    resp.read()
                served.append(i)

            t0 = time.perf_counter()
            threads = []
            for i in range(serve_requests):
                th = threading.Thread(target=fire, args=(i,))
                th.start()
                threads.append(th)
                time.sleep(0.0005)  # open loop: fixed arrival rate
            for th in threads:
                th.join()
            serve_s = time.perf_counter() - t0
            sstats = svc.batcher.stats()
            out["serve_rows_per_sec"] = round(
                len(served) * serve_rows / serve_s, 1)
            out["serve_p50_ms"] = round(sstats.get("p50_ms", 0.0), 3)
            out["serve_p99_ms"] = round(sstats.get("p99_ms", 0.0), 3)
            out["serve_batches"] = int(sstats["batches"])
            stages = svc.stats().get("stages", {})
            for stage, field in (("parse", "serve_parse_ms_p99"),
                                 ("queue_wait", "serve_queue_ms_p99"),
                                 ("assembly", "serve_assembly_ms_p99"),
                                 ("device", "serve_device_ms_p99"),
                                 ("d2h", "serve_d2h_ms_p99"),
                                 ("serialize", "serve_serialize_ms_p99")):
                out[field] = round(
                    stages.get(stage, {}).get("p99_ms", 0.0), 3)

            # binary wire format (serving/wire.py): the SAME rows as raw
            # f32 frames, zero-copy decoded server-side. Open-loop like
            # the JSON drive above: each persistent connection pipelines
            # its requests (send all frames, then drain the responses) so
            # the wire cost — not per-round-trip latency — is what's
            # measured; the JSON scenario is untouched for cross-PR
            # comparability
            import socket

            from lightgbm_tpu.serving import wire as wire_mod

            wire_workers = 16
            per_worker = max(1, serve_requests // wire_workers)

            def _wire_http(frame):
                return (b"POST /predict HTTP/1.1\r\nHost: bench\r\n"
                        b"Content-Type: " + wire_mod.CONTENT_TYPE.encode()
                        + b"\r\nContent-Length: " + str(len(frame)).encode()
                        + b"\r\n\r\n" + frame)

            frames = [_wire_http(wire_mod.encode_request(
                "bench",
                np.ascontiguousarray(
                    X[(i * serve_rows) % span:
                      (i * serve_rows) % span + serve_rows],
                    dtype=np.float32),
                raw_score=True)) for i in range(wire_workers)]
            wire_rows = [0] * wire_workers

            def fire_wire(w):
                sock = socket.create_connection(
                    ("127.0.0.1", server.port), timeout=60)
                sock.setsockopt(  # no Nagle stall between frames
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                payload = frames[w] * per_worker
                sender = threading.Thread(
                    target=lambda: sock.sendall(payload))
                sender.start()
                fh = sock.makefile("rb")
                try:
                    for _ in range(per_worker):
                        status = fh.readline()
                        clen = 0
                        while True:
                            line = fh.readline()
                            if not line or line == b"\r\n":
                                break
                            if line.lower().startswith(b"content-length:"):
                                clen = int(line.split(b":")[1])
                        fh.read(clen)
                        if b" 200 " in status:
                            wire_rows[w] += serve_rows
                finally:
                    sender.join()
                    fh.close()
                    sock.close()

            t0 = time.perf_counter()
            threads = [threading.Thread(target=fire_wire, args=(w,))
                       for w in range(wire_workers)]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            wire_s = time.perf_counter() - t0
            out["serve_wire_binary_rows_per_sec"] = round(
                sum(wire_rows) / wire_s, 1)
        finally:
            if server is not None:
                server.shutdown()
            svc.close()

        # replica cold start: persist the model + its AOT executable
        # bundle, drop every compile cache (a fresh process stand-in), and
        # time load -> first bucket-shaped answer, with and without the
        # bundle — the serve_cold_start_ms vs *_compile_ms gap is what the
        # warm-start tentpole buys a scale-out event
        import tempfile as _tmp

        import jax as _jax

        from lightgbm_tpu.checkpoint import save_checkpoint as _save_ckpt

        with _tmp.TemporaryDirectory() as td:
            mpath = os.path.join(td, "bench_model.txt")
            _save_ckpt(bst, mpath)
            svc_w = PredictionService(max_batch_rows=1024,
                                      batch_window_s=0.0)
            try:
                svc_w.load_model("warm", path=mpath)
                svc_w.export_aot("warm")
            finally:
                svc_w.close()
            probe = np.ascontiguousarray(X[:256], dtype=np.float32)

            def _cold_ms(drop_aot):
                if drop_aot:
                    os.remove(mpath + ".aot")
                _jax.clear_caches()
                svc_c = PredictionService(max_batch_rows=1024,
                                          batch_window_s=0.0)
                try:
                    t0 = time.perf_counter()
                    svc_c.load_model("cold", path=mpath)
                    svc_c.predict("cold", probe, raw_score=True)
                    return (time.perf_counter() - t0) * 1e3
                finally:
                    svc_c.close()

            out["serve_cold_start_ms"] = round(_cold_ms(False), 1)
            out["serve_cold_start_compile_ms"] = round(_cold_ms(True), 1)

        # fleet dispatch: throughput of one hot model on one replica vs
        # two hot models pinned to two replicas, closed-loop in-process
        # callers — perfect scaling is 1.0, contention shows below it
        from lightgbm_tpu import perfmodel as _perfmodel

        def _fleet_rows_per_sec(n_entries, replicas):
            svc_f = PredictionService(max_batch_rows=4096,
                                      batch_window_s=0.0,
                                      replicas=replicas)
            block = np.ascontiguousarray(X[:serve_rows], dtype=np.float32)
            reqs = max(50, serve_requests // 2)
            try:
                for i in range(n_entries):
                    svc_f.load_model(f"rep{i}", booster=bst)

                def drive(name):
                    for _ in range(reqs):
                        svc_f.predict(name, block, raw_score=True)

                drivers = [threading.Thread(target=drive, args=(f"rep{i}",))
                           for i in range(n_entries) for _ in range(2)]
                t0 = time.perf_counter()
                for th in drivers:
                    th.start()
                for th in drivers:
                    th.join()
                dt = time.perf_counter() - t0
                return 2 * n_entries * reqs * serve_rows / dt
            finally:
                svc_f.close()

        fleet_t1 = _fleet_rows_per_sec(1, 1)
        fleet_t2 = _fleet_rows_per_sec(2, 2)
        out["serve_replica_scaling_efficiency"] = \
            _perfmodel.serve_replica_scaling_efficiency(fleet_t1, fleet_t2, 2)

        # robustness-layer cost: one full-state checkpoint write of the
        # trained model (model text + sidecar, atomic + fsync) ...
        import tempfile

        from lightgbm_tpu.checkpoint import save_checkpoint

        with tempfile.TemporaryDirectory() as td:
            t0 = time.perf_counter()
            save_checkpoint(bst, os.path.join(td, "bench_model.txt"))
            out["checkpoint_write_ms"] = round(
                (time.perf_counter() - t0) * 1e3, 2)
    finally:
        tel_summary = telemetry.stop()
    out["compile_count"] = int(tel_summary["compile_count"])
    out["hbm_high_water_bytes"] = int(tel_summary["hbm_high_water_bytes"])

    # ... and the numerical-health guardrail at its most expensive setting
    # (policy=warn, sync every iteration) vs the same short train without it
    g_rows = min(n_rows, 100_000)
    Xg, yg = X[:g_rows], y[:g_rows]

    def _short_train(extra: dict) -> float:
        dg = lgb.Dataset(Xg, label=yg)
        bg = lgb.Booster(params={**params, **extra}, train_set=dg)
        for _ in range(WARMUP_ITERS):
            bg.update()
        t0 = time.perf_counter()
        for _ in range(N_ITERS):
            bg.update()
        return time.perf_counter() - t0

    base_s = _short_train({})
    guard_s = _short_train({"health_check_policy": "warn",
                            "health_check_every": 1})
    out["guardrail_overhead_pct"] = round((guard_s / base_s - 1.0) * 100.0, 2)

    # ... and the elastic collective heartbeat at its most aggressive
    # cadence (the psum health token EVERY iteration; the production
    # default is every 10th, riding the health monitor's existing sync
    # slot) vs the same short train with elastic mode off
    from lightgbm_tpu.parallel import elastic

    elastic.install(timeout_s=None, heartbeat_every=1)
    try:
        hb_s = _short_train({})
    finally:
        elastic.clear()
    out["heartbeat_overhead_pct"] = round((hb_s / base_s - 1.0) * 100.0, 2)

    # ... and one elastic gang recovery (detect -> reap -> respawn) on stub
    # workers, isolating the supervisor's loop latency from JAX startup
    out.update(_bench_gang_recovery())

    # ... and the telemetry stack at full tilt (file sinks + watchers + span
    # capture) vs the same short train with it off — the <1% overhead claim,
    # measured on every capture (can be negative on noisy hosts)
    with tempfile.TemporaryDirectory() as tel_td:
        from lightgbm_tpu import telemetry as _tel

        with _tel.capture(tel_td, label="bench-overhead"):
            tel_s = _short_train({})
    out["telemetry_overhead_pct"] = round((tel_s / base_s - 1.0) * 100.0, 2)

    # secondary quantized capture defaults ON only at moderate sizes — at
    # full HIGGS scale it would double the remote-compile + train time and
    # risk the round's single capture window
    quant_default = "1" if n_rows <= 4_000_000 else "0"
    if os.environ.get("BENCH_QUANTIZED", quant_default) not in ("0", "false"):
        # secondary metric: the int8 quantized-gradient path
        # (use_quantized_grad, the reference's gradient_discretizer feature)
        try:
            dq = lgb.Dataset(X, label=y)
            bq = lgb.Booster(params={**params, "use_quantized_grad": True},
                             train_set=dq)
            for _ in range(WARMUP_ITERS):
                bq.update()
            t0 = time.perf_counter()
            for _ in range(N_ITERS):
                bq.update()
            eq = time.perf_counter() - t0
            out["quantized_row_iters_per_sec"] = round(
                n_rows * N_ITERS / eq, 1)
            out["quantized_auc"] = round(_auc(yh, bq.predict(Xh)), 4)
        except Exception as e:  # noqa: BLE001 - secondary must not kill primary
            out["quantized_error"] = repr(e)[:200]

    # out-of-core streaming capture (docs/STREAMING.md): chunked ingest
    # through RowBlockStore, then training under a deliberately starved
    # HBM budget (2 of ~8 blocks resident) so the numbers reflect real
    # evictions and prefetch overlap, never the pin-everything fast path
    if os.environ.get("BENCH_STREAMING", "1") not in ("0", "false"):
        try:
            from lightgbm_tpu.streaming import RowBlockStore, wrap_dataset

            s_rows = min(n_rows, 200_000)
            push_chunk = 16_384
            store = RowBlockStore(params=params)
            t0 = time.perf_counter()
            for lo in range(0, s_rows, push_chunk):
                hi = min(s_rows, lo + push_chunk)
                store.push_rows(X[lo:hi], label=y[lo:hi])
            core = store.finalize()
            ingest_s = time.perf_counter() - t0
            out["stream_ingest_rows_per_sec"] = round(s_rows / ingest_s, 1)

            block_rows = max(256, -(-s_rows // 8))
            budget = 2 * perfmodel.stream_block_bytes(
                block_rows, core.bins.shape[0], core.bins.dtype.itemsize)
            saved = {k: os.environ.get(k) for k in
                     ("LGBM_TPU_HBM_BUDGET", "LGBM_TPU_STREAM_BLOCK_ROWS")}
            os.environ["LGBM_TPU_HBM_BUDGET"] = str(int(budget))
            os.environ["LGBM_TPU_STREAM_BLOCK_ROWS"] = str(block_rows)
            base = {k: int(global_timer.counters.get(k, 0)) for k in
                    ("stream_h2d_prefetched", "stream_h2d_cold")}
            try:
                bs = lgb.Booster(params=params,
                                 train_set=wrap_dataset(core, params=params))
                bs.update()  # compile warmup, not timed
                t0 = time.perf_counter()
                for _ in range(N_ITERS):
                    bs.update()
                stream_s = time.perf_counter() - t0
            finally:
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
            out["stream_train_rows_per_sec"] = round(
                s_rows * N_ITERS / stream_s, 1)
            c = global_timer.counters
            out["hbm_resident_fraction"] = round(
                c["stream_resident_blocks"] / c["stream_blocks_total"], 4)
            pre = int(c.get("stream_h2d_prefetched", 0)
                      ) - base["stream_h2d_prefetched"]
            cold = int(c.get("stream_h2d_cold", 0)) - base["stream_h2d_cold"]
            out["stream_h2d_overlap_pct"] = round(
                100.0 * pre / max(pre + cold, 1), 2)

            # drift capture (docs/STREAMING.md "Drift and generation
            # safety"): the sketch+occupancy tax on ingest, one forced
            # bin-mapper refresh, and one holdout gate evaluation — the
            # three costs the <2% overhead contract is priced against
            d_saved = os.environ.get("LGBM_TPU_DRIFT")
            os.environ["LGBM_TPU_DRIFT"] = "1"
            try:
                dstore = RowBlockStore(params=params)
                t0 = time.perf_counter()
                for lo in range(0, s_rows, push_chunk):
                    hi = min(s_rows, lo + push_chunk)
                    dstore.push_rows(X[lo:hi], label=y[lo:hi])
                dstore.finalize()
                drift_s = time.perf_counter() - t0
                out["drift_check_overhead_pct"] = round(
                    (drift_s / ingest_s - 1.0) * 100.0, 2)
                t0 = time.perf_counter()
                dstore.maybe_refresh_bins(force=True)
                out["bin_refresh_ms"] = round(
                    (time.perf_counter() - t0) * 1000.0, 3)
            finally:
                if d_saved is None:
                    os.environ.pop("LGBM_TPU_DRIFT", None)
                else:
                    os.environ["LGBM_TPU_DRIFT"] = d_saved

            from lightgbm_tpu import health as _health

            g_rows = min(4096, s_rows)
            Xg, yg = X[:g_rows], y[:g_rows]
            obj = str(params.get("objective", ""))
            t0 = time.perf_counter()
            _health.prediction_loss(bs.predict(Xg), yg, obj)
            _health.prediction_loss(bs.predict(Xg), yg, obj)
            out["gate_eval_ms"] = round(
                (time.perf_counter() - t0) * 1000.0, 3)
        except Exception as e:  # noqa: BLE001 - secondary must not kill primary
            out["stream_error"] = repr(e)[:200]

    # gang-sharded streaming capture (docs/STREAMING.md "Pod-scale
    # streaming"): chunked ingest through ShardedRowBlockStore (the rank-
    # merged sketch fit wall lands in stream_sketch_merge_ms), then
    # training through the gang-sharded learner — tree_learner=data +
    # quantized histograms, the psum-merged path — under the same starved
    # budget. The overlap ratio is re-measured on the gang run (the
    # per-gang stream_h2d_overlap_pct). On a single-device host the gang
    # degenerates to one shard; the code path and merge timing still
    # capture.
    if os.environ.get("BENCH_STREAMING", "1") not in ("0", "false"):
        try:
            from lightgbm_tpu.streaming import (ShardedRowBlockStore,
                                                wrap_dataset)

            s_rows = min(n_rows, 200_000)
            push_chunk = 16_384
            sh_store = ShardedRowBlockStore(params=params)
            for lo in range(0, s_rows, push_chunk):
                hi = min(s_rows, lo + push_chunk)
                sh_store.push_rows(X[lo:hi], label=y[lo:hi])
            sh_core = sh_store.finalize()
            out["stream_sketch_merge_ms"] = round(
                global_timer.counters.get("stream_sketch_merge_us", 0)
                / 1000.0, 3)

            block_rows = max(256, -(-s_rows // 8))
            budget = 2 * perfmodel.stream_block_bytes(
                block_rows, sh_core.bins.shape[0],
                sh_core.bins.dtype.itemsize)
            sh_params = {**params, "tree_learner": "data",
                         "use_quantized_grad": True}
            saved = {k: os.environ.get(k) for k in
                     ("LGBM_TPU_HBM_BUDGET", "LGBM_TPU_STREAM_BLOCK_ROWS")}
            os.environ["LGBM_TPU_HBM_BUDGET"] = str(int(budget))
            os.environ["LGBM_TPU_STREAM_BLOCK_ROWS"] = str(block_rows)
            base = {k: int(global_timer.counters.get(k, 0)) for k in
                    ("stream_h2d_prefetched", "stream_h2d_cold")}
            try:
                bsh = lgb.Booster(
                    params=sh_params,
                    train_set=wrap_dataset(sh_core, params=sh_params))
                bsh.update()  # compile warmup, not timed
                t0 = time.perf_counter()
                for _ in range(N_ITERS):
                    bsh.update()
                sh_s = time.perf_counter() - t0
            finally:
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
            out["stream_sharded_rows_per_sec"] = round(
                s_rows * N_ITERS / sh_s, 1)
            c = global_timer.counters
            out["stream_gang_shards"] = int(c.get("stream_shards", 1))
            pre = int(c.get("stream_h2d_prefetched", 0)
                      ) - base["stream_h2d_prefetched"]
            cold = int(c.get("stream_h2d_cold", 0)) - base["stream_h2d_cold"]
            out["stream_h2d_overlap_pct"] = round(
                100.0 * pre / max(pre + cold, 1), 2)
        except Exception as e:  # noqa: BLE001 - secondary must not kill primary
            out["stream_sharded_error"] = repr(e)[:200]

    # pod-scale learner comm capture (docs/PERF_NOTES.md round-9): the
    # three-way ICI model (data vs voting vs feature) on a fixed wide
    # dataset — cost is independent of n_rows, so it always runs
    if os.environ.get("BENCH_VOTING", "1") not in ("0", "false"):
        try:
            out.update(_bench_voting_fields())
        except Exception as e:  # noqa: BLE001 - secondary must not kill primary
            out["voting_error"] = repr(e)[:200]
    return out


def _append_ledger(record: dict) -> None:
    """Append the finished capture to BENCH_LEDGER.jsonl (atomic writer;
    $BENCH_LEDGER overrides the path or disables with 0/off). Only clean
    records enter the trail benchdiff gates on — and an append failure
    must never eat the capture itself."""
    try:
        from lightgbm_tpu.fingerprint import append_ledger

        path = append_ledger(record)
        if path:
            print(f"# ledger: appended to {path}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 - capture output comes first
        print(f"# ledger: append failed: {e!r}", file=sys.stderr)


def main() -> None:
    info = probe_backend()
    if info.get("fallback"):
        # the accelerator never answered: run on CPU so the record still
        # carries a real (if incomparable) number + the structured reason
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # noqa: BLE001 - best-effort override
            pass

    from lightgbm_tpu.fingerprint import fingerprint

    record = {
        "metric": "train_row_iters_per_sec",
        "value": 0.0,
        "unit": "row_iters/s",
        "vs_baseline": 0.0,
        "platform": info.get("platform"),
        "device": info.get("device"),
        "tpu_fallback_to_cpu": bool(info.get("fallback")),
    }
    # environment fingerprint: git sha, jax/jaxlib versions, device
    # kind/count, active LGBM_TPU_* flags + the ledger schema_version —
    # the provenance benchdiff keys its comparability checks on
    record["fingerprint"] = fingerprint()
    record["schema_version"] = record["fingerprint"]["schema_version"]
    if info.get("probe_error"):
        record["probe_error"] = info["probe_error"]

    n_rows = N_ROWS
    last_err = ""
    min_rows = min(50_000, N_ROWS)
    pallas_fallback_done = False
    while n_rows >= min_rows:
        try:
            res = run_bench(n_rows)
            record["value"] = round(res["row_iters_per_sec"], 1)
            record["vs_baseline"] = round(
                res["row_iters_per_sec"] / BASELINE_ROW_ITERS_PER_SEC, 4)
            record["elapsed_s"] = round(res["elapsed_s"], 3)
            record["rows"] = res["rows"]
            record["iters"] = res["iters"]
            for k in ("auc", "quantized_row_iters_per_sec", "quantized_auc",
                      "quantized_error", "device_hist_rows",
                      "est_carried_bytes_per_wave", "predict_rows_per_sec",
                      "predict_chunk_rows", "checkpoint_write_ms",
                      "guardrail_overhead_pct", "heartbeat_overhead_pct",
                      "gang_recovery_ms", "gang_error", "compile_count",
                      "hbm_high_water_bytes", "telemetry_overhead_pct",
                      "serve_rows_per_sec", "serve_p50_ms", "serve_p99_ms",
                      "serve_batches", "serve_parse_ms_p99",
                      "serve_queue_ms_p99", "serve_assembly_ms_p99",
                      "serve_device_ms_p99", "serve_d2h_ms_p99",
                      "serve_serialize_ms_p99",
                      "serve_wire_binary_rows_per_sec",
                      "serve_cold_start_ms", "serve_cold_start_compile_ms",
                      "serve_replica_scaling_efficiency",
                      "stream_ingest_rows_per_sec",
                      "stream_train_rows_per_sec", "hbm_resident_fraction",
                      "stream_h2d_overlap_pct", "drift_check_overhead_pct",
                      "bin_refresh_ms", "gate_eval_ms", "stream_error",
                      "stream_sharded_rows_per_sec", "stream_sketch_merge_ms",
                      "stream_gang_shards", "stream_sharded_error",
                      "wave_commit_rate", "adaptive_k_final",
                      "scan_kernel_ms", "goss_device_gather_ms",
                      "scan_kernel_error", "goss_kernel_error",
                      "voting_ici_bytes_per_wave",
                      "feature_ici_bytes_per_wave",
                      "device_ici_overlap_pct", "voting_miss_total",
                      "scaling_efficiency_data", "scaling_efficiency_voting",
                      "scaling_efficiency_feature", "voting_error",
                      "attribution"):
                if k in res:
                    record[k] = res[k]
            _append_ledger(record)
            emit(record)
            return
        except Exception as e:  # noqa: BLE001 - degrade, don't crash
            last_err = repr(e)[:400]
            if (not pallas_fallback_done
                    and ("osaic" in last_err or "pallas" in last_err
                         or "Pallas" in last_err)):
                # unproven-on-this-backend Pallas kernel: fall back to the
                # XLA histogram path and retry at full size
                pallas_fallback_done = True
                record["hist_backend_fallback"] = "xla"
                os.environ["LGBM_TPU_HIST"] = "xla"
                import jax

                jax.clear_caches()
                n_rows = N_ROWS  # retry the XLA path at full size
                continue
            oom = "RESOURCE_EXHAUSTED" in last_err or "Out of memory" in last_err
            n_rows //= 4
            if not oom and n_rows < N_ROWS // 16:
                break  # non-OOM failures get a few shrink retries, then stop
    record["error"] = last_err or "exhausted row-count fallbacks"
    emit(record)


if __name__ == "__main__":
    main()
