"""Training-throughput benchmark vs the reference's HIGGS baseline.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Reference anchor (BASELINE.md): LightGBM CPU trains HIGGS — 10.5M rows x 28
features, 500 iterations, 255 leaves — in 130.094 s (docs/Experiments.rst:113),
i.e. 10.5e6 * 500 / 130.094 = 40.36M row-iterations/second. HIGGS itself
cannot be downloaded in this sandbox (zero egress), so the bench trains on a
synthetic dataset with the HIGGS shape profile (28 dense numerical features,
binary labels, max_bin=255, num_leaves=255) and reports the same
row-iterations/second measure; vs_baseline = ours / 40.36e6 (>1 is faster).
"""
import json
import os
import time

import numpy as np

N_ROWS = int(os.environ.get("BENCH_ROWS", 1_000_000))
N_FEATURES = 28
N_ITERS = int(os.environ.get("BENCH_ITERS", 20))
WARMUP_ITERS = 2
BASELINE_ROW_ITERS_PER_SEC = 10_500_000 * 500 / 130.094


def main() -> None:
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(42)
    X = rng.randn(N_ROWS, N_FEATURES).astype(np.float32)
    w = rng.randn(N_FEATURES)
    logit = X[:5_000_000] @ w  # cap the label-gen matmul cost
    if N_ROWS > logit.shape[0]:
        logit = np.concatenate([logit, X[5_000_000:] @ w])
    y = (logit + rng.randn(N_ROWS).astype(np.float32) > 0).astype(np.float64)

    params = {
        "objective": "binary",
        "num_leaves": 255,
        "learning_rate": 0.1,
        "max_bin": 255,
        "min_data_in_leaf": 100,
        "verbosity": -1,
    }
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params=params, train_set=ds)
    for _ in range(WARMUP_ITERS):  # compile + cache warmup, not timed
        bst.update()
    t0 = time.perf_counter()
    for _ in range(N_ITERS):
        bst.update()
    elapsed = time.perf_counter() - t0

    row_iters_per_sec = N_ROWS * N_ITERS / elapsed
    print(json.dumps({
        "metric": "train_row_iters_per_sec",
        "value": round(row_iters_per_sec, 1),
        "unit": "row_iters/s",
        "vs_baseline": round(row_iters_per_sec / BASELINE_ROW_ITERS_PER_SEC, 4),
    }))


if __name__ == "__main__":
    main()
