"""LightGBM-TPU: a TPU-native gradient boosting framework.

A from-scratch reimplementation of the LightGBM feature set (reference:
shiyu1994/LightGBM) designed for TPU execution: JAX/XLA/Pallas compute kernels,
`jax.sharding` meshes + XLA collectives for distributed training, and a
lightgbm-compatible Python API.
"""
from .config import Config
from .models.tree import Tree
from .models.serialize import GBDTModel
from .utils.log import register_log_callback, LightGBMError

__version__ = "0.1.0"

__all__ = [
    "Config",
    "Tree",
    "GBDTModel",
    "register_log_callback",
    "LightGBMError",
    "__version__",
]


def __getattr__(name):
    # Lazy imports: keep `import lightgbm_tpu` cheap and avoid initializing
    # JAX until a training/inference entry point is touched.
    if name in ("Dataset", "Booster"):
        from . import basic

        return getattr(basic, name)
    if name in ("train", "cv", "CVBooster"):
        from . import engine

        return getattr(engine, name)
    if name in ("early_stopping", "log_evaluation", "record_evaluation", "reset_parameter"):
        from . import callback

        return getattr(callback, name)
    if name in ("LGBMModel", "LGBMClassifier", "LGBMRegressor", "LGBMRanker"):
        from . import sklearn

        return getattr(sklearn, name)
    if name in ("plot_importance", "plot_metric", "plot_tree", "plot_split_value_histogram"):
        from . import plotting

        return getattr(plotting, name)
    if name in ("RowBlockStore", "ContinuousTrainer"):
        from . import streaming

        return getattr(streaming, name)
    raise AttributeError(f"module 'lightgbm_tpu' has no attribute {name!r}")
