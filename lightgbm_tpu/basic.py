"""lightgbm-compatible Dataset and Booster.

Counterpart of python-package/lightgbm/basic.py (Dataset :1773, Booster :3581):
the user-facing objects with lazy Dataset construction, reference alignment
for validation data, and the Booster train/predict/save surface. Where the
reference binds to the C API through ctypes, this implementation drives the
in-process training engine (models/gbdt.py) directly — the C-API-shaped
boundary is preserved in naming and behavior so code written against lightgbm
ports over unchanged.
"""
from __future__ import annotations

import copy
import json
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Sequence, Union

import numpy as np

from .config import (Config, key_alias_transform, param_bool,
                     parse_objective_alias)
from .io.dataset import Dataset as _CoreDataset
from .io.parser import (load_positions, load_query_boundaries, load_weights,
                        parse_file)
from .models.gbdt import GBDT, create_boosting
from .models.serialize import GBDTModel
from .objectives import create_objective
from .utils.log import Log, LightGBMError

_NUMERIC_TYPES = (int, float, bool)


def _is_dataframe(data) -> bool:
    return hasattr(data, "dtypes") and hasattr(data, "columns")


def _pandas_to_matrix(df, pandas_categorical=None):
    """DataFrame -> (float64 matrix, categorical column indices,
    per-column category lists). Category-dtype columns become integer codes
    (NaN for missing/unseen); with `pandas_categorical` supplied (predict
    time), values map through the TRAINING categories — the python-package
    _data_from_pandas / pandas_categorical protocol."""
    cat_cols = [i for i, dt in enumerate(df.dtypes)
                if str(dt) == "category"]
    def _numeric(dt) -> bool:
        import pandas as pd

        # pd.api covers numpy dtypes AND nullable extension dtypes
        # (Int64/Float64/boolean), which np.asarray converts cleanly;
        # object/string/datetime columns are the ones to reject loudly
        return bool(pd.api.types.is_numeric_dtype(dt)
                    or pd.api.types.is_bool_dtype(dt))

    bad = [str(df.columns[i]) for i, dt in enumerate(df.dtypes)
           if i not in cat_cols and not _numeric(dt)]
    if bad:  # the python-package's explicit bad-dtype message (basic.py
        # _data_from_pandas), not an opaque numpy cast error
        raise ValueError(
            "DataFrame.dtypes for data must be int, float or bool. Did not "
            "expect the data types in the following fields: "
            + ", ".join(bad))
    if pandas_categorical is not None and \
            len(cat_cols) != len(pandas_categorical):
        raise ValueError(
            "train and valid dataset categorical_feature do not match")
    def _to_float(frame) -> np.ndarray:
        # to_numpy(na_value=...) maps pd.NA in nullable extension columns
        # to NaN; np.asarray would crash on NAType
        return frame.to_numpy(dtype=np.float64, na_value=np.nan)

    if not cat_cols:
        return _to_float(df), [], None
    df = df.copy(deep=False)
    cats_out = []
    for k, i in enumerate(cat_cols):
        col = df.iloc[:, i]
        if pandas_categorical is not None:
            cats = list(pandas_categorical[k])
            col = col.cat.set_categories(cats)
        else:
            cats = list(col.cat.categories)
        cats_out.append(cats)
        codes = col.cat.codes.to_numpy(dtype=np.float64, copy=True)
        codes[codes < 0] = np.nan  # missing / unseen categories
        df.isetitem(i, codes)
    return _to_float(df), cat_cols, cats_out


def _to_2d_float(data) -> np.ndarray:
    if hasattr(data, "toarray"):  # scipy sparse
        data = data.toarray()
    if _is_dataframe(data):
        data = _pandas_to_matrix(data)[0]
    elif hasattr(data, "values") and not isinstance(data, np.ndarray):
        data = data.values
    arr = np.asarray(data, dtype=np.float64)
    if arr.ndim == 1:
        arr = arr.reshape(1, -1)
    return arr


class Dataset:
    """Lazy-constructed training dataset (basic.py:1773)."""

    def __init__(self, data, label=None, reference: Optional["Dataset"] = None,
                 weight=None, group=None, init_score=None,
                 feature_name: Union[str, List[str]] = "auto",
                 categorical_feature: Union[str, List] = "auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = True, position=None) -> None:
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.position = position
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = copy.deepcopy(params) if params else {}
        self.free_raw_data = free_raw_data
        self._handle: Optional[_CoreDataset] = None
        self._raw: Optional[np.ndarray] = None
        self.used_indices: Optional[np.ndarray] = None
        self._predictor = None
        self.pandas_categorical = None

    # ------------------------------------------------------------ construction

    def construct(self) -> "Dataset":
        if self._handle is not None:
            return self
        params = dict(self.params)
        config = Config(params)
        data = self.data
        label = self.label
        feature_names: Optional[List[str]] = None

        if isinstance(data, (str, Path)) and _is_binary_cache(str(data)):
            self._handle = _CoreDataset.load_binary(str(data), config)
            self._raw = self._handle._loaded_raw
            # constructor args override the cached metadata, matching the
            # python-package's set_* after a binary load
            md = self._handle.metadata
            if self.label is not None:
                md.set_label(np.asarray(self.label))
            if self.weight is not None:
                md.set_weights(self.weight)
            if self.group is not None:
                md.set_query(self.group)
            if self.init_score is not None:
                md.set_init_score(self.init_score)
            if self.position is not None:
                md.set_positions(self.position)
            return self

        if isinstance(data, (str, Path)):
            X, y, names = parse_file(
                str(data), header=config.header,
                label_column=config.label_column or "0")
            if label is None:
                label = y
            feature_names = names
            w = load_weights(str(data))
            if w is not None and self.weight is None:
                self.weight = w
            q = load_query_boundaries(str(data))
            if q is not None and self.group is None:
                self.group = q
            p = load_positions(str(data))
            if p is not None and self.position is None:
                self.position = p
        elif _is_dataframe(data):
            # validation frames must encode through the TRAINING set's
            # category lists, not their own inferred order
            ref_pc = None
            if self.reference is not None:
                self.reference.construct()
                ref_pc = self.reference.pandas_categorical
            X, pd_cat_cols, pd_cats = _pandas_to_matrix(data, ref_pc)
            self.pandas_categorical = ref_pc if ref_pc is not None else pd_cats
            if self.feature_name == "auto":
                feature_names = [str(c) for c in data.columns]
            if pd_cat_cols and self.categorical_feature == "auto":
                self.categorical_feature = pd_cat_cols
        elif hasattr(data, "tocsc") and hasattr(data, "nnz"):
            # scipy sparse: stays sparse into the core Dataset, which bins
            # column-by-column (io/dataset.py) — never densified whole
            X = data
            if params.get("linear_tree"):
                Log.fatal("linear_tree requires dense input "
                          "(raw feature values per leaf)")
        else:
            X = _to_2d_float(data)

        if isinstance(self.feature_name, (list, tuple)):
            feature_names = list(self.feature_name)

        cats: List[int] = []
        if isinstance(self.categorical_feature, (list, tuple)):
            for c in self.categorical_feature:
                if isinstance(c, str) and feature_names and c in feature_names:
                    cats.append(feature_names.index(c))
                elif isinstance(c, _NUMERIC_TYPES):
                    cats.append(int(c))
        if "categorical_feature" in params or "categorical_column" in params:
            raw = params.get("categorical_feature", params.get("categorical_column"))
            if isinstance(raw, str):
                for tok in raw.split(","):
                    tok = tok.strip()
                    if tok.startswith("name:") and feature_names:
                        for nm in tok[5:].split(","):
                            if nm in feature_names:
                                cats.append(feature_names.index(nm))
                    elif tok:
                        cats.append(int(tok))

        ref_handle = None
        if self.reference is not None:
            self.reference.construct()
            ref_handle = self.reference._handle

        if self.used_indices is not None:
            if hasattr(X, "tocsr"):
                X = X.tocsr()[self.used_indices]
            else:
                X = X[self.used_indices]
            label = (np.asarray(label)[self.used_indices]
                     if label is not None else None)

        self._handle = _CoreDataset.from_matrix(
            X, label=label, weight=self.weight, group=self.group,
            init_score=self.init_score, position=self.position,
            config=config, categorical_feature=cats,
            feature_names=feature_names, reference=ref_handle)
        if config.monotone_constraints:
            self._handle.monotone_constraints = list(config.monotone_constraints)
        # raw values back linear trees / refit; a sparse X stays un-densified
        # (linear_tree was rejected above; refit/valid-eval densify on demand)
        if hasattr(X, "tocsc"):
            self._raw = None
            self._sparse_raw = X
        else:
            self._raw = np.asarray(X, dtype=np.float32)
        if self.free_raw_data:
            self.data = None
        return self

    # ---------------------------------------------------------------- helpers

    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None, position=None) -> "Dataset":
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score,
                       params=params or self.params, position=position)

    def subset(self, used_indices: Sequence[int], params=None) -> "Dataset":
        self.construct()
        sub = Dataset(None, params=params or self.params,
                      free_raw_data=self.free_raw_data)
        sub._handle = self._handle.subset(np.asarray(used_indices))
        sub._raw = self._raw[np.asarray(used_indices)] if self._raw is not None else None
        if self._raw is None and getattr(self, "_sparse_raw", None) is not None:
            # keep the sliced rows sparse too (cv folds of a sparse train set)
            sub._sparse_raw = self._sparse_raw.tocsr()[np.asarray(used_indices)]
        sub.reference = self
        return sub

    def set_label(self, label) -> "Dataset":
        self.label = label
        if self._handle is not None and label is not None:
            self._handle.metadata.set_label(label)
        return self

    def set_weight(self, weight) -> "Dataset":
        self.weight = weight
        if self._handle is not None:
            self._handle.metadata.set_weights(weight)
        return self

    def set_group(self, group) -> "Dataset":
        self.group = group
        if self._handle is not None and group is not None:
            self._handle.metadata.set_query(group)
        return self

    def set_init_score(self, init_score) -> "Dataset":
        self.init_score = init_score
        if self._handle is not None:
            self._handle.metadata.set_init_score(init_score)
        return self

    def set_position(self, position) -> "Dataset":
        self.position = position
        if self._handle is not None:
            self._handle.metadata.set_positions(position)
        return self

    def get_label(self):
        if self._handle is not None and self._handle.metadata.label is not None:
            return self._handle.metadata.label
        return self.label

    def get_weight(self):
        return self.weight

    def get_group(self):
        return self.group

    def get_init_score(self):
        return self.init_score

    def get_data(self):
        return self.data if self.data is not None else self._raw

    def get_feature_name(self) -> List[str]:
        self.construct()
        return list(self._handle.feature_names)

    def num_data(self) -> int:
        self.construct()
        return self._handle.num_data

    def num_feature(self) -> int:
        self.construct()
        return self._handle.num_total_features

    def save_binary(self, filename: str) -> "Dataset":
        """Binary dataset cache (Dataset::SaveBinaryFile analog, npz-based);
        `Dataset(filename)` loads it back, skipping parse + bin-finding."""
        self.construct()
        h = self._handle
        md = h.metadata
        from .checkpoint import atomic_open

        with atomic_open(filename, "wb") as fh:  # file object: numpy must
            np.savez_compressed(  # not append .npz to the requested name
                fh, bins=h.bins,
                label=md.label if md.label is not None else [],
                weight=md.weights if md.weights is not None else [],
                init_score=md.init_score if md.init_score is not None else [],
                query_boundaries=(md.query_boundaries
                                  if md.query_boundaries is not None else []),
                positions=md.positions if md.positions is not None else [],
                position_ids=(md.position_ids
                              if md.position_ids is not None else []),
                mappers=json.dumps([m.to_dict() for m in h.mappers]),
                feature_names=json.dumps(h.feature_names),
                group_lists=json.dumps(
                    [g.feature_indices for g in h.groups]),
                group_is_multi=json.dumps([g.is_multi for g in h.groups]),
                used_features=json.dumps(h.used_features),
                num_total_features=h.num_total_features,
                monotone=json.dumps(h.monotone_constraints),
                raw=self._raw if self._raw is not None else [])
        if self._raw is None and getattr(self, "_sparse_raw", None) is not None:
            Log.warning("save_binary: raw feature values of a sparse-built "
                        "Dataset are not cached; the reloaded Dataset can "
                        "train but cannot serve as a validation set")
        return self


def _is_binary_cache(path: str) -> bool:
    """A save_binary cache is an npz (zip) file: check the PK magic."""
    try:
        with open(path, "rb") as fh:
            return fh.read(2) == b"PK"
    except OSError:
        return False


class Booster:
    """Training/prediction handle (basic.py:3581)."""

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None) -> None:
        self.params = dict(params) if params else {}
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._train_data_name = "training"
        self.name_valid_sets: List[str] = []

        if train_set is not None:
            if not isinstance(train_set, Dataset):
                raise TypeError(f"Training data should be Dataset instance, "
                                f"met {type(train_set).__name__}")
            train_set.params = {**train_set.params, **self.params}
            train_set.construct()
            self.config = Config(self.params)
            objective = create_objective(self.config.objective, self.config)
            self._gbdt = create_boosting(self.config, train_set._handle,
                                         objective, train_raw=train_set._raw)
            self.train_set = train_set
            self.pandas_categorical = train_set.pandas_categorical
            self._model: Optional[GBDTModel] = None
        elif model_file is not None or model_str is not None:
            model = (GBDTModel.from_file(model_file) if model_file
                     else GBDTModel.from_string(model_str))
            self._model = model
            self.config = Config(self.params)
            self._gbdt = GBDT(self.config, None, None)
            self._gbdt.models = model.trees
            self._gbdt._predictor.invalidate()
            self._gbdt.num_class = model.num_class
            self._gbdt.num_tree_per_iteration = model.num_tree_per_iteration
            # restore the iteration counter (GBDT::LoadModelFromString sets
            # iter_ from the loaded tree count) so current_iteration() and
            # the C API's out_num_iterations are right after a file load
            self._gbdt.iter_ = (len(model.trees)
                                // max(model.num_tree_per_iteration, 1))
            self._gbdt.objective = _objective_from_string(model.objective_str, self.config)
            self._gbdt.average_output = model.average_output
            self.train_set = None
            self.pandas_categorical = model.pandas_categorical
        else:
            raise TypeError("Need at least one training dataset or model "
                            "file or model string to create Booster instance")

    # ------------------------------------------------------------------ train

    def add_valid(self, data: Dataset, name: str) -> "Booster":
        if not isinstance(data, Dataset):
            raise TypeError(f"Validation data should be Dataset instance, "
                            f"met {type(data).__name__}")
        data.construct()
        raw = data._raw
        if raw is None and getattr(data, "_sparse_raw", None) is None:
            Log.fatal("Validation-set evaluation needs raw feature values; "
                      "this Dataset has none (e.g. reloaded from a binary "
                      "cache of sparse input)")
        if raw is None and getattr(data, "_sparse_raw", None) is not None:
            # valid-set eval traverses raw feature values on device; a
            # sparse VALID set densifies here (valid << train in practice —
            # the train matrix itself is never densified). astype BEFORE
            # toarray: the f32 conversion on the sparse side halves the
            # transient peak vs densify-then-cast.
            raw = data._sparse_raw.astype(np.float32).toarray()
        self._gbdt.add_valid(data._handle, raw, name)
        self.name_valid_sets.append(name)
        return self

    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting iteration; returns True if training finished
        (basic.py:4091 / LGBM_BoosterUpdateOneIter)."""
        if fobj is not None:
            if self._gbdt.objective is not None:
                raise LightGBMError("Cannot use fobj with a built-in objective; "
                                    "set objective='none'")
            grad, hess = fobj(self.__pred_for_fobj(), self.train_set)
            return self.__boost(grad, hess)
        return self._gbdt.train_one_iter()

    def __pred_for_fobj(self):
        # DART must drop trees BEFORE custom gradients read the score
        # (GetTrainingScore triggers DroppingTrees, dart.hpp:78-88)
        self._gbdt.prepare_training_score()
        score = np.asarray(self._gbdt.score)
        return score.ravel() if score.shape[0] == 1 else score.T

    def __boost(self, grad, hess) -> bool:
        grad = np.asarray(grad, dtype=np.float32)
        hess = np.asarray(hess, dtype=np.float32)
        return self._gbdt.train_one_iter(grad, hess)

    def refit(self, data, label, decay_rate: float = 0.9,
              **kwargs: Any) -> "Booster":
        """Refit the existing tree structures on new data
        (python-package Booster.refit / LGBM_BoosterRefit)."""
        if hasattr(data, "toarray"):  # scipy sparse: refit needs raw values
            data = data.toarray()
        data = np.asarray(data, dtype=np.float64)
        pred_leaf = self.predict(data, pred_leaf=True)
        new_params = {**self.params, "refit_decay_rate": decay_rate}
        train_set = Dataset(data, label=label, **kwargs)
        new_booster = Booster(new_params, train_set)
        new_booster._gbdt.models = GBDTModel.from_string(
            self.model_to_string()).trees
        new_booster._gbdt._predictor.invalidate()
        new_booster._gbdt.iter_ = (len(new_booster._gbdt.models)
                                   // new_booster._gbdt.num_tree_per_iteration)
        new_booster._gbdt.refit(
            np.asarray(pred_leaf, dtype=np.int32).reshape(data.shape[0], -1))
        return new_booster

    def rollback_one_iter(self) -> "Booster":
        self._gbdt.rollback_one_iter()
        return self

    def current_iteration(self) -> int:
        return self._gbdt.iter_

    def num_trees(self) -> int:
        return len(self._gbdt.models)

    def num_model_per_iteration(self) -> int:
        return self._gbdt.num_tree_per_iteration

    def num_feature(self) -> int:
        if self.train_set is not None:
            return self.train_set.num_feature()
        return self._model.max_feature_idx + 1 if self._model else 0

    def feature_name(self) -> List[str]:
        if self.train_set is not None:
            return self.train_set.get_feature_name()
        return list(self._model.feature_names) if self._model else []

    # ------------------------------------------------------------------- eval

    def eval_train(self, feval=None) -> List:
        return self.__format_eval(self._gbdt.eval_train(), feval, "train")

    def eval_valid(self, feval=None) -> List:
        return self.__format_eval(self._gbdt.eval_valid(), feval, "valid")

    def __format_eval(self, results, feval, which) -> List:
        out = [(dname, mname, val, bigger) for dname, mname, val, bigger in results]
        if feval is not None:
            fevals = feval if isinstance(feval, (list, tuple)) else [feval]
            for fe in fevals:
                if which == "train" and self.train_set is not None:
                    res = fe(self.__pred_for_feval(self.train_set), self.train_set)
                    name, val, bigger = res
                    out.append((self._train_data_name, name, val, bigger))
        return out

    def __pred_for_feval(self, dataset):
        score = np.asarray(self._gbdt.score)
        return score.ravel() if score.shape[0] == 1 else score.T

    # ---------------------------------------------------------------- predict

    def predict(self, data, start_iteration: int = 0, num_iteration: Optional[int] = None,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False, validate_features: bool = False,
                **kwargs) -> np.ndarray:
        if validate_features and _is_dataframe(data):
            trained = self.feature_name()
            given = [str(c) for c in data.columns]
            if trained and given != trained:
                raise LightGBMError(
                    f"The features names of the data to predict {given} do "
                    f"not match the ones used in training {trained}")
        if _is_dataframe(data) and self.pandas_categorical:
            data = _pandas_to_matrix(data, self.pandas_categorical)[0]
        # keep the caller's f32/f64 values: models/gbdt.py routes the device
        # dtype (f64 stays f64 under jax x64; otherwise the pack-time
        # round-toward--inf threshold downcast keeps f32 bit-exact)
        if isinstance(data, np.ndarray) and data.dtype == np.float32 \
                and data.ndim == 2:
            X = data
        else:
            X = _to_2d_float(data)
        if validate_features:
            expected = self.num_feature()
            if expected > 0 and X.shape[1] != expected:
                raise LightGBMError(
                    f"The number of features in data ({X.shape[1]}) is not "
                    f"the same as it was in training data ({expected})")
        if num_iteration is None:
            # best-iteration truncation applies to whole-model predicts only;
            # an explicit start_iteration means "this slice onward"
            num_iteration = (self.best_iteration
                             if self.best_iteration > 0
                             and start_iteration <= 0 else 0)
        if pred_leaf:
            return self._gbdt.predict_leaf_index(X, num_iteration,
                                                 start_iteration)
        if pred_contrib:
            from .shap import predict_contrib

            C = self._gbdt.num_tree_per_iteration
            trees = self._gbdt.models[max(start_iteration, 0) * C:]
            return predict_contrib(trees, X, C, num_iteration)
        chunk_kw = kwargs.get("pred_chunk_rows",
                              self.params.get("pred_chunk_rows"))
        chunk_rows = int(chunk_kw) if chunk_kw is not None else None
        # pred_shard_rows: row-shard this predict across the data mesh once
        # the batch reaches the given row count (parallel/predict.py policy;
        # inert on single-device platforms)
        shard_kw = kwargs.get("pred_shard_rows",
                              self.params.get("pred_shard_rows"))
        shard_rows = int(shard_kw) if shard_kw is not None else None
        if param_bool(kwargs.get("pred_early_stop",
                                 self.params.get("pred_early_stop"))):
            return self._gbdt.predict(
                X, raw_score=raw_score, num_iteration=num_iteration,
                start_iteration=start_iteration,
                early_stop=(
                    int(kwargs.get("pred_early_stop_freq",
                                   self.params.get("pred_early_stop_freq", 10))),
                    float(kwargs.get(
                        "pred_early_stop_margin",
                        self.params.get("pred_early_stop_margin", 10.0)))))
        return self._gbdt.predict(X, raw_score=raw_score,
                                  num_iteration=num_iteration,
                                  start_iteration=start_iteration,
                                  chunk_rows=chunk_rows,
                                  shard_rows=shard_rows)

    # ------------------------------------------------------------------ model

    def save_model(self, filename: str, num_iteration: Optional[int] = None,
                   start_iteration: int = 0,
                   importance_type: str = "split") -> "Booster":
        model = self.__get_model()
        if num_iteration is None:
            num_iteration = self.best_iteration if self.best_iteration > 0 else -1
        model.save_to_file(str(filename), start_iteration, num_iteration or -1,
                           importance_type)
        return self

    def model_to_string(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0,
                        importance_type: str = "split") -> str:
        model = self.__get_model()
        if num_iteration is None:
            num_iteration = self.best_iteration if self.best_iteration > 0 else -1
        return model.to_string(start_iteration, num_iteration or -1, importance_type)

    def dump_model(self, num_iteration: Optional[int] = None,
                   start_iteration: int = 0,
                   importance_type: str = "split") -> Dict:
        model = self.__get_model()
        if num_iteration is None:
            num_iteration = self.best_iteration if self.best_iteration > 0 else -1
        return json.loads(model.dump_json(start_iteration, num_iteration or -1,
                                          importance_type))

    def __get_model(self) -> GBDTModel:
        if self.train_set is not None:
            model = self._gbdt.to_model()
            model.best_iteration = self.best_iteration
            model.pandas_categorical = self.pandas_categorical
            return model
        return self._model

    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        model = self.__get_model()
        imp = model.feature_importance(importance_type, iteration or 0)
        return imp if importance_type == "gain" else imp.astype(np.int64)

    def lower_bound(self):
        vals = [t.leaf_value[: t.num_leaves].min() for t in self._gbdt.models]
        return float(np.sum(vals)) if vals else 0.0

    def upper_bound(self):
        vals = [t.leaf_value[: t.num_leaves].max() for t in self._gbdt.models]
        return float(np.sum(vals)) if vals else 0.0

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        self.params.update(params)
        self.config.set(params)
        if self.train_set is not None:
            self._gbdt.shrinkage_rate = self.config.learning_rate
            self._gbdt.tree_learner.config = self.config
            self._gbdt.tree_learner.params_dev = _learner_params(self.config)
        return self


def _learner_params(config: Config):
    import jax.numpy as jnp

    return jnp.asarray([
        config.lambda_l1, config.lambda_l2, float(config.min_data_in_leaf),
        config.min_sum_hessian_in_leaf, config.min_gain_to_split,
        config.max_delta_step], dtype=jnp.float32)


def _objective_from_string(objective_str: Optional[str], config: Config):
    """Rebuild an objective from a model file's `objective=` line
    (e.g. 'binary sigmoid:1', 'multiclass num_class:3')."""
    if not objective_str:
        return None
    parts = objective_str.split()
    name = parse_objective_alias(parts[0])
    for tok in parts[1:]:
        if ":" in tok:
            k, v = tok.split(":", 1)
            try:
                config.set({k: v})
            except Exception:
                pass
    try:
        return create_objective(name, config)
    except LightGBMError:
        return None
