"""Training callbacks.

Counterpart of python-package/lightgbm/callback.py: early_stopping (:278,456),
log_evaluation (:109), record_evaluation (:183), reset_parameter (:254), with
the same CallbackEnv protocol and before/after-iteration ordering.
"""
from __future__ import annotations

from collections import namedtuple
from typing import Callable, Dict, List, Union

from .utils.log import Log

CallbackEnv = namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score) -> None:
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


def _format_eval_result(value, show_stdv: bool = True) -> str:
    if len(value) == 4:
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    if len(value) == 5:
        if show_stdv:
            return f"{value[0]}'s {value[1]}: {value[2]:g} + {value[4]:g}"
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    raise ValueError("Wrong metric value")


def log_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list \
                and (env.iteration + 1) % period == 0:
            result = "\t".join(_format_eval_result(x, show_stdv)
                               for x in env.evaluation_result_list)
            Log.info("[%d]\t%s", env.iteration + 1, result)

    _callback.order = 10
    return _callback


def record_evaluation(eval_result: Dict) -> Callable:
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")

    def _init(env: CallbackEnv) -> None:
        eval_result.clear()
        for item in env.evaluation_result_list or []:
            data_name, eval_name = item[0], item[1]
            eval_result.setdefault(data_name, {}).setdefault(eval_name, [])

    def _callback(env: CallbackEnv) -> None:
        if not eval_result:
            _init(env)
        for item in env.evaluation_result_list or []:
            data_name, eval_name, result = item[0], item[1], item[2]
            eval_result.setdefault(data_name, {}).setdefault(eval_name, []).append(result)

    _callback.order = 20
    return _callback


def reset_parameter(**kwargs) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        new_parameters = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(f"Length of list {key!r} has to equal to "
                                     f"'num_boost_round'.")
                new_param = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_param = value(env.iteration - env.begin_iteration)
            else:
                raise ValueError("Only list and callable values are supported "
                                 "as a mapping from boosting round index to new parameter value.")
            if new_param != env.params.get(key, None):
                new_parameters[key] = new_param
        if new_parameters:
            if env.model is not None:
                env.model.reset_parameter(new_parameters)
            env.params.update(new_parameters)

    _callback.before_iteration = True
    _callback.order = 10
    return _callback


class _EarlyStoppingCallback:
    """callback.py:278-455."""

    # checkpoint.restore_trainer_state hands resumed early-stop state to any
    # callback that sets this flag (via _pending_restore, applied post-_init)
    _accepts_state_restore = True

    def __init__(self, stopping_rounds: int, first_metric_only: bool = False,
                 verbose: bool = True, min_delta: Union[float, List[float]] = 0.0) -> None:
        if not isinstance(stopping_rounds, int) or stopping_rounds <= 0:
            raise ValueError(f"stopping_rounds should be an integer and greater"
                             f" than 0. got: {stopping_rounds}")
        self.order = 30
        self.before_iteration = False
        self.stopping_rounds = stopping_rounds
        self.first_metric_only = first_metric_only
        self.verbose = verbose
        self.min_delta = min_delta
        self.enabled = True
        self._pending_restore = None
        self._reset_storages()

    def _reset_storages(self) -> None:
        self.best_score: List[float] = []
        self.best_iter: List[int] = []
        self.best_score_list: List = []
        self.cmp_op: List[Callable] = []
        self.first_metric = ""

    def _init(self, env: CallbackEnv) -> None:
        self._reset_storages()
        if not env.evaluation_result_list:
            self.enabled = False
            Log.warning("For early stopping, at least one dataset and eval "
                        "metric is required for evaluation")
            return
        n_metrics = len({m[1] for m in env.evaluation_result_list})
        n_datasets = len(env.evaluation_result_list) // max(n_metrics, 1)
        if isinstance(self.min_delta, list):
            deltas = self.min_delta * n_datasets
        else:
            deltas = [self.min_delta] * n_datasets * n_metrics
        self.first_metric = env.evaluation_result_list[0][1]
        for eval_ret, delta in zip(env.evaluation_result_list, deltas):
            self.best_iter.append(0)
            if eval_ret[3]:  # greater is better
                self.best_score.append(float("-inf"))
                self.cmp_op.append(lambda cur, best, d=delta: cur > best + d)
            else:
                self.best_score.append(float("inf"))
                self.cmp_op.append(lambda cur, best, d=delta: cur < best - d)
            self.best_score_list.append(None)

    def snapshot(self) -> Dict:
        """JSON-serializable early-stop state for checkpointing. cmp_op is
        not stored: _init rebuilds the comparators deterministically from
        min_delta + the eval list, which resume reproduces exactly."""
        return {
            "enabled": self.enabled,
            "best_score": list(self.best_score),
            "best_iter": list(self.best_iter),
            "best_score_list": [
                None if bsl is None else [list(item) for item in bsl]
                for bsl in self.best_score_list],
            "first_metric": self.first_metric,
        }

    def _apply_restore(self, state: Dict) -> None:
        if len(state.get("best_score", [])) != len(self.best_score):
            Log.warning("Checkpointed early-stop state tracks %d metrics but "
                        "the resume run evaluates %d; starting early-stop "
                        "bookkeeping fresh",
                        len(state.get("best_score", [])), len(self.best_score))
            return
        self.enabled = bool(state["enabled"])
        self.best_score = [float(s) for s in state["best_score"]]
        self.best_iter = [int(it) for it in state["best_iter"]]
        self.best_score_list = [
            None if bsl is None else [tuple(item) for item in bsl]
            for bsl in state["best_score_list"]]
        self.first_metric = state["first_metric"]

    def _final_iteration_check(self, env: CallbackEnv, eval_name_splitted, i) -> None:
        if env.iteration == env.end_iteration - 1:
            if self.verbose:
                Log.info("Did not meet early stopping. Best iteration is: [%d]\t%s",
                         self.best_iter[i] + 1,
                         "\t".join(_format_eval_result(x) for x in self.best_score_list[i]))
            raise EarlyStopException(self.best_iter[i], self.best_score_list[i])

    def __call__(self, env: CallbackEnv) -> None:
        if env.iteration == env.begin_iteration:
            self._init(env)
            if self._pending_restore is not None:
                self._apply_restore(self._pending_restore)
                self._pending_restore = None
        if not self.enabled:
            return
        for i, eval_ret in enumerate(env.evaluation_result_list):
            data_name, metric_name, score = eval_ret[0], eval_ret[1], eval_ret[2]
            if self.best_score_list[i] is None or self.cmp_op[i](score, self.best_score[i]):
                self.best_score[i] = score
                self.best_iter[i] = env.iteration
                self.best_score_list[i] = env.evaluation_result_list
            if self.first_metric_only and self.first_metric != metric_name:
                continue
            if data_name == "training":
                continue  # train metric never triggers early stop
            if env.iteration - self.best_iter[i] >= self.stopping_rounds:
                if self.verbose:
                    Log.info("Early stopping, best iteration is: [%d]\t%s",
                             self.best_iter[i] + 1,
                             "\t".join(_format_eval_result(x) for x in self.best_score_list[i]))
                raise EarlyStopException(self.best_iter[i], self.best_score_list[i])
            self._final_iteration_check(env, metric_name, i)
        if env.model is not None:
            # published for the checkpoint callback (order 40, runs next)
            env.model._early_stop_state = self.snapshot()


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True, min_delta: Union[float, List[float]] = 0.0
                   ) -> _EarlyStoppingCallback:
    return _EarlyStoppingCallback(stopping_rounds, first_metric_only, verbose, min_delta)
