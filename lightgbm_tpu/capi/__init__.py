"""C API: a real shared library exporting the reference's LGBM_* surface.

The reference ships its C API as src/c_api.cpp compiled into lib_lightgbm
(include/LightGBM/c_api.h); every language binding (R, Java, C#, the CLI
wrappers) sits on those symbols. Here the engine itself is Python/JAX, so
the C API is built the other way around: `cffi` embedding compiles a
native .so whose exported LGBM_* symbols trampoline into this package
(build_capi.py). C clients #include lightgbm_tpu_c.h, link the .so, and
get the familiar handle-based workflow:

    LGBM_DatasetCreateFromMat -> LGBM_BoosterCreate ->
    LGBM_BoosterUpdateOneIter -> LGBM_BoosterPredictForMat ->
    LGBM_BoosterSaveModel / LGBM_GetLastError

Handles are opaque integers into a process-global registry; every entry
point stores its last exception for LGBM_GetLastError (c_api.cpp's
LGBM_SetLastError convention). Build with:

    python -m lightgbm_tpu.capi.build_capi --out build/

Constants mirror c_api.h:35-43 (dtype / predict-type enums).
"""

C_API_DTYPE_FLOAT32 = 0
C_API_DTYPE_FLOAT64 = 1
C_API_DTYPE_INT32 = 2
C_API_DTYPE_INT64 = 3

C_API_PREDICT_NORMAL = 0
C_API_PREDICT_RAW_SCORE = 1
C_API_PREDICT_LEAF_INDEX = 2
C_API_PREDICT_CONTRIB = 3
