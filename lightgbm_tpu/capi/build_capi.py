"""Build the native C API shared library with cffi embedding.

    python -m lightgbm_tpu.capi.build_capi --out build/

produces liblightgbm_tpu.(so|dylib) exporting the LGBM_* symbols declared
in lightgbm_tpu_c.h, plus the header itself. The .so embeds a Python
interpreter (cffi embedding API): when a C program dlopens/links it, the
first LGBM_* call initializes Python, imports lightgbm_tpu, and dispatches
into capi.impl. Loaded inside an existing Python process it reuses that
interpreter. Counterpart of src/c_api.cpp + lib_lightgbm in the reference
build (CMakeLists.txt); signatures mirror include/LightGBM/c_api.h.
"""
from __future__ import annotations

import argparse
import os
import sys

# Declarations shared by the embedding API and the public header.
DECLS = """
typedef void* DatasetHandle;
typedef void* BoosterHandle;

const char* LGBM_GetLastError(void);

int LGBM_DatasetCreateFromMat(const void* data, int data_type,
                              int32_t nrow, int32_t ncol, int is_row_major,
                              const char* parameters,
                              DatasetHandle reference, DatasetHandle* out);
int LGBM_DatasetCreateFromFile(const char* filename, const char* parameters,
                               DatasetHandle reference, DatasetHandle* out);
int LGBM_DatasetSetField(DatasetHandle handle, const char* field_name,
                         const void* field_data, int32_t num_element,
                         int data_type);
int LGBM_DatasetGetNumData(DatasetHandle handle, int32_t* out);
int LGBM_DatasetGetNumFeature(DatasetHandle handle, int32_t* out);
int LGBM_DatasetFree(DatasetHandle handle);

int LGBM_DatasetCreateStreaming(int32_t ncol, const char* parameters,
                                DatasetHandle* out);
int LGBM_DatasetPushRows(DatasetHandle dataset, const void* data,
                         int data_type, int32_t nrow, int32_t ncol,
                         int32_t start_row);
int LGBM_DatasetPushRowsByCSR(DatasetHandle dataset, const void* indptr,
                              int indptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t nindptr, int64_t nelem,
                              int64_t num_col, int64_t start_row);

int LGBM_BoosterCreate(DatasetHandle train_data, const char* parameters,
                       BoosterHandle* out);
int LGBM_BoosterAddValidData(BoosterHandle handle, DatasetHandle valid_data);
int LGBM_BoosterCreateFromModelfile(const char* filename,
                                    int* out_num_iterations,
                                    BoosterHandle* out);
int LGBM_BoosterLoadModelFromString(const char* model_str,
                                    int* out_num_iterations,
                                    BoosterHandle* out);
int LGBM_BoosterSaveModel(BoosterHandle handle, int start_iteration,
                          int num_iteration, int feature_importance_type,
                          const char* filename);
int LGBM_BoosterSaveModelToString(BoosterHandle handle, int start_iteration,
                                  int num_iteration,
                                  int feature_importance_type,
                                  int64_t buffer_len, int64_t* out_len,
                                  char* out_str);
int LGBM_BoosterUpdateOneIter(BoosterHandle handle, int* is_finished);
int LGBM_BoosterGetCurrentIteration(BoosterHandle handle, int* out_iteration);
int LGBM_BoosterGetNumClasses(BoosterHandle handle, int* out_len);
int LGBM_BoosterNumberOfTotalModel(BoosterHandle handle, int* out_models);
int LGBM_BoosterPredictForMat(BoosterHandle handle, const void* data,
                              int data_type, int32_t nrow, int32_t ncol,
                              int is_row_major, int predict_type,
                              int start_iteration, int num_iteration,
                              const char* parameter, int64_t* out_len,
                              double* out_result);
int LGBM_BoosterPredictForCSR(BoosterHandle handle, const void* indptr,
                              int indptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t nindptr, int64_t nelem,
                              int64_t num_col, int predict_type,
                              int start_iteration, int num_iteration,
                              const char* parameter, int64_t* out_len,
                              double* out_result);
int LGBM_BoosterFree(BoosterHandle handle);
"""

HEADER_TEMPLATE = """/* lightgbm_tpu C API — LGBM_* surface per the reference's c_api.h.
 * Link against liblightgbm_tpu; the library embeds the Python engine. */
#ifndef LIGHTGBM_TPU_C_H_
#define LIGHTGBM_TPU_C_H_
#include <stdint.h>

#define C_API_DTYPE_FLOAT32 (0)
#define C_API_DTYPE_FLOAT64 (1)
#define C_API_DTYPE_INT32   (2)
#define C_API_DTYPE_INT64   (3)

#define C_API_PREDICT_NORMAL     (0)
#define C_API_PREDICT_RAW_SCORE  (1)
#define C_API_PREDICT_LEAF_INDEX (2)
#define C_API_PREDICT_CONTRIB    (3)

#ifdef __cplusplus
extern "C" {
#endif
%s
#ifdef __cplusplus
}
#endif
#endif  /* LIGHTGBM_TPU_C_H_ */
"""

# Runs inside the embedded interpreter on first symbol use.
INIT_CODE = """
from lightgbm_tpu_capi_embed import ffi


@ffi.def_extern()
def LGBM_GetLastError():
    from lightgbm_tpu.capi import impl
    err = impl.last_error().encode()
    # keep the buffer alive per-thread (c_api.cpp uses a thread_local
    # std::string for the same reason): another thread's error must not
    # free the pointer this thread is still reading
    impl._err_local.buf = ffi.new("char[]", err)
    return impl._err_local.buf


def _bind(name, pyname):
    def call(*args):
        from lightgbm_tpu.capi import impl
        try:
            return getattr(impl, pyname)(ffi, *args)
        except Exception as e:  # noqa: BLE001 - C boundary
            from lightgbm_tpu.capi import impl
            return impl.set_last_error(f"{type(e).__name__}: {e}")

    ffi.def_extern(name=name)(call)


_bind("LGBM_DatasetCreateFromMat", "dataset_create_from_mat")
_bind("LGBM_DatasetCreateFromFile", "dataset_create_from_file")
_bind("LGBM_DatasetSetField", "dataset_set_field")
_bind("LGBM_DatasetGetNumData", "dataset_get_num_data")
_bind("LGBM_DatasetGetNumFeature", "dataset_get_num_feature")
_bind("LGBM_DatasetFree", "dataset_free")
_bind("LGBM_DatasetCreateStreaming", "dataset_create_streaming")
_bind("LGBM_DatasetPushRows", "dataset_push_rows")
_bind("LGBM_DatasetPushRowsByCSR", "dataset_push_rows_by_csr")
_bind("LGBM_BoosterCreate", "booster_create")
_bind("LGBM_BoosterAddValidData", "booster_add_valid_data")
_bind("LGBM_BoosterCreateFromModelfile", "booster_create_from_modelfile")
_bind("LGBM_BoosterLoadModelFromString", "booster_load_model_from_string")
_bind("LGBM_BoosterSaveModel", "booster_save_model")
_bind("LGBM_BoosterSaveModelToString", "booster_save_model_to_string")
_bind("LGBM_BoosterUpdateOneIter", "booster_update_one_iter")
_bind("LGBM_BoosterGetCurrentIteration", "booster_get_current_iteration")
_bind("LGBM_BoosterGetNumClasses", "booster_get_num_classes")
_bind("LGBM_BoosterNumberOfTotalModel", "booster_number_of_total_model")
_bind("LGBM_BoosterPredictForMat", "booster_predict_for_mat")
_bind("LGBM_BoosterPredictForCSR", "booster_predict_for_csr")
_bind("LGBM_BoosterFree", "booster_free")
"""


def _handles_as_intptr(decls: str) -> str:
    """cffi embedding wants concrete types; handles travel as intptr_t."""
    return (decls.replace("typedef void* DatasetHandle;", "")
                 .replace("typedef void* BoosterHandle;", "")
                 .replace("DatasetHandle*", "intptr_t*")
                 .replace("BoosterHandle*", "intptr_t*")
                 .replace("DatasetHandle", "intptr_t")
                 .replace("BoosterHandle", "intptr_t"))


def build(out_dir: str) -> str:
    import cffi

    os.makedirs(out_dir, exist_ok=True)
    ffibuilder = cffi.FFI()
    ffibuilder.embedding_api(_handles_as_intptr(DECLS))
    ffibuilder.set_source("lightgbm_tpu_capi_embed", """
        #include <stdint.h>
    """)
    # make the package importable inside the embedded interpreter even when
    # the host process is a plain C program started anywhere
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    init = (f"import sys; sys.path.insert(0, {repo_root!r})\n" + INIT_CODE)
    ffibuilder.embedding_init_code(init)
    target = os.path.join(out_dir, "liblightgbm_tpu.*")
    so_path = ffibuilder.compile(target=target, tmpdir=out_dir, verbose=False)
    header = os.path.join(out_dir, "lightgbm_tpu_c.h")
    with open(header, "w") as f:
        f.write(HEADER_TEMPLATE % DECLS)
    return so_path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="build")
    args = ap.parse_args(argv)
    so = build(args.out)
    print(so)
    return 0


if __name__ == "__main__":
    sys.exit(main())
