"""Python implementations behind the exported LGBM_* symbols.

Each function mirrors the contract of its namesake in the reference's
src/c_api.cpp (return 0 on success, -1 on error with the message readable
via LGBM_GetLastError; out-params filled through cffi pointers). The cffi
embedding module (build_capi.py) binds these to the real C symbols.
"""
from __future__ import annotations

import threading
from typing import Any, Dict

import numpy as np

_lock = threading.RLock()
_handles: Dict[int, Any] = {}
_next_handle = [1]
_last_error = threading.local()  # per-thread, like c_api.cpp's thread_local
_err_local = threading.local()  # keeps each thread's returned char* alive

_DTYPES = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}


def set_last_error(msg: str) -> int:
    _last_error.msg = msg
    return -1


def last_error() -> str:
    return getattr(_last_error, "msg", "ok")


def _register(obj) -> int:
    with _lock:
        h = _next_handle[0]
        _next_handle[0] += 1
        _handles[h] = obj
    return h


def _get(handle: int):
    obj = _handles.get(int(handle))
    if obj is None:
        raise KeyError(f"invalid handle {handle}")
    return obj


def _free(handle: int) -> None:
    _handles.pop(int(handle), None)


def _parse_params(parameters: str) -> dict:
    """'key=value key2=val2' (c_api.cpp Config::Str2Map format)."""
    out = {}
    for tok in (parameters or "").replace("\n", " ").split():
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k] = v
    return out


def _mat_from_ptr(ffi, data, data_type, nrow, ncol, is_row_major):
    dt = _DTYPES.get(int(data_type))
    if dt is None:
        raise ValueError(f"unknown C_API_DTYPE {data_type}")
    n = int(nrow) * int(ncol)
    buf = ffi.buffer(data, n * np.dtype(dt).itemsize)
    arr = np.frombuffer(buf, dtype=dt).copy()
    if is_row_major:
        return arr.reshape(int(nrow), int(ncol))
    return arr.reshape(int(ncol), int(nrow)).T


# ---- Dataset ----------------------------------------------------------------

def dataset_create_from_mat(ffi, data, data_type, nrow, ncol, is_row_major,
                            parameters, reference, out):
    import lightgbm_tpu as lgb

    X = _mat_from_ptr(ffi, data, data_type, nrow, ncol, is_row_major)
    params = _parse_params(ffi.string(parameters).decode())
    ref = _get(reference) if reference else None
    ds = lgb.Dataset(X, params=params, reference=ref, free_raw_data=False)
    out[0] = _register(ds)
    return 0


def dataset_create_from_file(ffi, filename, parameters, reference, out):
    import lightgbm_tpu as lgb

    params = _parse_params(ffi.string(parameters).decode())
    ref = _get(reference) if reference else None
    ds = lgb.Dataset(ffi.string(filename).decode(), params=params,
                     reference=ref, free_raw_data=False)
    out[0] = _register(ds)
    return 0


def dataset_set_field(ffi, handle, field_name, field_data, num_element,
                      data_type):
    """Field-name routing per c_api.cpp LGBM_DatasetSetField /
    Metadata::SetField (label/weight/group/init_score/position)."""
    ds = _get(handle)
    name = ffi.string(field_name).decode()
    dt = _DTYPES[int(data_type)]
    buf = ffi.buffer(field_data, int(num_element) * np.dtype(dt).itemsize)
    values = np.frombuffer(buf, dtype=dt).copy()
    setters = {"label": ds.set_label, "weight": ds.set_weight,
               "group": ds.set_group, "query": ds.set_group,
               "init_score": ds.set_init_score, "position": ds.set_position}
    if name not in setters:
        raise ValueError(f"unknown field name {name!r}")
    setters[name](values)
    return 0


def dataset_get_num_data(ffi, handle, out):
    ds = _get(handle)
    out[0] = int(ds.num_data())
    return 0


def dataset_get_num_feature(ffi, handle, out):
    ds = _get(handle)
    out[0] = int(ds.num_feature())
    return 0


def dataset_free(ffi, handle):
    _free(handle)
    return 0


# ---- Streaming dataset ------------------------------------------------------
# LGBM_DatasetPushRows / LGBM_DatasetPushRowsByCSR parity (c_api.cpp): a
# streaming handle is a RowBlockStore (streaming/ingest.py), which shares
# the basic.Dataset duck surface, so LGBM_DatasetSetField /
# LGBM_DatasetGetNumData / LGBM_DatasetGetNumFeature route through
# unchanged. LGBM_BoosterCreate finalizes the store into a real Dataset.

def dataset_create_streaming(ffi, ncol, parameters, out):
    """Open a push-rows dataset. Stands in for the reference's
    CreateByReference/CreateFromSampledColumn entry points: the bin layout
    is fitted from the pushed sample prefix instead of a donor dataset."""
    from ..streaming.ingest import RowBlockStore

    params = _parse_params(ffi.string(parameters).decode())
    store = RowBlockStore(params=params,
                          n_features=int(ncol) if int(ncol) > 0 else None)
    out[0] = _register(store)
    return 0


def _as_store(handle):
    from ..streaming.ingest import RowBlockStore

    obj = _get(handle)
    if not isinstance(obj, RowBlockStore):
        raise TypeError("handle is not a streaming dataset "
                        "(LGBM_DatasetCreateStreaming)")
    return obj


def _check_start_row(store, start_row) -> None:
    # the reference writes blocks at arbitrary offsets from parallel
    # pushers; this port keeps the common sequential contract explicit
    if int(start_row) != store.total_rows:
        raise ValueError(
            f"non-sequential push: start_row={int(start_row)} but "
            f"{store.total_rows} rows are already pushed")


def dataset_push_rows(ffi, handle, data, data_type, nrow, ncol, start_row):
    store = _as_store(handle)
    _check_start_row(store, start_row)
    X = _mat_from_ptr(ffi, data, data_type, nrow, ncol, 1)  # row-major ABI
    store.push_rows(X)
    return 0


def dataset_push_rows_by_csr(ffi, handle, indptr, indptr_type, indices, data,
                             data_type, nindptr, nelem, num_col, start_row):
    store = _as_store(handle)
    _check_start_row(store, start_row)
    ip_dt = _DTYPES.get(int(indptr_type))
    if ip_dt not in (np.int32, np.int64):
        raise ValueError(f"indptr_type must be int32/int64, got {indptr_type}")
    ip_buf = ffi.buffer(indptr, int(nindptr) * np.dtype(ip_dt).itemsize)
    ip = np.frombuffer(ip_buf, dtype=ip_dt).copy()
    idx_buf = ffi.buffer(indices, int(nelem) * np.dtype(np.int32).itemsize)
    idx = np.frombuffer(idx_buf, dtype=np.int32).copy()
    dt = _DTYPES.get(int(data_type))
    if dt is None:
        raise ValueError(f"unknown C_API_DTYPE {data_type}")
    val_buf = ffi.buffer(data, int(nelem) * np.dtype(dt).itemsize)
    values = np.frombuffer(val_buf, dtype=dt).copy()
    store.push_csr(ip, idx, values, int(num_col))
    return 0


# ---- Booster ----------------------------------------------------------------

def _as_train_set(obj, params):
    """A streaming store handed to BoosterCreate finalizes here — the
    construct-on-first-use moment the reference reaches inside
    LGBM_BoosterCreate via Dataset::FinishLoad."""
    from ..streaming.ingest import RowBlockStore

    if isinstance(obj, RowBlockStore):
        return obj.to_basic_dataset(params=params)
    return obj


def booster_create(ffi, train_data, parameters, out):
    import lightgbm_tpu as lgb

    params = _parse_params(ffi.string(parameters).decode())
    bst = lgb.Booster(params=params,
                      train_set=_as_train_set(_get(train_data), params))
    out[0] = _register(bst)
    return 0


def booster_add_valid_data(ffi, handle, valid_data):
    bst = _get(handle)
    n = getattr(bst, "_capi_valid_count", 0) + 1
    bst._capi_valid_count = n
    bst.add_valid(_get(valid_data), f"valid_{n}")
    return 0


def booster_create_from_modelfile(ffi, filename, out_num_iterations, out):
    import lightgbm_tpu as lgb

    bst = lgb.Booster(model_file=ffi.string(filename).decode())
    out_num_iterations[0] = int(bst.current_iteration())
    out[0] = _register(bst)
    return 0


def booster_load_model_from_string(ffi, model_str, out_num_iterations, out):
    import lightgbm_tpu as lgb

    bst = lgb.Booster(model_str=ffi.string(model_str).decode())
    out_num_iterations[0] = int(bst.current_iteration())
    out[0] = _register(bst)
    return 0


def booster_save_model(ffi, handle, start_iteration, num_iteration,
                       importance_type, filename):
    bst = _get(handle)
    bst.save_model(ffi.string(filename).decode(),
                   num_iteration=int(num_iteration),
                   start_iteration=int(start_iteration),
                   importance_type=("split" if int(importance_type) == 0
                                    else "gain"))
    return 0


def booster_save_model_to_string(ffi, handle, start_iteration, num_iteration,
                                 importance_type, buffer_len, out_len,
                                 out_str):
    bst = _get(handle)
    s = bst.model_to_string(num_iteration=int(num_iteration),
                            start_iteration=int(start_iteration),
                            importance_type=("split" if int(importance_type)
                                             == 0 else "gain")).encode()
    out_len[0] = len(s) + 1
    if int(buffer_len) >= len(s) + 1:
        buf = ffi.buffer(out_str, len(s) + 1)
        buf[:len(s)] = s
        buf[len(s):len(s) + 1] = b"\0"
    return 0


def booster_update_one_iter(ffi, handle, is_finished):
    bst = _get(handle)
    finished = bst.update()
    is_finished[0] = 1 if finished else 0
    return 0


def booster_get_current_iteration(ffi, handle, out_iteration):
    out_iteration[0] = int(_get(handle).current_iteration())
    return 0


def booster_get_num_classes(ffi, handle, out_len):
    out_len[0] = int(getattr(_get(handle), "num_model_per_iteration",
                             lambda: 1)())
    return 0


def booster_number_of_total_model(ffi, handle, out_models):
    bst = _get(handle)
    out_models[0] = int(bst.num_trees())
    return 0


def booster_predict_for_mat(ffi, handle, data, data_type, nrow, ncol,
                            is_row_major, predict_type, start_iteration,
                            num_iteration, parameter, out_len, out_result):
    bst = _get(handle)
    X = _mat_from_ptr(ffi, data, data_type, nrow, ncol, is_row_major)
    pt = int(predict_type)
    # prediction options travel in the parameter string
    # (LGBM_BoosterPredictForMat parses it via Config::Str2Map)
    extra = _parse_params(ffi.string(parameter).decode())
    pred = bst.predict(
        X,
        raw_score=(pt == 1),
        pred_leaf=(pt == 2),
        pred_contrib=(pt == 3),
        start_iteration=int(start_iteration),
        num_iteration=int(num_iteration),
        **extra,
    )
    flat = np.ascontiguousarray(pred, dtype=np.float64).ravel()
    out_len[0] = flat.size
    ffi.buffer(out_result, flat.size * 8)[:] = flat.tobytes()
    return 0


def booster_predict_for_csr(ffi, handle, indptr, indptr_type, indices, data,
                            data_type, nindptr, nelem, num_col,
                            predict_type, start_iteration, num_iteration,
                            parameter, out_len, out_result):
    bst = _get(handle)
    ip_dt = _DTYPES.get(int(indptr_type))
    if ip_dt not in (np.int32, np.int64):
        raise ValueError(f"indptr_type must be int32/int64, got {indptr_type}")
    ip_buf = ffi.buffer(indptr, int(nindptr) * np.dtype(ip_dt).itemsize)
    ip = np.frombuffer(ip_buf, dtype=ip_dt).copy()
    idx_buf = ffi.buffer(indices, int(nelem) * np.dtype(np.int32).itemsize)
    idx = np.frombuffer(idx_buf, dtype=np.int32).copy()
    dt = _DTYPES.get(int(data_type))
    if dt is None:
        raise ValueError(f"unknown C_API_DTYPE {data_type}")
    val_buf = ffi.buffer(data, int(nelem) * np.dtype(dt).itemsize)
    values = np.frombuffer(val_buf, dtype=dt).copy()
    nrow = int(nindptr) - 1
    # densify: absent CSR entries are 0.0 (the reference's default
    # zero-elimination contract; zero_as_missing remaps them later in the
    # bin mapper, not here), then route onto the same Booster.predict the
    # ForMat entry uses so both surfaces answer bit-identically
    X = np.zeros((nrow, int(num_col)), dtype=np.float64)
    for r in range(nrow):
        lo, hi = int(ip[r]), int(ip[r + 1])
        X[r, idx[lo:hi]] = values[lo:hi]
    pt = int(predict_type)
    extra = _parse_params(ffi.string(parameter).decode())
    pred = bst.predict(
        X,
        raw_score=(pt == 1),
        pred_leaf=(pt == 2),
        pred_contrib=(pt == 3),
        start_iteration=int(start_iteration),
        num_iteration=int(num_iteration),
        **extra,
    )
    flat = np.ascontiguousarray(pred, dtype=np.float64).ravel()
    out_len[0] = flat.size
    ffi.buffer(out_result, flat.size * 8)[:] = flat.tobytes()
    return 0


def booster_free(ffi, handle):
    _free(handle)
    return 0
