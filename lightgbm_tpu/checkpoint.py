"""Crash-consistent checkpointing: full trainer state, written atomically.

The reference's ``snapshot_freq`` (gbdt.cpp:258-262) writes the model text
mid-train; resuming from it with ``init_model`` silently diverges from the
uninterrupted run because none of the trainer state — bagging/feature RNG
streams, early-stop bookkeeping, quantized-gradient PRNG, the f32 score
arrays — survives. A checkpoint here is two artifacts:

* ``<path>``           — the plain reference-format model text (loadable by
                         any LightGBM, reference included), ALL trees.
* ``<path>.ckpt``      — a sidecar blob: 8-byte magic ``LGBMCKPT`` +
                         sha256(payload) + an npz payload holding a JSON
                         manifest (iteration counter, early-stop state,
                         learner scalars, sha256 of the model text) and the
                         state arrays (train/valid scores, bag indices,
                         column-sampler MT19937 keys, quantized PRNG key).

Both are written atomically — temp file in the target directory, flush +
fsync, ``os.replace``, directory fsync — inside a bounded
retry-with-backoff loop, so a crash at ANY instant leaves either the old
checkpoint or the new one, never a torn file. The sidecar references the
model text by content hash: if either half is missing, damaged, or from a
different write, ``load_checkpoint`` invalidates the pair with a warning
and training falls back to plain continued training from the model text
alone. ``engine.train(init_model=<path>)`` with a valid sidecar resumes
BIT-IDENTICALLY to the uninterrupted run (docs/ROBUSTNESS.md).
"""
from __future__ import annotations

import hashlib
import io
import json
import os
import tempfile
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from . import telemetry
from .fingerprint import world_fingerprint
from .utils import faults
from .utils.log import Log
from .utils.timer import global_timer

CKPT_MAGIC = b"LGBMCKPT"
CKPT_VERSION = 1
SIDECAR_SUFFIX = ".ckpt"
AOT_MAGIC = b"LGBMAOT1"
AOT_SUFFIX = ".aot"
_BACKOFF_S = 0.05  # doubled per retry attempt


class CheckpointError(Exception):
    """Sidecar validation failure; callers treat it as 'no sidecar'."""


# ------------------------------------------------------------ atomic writes

def _fsync_dir(dirname: str) -> None:
    """Durability of the os.replace itself: fsync the directory entry
    (best effort — some filesystems refuse O_RDONLY dir fsync)."""
    try:
        fd = os.open(dirname or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


@contextmanager
def atomic_open(path: str, mode: str = "w"):
    """Yield a temp-file handle in `path`'s directory; on clean exit flush +
    fsync + os.replace onto `path`, on failure unlink the temp file. The
    single-shot primitive for streaming writers (Dataset.save_binary);
    whole-content writes go through atomic_write_text/bytes, which add the
    bounded retry-with-backoff loop."""
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".tmp.")
    try:
        with os.fdopen(fd, mode) as fh:
            yield fh
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(d)


def _atomic_write(path: str, data, mode: str, retries: int) -> None:
    last: Optional[OSError] = None
    for attempt in range(max(1, retries)):
        if attempt:
            time.sleep(_BACKOFF_S * (2 ** (attempt - 1)))
        try:
            faults.maybe_fail_write(path)
            with atomic_open(path, mode) as fh:
                fh.write(data)
            faults.maybe_corrupt_artifact(path)
            return
        except OSError as exc:
            last = exc
            Log.warning("Atomic write of %s failed (attempt %d/%d): %s",
                        path, attempt + 1, max(1, retries), exc)
    raise last


def atomic_write_text(path: str, text: str, retries: int = 3) -> None:
    _atomic_write(path, text, "w", retries)


def atomic_write_bytes(path: str, data: bytes, retries: int = 3) -> None:
    _atomic_write(path, data, "wb", retries)


# ------------------------------------------------------------- state model

@dataclass
class TrainerState:
    """Everything load_checkpoint recovered from a valid snapshot pair."""

    iteration: int
    model_text: str
    score: np.ndarray
    valid_scores: List[np.ndarray]
    bag: Optional[np.ndarray]
    learner: Dict[str, Any]
    es: Optional[Dict[str, Any]]
    health: Optional[Dict[str, Any]]
    manifest: Dict[str, Any]


_model_only_warned = False


def save_checkpoint(booster, path: str, retries: int = 3,
                    extra_manifest: Optional[Dict[str, Any]] = None) -> None:
    """Write a crash-consistent snapshot of `booster` (a Booster, or a raw
    GBDT driver in learner-level tests) to `path` + `path`.ckpt.

    extra_manifest merges caller-owned keys into the sidecar manifest
    (core keys win on collision) — the continuous trainer records its
    stream generation and the bin-mapper generation there, so a resume
    can verify it replays against the same mapper the crashed run used."""
    global _model_only_warned
    gbdt = getattr(booster, "_gbdt", booster)
    gbdt._flush_pending()  # a half-grown async tree is not checkpointable
    model_text = gbdt.to_model().to_string(num_iteration=-1)
    with global_timer.scope("checkpoint_write"):
        atomic_write_text(path, model_text, retries=retries)
        if type(gbdt).__name__ != "GBDT":
            # DART/RF carry per-iteration state (drop sets, averaging) that
            # has no resume contract yet: their snapshot is model-only and
            # resume falls back to plain continued training.
            if not _model_only_warned:
                _model_only_warned = True
                Log.warning("Checkpoint for boosting type %s saves model "
                            "text only; resume will not be bit-identical",
                            type(gbdt).__name__)
            telemetry.emit("checkpoint", path=path, model_only=True,
                           iteration=int(gbdt.iter_))
            return
        arrays: Dict[str, np.ndarray] = {"score": np.asarray(gbdt.score)}
        for i, vd in enumerate(gbdt.valid_sets):
            arrays[f"valid_score_{i}"] = np.asarray(vd.score)
        bag = getattr(gbdt.sample_strategy, "_bag", None)
        if bag is not None:
            arrays["bag"] = np.asarray(bag, dtype=np.int32)
        learner_scalars: Dict[str, Any] = {}
        learner = getattr(gbdt, "tree_learner", None)
        if learner is not None and hasattr(learner, "snapshot_state"):
            for k, v in learner.snapshot_state().items():
                if isinstance(v, np.ndarray):
                    arrays[f"learner_{k}"] = v
                else:
                    learner_scalars[k] = v
        health = getattr(gbdt, "_health", None)
        world = world_fingerprint()
        if learner is not None and hasattr(learner, "D"):
            # the in-process mesh can be capped below len(jax.devices())
            # (num_machines / LGBM_TPU_FORCE_MESH_DEVICES): record the shape
            # the learner actually sharded over, not the device inventory
            world["mesh_shape"] = [int(learner.D)]
        else:
            # serial learner: nothing is sharded, so the host's device
            # inventory is irrelevant to restore compatibility
            world["mesh_shape"] = [1]
        manifest = dict(extra_manifest or {})
        manifest.update({
            "version": CKPT_VERSION,
            "iteration": int(gbdt.iter_),
            "num_class": int(gbdt.num_class),
            "num_tree_per_iteration": int(gbdt.num_tree_per_iteration),
            "num_data": int(getattr(gbdt, "num_data", -1)),
            "boosting": type(gbdt).__name__,
            "model_sha256": hashlib.sha256(model_text.encode()).hexdigest(),
            "valid_names": list(gbdt.valid_names),
            "async_stub_stop": bool(gbdt._async_stub_stop),
            "learner": learner_scalars,
            "es": getattr(booster, "_early_stop_state", None),
            "health": health.snapshot() if health is not None else None,
            "world": world,
        })
        buf = io.BytesIO()
        np.savez_compressed(
            buf,
            manifest=np.frombuffer(json.dumps(manifest).encode("utf-8"),
                                   dtype=np.uint8),
            **arrays)
        payload = buf.getvalue()
        blob = CKPT_MAGIC + hashlib.sha256(payload).digest() + payload
        atomic_write_bytes(path + SIDECAR_SUFFIX, blob, retries=retries)
    telemetry.emit("checkpoint", path=path, model_only=False,
                   iteration=int(gbdt.iter_), sidecar_bytes=len(blob))


def _load_sidecar_payload(sidecar: str):
    """Validate a sidecar blob (magic + payload sha256) and return its npz;
    shared by the resume path and the serving upload verifier."""
    with open(sidecar, "rb") as fh:
        blob = fh.read()
    if blob[:len(CKPT_MAGIC)] != CKPT_MAGIC:
        raise CheckpointError("bad magic")
    digest = blob[len(CKPT_MAGIC):len(CKPT_MAGIC) + 32]
    payload = blob[len(CKPT_MAGIC) + 32:]
    if hashlib.sha256(payload).digest() != digest:
        raise CheckpointError("payload checksum mismatch")
    z = np.load(io.BytesIO(payload), allow_pickle=False)
    manifest = json.loads(bytes(z["manifest"].tobytes()).decode("utf-8"))
    if int(manifest.get("version", -1)) != CKPT_VERSION:
        raise CheckpointError(
            "unsupported checkpoint version %r" % manifest.get("version"))
    return manifest, z


def read_sidecar_manifest(path: str) -> Optional[Dict[str, Any]]:
    """Serving-upload verifier: the validated sidecar manifest for the
    model text at `path`, or None when no ``.ckpt`` sidecar exists.

    The manifest's ``model_sha256`` is the content hash the writer vouched
    for — the model registry compares it against the staged upload before a
    hot-swap. A sidecar that exists but is damaged raises CheckpointError:
    for a serving upload that means REJECT (the training-resume path
    degrades instead — load_checkpoint warns and returns None), because a
    model swap must never promote bytes the writer did not produce."""
    sidecar = path + SIDECAR_SUFFIX
    if not os.path.exists(sidecar):
        return None
    manifest, _ = _load_sidecar_payload(sidecar)
    return manifest


def write_aot_sidecar(path: str, bundle: bytes, retries: int = 3) -> str:
    """Persist a compiled-executable bundle next to the model at `path`
    as ``path + '.aot'`` (magic + payload sha256 + payload, same framing
    as the checkpoint sidecar). Returns the sidecar path."""
    blob = AOT_MAGIC + hashlib.sha256(bundle).digest() + bundle
    sidecar = path + AOT_SUFFIX
    atomic_write_bytes(sidecar, blob, retries=retries)
    return sidecar


def read_aot_sidecar(path: str) -> Optional[bytes]:
    """The validated AOT bundle bytes for the model at `path`, or None
    when no ``.aot`` sidecar exists. A sidecar that exists but is damaged
    (bad magic / checksum mismatch) raises CheckpointError: the loader
    must fall back to fresh compiles, never deserialize torn bytes."""
    sidecar = path + AOT_SUFFIX
    if not os.path.exists(sidecar):
        return None
    with open(sidecar, "rb") as fh:
        blob = fh.read()
    if blob[:len(AOT_MAGIC)] != AOT_MAGIC:
        raise CheckpointError("bad AOT sidecar magic")
    digest = blob[len(AOT_MAGIC):len(AOT_MAGIC) + 32]
    payload = blob[len(AOT_MAGIC) + 32:]
    if hashlib.sha256(payload).digest() != digest:
        raise CheckpointError("AOT sidecar checksum mismatch")
    return payload


def load_checkpoint(path: str) -> Optional[TrainerState]:
    """Validate and load the snapshot pair at `path`. Returns None — with a
    warning naming the failed invariant — whenever the sidecar is absent or
    unusable, so callers degrade to plain continued training instead of
    crashing on damaged state."""
    sidecar = path + SIDECAR_SUFFIX
    if not os.path.exists(sidecar):
        return None
    try:
        manifest, z = _load_sidecar_payload(sidecar)
        with open(path) as fh:
            model_text = fh.read()
        if (hashlib.sha256(model_text.encode()).hexdigest()
                != manifest["model_sha256"]):
            raise CheckpointError(
                "model text does not match the sidecar's content hash "
                "(the two files are from different writes)")
        valid_scores = []
        for i in range(len(manifest.get("valid_names", []))):
            valid_scores.append(np.asarray(z[f"valid_score_{i}"]))
        learner: Dict[str, Any] = dict(manifest.get("learner", {}))
        for k in z.files:
            if k.startswith("learner_"):
                learner[k[len("learner_"):]] = np.asarray(z[k])
        return TrainerState(
            iteration=int(manifest["iteration"]),
            model_text=model_text,
            score=np.asarray(z["score"]),
            valid_scores=valid_scores,
            bag=np.asarray(z["bag"]) if "bag" in z.files else None,
            learner=learner,
            es=manifest.get("es"),
            health=manifest.get("health"),
            manifest=manifest)
    except Exception as exc:  # noqa: BLE001 - ANY damage means "no sidecar"
        Log.warning("Checkpoint sidecar %s is unusable (%s); falling back "
                    "to plain continued training from the model file",
                    sidecar, exc)
        return None


def restore_trainer_state(booster, state: TrainerState,
                          callbacks=()) -> int:
    """Reinstate `state` onto a freshly constructed booster: trees +
    iteration counter, f32 score arrays (train + valids), bagging cache,
    learner RNG/scan state, async-pipeline carry, early-stop bookkeeping.
    Returns the iteration to resume from. Structural mismatches between the
    checkpoint and the resume call are fatal with a named invariant — a
    silently divergent resume is worse than no resume."""
    import jax.numpy as jnp

    from .models.serialize import GBDTModel

    gbdt = getattr(booster, "_gbdt", booster)
    man = state.manifest
    if man.get("boosting") != type(gbdt).__name__:
        Log.fatal("Checkpoint was written by boosting type %s but the "
                  "resume run built %s — refusing to resume",
                  man.get("boosting"), type(gbdt).__name__)
    if int(man["num_data"]) != int(gbdt.num_data):
        Log.fatal("Checkpoint was written for %d training rows but the "
                  "resume dataset has %d — refusing to resume",
                  int(man["num_data"]), int(gbdt.num_data))
    if int(man["num_tree_per_iteration"]) != int(gbdt.num_tree_per_iteration):
        Log.fatal("Checkpoint has %d trees/iteration but the resume run "
                  "has %d — refusing to resume",
                  int(man["num_tree_per_iteration"]),
                  int(gbdt.num_tree_per_iteration))
    if list(man.get("valid_names", [])) != list(gbdt.valid_names):
        Log.fatal("Checkpoint valid sets %s do not match the resume call's "
                  "%s (same valid_sets, same order, same names required)",
                  man.get("valid_names"), gbdt.valid_names)
    saved_world = man.get("world")
    if saved_world is not None:
        here = world_fingerprint()
        learner = getattr(gbdt, "tree_learner", None)
        if learner is not None and hasattr(learner, "D"):
            here["mesh_shape"] = [int(learner.D)]
        else:
            here["mesh_shape"] = [1]  # mirrors the save-side serial shape
        keys = ("process_count", "mesh_shape", "device_kinds")
        if any(saved_world.get(k) != here.get(k) for k in keys):
            # not fatal: restore re-shards deterministically onto the new
            # mesh (docs/ROBUSTNESS.md "shrink-to-fit") — but the shapes are
            # named HERE, not discovered deep in make_array_from_callback,
            # and float32 runs should expect drift across shard boundaries
            Log.warning(
                "Checkpoint was written under world %s but is being "
                "restored under %s; state will be re-sharded onto the "
                "current mesh (bit-identity across world sizes holds only "
                "for quantized histograms — see docs/ROBUSTNESS.md)",
                {k: saved_world.get(k) for k in keys},
                {k: here.get(k) for k in keys})
            telemetry.emit("checkpoint_world_mismatch",
                           saved=saved_world, current=here,
                           iteration=int(state.iteration))
    gbdt.models = GBDTModel.from_string(state.model_text).trees
    gbdt.iter_ = int(state.iteration)
    gbdt._async_stub_stop = bool(man.get("async_stub_stop", False))
    gbdt.score = jnp.asarray(state.score, dtype=jnp.float32)
    for vd, s in zip(gbdt.valid_sets, state.valid_scores):
        vd.score = jnp.asarray(s, dtype=jnp.float32)
    if state.bag is not None and hasattr(gbdt.sample_strategy, "_bag"):
        gbdt.sample_strategy._bag = np.asarray(state.bag, dtype=np.int32)
    learner = getattr(gbdt, "tree_learner", None)
    if learner is not None and hasattr(learner, "restore_snapshot_state"):
        learner.restore_snapshot_state(state.learner)
    health = getattr(gbdt, "_health", None)
    if health is not None and state.health is not None:
        health.restore(state.health)
    for cb in callbacks or ():
        if getattr(cb, "_accepts_state_restore", False):
            cb._pending_restore = state.es
    gbdt._predictor.invalidate()
    Log.info("Resumed trainer state from checkpoint: iteration %d, %d trees",
             gbdt.iter_, len(gbdt.models))
    telemetry.emit("checkpoint_resume", iteration=int(state.iteration),
                   num_trees=len(gbdt.models))
    return int(state.iteration)


# ---------------------------------------------------------------- callback

def checkpoint_callback(path: Union[str, Callable[[int], str]],
                        period: int = 1, retries: int = 3,
                        extra_manifest: Optional[Dict[str, Any]] = None
                        ) -> Callable:
    """After-iteration callback writing a full crash-consistent snapshot
    every `period` iterations. `path` is a fixed file name or a callable
    mapping the 1-based finished-iteration count to one (the CLI names
    snapshots ``<output_model>.snapshot_iter_<k>``). Runs at order 40 —
    after early stopping (order 30), so the snapshot carries the freshest
    early-stop state and a stop iteration is never snapshotted."""
    if period <= 0:
        raise ValueError("checkpoint period must be positive")

    def _callback(env) -> None:
        it = env.iteration + 1
        if it % period != 0:
            return
        if not hasattr(env.model, "_gbdt"):
            return  # CVBooster: per-fold checkpointing has no single state
        target = path(it) if callable(path) else path
        save_checkpoint(env.model, target, retries=retries,
                        extra_manifest=extra_manifest)

    _callback.order = 40
    _callback.before_iteration = False
    return _callback
