"""Command-line application: train / predict / convert_model / refit /
save_binary driven by reference-format config files.

Counterpart of src/main.cpp + src/application/application.cpp: accepts the
same `key=value` arguments and `config=train.conf` files as the reference CLI
so reference example configs run unchanged:

    python -m lightgbm_tpu.cli config=examples/binary_classification/train.conf

Observability: pass `telemetry_dir=<dir>` (or set LGBM_TPU_TELEMETRY=<dir>)
to record the structured per-iteration event stream plus a Perfetto-loadable
Chrome trace for the run; summarize or diff runs with tools/teldiff.py
(docs/OBSERVABILITY.md).
"""
from __future__ import annotations

import sys
from typing import Dict, List

import numpy as np

from . import callback as callback_mod
from . import checkpoint as checkpoint_mod
from .basic import Booster, Dataset
from .config import Config, key_alias_transform, kv2map, load_config_file
from .engine import train as train_fn
from .utils.log import Log, set_verbosity


def _parse_args(argv: List[str]) -> Dict[str, str]:
    kvs = kv2map(argv)
    if "config" in kvs:
        file_kvs = load_config_file(kvs["config"])
        for k, v in file_kvs.items():
            kvs.setdefault(k, v)
    return kvs


def run(argv: List[str]) -> int:
    kvs = _parse_args(argv)
    params = key_alias_transform(kvs)
    task = params.pop("task", "train")
    config = Config(params)
    set_verbosity(config.verbosity)

    if config.device_type == "cpu":
        # select the CPU backend before any JAX computation initializes it;
        # the hosted-TPU plugin otherwise claims the platform
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    # join the multi-host world BEFORE any JAX computation initializes a
    # backend (jax.distributed.initialize requirement); no-op single-process
    from .parallel.dist import init_distributed

    init_distributed(config)

    if task == "train":
        return _task_train(config, params)
    if task in ("predict", "prediction", "test"):
        return _task_predict(config, params)
    if task == "convert_model":
        return _task_convert(config, params)
    if task == "refit":
        return _task_refit(config, params)
    if task == "save_binary":
        ds = Dataset(config.data, params=params)
        ds.construct()
        ds.save_binary((config.data or "train") + ".bin")
        return 0
    if task == "serve":
        return _task_serve(config, params)
    Log.fatal("Unknown task type %s", task)
    return 1


def _task_train(config: Config, params: Dict[str, str]) -> int:
    if not config.data:
        Log.fatal("No training data, please set data=... in the config")
    train_ds = Dataset(config.data, params=params)
    if config.save_binary:
        # is_save_binary_file: persist the constructed dataset cache next to
        # the text file (application.cpp LoadData -> SaveBinaryFile)
        train_ds.construct()
        train_ds.save_binary(str(config.data) + ".bin")
        Log.info("Saved binary dataset cache to %s.bin", config.data)
    valid_sets = []
    valid_names = []
    valid_paths = config.valid if isinstance(config.valid, list) else (
        [v for v in str(config.valid).split(",") if v])
    for i, vp in enumerate(valid_paths):
        valid_sets.append(Dataset(vp, reference=train_ds, params=params))
        valid_names.append(f"valid_{i + 1}")
    callbacks = [callback_mod.log_evaluation(period=max(config.metric_freq, 1))]
    out = config.output_model or "LightGBM_model.txt"
    if config.snapshot_freq > 0:
        # gbdt.cpp:258-262 periodic checkpoints, upgraded from bare model
        # text to crash-consistent full-state snapshots: each
        # <out>.snapshot_iter_<k> model file gains a .ckpt sidecar, and
        # input_model=<snapshot> resumes bit-identically
        callbacks.append(checkpoint_mod.checkpoint_callback(
            lambda it: f"{out}.snapshot_iter_{it}",
            period=config.snapshot_freq))
    booster = train_fn(params, train_ds, num_boost_round=config.num_iterations,
                       valid_sets=valid_sets or None,
                       valid_names=valid_names or None,
                       init_model=config.input_model or None,
                       callbacks=callbacks)
    booster.save_model(out)
    Log.info("Finished training, model saved to %s", out)
    return 0


def _task_refit(config: Config, params: Dict[str, str]) -> int:
    """Application refit task (application.cpp:229-268): predict leaf
    indices of the input model on the refit data, then RefitTree."""
    if not config.input_model:
        Log.fatal("No input model, please set input_model=...")
    if not config.data:
        Log.fatal("No refit data, please set data=...")
    from .io.parser import (load_query_boundaries, load_weights, parse_file)

    old = Booster(model_file=config.input_model, params=params)
    X, y, _ = parse_file(config.data, header=config.header,
                         label_column=config.label_column or "0")
    new_booster = old.refit(X, y, decay_rate=config.refit_decay_rate,
                            weight=load_weights(config.data),
                            group=load_query_boundaries(config.data),
                            params=params)
    out = config.output_model or "LightGBM_model.txt"
    new_booster.save_model(out)
    Log.info("Finished RefitTree, model saved to %s", out)
    return 0


def _task_predict(config: Config, params: Dict[str, str]) -> int:
    if not config.input_model:
        Log.fatal("No input model, please set input_model=...")
    booster = Booster(model_file=config.input_model, params=params)
    data_path = config.data
    from .io.parser import parse_file

    X, _, _ = parse_file(data_path, header=config.header,
                         label_column=config.label_column or "0")
    pred = booster.predict(
        X, raw_score=config.predict_raw_score,
        pred_leaf=config.predict_leaf_index,
        pred_contrib=config.predict_contrib,
        num_iteration=config.num_iteration_predict
        if config.num_iteration_predict > 0 else None)
    out = config.output_result or "LightGBM_predict_result.txt"
    np.savetxt(out, np.asarray(pred), fmt="%.9g",
               delimiter="\t" if np.ndim(pred) > 1 else "\n")
    Log.info("Finished prediction, results saved to %s", out)
    return 0


def _task_serve(config: Config, params: Dict[str, str]) -> int:
    """Hardened prediction server (docs/SERVING.md):

        python -m lightgbm_tpu.cli task=serve input_model=model.txt \\
            serve_port=8080 serve_model_name=default

    Serve-specific keys are read from the raw params map (Config tolerates
    unknown keys): serve_host, serve_port, serve_model_name,
    serve_max_batch_rows, serve_max_queue_rows, serve_batch_window_ms,
    serve_default_timeout_ms, serve_reject_nonfinite. The model is
    checksum-verified against its .ckpt sidecar when one exists, and every
    power-of-two batch bucket is jit-warmed before the socket opens."""
    if not config.input_model:
        Log.fatal("No input model, please set input_model=...")
    from .serving import CircuitBreaker, PredictionService
    from .serving.http import serve as serve_http

    timeout_ms = params.get("serve_default_timeout_ms")
    service = PredictionService(
        max_batch_rows=int(params.get("serve_max_batch_rows", 4096)),
        max_queue_rows=int(params.get("serve_max_queue_rows", 32768)),
        batch_window_s=float(params.get("serve_batch_window_ms", 1.0)) / 1e3,
        default_timeout_s=(float(timeout_ms) / 1e3
                           if timeout_ms is not None else None),
        breaker=CircuitBreaker(
            hbm_limit_bytes=int(params.get("serve_hbm_limit_bytes", 0))))
    name = params.get("serve_model_name", "default")
    service.load_model(
        name, path=config.input_model,
        reject_nonfinite=params.get("serve_reject_nonfinite", "")
        in ("1", "true", "True"))
    server, thread = serve_http(
        service, host=params.get("serve_host", "127.0.0.1"),
        port=int(params.get("serve_port", 8080)))
    Log.info("serving model '%s' from %s; Ctrl-C to stop",
             name, config.input_model)
    try:
        thread.join()
    except KeyboardInterrupt:
        Log.info("shutting down")
        server.shutdown()
        service.close()
    return 0


def _task_convert(config: Config, params: Dict[str, str]) -> int:
    from .models.codegen import model_to_cpp
    from .models.serialize import GBDTModel

    if not config.input_model:
        Log.fatal("No input model, please set input_model=...")
    model = GBDTModel.from_file(config.input_model)
    out = config.convert_model or "gbdt_prediction.cpp"
    if config.convert_model_language in ("", "cpp"):
        checkpoint_mod.atomic_write_text(out, model_to_cpp(model))
        Log.info("Model converted to if-else C++ at %s", out)
    elif config.convert_model_language == "json":
        checkpoint_mod.atomic_write_text(out, model.dump_json())
        Log.info("Model converted (JSON form) to %s", out)
    else:
        Log.fatal("Unknown convert_model_language %s",
                  config.convert_model_language)
    return 0


def main() -> None:
    sys.exit(run(sys.argv[1:]))


if __name__ == "__main__":
    main()
