"""Shared framework-wide constants.

Counterpart of include/LightGBM/meta.h: the missing-value type enum
(bin.h:27-31) and the zero threshold (meta.h:56) used consistently by binning,
tree decisions, and device inference.
"""

MISSING_NONE = 0  # MissingType::None
MISSING_ZERO = 1  # MissingType::Zero
MISSING_NAN = 2  # MissingType::NaN

K_ZERO_THRESHOLD = 1e-35  # meta.h:56 kZeroThreshold

K_EPSILON = 1e-15  # meta.h kEpsilon
K_MIN_SCORE = -float("inf")


def round_int(x: float) -> int:
    """Round half away from zero (Common::RoundInt / std::lround semantics)."""
    return int(x + 0.5) if x >= 0 else -int(-x + 0.5)
