"""Typed parameter system with full alias resolution.

TPU-native counterpart of the reference Config (include/LightGBM/config.h:40,
src/io/config.cpp, generated src/io/config_auto.cpp). The parameter universe —
names, types, defaults, aliases, range checks — lives in `_param_spec.py`,
extracted mechanically from the reference's config.h doc-comments exactly as the
reference's own `.ci/parameter-generator.py` does, so the public parameter API
matches the reference parameter-for-parameter.

Key behaviors reproduced:
  * alias → canonical-name mapping (ParameterAlias::KeyAliasTransform,
    config.cpp:101); first-occurrence-wins on duplicates; `verbosity` takes the
    minimum of duplicates like the reference does for conflicting values.
  * objective / metric family aliases (ParseObjectiveAlias /
    ParseMetricAlias, config.h:1274-1329).
  * `Config.set(params)` type coercion + range checks (config_auto.cpp
    GetMembersFromString).
  * `config.to_string()` — the `parameters:` section of the model file
    (Config::SaveMembersToString).
  * key=value / config-file parsing (KV2Map, application.cpp:53-89).
"""
from __future__ import annotations

import copy
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple, Union

from ._param_spec import PARAM_SPEC
from .utils.log import Log

# canonical name -> (pytype, default, aliases, checks, no_save)
_SPEC: Dict[str, Tuple[str, Any, List[str], List[str], bool]] = {
    name: (ptype, default, aliases, checks, no_save)
    for name, ptype, default, aliases, checks, no_save in PARAM_SPEC
}

# alias (and canonical) -> canonical
_ALIAS: Dict[str, str] = {}
for _name, (_t, _d, _aliases, _c, _ns) in _SPEC.items():
    _ALIAS[_name] = _name
    for _a in _aliases:
        _ALIAS.setdefault(_a, _name)

# Objective aliases — reference config.h:1274-1299 (ParseObjectiveAlias)
_OBJECTIVE_ALIAS = {}
for _canon, _names in [
    ("regression", ["regression", "regression_l2", "mean_squared_error", "mse", "l2",
                    "l2_root", "root_mean_squared_error", "rmse"]),
    ("regression_l1", ["regression_l1", "mean_absolute_error", "l1", "mae"]),
    ("multiclass", ["multiclass", "softmax"]),
    ("multiclassova", ["multiclassova", "multiclass_ova", "ova", "ovr"]),
    ("cross_entropy", ["xentropy", "cross_entropy"]),
    ("cross_entropy_lambda", ["xentlambda", "cross_entropy_lambda"]),
    ("mape", ["mean_absolute_percentage_error", "mape"]),
    ("rank_xendcg", ["rank_xendcg", "xendcg", "xe_ndcg", "xe_ndcg_mart", "xendcg_mart"]),
    ("custom", ["none", "null", "custom", "na"]),
]:
    for _n in _names:
        _OBJECTIVE_ALIAS[_n] = _canon

# Metric aliases — reference config.h:1301-1329 (ParseMetricAlias)
_METRIC_ALIAS = {}
for _canon, _names in [
    ("l2", ["regression", "regression_l2", "l2", "mean_squared_error", "mse"]),
    ("rmse", ["l2_root", "root_mean_squared_error", "rmse"]),
    ("l1", ["regression_l1", "l1", "mean_absolute_error", "mae"]),
    ("binary_logloss", ["binary_logloss", "binary"]),
    ("ndcg", ["ndcg", "lambdarank", "rank_xendcg", "xendcg", "xe_ndcg", "xe_ndcg_mart",
              "xendcg_mart"]),
    ("map", ["map", "mean_average_precision"]),
    ("multi_logloss", ["multi_logloss", "multiclass", "softmax", "multiclassova",
                       "multiclass_ova", "ova", "ovr"]),
    ("cross_entropy", ["xentropy", "cross_entropy"]),
    ("cross_entropy_lambda", ["xentlambda", "cross_entropy_lambda"]),
    ("kullback_leibler", ["kldiv", "kullback_leibler"]),
    ("mape", ["mean_absolute_percentage_error", "mape"]),
    ("custom", ["none", "null", "custom", "na"]),
]:
    for _n in _names:
        _METRIC_ALIAS[_n] = _canon


def parse_objective_alias(name: str) -> str:
    return _OBJECTIVE_ALIAS.get(name, name)


def parse_metric_alias(name: str) -> str:
    return _METRIC_ALIAS.get(name, name)


def param_bool(value: Any, default: bool = False) -> bool:
    """Reference bool-string coercion (true/1/+/yes vs false/0/-/no) for
    values reaching python surfaces as raw conf strings; non-coercible
    strings fall back to `default` instead of fataling."""
    if value is None:
        return default
    if isinstance(value, str):
        v = value.strip().lower()
        if v in ("true", "1", "+", "yes"):
            return True
        if v in ("false", "0", "-", "no", ""):
            return False
        return default
    return bool(value)


def _coerce(name: str, ptype: str, value: Any) -> Any:
    if isinstance(value, str):
        v = value.strip()
        if ptype == "str":
            return v
        if ptype == "bool":
            if v.lower() in ("true", "1", "+", "yes"):
                return True
            if v.lower() in ("false", "0", "-", "no"):
                return False
            Log.fatal("Parameter %s should be of type bool, got \"%s\"", name, v)
        if ptype == "int":
            return int(float(v))
        if ptype == "float":
            return float(v)
        if ptype.startswith("list"):
            if not v:
                return []
            items = [x for x in v.replace(";", ",").split(",") if x != ""]
            if ptype == "list_int":
                return [int(float(x)) for x in items]
            if ptype == "list_float":
                return [float(x) for x in items]
            return items
    if ptype == "bool":
        return bool(value)
    if ptype == "int":
        return int(value)
    if ptype == "float":
        return float(value)
    if ptype == "str":
        return str(value)
    if ptype.startswith("list"):
        seq = list(value) if isinstance(value, (list, tuple)) else [value]
        if ptype == "list_int":
            return [int(x) for x in seq]
        if ptype == "list_float":
            return [float(x) for x in seq]
        return [str(x) for x in seq]
    return value


def _check(name: str, value: Any, checks: List[str]) -> None:
    if not checks or not isinstance(value, (int, float)) or isinstance(value, bool):
        return
    for chk in checks:
        op = "".join(c for c in chk if c in "<>=!")
        num = float(chk.replace(op, ""))
        ok = {
            ">": value > num,
            ">=": value >= num,
            "<": value < num,
            "<=": value <= num,
        }.get(op, True)
        if not ok:
            Log.fatal("Check failed: %s %s for parameter %s=%s", name, chk, name, value)


def key_alias_transform(params: Mapping[str, Any]) -> Dict[str, Any]:
    """Resolve aliases to canonical parameter names.

    Mirrors ParameterAlias::KeyAliasTransform: when both an alias and the
    canonical name (or two aliases) are present, the canonical name wins,
    otherwise the first alias in spec order; a warning is emitted for ignored
    duplicates. Unknown keys pass through untouched (the reference keeps them
    for pluggable parsers / custom objectives).
    """
    out: Dict[str, Any] = {}
    chosen_src: Dict[str, str] = {}
    for key, value in params.items():
        canonical = _ALIAS.get(key, key)
        if canonical not in out:
            out[canonical] = value
            chosen_src[canonical] = key
            continue
        if canonical == "verbosity":
            # reference special case: conflicting verbosity resolves to the
            # minimum (most silent wins)
            out[canonical] = min(int(out[canonical]), int(value))
            continue
        # duplicate: canonical key itself has priority
        if key == canonical and chosen_src[canonical] != canonical:
            Log.warning("%s is set with %s=%s, %s=%s will be ignored. Current value: %s=%s",
                        canonical, key, value, chosen_src[canonical], out[canonical],
                        canonical, value)
            out[canonical] = value
            chosen_src[canonical] = key
        else:
            Log.warning("%s is set=%s, %s=%s will be ignored. Current value: %s=%s",
                        chosen_src[canonical], out[canonical], key, value,
                        canonical, out[canonical])
    return out


def kv2map(args: Iterable[str]) -> Dict[str, str]:
    """Parse `key=value` tokens (CLI/config-file lines) — reference KV2Map."""
    out: Dict[str, str] = {}
    for arg in args:
        arg = arg.strip()
        if not arg or arg.startswith("#"):
            continue
        if "=" not in arg:
            continue
        key, value = arg.split("=", 1)
        key = key.strip()
        value = value.split("#", 1)[0].strip()
        if key in out:
            if _ALIAS.get(key, key) == "verbosity":
                # duplicate verbosity resolves to the minimum (config.cpp)
                try:
                    out[key] = str(min(int(out[key]), int(value)))
                except ValueError:
                    pass
            continue  # otherwise first occurrence wins
        out[key] = value
    return out


class Config:
    """All training/prediction parameters as attributes.

    `Config()` gives reference defaults; `Config(params_dict)` applies
    overrides with alias resolution, coercion, and checks.
    """

    def __init__(self, params: Optional[Mapping[str, Any]] = None) -> None:
        for name, (_ptype, default, _aliases, _checks, _ns) in _SPEC.items():
            setattr(self, name, copy.copy(default))
        # derived / non-spec state
        self.raw_params: Dict[str, Any] = {}
        self.metric: List[str] = []
        if params:
            self.set(params)
        else:  # defaults still need post-processing (device_type=auto etc.)
            self._post_process()

    def set(self, params: Mapping[str, Any]) -> None:
        params = key_alias_transform(dict(params))
        self.raw_params.update(params)
        # objective family alias
        if "objective" in params:
            params = dict(params)
            params["objective"] = parse_objective_alias(str(params["objective"]))
        # metric parsing (GetMetricType config.cpp:158-167): explicit metric list,
        # else derived from objective
        metric_value = params.pop("metric", None) if isinstance(params, dict) else None
        for name, value in params.items():
            if name not in _SPEC:
                continue  # unknown keys tolerated (custom parsers etc.)
            ptype, _default, _aliases, checks, _ns = _SPEC[name]
            coerced = _coerce(name, ptype, value)
            _check(name, coerced, checks)
            setattr(self, name, coerced)
        if metric_value is not None:
            if isinstance(metric_value, str):
                names = [m for m in metric_value.replace(";", ",").split(",") if m]
            else:
                names = list(metric_value)
            self.metric = []
            for m in names:
                canon = parse_metric_alias(m.strip())
                if canon and canon not in self.metric:
                    self.metric.append(canon)
        # an empty metric (unset, or explicitly "") derives from the objective
        # (GetMetricType, config.cpp:158-167)
        if not self.metric and self.objective:
            derived = parse_metric_alias(self.objective)
            self.metric = [] if derived == "custom" else [derived]
        self._post_process()

    def _post_process(self) -> None:
        # The reference's device_type default is "cpu" (it IS a CPU library,
        # config.h:690); defaulting a TPU-native framework to the host path
        # would leave the attached accelerator idle. Unset device_type means
        # "auto": the tree-learner factory picks the on-device learner when
        # an accelerator backend is live. An EXPLICIT device_type=cpu (or
        # device=cpu alias) still forces the host-driven path.
        if "device_type" not in self.raw_params:
            self.device_type = "auto"
        # mirrors Config::CheckParamConflict essentials
        if self.is_unbalance and self.scale_pos_weight != 1.0:
            Log.fatal("Cannot set both is_unbalance and scale_pos_weight, choose only one of them")
        if self.boosting == "goss":  # legacy spelling → gbdt + goss strategy
            self.boosting = "gbdt"
            self.data_sample_strategy = "goss"
        if self.bagging_freq > 0 and (self.bagging_fraction >= 1.0 and self.neg_bagging_fraction >= 1.0
                                      and self.pos_bagging_fraction >= 1.0):
            self.bagging_freq = 0
        # reference clamps num_leaves from max_depth only when the user did not
        # set num_leaves explicitly (config.cpp CheckParamConflict)
        if self.max_depth > 0 and "num_leaves" not in self.raw_params:
            self.num_leaves = min(self.num_leaves, (1 << self.max_depth))
        # accepted-but-unimplemented gain modifiers: warn LOUDLY at config
        # time rather than silently training a different model than the
        # reference would (config.h:554 path_smooth, config.h:600
        # monotone_penalty feed SplitInfo gains there; the split scan here
        # does not read them yet)
        if self.path_smooth > 0:
            Log.warning(
                "path_smooth=%g is NOT implemented by this learner and is "
                "IGNORED; the trained model will differ from the reference. "
                "Set path_smooth=0 to silence.", self.path_smooth)
        if self.monotone_penalty > 0:
            Log.warning(
                "monotone_penalty=%g is NOT implemented by this learner and "
                "is IGNORED (monotone_constraints themselves ARE enforced); "
                "set monotone_penalty=0 to silence.", self.monotone_penalty)
        # same contract for the rest of the accepted-but-unimplemented
        # model-altering params (graftlint R4 enforces that every spec
        # entry is either read by a subsystem or acknowledged here)
        if self.extra_trees:
            Log.warning(
                "extra_trees=true (and extra_seed=%d) is NOT implemented: "
                "thresholds are always scanned exhaustively, so the trained "
                "model will differ from the reference.", self.extra_seed)
        if self.feature_contri:
            Log.warning(
                "feature_contri is NOT implemented and is IGNORED; per-"
                "feature gain scaling will not be applied.")
        if self.early_stopping_min_delta > 0:
            Log.warning(
                "early_stopping_min_delta=%g is NOT implemented; early "
                "stopping compares scores without a minimum improvement "
                "threshold.", self.early_stopping_min_delta)
        if self.bagging_by_query:
            Log.warning(
                "bagging_by_query=true is NOT implemented; bagging always "
                "samples individual rows, not whole queries.")
        if self.weight_column or self.group_column or self.ignore_column:
            Log.warning(
                "weight_column/group_column/ignore_column are text-parser "
                "directives and are IGNORED by the array-input pipeline; "
                "pass weights/groups to fit() and drop columns before "
                "construction instead.")
        if self.deterministic:
            Log.info(
                "deterministic=true needs no special handling here: XLA "
                "reductions are deterministic for a fixed device topology.")
        # linear-tree constraints (config.cpp:425-440)
        if self.linear_tree:
            if self.tree_learner != "serial":
                Log.warning("Linear tree learner must be serial.")
                self.tree_learner = "serial"
            if self.zero_as_missing:
                Log.fatal("zero_as_missing must be false when fitting linear trees.")
            if self.objective == "regression_l1":
                Log.fatal("Cannot use regression_l1 objective when fitting linear trees.")

    def to_string(self) -> str:
        """Model-file `parameters:` section — Config::SaveMembersToString format.

        Parameters tagged [no-save] in the reference spec (IO paths, task
        selection, prediction-time options) are excluded, matching
        config_auto.cpp's generated SaveMembersToString.
        """
        lines = []
        for name, (ptype, default, _aliases, _checks, no_save) in _SPEC.items():
            if no_save:
                continue
            value = getattr(self, name)
            if ptype.startswith("list"):
                sval = ",".join(str(x) for x in value)
            elif ptype == "bool":
                sval = "1" if value else "0"
            else:
                sval = str(value)
            lines.append(f"[{name}: {sval}]")
        return "\n".join(lines)

    def clone(self) -> "Config":
        return copy.deepcopy(self)

    @staticmethod
    def param_names() -> List[str]:
        return list(_SPEC.keys())

    @staticmethod
    def aliases() -> Dict[str, str]:
        return dict(_ALIAS)


def load_config_file(path: str) -> Dict[str, str]:
    """Read a reference-format train.conf (key = value lines, # comments)."""
    kvs: Dict[str, str] = {}
    with open(path) as fh:
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            key, value = line.split("=", 1)
            kvs.setdefault(key.strip(), value.strip())
    return kvs
