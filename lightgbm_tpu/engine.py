"""Training entry points: train() and cv().

Counterpart of python-package/lightgbm/engine.py (train :109, cv :627):
parameter normalization, validation wiring, the before/after-iteration
callback loop, early stopping, and stratified/grouped CV folds.
"""
from __future__ import annotations

import collections
import copy
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Union

import numpy as np

from . import callback as callback_mod
from . import checkpoint as checkpoint_mod
from . import telemetry, tracing
from .basic import Booster, Dataset
from .callback import CallbackEnv, EarlyStopException
from .config import key_alias_transform
from .utils.log import Log, LightGBMError
from .utils.timer import global_timer


_INIT_SCORE_CHUNK = 262_144  # rows densified at a time for sparse inputs


def _init_score_predict(model: Booster, raw) -> np.ndarray:
    """Raw-score predict for continued-training init scores. Sparse inputs
    above the chunk size densify one row-chunk at a time (the full
    `.toarray()` of a big sparse train matrix is exactly the transient the
    streamed predict path exists to avoid)."""
    if hasattr(raw, "toarray") and raw.shape[0] > _INIT_SCORE_CHUNK:
        parts = []
        for s in range(0, raw.shape[0], _INIT_SCORE_CHUNK):
            dense = raw[s:s + _INIT_SCORE_CHUNK].toarray()
            parts.append(np.atleast_1d(model.predict(dense, raw_score=True)))
        return np.concatenate(parts, axis=0)
    return model.predict(raw, raw_score=True)


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          feval: Optional[Union[Callable, List[Callable]]] = None,
          init_model: Optional[Union[str, Booster]] = None,
          keep_training_booster: bool = False,
          callbacks: Optional[List[Callable]] = None) -> Booster:
    params = key_alias_transform(params or {})
    # fresh wall-clock window per run: back-to-back train() calls in one
    # process stop conflating totals (work counters survive — see timer.py)
    global_timer.new_epoch()
    # telemetry session from the `telemetry_dir` param / $LGBM_TPU_TELEMETRY;
    # a session already active (e.g. bench.py's) is left alone and reused
    own_tel = None
    tel_dir = telemetry.resolve_dir(params)
    if tel_dir and telemetry.session() is None:
        own_tel = telemetry.start(tel_dir, label="train")
    try:
        return _train_impl(params, train_set, num_boost_round, valid_sets,
                           valid_names, feval, init_model,
                           keep_training_booster, callbacks)
    except Exception as exc:
        # black box for the postmortem: whatever the ring saw right up to
        # the unhandled failure (recorder works with telemetry off too)
        tracing.note("train_exception", error=repr(exc)[:400])
        tracing.dump_flight("train_exception")
        raise
    finally:
        # beats stop legitimately now — the collective watchdog must not
        # convert post-training silence into a worker loss
        from .parallel import elastic

        elastic.notify_train_end()
        if own_tel is not None:
            telemetry.stop()


def _train_impl(params, train_set, num_boost_round, valid_sets, valid_names,
                feval, init_model, keep_training_booster,
                callbacks) -> Booster:
    # num_boost_round param aliases override the argument (engine.py:158-170)
    if "num_iterations" in params:
        num_boost_round = int(params.pop("num_iterations"))
    params["num_iterations"] = num_boost_round
    fobj = None
    if callable(params.get("objective")):
        fobj = params["objective"]
        params["objective"] = "none"

    first_metric_only = bool(params.get("first_metric_only", False))

    if num_boost_round <= 0:
        raise ValueError("num_boost_round should be greater than zero.")
    predictor_model = None
    ckpt_state = None
    if isinstance(init_model, (str,)):
        # a full-state checkpoint sidecar next to the model file means
        # bit-identical resume: trainer state is reinstated onto the fresh
        # booster below and the predict-seeded init_score path is skipped
        ckpt_state = checkpoint_mod.load_checkpoint(init_model)
        if ckpt_state is None:
            predictor_model = Booster(model_file=init_model)
    elif isinstance(init_model, Booster):
        predictor_model = init_model
    if ckpt_state is not None:
        # checkpoint resume finishes the ORIGINAL run: re-running the same
        # command (same num_boost_round) after a crash reproduces the
        # uninterrupted run bit for bit, parameters echo included. Plain
        # init_model (no sidecar) keeps continued-training semantics below:
        # num_boost_round MORE iterations on top of the loaded model.
        init_iteration = ckpt_state.iteration
        num_boost_round = max(num_boost_round - init_iteration, 0)
    else:
        init_iteration = predictor_model.current_iteration() if predictor_model else 0

    train_set.params = {**train_set.params, **params}
    if predictor_model is not None:
        # continued training: raw scores of the loaded model seed init_score
        train_set.construct()
        raw = train_set._raw
        if raw is None:  # sparse train set: predict densifies per matrix
            raw = getattr(train_set, "_sparse_raw", None)
        init_score = _init_score_predict(predictor_model, raw)
        train_set.set_init_score(np.asarray(init_score, dtype=np.float64).ravel(order="F"))

    booster = Booster(params=params, train_set=train_set)
    if valid_sets is not None:
        if isinstance(valid_sets, Dataset):
            valid_sets = [valid_sets]
        for i, valid_data in enumerate(valid_sets):
            if valid_data is train_set:
                name = "training"
                booster._train_data_name = name
                continue
            name = (valid_names[i] if valid_names and i < len(valid_names)
                    else f"valid_{i}")
            if valid_data.reference is None:
                valid_data.reference = train_set
            valid_data.params = {**valid_data.params, **params}
            if predictor_model is not None:
                valid_data.construct()
                vraw = valid_data._raw
                if vraw is None:
                    vraw = getattr(valid_data, "_sparse_raw", None)
                vi = _init_score_predict(predictor_model, vraw)
                valid_data.set_init_score(np.asarray(vi, dtype=np.float64).ravel(order="F"))
            booster.add_valid(valid_data, name)

    cbs = set(callbacks or [])
    verbosity = int(params.get("verbosity", 1))  # CLI conf values arrive as str
    if params.get("early_stopping_round") and int(params["early_stopping_round"]) > 0:
        cbs.add(callback_mod.early_stopping(int(params["early_stopping_round"]),
                                            first_metric_only,
                                            verbose=verbosity >= 1))
    callbacks_before = sorted((cb for cb in cbs if getattr(cb, "before_iteration", False)),
                              key=lambda cb: getattr(cb, "order", 0))
    callbacks_after = sorted((cb for cb in cbs if not getattr(cb, "before_iteration", False)),
                             key=lambda cb: getattr(cb, "order", 0))

    if ckpt_state is not None:
        checkpoint_mod.restore_trainer_state(booster, ckpt_state,
                                             callbacks_after)

    booster.best_iteration = -1
    is_finished = False
    # §5 tracing: _train_loop wraps the boosting loop in a jax.profiler
    # trace when LGBM_TPU_PROFILE(_DIR) is set (utils/profile.maybe_trace),
    # composing with LGBM_TPU_TIMETAG per-scope TraceAnnotations
    try:
        is_finished = _train_loop(
            booster, params, feval, fobj, init_iteration, num_boost_round,
            callbacks_before, callbacks_after)
    finally:
        if global_timer.enabled:
            Log.info("%s", global_timer.report())
    if booster.best_iteration <= 0:
        booster.best_iteration = booster.current_iteration()
    return booster


def _train_loop(booster, params, feval, fobj, init_iteration, num_boost_round,
                callbacks_before, callbacks_after) -> bool:
    from .utils.profile import maybe_trace

    with maybe_trace():  # device trace when LGBM_TPU_PROFILE=<dir> is set
        return _train_loop_inner(booster, params, feval, fobj,
                                 init_iteration, num_boost_round,
                                 callbacks_before, callbacks_after)


def _train_loop_inner(booster, params, feval, fobj, init_iteration,
                      num_boost_round, callbacks_before,
                      callbacks_after) -> bool:
    is_finished = False
    evaluation_result_list = None
    if telemetry.enabled():
        telemetry.emit("train_begin", begin_iteration=init_iteration,
                       end_iteration=init_iteration + num_boost_round,
                       objective=str(params.get("objective", "")))
    for i in range(init_iteration, init_iteration + num_boost_round):
        if is_finished:
            break
        it_t0 = time.perf_counter()
        # iteration span: same API as the serving request spans, so the
        # Chrome-trace export and the flight recorder speak one format
        it_span = tracing.start_span("train_iteration")
        it_span.attrs["iteration"] = int(i)
        counters_before = (dict(global_timer.counters)
                           if telemetry.enabled() else None)
        for cb in callbacks_before:
            cb(CallbackEnv(model=booster, params=params, iteration=i,
                           begin_iteration=init_iteration,
                           end_iteration=init_iteration + num_boost_round,
                           evaluation_result_list=None))
        is_finished = booster.update(fobj=fobj)
        t_boost_end = time.perf_counter()
        it_span.add_stage("boost", t_boost_end - it_t0)

        evaluation_result_list = []
        if booster._gbdt.valid_sets or booster._gbdt.train_metrics:
            if booster._train_data_name == "training" and _wants_train_metric(params):
                evaluation_result_list.extend(booster.eval_train(feval))
            evaluation_result_list.extend(booster.eval_valid(feval))
        try:
            for cb in callbacks_after:
                cb(CallbackEnv(model=booster, params=params, iteration=i,
                               begin_iteration=init_iteration,
                               end_iteration=init_iteration + num_boost_round,
                               evaluation_result_list=evaluation_result_list))
        except EarlyStopException as earlyStopException:
            booster.best_iteration = earlyStopException.best_iteration + 1
            evaluation_result_list = earlyStopException.best_score
            is_finished = True
        it_span.add_stage("eval", time.perf_counter() - t_boost_end)
        it_span.finish()
        if counters_before is not None:
            _emit_iteration_record(booster, i, evaluation_result_list,
                                   time.perf_counter() - it_t0,
                                   counters_before)
    booster.best_score = collections.defaultdict(collections.OrderedDict)
    for item in evaluation_result_list or []:
        booster.best_score[item[0]][item[1]] = item[2]
    return is_finished


def _emit_iteration_record(booster, iteration, evals, wall_s,
                           counters_before) -> None:
    """One structured record per boosting iteration: eval results, tree
    stats, work-counter deltas, wall time — plus an HBM gauge sample."""
    gbdt = getattr(booster, "_gbdt", None)
    models = getattr(gbdt, "models", None) or []
    last = models[-1] if models else None
    deltas = {}
    for k, v in global_timer.counters.items():
        d = int(v) - int(counters_before.get(k, 0))
        if d:
            deltas[k] = d
    telemetry.emit(
        "iteration", iteration=int(iteration), wall_s=round(wall_s, 6),
        num_trees=len(models),
        tree_leaves=int(getattr(last, "num_leaves", 0) or 0),
        evals=[[e[0], e[1], float(e[2])] for e in (evals or [])],
        counters=deltas)
    telemetry.sample_hbm()


def _wants_train_metric(params) -> bool:
    for key in ("is_provide_training_metric", "training_metric",
                "is_training_metric", "train_metric"):
        if params.get(key):
            return True
    return False


class CVBooster:
    """Ensemble of per-fold boosters (engine.py CVBooster)."""

    def __init__(self) -> None:
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name):
        def handler_function(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]

        return handler_function


def _make_n_folds(full_data: Dataset, folds, nfold: int, params, seed: int,
                  stratified: bool, shuffle: bool):
    full_data.construct()
    num_data = full_data.num_data()
    label = np.asarray(full_data.get_label())
    rng = np.random.RandomState(seed)
    if folds is not None:
        if hasattr(folds, "split"):
            group = full_data.get_group()
            group_info = (np.asarray(group, dtype=np.int64)
                          if group is not None else None)
            folds = folds.split(X=np.empty(num_data), y=label, groups=group_info)
        yield from folds
        return
    if stratified:
        # stratify by label classes
        classes = np.unique(label)
        idx_by_class = [np.where(label == c)[0] for c in classes]
        if shuffle:
            for a in idx_by_class:
                rng.shuffle(a)
        fold_members: List[List[int]] = [[] for _ in range(nfold)]
        for a in idx_by_class:
            for i, ix in enumerate(a):
                fold_members[i % nfold].append(ix)
        for k in range(nfold):
            test_idx = np.array(sorted(fold_members[k]), dtype=np.int64)
            train_idx = np.setdiff1d(np.arange(num_data), test_idx)
            yield train_idx, test_idx
    else:
        perm = rng.permutation(num_data) if shuffle else np.arange(num_data)
        kstep = int(num_data / nfold)
        for k in range(nfold):
            test_idx = perm[k * kstep: (k + 1) * kstep if k < nfold - 1 else num_data]
            train_idx = np.setdiff1d(np.arange(num_data), test_idx)
            yield train_idx, test_idx


def cv(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True, shuffle: bool = True,
       metrics=None, feval=None, init_model=None,
       fpreproc=None, seed: int = 0, callbacks=None, eval_train_metric: bool = False,
       return_cvbooster: bool = False) -> Dict[str, List[float]]:
    """K-fold cross-validation (engine.py:627)."""
    params = key_alias_transform(params or {})
    if "num_iterations" in params:
        num_boost_round = int(params.pop("num_iterations"))
    if metrics is not None:
        params["metric"] = metrics
    if stratified and params.get("objective") not in (
            None, "binary", "multiclass", "multiclassova", "softmax"):
        stratified = False

    results = collections.defaultdict(list)
    cvbooster = CVBooster()
    fold_data = []
    for train_idx, test_idx in _make_n_folds(train_set, folds, nfold, params,
                                             seed, stratified, shuffle):
        tr = train_set.subset(train_idx)
        te = train_set.subset(test_idx)
        if fpreproc is not None:
            tr, te, params = fpreproc(tr, te, params.copy())
        fold_data.append((tr, te))

    boosters = []
    for tr, te in fold_data:
        te.reference = tr
        bst = Booster(params=params, train_set=tr)
        bst.add_valid(te, "valid")
        boosters.append(bst)
        cvbooster.append(bst)

    cbs = set(callbacks or [])
    es_cb = None
    if params.get("early_stopping_round") and int(params["early_stopping_round"]) > 0:
        es_cb = callback_mod.early_stopping(int(params["early_stopping_round"]))
        cbs.add(es_cb)
    callbacks_after = sorted((cb for cb in cbs if not getattr(cb, "before_iteration", False)),
                             key=lambda cb: getattr(cb, "order", 0))

    is_finished = False
    for i in range(num_boost_round):
        if is_finished:
            break
        merged: Dict = collections.defaultdict(list)
        for bst in boosters:
            bst.update()
            for dname, mname, val, bigger in bst.eval_valid(feval):
                merged[(dname, mname, bigger)].append(val)
        agg = []
        for (dname, mname, bigger), vals in merged.items():
            mean, std = float(np.mean(vals)), float(np.std(vals))
            results[f"{dname} {mname}-mean"].append(mean)
            results[f"{dname} {mname}-stdv"].append(std)
            agg.append((dname, mname, mean, bigger, std))
        try:
            for cb in callbacks_after:
                cb(CallbackEnv(model=cvbooster, params=params, iteration=i,
                               begin_iteration=0, end_iteration=num_boost_round,
                               evaluation_result_list=agg))
        except EarlyStopException as e:
            cvbooster.best_iteration = e.best_iteration + 1
            for key in list(results.keys()):
                results[key] = results[key][: cvbooster.best_iteration]
            is_finished = True
    out = dict(results)
    if return_cvbooster:
        out["cvbooster"] = cvbooster
    return out
