"""Prometheus text exposition for the telemetry counter namespace.

One renderer, two transports: the serving HTTP server mounts it at
`GET /metrics` (serving/http.py) and training writes the same text as a
`metrics.prom` snapshot into the telemetry dir at every flush
(telemetry.py), so a node-exporter textfile collector scrapes a live
train exactly like a live server. Everything renders from the namespaces
that already exist — `telemetry.signals()` (authoritative for compile
counts and HBM high-water), `global_timer.counters` (work counters and
gauges: ICI bytes/wave, device_hist_rows, committed-vs-speculated waves,
serve queue depth, the drift family: `drift_psi_milli_max` /
`drift_edge_milli_max` milli-int gauges, `drift_alarms` /
`bin_refresh_total` / `stream_generation_rejected` counters and the
`stream_bin_generation` / `stream_generation` gauges from
streaming/drift.py...), and `global_timer.totals`/`counts` (per-stage
seconds/calls) — no second bookkeeping layer to drift.

Exposition format 0.0.4 (text/plain). Naming:

  * accumulating counters  -> ``lgbm_tpu_<name>_total``
  * gauges (set_count)     -> ``lgbm_tpu_<name>``
  * timer scopes           -> ``lgbm_tpu_stage_seconds_total{stage="..."}``
                              and ``lgbm_tpu_stage_calls_total{stage=...}``
  * signals                -> ``lgbm_tpu_compiles_total``,
                              ``lgbm_tpu_kernel_compiles_total``,
                              ``lgbm_tpu_hbm_high_water_bytes``

Rendering walks a few small dicts — cheap enough for a per-scrape call —
and emits nothing in the hot path itself (graftlint R9 covers this file:
any future telemetry.emit here must be enabled-guarded).
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Mapping, Optional, Tuple

from . import telemetry, tracing
from .utils.timer import global_timer

PREFIX = "lgbm_tpu"
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
SNAPSHOT_FILE = "metrics.prom"

# counter-namespace keys the signals() snapshot owns; skipped in the
# generic counter walk so each figure appears exactly once
_SIGNAL_OWNED = ("jit_compiles", "kernel_compiles", "hbm_high_water_bytes")

_NAME_OK = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(raw: str, suffix: str = "") -> str:
    name = _NAME_OK.sub("_", raw.strip())
    if name and name[0].isdigit():
        name = "_" + name
    return f"{PREFIX}_{name}{suffix}"


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: Any) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Writer:
    def __init__(self) -> None:
        self.lines: List[str] = []
        self._typed: set = set()

    def sample(self, name: str, mtype: str, value: Any, help_text: str = "",
               labels: Optional[Mapping[str, str]] = None) -> None:
        if name not in self._typed:
            self._typed.add(name)
            if help_text:
                self.lines.append(f"# HELP {name} {help_text}")
            self.lines.append(f"# TYPE {name} {mtype}")
        label_s = ""
        if labels:
            inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                             for k, v in sorted(labels.items()))
            label_s = "{" + inner + "}"
        self.lines.append(f"{name}{label_s} {_fmt(value)}")

    def text(self) -> str:
        return "\n".join(self.lines) + "\n" if self.lines else ""


def render_metrics(extra: Optional[Mapping[str, Any]] = None,
                   signals: Optional[Mapping[str, int]] = None) -> str:
    """The full exposition document as a string.

    `extra` adds flat name->number gauges (the serving handler passes the
    service's latency/queue stats); names are sanitized into the
    ``lgbm_tpu_`` namespace like everything else. `signals` overrides the
    live `telemetry.signals()` read — the close-time snapshot passes the
    closing session's own figures, which the module global no longer
    reaches at that point."""
    w = _Writer()
    sig = telemetry.signals() if signals is None else signals
    w.sample(_metric_name("compiles", "_total"), "counter",
             sig.get("compiles", 0),
             "XLA jit cache misses seen by the recompile watcher")
    w.sample(_metric_name("kernel_compiles", "_total"), "counter",
             sig.get("kernel_compiles", 0),
             "Pallas/Mosaic kernel compiles (subset of compiles)")
    w.sample(_metric_name("hbm_high_water_bytes"), "gauge",
             sig.get("hbm_high_water_bytes", 0),
             "Peak per-device HBM bytes in use this session")
    w.sample(_metric_name("telemetry_enabled"), "gauge",
             1 if telemetry.enabled() else 0,
             "1 while a telemetry session is recording")
    for key in sorted(global_timer.counters):
        if key in _SIGNAL_OWNED:
            continue
        value = global_timer.counters[key]
        if key in global_timer.gauges:
            w.sample(_metric_name(key), "gauge", value,
                     "level gauge from the global_timer counter namespace")
        else:
            w.sample(_metric_name(key, "_total"), "counter", value,
                     "work counter from the global_timer counter namespace")
    # request/iteration stage quantiles from the tracing histograms
    # (log-bucketed streaming p50/p99 — serving's 25× decomposition)
    for key, value in sorted(tracing.quantile_gauges().items()):
        w.sample(_metric_name(key), "gauge", value,
                 "stage latency quantile from the tracing histograms (ms)")
    sec_name = f"{PREFIX}_stage_seconds_total"
    calls_name = f"{PREFIX}_stage_calls_total"
    for label in sorted(global_timer.totals):
        w.sample(sec_name, "counter", global_timer.totals[label],
                 "accumulated wall seconds per timer scope",
                 labels={"stage": label})
    for label in sorted(global_timer.counts):
        w.sample(calls_name, "counter", global_timer.counts[label],
                 "closed-scope count per timer scope",
                 labels={"stage": label})
    for key in sorted(extra or {}):
        val = (extra or {})[key]
        if isinstance(val, bool) or not isinstance(val, (int, float)):
            continue
        w.sample(_metric_name(key), "gauge", val,
                 "point-in-time gauge supplied by the exposition caller")
    return w.text()


def parse_exposition(text: str) -> Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                                        float]:
    """Minimal 0.0.4 parser for tests and tools: sample lines to
    {(name, ((label, value), ...)): float}. Raises ValueError on a
    malformed sample line, which is exactly what the format test wants."""
    out: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = re.match(r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
                     r'(?:\{([^}]*)\})?\s+(\S+)$', line)
        if m is None:
            raise ValueError(f"malformed exposition line: {line!r}")
        labels: List[Tuple[str, str]] = []
        if m.group(2):
            for part in re.findall(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"',
                                   m.group(2)):
                labels.append(part)
        out[(m.group(1), tuple(labels))] = float(m.group(3))
    return out


def write_snapshot(path: str, extra: Optional[Mapping[str, Any]] = None,
                   signals: Optional[Mapping[str, int]] = None) -> str:
    """Render and atomically write the exposition text to `path` (the
    training-side textfile-collector hand-off). Returns the text."""
    from .checkpoint import atomic_write_text

    text = render_metrics(extra, signals=signals)
    atomic_write_text(path, text)
    return text
