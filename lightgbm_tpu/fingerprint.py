"""Environment fingerprint + the append-only bench ledger.

A bench number with no provenance is noise: 2.38M row-iters/s means
nothing until you know which commit, which jax, which device, and which
`LGBM_TPU_*` kernel flags produced it. `fingerprint()` captures exactly
that — cheaply and without ever raising (a capture must not die because
git is absent) — and bench.py stamps it into every record.

`append_ledger()` is the durable trail: one fingerprinted record per
line in BENCH_LEDGER.jsonl, appended via checkpoint.py's atomic
read-modify-replace so a crash mid-capture never tears the file.
tools/benchdiff.py reads the ledger back and gates PRs on it; the record
schema is documented in docs/OBSERVABILITY.md and versioned by
``LEDGER_SCHEMA_VERSION`` so readers can reject records they predate.
"""
from __future__ import annotations

import os
import subprocess
from typing import Any, Dict, Optional

# bump on any breaking change to the bench-record key set; benchdiff
# refuses to compare records across major schema versions
LEDGER_SCHEMA_VERSION = 1

DEFAULT_LEDGER = "BENCH_LEDGER.jsonl"
ENV_LEDGER = "BENCH_LEDGER"  # path override; "0"/"off" disables appends


def _git_sha(repo_dir: Optional[str] = None) -> str:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=repo_dir or os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
        if out.returncode == 0:
            return out.stdout.strip()
    except Exception:
        pass
    return "unknown"


def _flag_env() -> Dict[str, str]:
    """Every set LGBM_TPU_* flag plus the jax/bench knobs that change what
    a capture measures — the flags ARE the experiment axes (GH_BF16,
    COMPACT_ALIAS, ...), so they belong in the fingerprint."""
    keep_exact = ("JAX_PLATFORMS",)
    out = {k: v for k, v in os.environ.items()
           if k.startswith("LGBM_TPU_") or k in keep_exact}
    return dict(sorted(out.items()))


def fingerprint(repo_dir: Optional[str] = None) -> Dict[str, Any]:
    """The environment identity block stamped on every bench record.
    Pure observation, never raises; unknown fields degrade to "unknown"
    (no jax on the path, no git checkout) rather than failing a capture."""
    fp: Dict[str, Any] = {
        "git_sha": _git_sha(repo_dir),
        "schema_version": LEDGER_SCHEMA_VERSION,
        "flags": _flag_env(),
    }
    try:
        import jax

        fp["jax_version"] = str(jax.__version__)
        try:
            import jaxlib

            fp["jaxlib_version"] = str(jaxlib.__version__)
        except Exception:
            fp["jaxlib_version"] = "unknown"
        try:
            devs = jax.devices()
            fp["device_kind"] = str(devs[0].device_kind) if devs else "none"
            fp["device_count"] = len(devs)
            fp["backend"] = str(jax.default_backend())
        except Exception:
            fp["device_kind"] = "unknown"
            fp["device_count"] = 0
            fp["backend"] = "unknown"
    except Exception:
        fp["jax_version"] = "unknown"
        fp["jaxlib_version"] = "unknown"
        fp["device_kind"] = "unknown"
        fp["device_count"] = 0
        fp["backend"] = "unknown"
    return fp


def world_fingerprint() -> Dict[str, Any]:
    """The distributed-world identity block stamped into checkpoint
    sidecars (docs/ROBUSTNESS.md, "Distributed fault domain"): enough to
    name BOTH shapes when a restore lands on a different world than the
    save. Same contract as fingerprint(): pure observation, never raises,
    unknown fields degrade to safe defaults."""
    fp: Dict[str, Any] = {
        "process_count": 1,
        "mesh_shape": [1],
        "device_kinds": ["unknown"],
        "jax_version": "unknown",
        "jaxlib_version": "unknown",
    }
    try:
        import jax

        fp["jax_version"] = str(jax.__version__)
        try:
            import jaxlib

            fp["jaxlib_version"] = str(jaxlib.__version__)
        except Exception:
            pass
        fp["process_count"] = int(jax.process_count())
        devs = jax.devices()
        fp["mesh_shape"] = [len(devs)]
        fp["device_kinds"] = sorted({str(d.device_kind) for d in devs}) \
            or ["none"]
    except Exception:
        pass
    return fp


def ledger_path(repo_dir: Optional[str] = None) -> Optional[str]:
    """Resolved ledger file path, or None when appends are disabled via
    $BENCH_LEDGER=0/off/empty-string-sentinel."""
    env = os.environ.get(ENV_LEDGER)
    if env is not None:
        if env.strip().lower() in ("0", "off", "none", ""):
            return None
        return env
    base = repo_dir or os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    return os.path.join(base, DEFAULT_LEDGER)


def append_ledger(record: Dict[str, Any],
                  path: Optional[str] = None) -> Optional[str]:
    """Append one JSON record line to the ledger (atomic whole-file
    replace — the ledger stays a few thousand lines, so rewrite cost is
    irrelevant next to crash consistency). Returns the path written, or
    None when the ledger is disabled."""
    import json

    from .checkpoint import atomic_write_text

    if path is None:
        path = ledger_path()
    if path is None:
        return None
    prior = ""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            prior = fh.read()
        if prior and not prior.endswith("\n"):
            prior += "\n"
    except FileNotFoundError:
        pass
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    line = json.dumps(record, sort_keys=True) + "\n"
    atomic_write_text(path, prior + line)
    return path
