"""Numerical-health guardrails for the boosting loop.

A diverging run on TPU looks like this: one bad gradient wave (overflow in
a custom objective, a NaN feature snuck past ingest, an XLA bug) silently
turns the root histogram totals NaN, every split gain goes NaN, the learner
emits stub trees, and a multi-hour job "converges" to garbage — or worse,
early-stops gracefully and reports success. The monitor makes that loud:

* **observe** — per-iteration on-device finiteness reductions over the gh
  wave and the score matrix, AND-accumulated into one boolean scalar.
  No host sync: the accumulator stays on device.
* **admit** — every ``check_every`` iterations the accumulated boolean is
  synced once (ONE scalar D2H per window — the async pipeline stays hot)
  together with a host-side check of the freshest committed tree's leaf
  values / split gains. On failure the configured policy runs:

  - ``fatal``    — Log.fatal with the iteration number (default loud stop)
  - ``warn``     — log and keep going (observability only)
  - ``rollback`` — restore the last healthy backup (device copies of the
                   score arrays + model-list length taken at each healthy
                   sync), recompute gradients from the restored scores, and
                   continue with NaN-sanitized + clipped gh from then on.

Cost model in docs/ROBUSTNESS.md: the reductions fuse into the gradient
pass; the only serialization point is the one bool() sync per window.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from . import telemetry, tracing
from .utils.log import Log

_POLICIES = ("fatal", "warn", "rollback")
GRAD_CLIP = 1e6  # post-rollback clip bound for gradients/hessians


def first_nonfinite_column(X) -> Optional[int]:
    """Column index of the first non-finite value in a host batch, or None.

    The serving boundary's reuse of the guardrail finiteness machinery: a
    prediction service with ``reject_nonfinite`` enabled runs this on every
    request payload BEFORE admission, so a NaN/inf row gets a typed 400
    naming the offending column instead of a device dispatch. One vectorized
    isfinite pass on host — NaN stays a legitimate missing value for models
    that opted out."""
    import numpy as np

    finite = np.isfinite(X)
    if finite.all():
        return None
    return int(np.argmax(~finite.all(axis=0)))


def prediction_loss(preds, y, objective: str = "") -> float:
    """Scalar holdout loss for the streaming publish quality gate
    (streaming/continuous.py): clipped logloss for binary objectives,
    MSE otherwise. Any non-finite prediction is an automatic +inf — a
    candidate that emits NaN must never win a gate comparison."""
    import numpy as np

    preds = np.asarray(preds, dtype=np.float64).ravel()
    y = np.asarray(y, dtype=np.float64).ravel()
    if preds.shape != y.shape or len(y) == 0 \
            or not np.isfinite(preds).all():
        return float("inf")
    if objective in ("binary", "cross_entropy", "xentropy"):
        p = np.clip(preds, 1e-7, 1.0 - 1e-7)
        return float(-np.mean(y * np.log(p) + (1.0 - y) * np.log(1.0 - p)))
    return float(np.mean((preds - y) ** 2))


def create_monitor(config) -> Optional["HealthMonitor"]:
    policy = str(getattr(config, "health_check_policy", "") or "").strip()
    if not policy:
        return None
    if policy not in _POLICIES:
        Log.fatal("Unknown health_check_policy %r (choose one of %s)",
                  policy, "/".join(_POLICIES))
    return HealthMonitor(policy, int(getattr(config, "health_check_every", 10)))


class HealthMonitor:
    def __init__(self, policy: str, check_every: int = 10) -> None:
        self.policy = policy
        self.check_every = max(1, int(check_every))
        self.clip_on = False  # armed permanently after a rollback recovery
        self._acc = None      # device bool: AND of all observations so far
        self._host_ok = True  # host-side tree-structure observations
        self._since_sync = 0
        self._backup = None   # (iter_, n_models, score, [valid scores])

    # ------------------------------------------------------------ observers

    def observe(self, *arrays) -> None:
        """Fold finiteness of device arrays into the accumulator (no sync)."""
        import jax.numpy as jnp

        for a in arrays:
            if a is None:
                continue
            ok = jnp.isfinite(a).all()
            self._acc = ok if self._acc is None else jnp.logical_and(
                self._acc, ok)

    def observe_tree(self, tree) -> None:
        """Host-side finiteness of a committed tree's outputs (leaf values,
        split gains) — trees are already host-resident after replay, so this
        costs no device sync."""
        import numpy as np

        n = int(tree.num_leaves)
        if n <= 0:
            return
        finite = np.isfinite(tree.leaf_value[:n]).all()
        if finite and n > 1:
            finite = np.isfinite(tree.split_gain[:n - 1]).all()
        if not finite:
            self._host_ok = False

    # -------------------------------------------------------------- admit

    def admit(self, gbdt, grads, hesses):
        """Gate iteration `gbdt.iter_`'s gh wave. Called after the gradient
        pass, BEFORE bagging/tree growth, so an unhealthy wave is caught in
        the same iteration and never grows a tree."""
        self.observe(grads, hesses, gbdt.score)
        self._since_sync += 1
        if self._since_sync >= self.check_every:
            # graftlint: disable=R1 -- the ONE deliberate scalar sync per check_every window; the accumulated logical_and collapses to a single bool pull, amortized per docs/ROBUSTNESS.md
            healthy = ((self._acc is None or bool(self._acc))
                       and self._host_ok)
            self._acc = None
            self._host_ok = True
            self._since_sync = 0
            telemetry.emit("health_check", healthy=healthy,
                           policy=self.policy, iteration=int(gbdt.iter_))
            # the elastic heartbeat rides THIS window: the scalar pull above
            # already serialized the dispatch stream, so the gang-cardinality
            # token costs no additional host sync (parallel/elastic.py)
            from .parallel import elastic

            rt = elastic.active()
            if rt is not None:
                rt.heartbeat_sync(int(gbdt.iter_))
            if not healthy:
                grads, hesses = self._handle(gbdt, grads, hesses)
            elif self.policy == "rollback":
                self._take_backup(gbdt)
        if self.clip_on:
            grads, hesses = self._sanitize(grads, hesses)
        return grads, hesses

    # ------------------------------------------------------------ handlers

    def _handle(self, gbdt, grads, hesses):
        it = int(gbdt.iter_)
        if self.policy == "fatal":
            Log.fatal("Numerical health check failed at iteration %d: "
                      "non-finite values in gradients/hessians/scores or "
                      "committed tree outputs", it)
        if self.policy == "warn":
            Log.warning("Numerical health check failed at iteration %d "
                        "(policy=warn: continuing)", it)
            return grads, hesses
        # rollback: restore the last healthy snapshot and re-boost with
        # sanitized, clipped gradients from the restored scores
        gbdt._flush_pending()
        rolled = self._restore_backup(gbdt)
        Log.warning("Numerical health check failed at iteration %d; rolled "
                    "back %d iteration(s) to %d and re-boosting with "
                    "clipped gradients", it, rolled, int(gbdt.iter_))
        telemetry.emit("health_rollback", iteration=it,
                       rolled_back=int(rolled), resumed_at=int(gbdt.iter_))
        tracing.note("health_rollback", iteration=it,
                     rolled_back=int(rolled), resumed_at=int(gbdt.iter_))
        tracing.dump_flight("health_rollback")
        self.clip_on = True
        if gbdt._grad_fn is not None:
            score = gbdt.score if gbdt.num_tree_per_iteration > 1 \
                else gbdt.score[0]
            grads, hesses = gbdt._grad_fn(score)
        return self._sanitize(grads, hesses)

    @staticmethod
    def _sanitize(grads, hesses):
        import jax.numpy as jnp

        g = jnp.clip(jnp.nan_to_num(grads, nan=0.0, posinf=GRAD_CLIP,
                                    neginf=-GRAD_CLIP), -GRAD_CLIP, GRAD_CLIP)
        h = jnp.clip(jnp.nan_to_num(hesses, nan=0.0, posinf=GRAD_CLIP,
                                    neginf=0.0), 0.0, GRAD_CLIP)
        return g, h

    # ------------------------------------------------------------- backups

    def _take_backup(self, gbdt) -> None:
        import jax.numpy as jnp

        self._backup = (
            int(gbdt.iter_),
            len(gbdt.models),
            jnp.array(gbdt.score, copy=True),
            [jnp.array(vd.score, copy=True) for vd in gbdt.valid_sets],
        )

    def _restore_backup(self, gbdt) -> int:
        import jax.numpy as jnp

        if self._backup is None:
            # no healthy sync happened yet: nothing to roll back to — scrub
            # the live scores in place so re-boosting can proceed
            gbdt.score = jnp.nan_to_num(gbdt.score, nan=0.0,
                                        posinf=GRAD_CLIP, neginf=-GRAD_CLIP)
            for vd in gbdt.valid_sets:
                vd.score = jnp.nan_to_num(vd.score, nan=0.0,
                                          posinf=GRAD_CLIP, neginf=-GRAD_CLIP)
            return 0
        it, n_models, score, valid_scores = self._backup
        rolled = int(gbdt.iter_) - it
        del gbdt.models[n_models:]
        gbdt.iter_ = it
        gbdt.score = score
        for vd, s in zip(gbdt.valid_sets, valid_scores):
            vd.score = s
        gbdt._predictor.invalidate()
        self._backup = None  # consumed; next healthy sync takes a fresh one
        return rolled

    # ------------------------------------------------------ checkpointing

    def snapshot(self) -> Dict[str, Any]:
        return {"clip_on": bool(self.clip_on)}

    def restore(self, state: Dict[str, Any]) -> None:
        self.clip_on = bool(state.get("clip_on", False))
