from .binning import BinMapper, BIN_TYPE_NUMERICAL, BIN_TYPE_CATEGORICAL

__all__ = ["BinMapper", "BIN_TYPE_NUMERICAL", "BIN_TYPE_CATEGORICAL"]
