"""Per-feature quantile binning with reference-parity semantics.

Counterpart of BinMapper (include/LightGBM/bin.h:85-259, src/io/bin.cpp):
  * GreedyFindBin (bin.cpp:80-159): count-weighted greedy boundary placement
    over distinct values, big-count values get dedicated bins.
  * FindBinWithZeroAsOneBin (bin.cpp:246-291): zero gets its own
    [-1e-35, 1e-35] bin; negative/positive ranges binned separately.
  * FindBinWithPredefinedBin (bin.cpp:161-244): user-forced bin bounds.
  * BinMapper::FindBin (bin.cpp:315-513): missing handling (None/Zero/NaN),
    categorical count-ordered bin assignment with 99% mass cutoff,
    trivial-feature detection, most_freq_bin/default_bin bookkeeping.
  * ValueToBin (bin.h:612-650): searchsorted over upper bounds.

Binning runs on host (numpy) at dataset-construction time — it is a one-shot
O(#samples log #samples) preprocessing step; the resulting small per-feature
arrays ship to device as part of the binned matrix build.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..common import (MISSING_NONE, MISSING_ZERO, MISSING_NAN,
                      K_ZERO_THRESHOLD, round_int)
from ..utils.log import Log

K_SPARSE_THRESHOLD = 0.8  # bin.h kSparseThreshold

BIN_TYPE_NUMERICAL = 0
BIN_TYPE_CATEGORICAL = 1


def _next_after_up(a: float) -> float:
    return math.nextafter(a, math.inf)


def _check_double_equal_ordered(a: float, b: float) -> bool:
    return b <= _next_after_up(a)


def greedy_find_bin(distinct_values: Sequence[float], counts: Sequence[int],
                    num_distinct_values: int, max_bin: int, total_cnt: int,
                    min_data_in_bin: int) -> List[float]:
    """bin.cpp:80-159 — returns upper bounds, last is +inf."""
    bin_upper_bound: List[float] = []
    assert max_bin > 0
    if num_distinct_values <= max_bin:
        cur_cnt_inbin = 0
        for i in range(num_distinct_values - 1):
            cur_cnt_inbin += counts[i]
            if cur_cnt_inbin >= min_data_in_bin:
                val = _next_after_up((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                if not bin_upper_bound or not _check_double_equal_ordered(bin_upper_bound[-1], val):
                    bin_upper_bound.append(val)
                    cur_cnt_inbin = 0
        bin_upper_bound.append(math.inf)
    else:
        if min_data_in_bin > 0:
            max_bin = min(max_bin, total_cnt // min_data_in_bin)
            max_bin = max(max_bin, 1)
        mean_bin_size = total_cnt / max_bin
        rest_bin_cnt = max_bin
        rest_sample_cnt = total_cnt
        is_big = [counts[i] >= mean_bin_size for i in range(num_distinct_values)]
        for i in range(num_distinct_values):
            if is_big[i]:
                rest_bin_cnt -= 1
                rest_sample_cnt -= counts[i]
        mean_bin_size = rest_sample_cnt / rest_bin_cnt if rest_bin_cnt else math.inf
        upper_bounds = [math.inf] * max_bin
        lower_bounds = [math.inf] * max_bin
        bin_cnt = 0
        lower_bounds[0] = distinct_values[0]
        cur_cnt_inbin = 0
        for i in range(num_distinct_values - 1):
            if not is_big[i]:
                rest_sample_cnt -= counts[i]
            cur_cnt_inbin += counts[i]
            if (is_big[i] or cur_cnt_inbin >= mean_bin_size or
                    (is_big[i + 1] and cur_cnt_inbin >= max(1.0, mean_bin_size * 0.5))):
                upper_bounds[bin_cnt] = distinct_values[i]
                bin_cnt += 1
                lower_bounds[bin_cnt] = distinct_values[i + 1]
                if bin_cnt >= max_bin - 1:
                    break
                cur_cnt_inbin = 0
                if not is_big[i]:
                    rest_bin_cnt -= 1
                    mean_bin_size = rest_sample_cnt / rest_bin_cnt if rest_bin_cnt else math.inf
        bin_cnt += 1
        for i in range(bin_cnt - 1):
            val = _next_after_up((upper_bounds[i] + lower_bounds[i + 1]) / 2.0)
            if not bin_upper_bound or not _check_double_equal_ordered(bin_upper_bound[-1], val):
                bin_upper_bound.append(val)
        bin_upper_bound.append(math.inf)
    return bin_upper_bound


def find_bin_with_zero_as_one_bin(distinct_values: Sequence[float], counts: Sequence[int],
                                  num_distinct_values: int, max_bin: int,
                                  total_sample_cnt: int, min_data_in_bin: int) -> List[float]:
    """bin.cpp:246-291."""
    bin_upper_bound: List[float] = []
    left_cnt_data = cnt_zero = right_cnt_data = 0
    for i in range(num_distinct_values):
        if distinct_values[i] <= -K_ZERO_THRESHOLD:
            left_cnt_data += counts[i]
        elif distinct_values[i] > K_ZERO_THRESHOLD:
            right_cnt_data += counts[i]
        else:
            cnt_zero += counts[i]

    left_cnt = -1
    for i in range(num_distinct_values):
        if distinct_values[i] > -K_ZERO_THRESHOLD:
            left_cnt = i
            break
    if left_cnt < 0:
        left_cnt = num_distinct_values

    if left_cnt > 0 and max_bin > 1:
        denom = total_sample_cnt - cnt_zero
        left_max_bin = int(left_cnt_data / denom * (max_bin - 1)) if denom else 1
        left_max_bin = max(1, left_max_bin)
        bin_upper_bound = greedy_find_bin(distinct_values, counts, left_cnt,
                                          left_max_bin, left_cnt_data, min_data_in_bin)
        if bin_upper_bound:
            bin_upper_bound[-1] = -K_ZERO_THRESHOLD

    right_start = -1
    for i in range(left_cnt, num_distinct_values):
        if distinct_values[i] > K_ZERO_THRESHOLD:
            right_start = i
            break

    right_max_bin = max_bin - 1 - len(bin_upper_bound)
    if right_start >= 0 and right_max_bin > 0:
        right_bounds = greedy_find_bin(distinct_values[right_start:], counts[right_start:],
                                       num_distinct_values - right_start, right_max_bin,
                                       right_cnt_data, min_data_in_bin)
        bin_upper_bound.append(K_ZERO_THRESHOLD)
        bin_upper_bound.extend(right_bounds)
    else:
        bin_upper_bound.append(math.inf)
    assert len(bin_upper_bound) <= max_bin
    return bin_upper_bound


def find_bin_with_predefined_bin(distinct_values: Sequence[float], counts: Sequence[int],
                                 num_distinct_values: int, max_bin: int,
                                 total_sample_cnt: int, min_data_in_bin: int,
                                 forced_upper_bounds: Sequence[float]) -> List[float]:
    """bin.cpp:161-244 — forced bounds + zero bounds, greedy fill between."""
    bin_upper_bound: List[float] = []
    left_cnt = -1
    for i in range(num_distinct_values):
        if distinct_values[i] > -K_ZERO_THRESHOLD:
            left_cnt = i
            break
    if left_cnt < 0:
        left_cnt = num_distinct_values
    right_start = -1
    for i in range(left_cnt, num_distinct_values):
        if distinct_values[i] > K_ZERO_THRESHOLD:
            right_start = i
            break

    if max_bin == 2:
        bin_upper_bound.append(K_ZERO_THRESHOLD if left_cnt == 0 else -K_ZERO_THRESHOLD)
    elif max_bin >= 3:
        if left_cnt > 0:
            bin_upper_bound.append(-K_ZERO_THRESHOLD)
        if right_start >= 0:
            bin_upper_bound.append(K_ZERO_THRESHOLD)
    bin_upper_bound.append(math.inf)

    max_to_insert = max_bin - len(bin_upper_bound)
    num_inserted = 0
    for b in forced_upper_bounds:
        if num_inserted >= max_to_insert:
            break
        if abs(b) > K_ZERO_THRESHOLD:
            bin_upper_bound.append(b)
            num_inserted += 1
    bin_upper_bound.sort()

    free_bins = max_bin - len(bin_upper_bound)
    bounds_to_add: List[float] = []
    value_ind = 0
    n_fixed = len(bin_upper_bound)
    for i in range(n_fixed):
        cnt_in_bin = 0
        distinct_cnt_in_bin = 0
        bin_start = value_ind
        while value_ind < num_distinct_values and distinct_values[value_ind] < bin_upper_bound[i]:
            cnt_in_bin += counts[value_ind]
            distinct_cnt_in_bin += 1
            value_ind += 1
        bins_remaining = max_bin - n_fixed - len(bounds_to_add)
        num_sub_bins = round_int(cnt_in_bin * free_bins / total_sample_cnt) if total_sample_cnt else 0
        num_sub_bins = min(num_sub_bins, bins_remaining) + 1
        if i == n_fixed - 1:
            num_sub_bins = bins_remaining + 1
        if distinct_cnt_in_bin > 0:
            new_bounds = greedy_find_bin(distinct_values[bin_start:], counts[bin_start:],
                                         distinct_cnt_in_bin, num_sub_bins, cnt_in_bin,
                                         min_data_in_bin)
            bounds_to_add.extend(new_bounds[:-1])  # last is inf
    bin_upper_bound.extend(bounds_to_add)
    bin_upper_bound.sort()
    assert len(bin_upper_bound) <= max_bin
    return bin_upper_bound


class BinMapper:
    """Maps raw feature values to bins and back."""

    def __init__(self) -> None:
        self.num_bin = 1
        self.missing_type = MISSING_NONE
        self.bin_type = BIN_TYPE_NUMERICAL
        self.is_trivial = True
        self.sparse_rate = 1.0
        self.bin_upper_bound: np.ndarray = np.array([np.inf])
        self.categorical_2_bin: Dict[int, int] = {}
        self.bin_2_categorical: List[int] = []
        self.min_val = 0.0
        self.max_val = 0.0
        self.default_bin = 0
        self.most_freq_bin = 0

    # ------------------------------------------------------------------ build

    def find_bin(self, values: np.ndarray, total_sample_cnt: int, max_bin: int,
                 min_data_in_bin: int = 3, min_split_data: int = 0,
                 pre_filter: bool = False, bin_type: int = BIN_TYPE_NUMERICAL,
                 use_missing: bool = True, zero_as_missing: bool = False,
                 forced_upper_bounds: Sequence[float] = ()) -> None:
        """BinMapper::FindBin (bin.cpp:315-513) on a sampled value array.

        `values` holds the sampled non-zero values (zeros are implicit:
        total_sample_cnt - len(values) zeros), possibly with NaNs.
        """
        values = np.asarray(values, dtype=np.float64)
        num_sample_values = len(values)
        non_na = values[~np.isnan(values)]
        na_cnt = 0
        if not use_missing:
            self.missing_type = MISSING_NONE
        elif zero_as_missing:
            self.missing_type = MISSING_ZERO
        else:
            if len(non_na) == num_sample_values:
                self.missing_type = MISSING_NONE
            else:
                self.missing_type = MISSING_NAN
                na_cnt = num_sample_values - len(non_na)
        self.bin_type = bin_type
        self.default_bin = 0
        zero_cnt = int(total_sample_cnt - len(non_na) - na_cnt)

        # distinct values with zero folded in at the right place
        sorted_vals = np.sort(non_na, kind="stable")
        distinct_values: List[float] = []
        counts: List[int] = []
        if len(sorted_vals) == 0 or (sorted_vals[0] > 0.0 and zero_cnt > 0):
            distinct_values.append(0.0)
            counts.append(zero_cnt)
        if len(sorted_vals) > 0:
            distinct_values.append(float(sorted_vals[0]))
            counts.append(1)
        for i in range(1, len(sorted_vals)):
            prev, cur = float(sorted_vals[i - 1]), float(sorted_vals[i])
            if not _check_double_equal_ordered(prev, cur):
                if prev < 0.0 and cur > 0.0:
                    distinct_values.append(0.0)
                    counts.append(zero_cnt)
                distinct_values.append(cur)
                counts.append(1)
            else:
                distinct_values[-1] = cur
                counts[-1] += 1
        if len(sorted_vals) > 0 and sorted_vals[-1] < 0.0 and zero_cnt > 0:
            distinct_values.append(0.0)
            counts.append(zero_cnt)

        self.min_val = distinct_values[0]
        self.max_val = distinct_values[-1]
        num_distinct_values = len(distinct_values)
        cnt_in_bin: List[int] = []

        if bin_type == BIN_TYPE_NUMERICAL:
            if self.missing_type == MISSING_NAN:
                bounds = find_bin_with_zero_as_one_bin(
                    distinct_values, counts, num_distinct_values, max_bin - 1,
                    total_sample_cnt - na_cnt, min_data_in_bin) if not forced_upper_bounds else \
                    find_bin_with_predefined_bin(distinct_values, counts, num_distinct_values,
                                                 max_bin - 1, total_sample_cnt - na_cnt,
                                                 min_data_in_bin, forced_upper_bounds)
                bounds = list(bounds) + [math.nan]
            else:
                bounds = find_bin_with_zero_as_one_bin(
                    distinct_values, counts, num_distinct_values, max_bin,
                    total_sample_cnt, min_data_in_bin) if not forced_upper_bounds else \
                    find_bin_with_predefined_bin(distinct_values, counts, num_distinct_values,
                                                 max_bin, total_sample_cnt,
                                                 min_data_in_bin, forced_upper_bounds)
                if self.missing_type == MISSING_ZERO and len(bounds) == 2:
                    self.missing_type = MISSING_NONE
            self.bin_upper_bound = np.array(bounds, dtype=np.float64)
            self.num_bin = len(bounds)
            cnt_in_bin = [0] * self.num_bin
            i_bin = 0
            for i in range(num_distinct_values):
                while (i_bin < self.num_bin - 1 and
                       distinct_values[i] > self.bin_upper_bound[i_bin]):
                    i_bin += 1
                cnt_in_bin[i_bin] += counts[i]
            if self.missing_type == MISSING_NAN:
                cnt_in_bin[self.num_bin - 1] = na_cnt
            assert self.num_bin <= max_bin
        else:
            # categorical (bin.cpp:416-481)
            dv_int: List[int] = []
            cnt_int: List[int] = []
            for v, c in zip(distinct_values, counts):
                iv = int(v)
                if iv < 0:
                    na_cnt += c
                    Log.warning("Met negative value in categorical features, will convert it to NaN")
                else:
                    if not dv_int or iv != dv_int[-1]:
                        dv_int.append(iv)
                        cnt_int.append(c)
                    else:
                        cnt_int[-1] += c
            rest_cnt = total_sample_cnt - na_cnt
            if rest_cnt > 0:
                # sort by counts descending (stable)
                order = sorted(range(len(dv_int)), key=lambda i: -cnt_int[i])
                dv_int = [dv_int[i] for i in order]
                cnt_int = [cnt_int[i] for i in order]
                cut_cnt = round_int((total_sample_cnt - na_cnt) * 0.99)
                distinct_cnt = len(dv_int) + (1 if na_cnt > 0 else 0)
                max_bin = min(distinct_cnt, max_bin)
                self.bin_2_categorical = [-1]
                self.categorical_2_bin = {-1: 0}
                cnt_in_bin = [0]
                self.num_bin = 1
                used_cnt = 0
                cur = 0
                while cur < len(dv_int) and (used_cnt < cut_cnt or self.num_bin < max_bin):
                    if cnt_int[cur] < min_data_in_bin and cur > 1:
                        break
                    self.bin_2_categorical.append(dv_int[cur])
                    self.categorical_2_bin[dv_int[cur]] = self.num_bin
                    used_cnt += cnt_int[cur]
                    cnt_in_bin.append(cnt_int[cur])
                    self.num_bin += 1
                    cur += 1
                if cur == len(dv_int) and na_cnt == 0:
                    self.missing_type = MISSING_NONE
                else:
                    self.missing_type = MISSING_NAN
                cnt_in_bin[0] = int(total_sample_cnt - used_cnt)

        self.is_trivial = self.num_bin <= 1
        if not self.is_trivial and pre_filter and self._need_filter(
                cnt_in_bin, int(total_sample_cnt), min_split_data):
            self.is_trivial = True
        if not self.is_trivial:
            self.default_bin = self.value_to_bin(0.0)
            self.most_freq_bin = int(np.argmax(cnt_in_bin))
            max_sparse_rate = cnt_in_bin[self.most_freq_bin] / total_sample_cnt
            if self.most_freq_bin != self.default_bin and max_sparse_rate < K_SPARSE_THRESHOLD:
                self.most_freq_bin = self.default_bin
            self.sparse_rate = cnt_in_bin[self.most_freq_bin] / total_sample_cnt
        else:
            self.sparse_rate = 1.0

    def _need_filter(self, cnt_in_bin: List[int], total_cnt: int, filter_cnt: int) -> bool:
        """bin.cpp NeedFilter: no split can satisfy min counts on either side."""
        if self.bin_type == BIN_TYPE_NUMERICAL:
            sum_left = 0
            for i in range(len(cnt_in_bin) - 1):
                sum_left += cnt_in_bin[i]
                if sum_left >= filter_cnt and total_cnt - sum_left >= filter_cnt:
                    return False
            return True
        else:
            if len(cnt_in_bin) <= 2:
                for i in range(len(cnt_in_bin)):
                    sum_left = cnt_in_bin[i]
                    if sum_left >= filter_cnt and total_cnt - sum_left >= filter_cnt:
                        return False
                return True
            return False

    # ------------------------------------------------------------------ query

    def value_to_bin(self, value: float) -> int:
        """bin.h:612-650."""
        if isinstance(value, str):
            value = float(value)
        if math.isnan(value):
            if self.bin_type == BIN_TYPE_CATEGORICAL:
                return 0
            if self.missing_type == MISSING_NAN:
                return self.num_bin - 1
            value = 0.0
        if self.bin_type == BIN_TYPE_NUMERICAL:
            ub = self.bin_upper_bound
            hi = self.num_bin - 1 if self.missing_type == MISSING_NAN else self.num_bin
            lo, r = 0, hi - 1
            while lo < r:
                mid = (lo + r) // 2
                if value <= ub[mid]:
                    r = mid
                else:
                    lo = mid + 1
            return lo
        iv = int(value)
        return self.categorical_2_bin.get(iv, 0)

    def values_to_bins(self, values: np.ndarray) -> np.ndarray:
        """Vectorized ValueToBin over a column."""
        values = np.asarray(values, dtype=np.float64)
        if self.bin_type == BIN_TYPE_NUMERICAL:
            ub = self.bin_upper_bound
            n_search = self.num_bin - 1 if self.missing_type == MISSING_NAN else self.num_bin
            search_ub = ub[:n_search]
            vals = values.copy()
            nan_mask = np.isnan(vals)
            vals[nan_mask] = 0.0
            bins = np.searchsorted(search_ub, vals, side="left").astype(np.int32)
            bins = np.minimum(bins, n_search - 1)
            if self.missing_type == MISSING_NAN:
                bins[nan_mask] = self.num_bin - 1
            return bins
        out = np.zeros(len(values), dtype=np.int32)
        for i, v in enumerate(values):
            out[i] = 0 if math.isnan(v) else self.categorical_2_bin.get(int(v), 0)
        return out

    def bin_to_value(self, bin_idx: int) -> float:
        """Real threshold for a bin (BinMapper::BinToValue)."""
        if self.bin_type == BIN_TYPE_NUMERICAL:
            return float(self.bin_upper_bound[bin_idx])
        return float(self.bin_2_categorical[bin_idx])

    def bin_info_string(self) -> str:
        """feature_infos entry (bin.h:224-233)."""
        if self.is_trivial:
            return "none"
        if self.bin_type == BIN_TYPE_CATEGORICAL:
            return ":".join(str(c) for c in self.bin_2_categorical)
        return f"[{self.min_val!r}:{self.max_val!r}]"

    # -------------------------------------------------------------- serialize

    def to_dict(self) -> dict:
        return {
            "num_bin": self.num_bin,
            "missing_type": self.missing_type,
            "bin_type": self.bin_type,
            "is_trivial": self.is_trivial,
            "sparse_rate": self.sparse_rate,
            "bin_upper_bound": self.bin_upper_bound.tolist(),
            "bin_2_categorical": self.bin_2_categorical,
            "min_val": self.min_val,
            "max_val": self.max_val,
            "default_bin": self.default_bin,
            "most_freq_bin": self.most_freq_bin,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "BinMapper":
        m = cls()
        m.num_bin = d["num_bin"]
        m.missing_type = d["missing_type"]
        m.bin_type = d["bin_type"]
        m.is_trivial = d["is_trivial"]
        m.sparse_rate = d["sparse_rate"]
        m.bin_upper_bound = np.array(d["bin_upper_bound"], dtype=np.float64)
        m.bin_2_categorical = list(d["bin_2_categorical"])
        m.categorical_2_bin = {c: i for i, c in enumerate(m.bin_2_categorical)}
        m.min_val = d["min_val"]
        m.max_val = d["max_val"]
        m.default_bin = d["default_bin"]
        m.most_freq_bin = d["most_freq_bin"]
        return m
