"""Binned training dataset: host construction, device-resident bin matrix.

Counterpart of the reference Dataset/FeatureGroup/DatasetLoader
(include/LightGBM/dataset.h:487-1070, src/io/dataset.cpp,
src/io/dataset_loader.cpp), redesigned for TPU execution:

  * The reference stores column-major per-group Bin objects (dense 4/8/16/32
    bit, sparse delta-encoded) chosen per sparsity. On TPU the histogram
    kernel is a batched one-hot contraction on the MXU (ops/histogram.py), so
    the canonical layout is ONE dense packed matrix `bins[num_groups, N]`
    (uint8/uint16) resident in HBM — the analog of CUDARowData/CUDAColumnData
    (include/LightGBM/cuda/cuda_row_data.hpp) rather than the CPU bins.
  * Feature bundling (EFB, dataset.cpp:111-366) packs mutually-exclusive
    sparse features into one column; bundled features omit their default bin
    (reconstructed from leaf totals at split time, mirroring the reference's
    most_freq_bin/FixHistogram trick, dataset.h:770).
  * Bin mapping runs on host over a sample (DatasetLoader::ConstructFromSampleData,
    dataset_loader.cpp:600), then the whole matrix is binned vectorized and
    shipped to device once.

Construction entry points mirror the C-API surface: from numpy/CSR matrices
or text files (io/parser.py).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from .binning import (BIN_TYPE_CATEGORICAL, BIN_TYPE_NUMERICAL, BinMapper)
from .metadata import Metadata
from ..common import MISSING_NAN, MISSING_NONE, MISSING_ZERO
from ..config import Config
from ..utils.log import Log


class FeatureGroup:
    """One packed bin column — a single feature or an EFB bundle.

    Mirrors include/LightGBM/feature_group.h:26: per-member bin offsets within
    the group's bin range. For bundles (is_multi), each member's default bin is
    omitted; group bin 0 means "all members at default".
    """

    def __init__(self, feature_indices: List[int], mappers: List[BinMapper],
                 is_multi: bool) -> None:
        self.feature_indices = feature_indices
        self.mappers = mappers
        self.is_multi = is_multi
        if not is_multi:
            self.num_total_bin = mappers[0].num_bin
            self.bin_offsets = [0]
        else:
            # bundle: slot 0 = all-default; member j owns
            # [offset_j, offset_j + num_bin_j - 1) (its default bin removed)
            self.num_total_bin = 1
            self.bin_offsets = []
            for m in mappers:
                self.bin_offsets.append(self.num_total_bin)
                self.num_total_bin += m.num_bin - 1

    def bin_for_feature(self, member_idx: int, raw_bins: np.ndarray) -> np.ndarray:
        """Group-space bins for one member's per-feature bins."""
        if not self.is_multi:
            return raw_bins
        m = self.mappers[member_idx]
        off = self.bin_offsets[member_idx]
        out = np.zeros_like(raw_bins)
        nondef = raw_bins != m.default_bin
        # bins above the default shift down one slot (default removed)
        shifted = raw_bins - (raw_bins > m.default_bin).astype(raw_bins.dtype)
        out[nondef] = off + shifted[nondef]
        return out

    def feature_bin_range(self, member_idx: int) -> Tuple[int, int, int]:
        """(group_bin_lo, group_bin_hi, default_bin) for split translation."""
        if not self.is_multi:
            return 0, self.num_total_bin, -1
        m = self.mappers[member_idx]
        off = self.bin_offsets[member_idx]
        return off, off + m.num_bin - 1, m.default_bin


def _is_sparse(data) -> bool:
    """scipy.sparse duck-check (no hard scipy dependency)."""
    return hasattr(data, "tocsc") and hasattr(data, "nnz")


def _column(data, j: int) -> np.ndarray:
    """Dense f64 view of column j for ndarray or CSC input — sparse stays
    sparse end to end except for one transient column at a time. Slice
    syntax (not getcol) so both spmatrix and the newer sparse-array classes
    (csc_array has no getcol) work."""
    if _is_sparse(data):
        return data[:, [j]].toarray().ravel().astype(np.float64)
    return data[:, j]


def _sample_for_binning(col: np.ndarray, sample_cnt: int, rng: np.random.RandomState) -> Tuple[np.ndarray, int]:
    """Sample values (keeping NaNs, dropping zeros implicitly like the
    reference's sparse sample push) for bin finding."""
    n = len(col)
    if n > sample_cnt:
        idx = rng.choice(n, sample_cnt, replace=False)
        sample = col[idx]
        total = sample_cnt
    else:
        sample = col
        total = n
    nonzero = sample[(sample != 0) | np.isnan(sample)]
    return nonzero, total


def find_feature_groups(mappers: List[BinMapper], sample_nonzero: List[np.ndarray],
                        sample_total: int, used_features: List[int],
                        max_conflict_rate: float, enable_bundle: bool,
                        max_bin_per_group: int = 256) -> List[List[int]]:
    """Exclusive Feature Bundling — greedy conflict-bounded grouping.

    Behavioral counterpart of GetConflictCount/FindGroups (dataset.cpp:64-249):
    features are visited in descending non-zero count; each joins the first
    existing bundle whose accumulated conflicts stay under
    max_conflict_rate * sample_total, else starts a new bundle. Conflicts are
    computed on boolean non-default masks over the binning sample.
    """
    if not enable_bundle or len(used_features) <= 1:
        return [[f] for f in used_features]
    dense: List[int] = []
    sparse_feats: List[int] = []
    for f in used_features:
        # bundling only pays for sparse features; dense ones keep own groups
        if mappers[f].sparse_rate >= 0.8 and mappers[f].bin_type == BIN_TYPE_NUMERICAL:
            sparse_feats.append(f)
        else:
            dense.append(f)
    if len(sparse_feats) <= 1:
        return [[f] for f in used_features]
    order = sorted(sparse_feats, key=lambda f: -len(sample_nonzero[f]))
    max_conflicts = int(max_conflict_rate * sample_total)
    groups: List[List[int]] = []
    group_masks: List[np.ndarray] = []
    group_conflicts: List[int] = []
    group_bins: List[int] = []
    for f in order:
        mask = sample_nonzero[f]
        nnz = int(mask.sum())
        placed = False
        for gi in range(len(groups)):
            if group_bins[gi] + mappers[f].num_bin - 1 > max_bin_per_group:
                continue
            conflict = int(np.count_nonzero(group_masks[gi] & mask))
            if group_conflicts[gi] + conflict <= max_conflicts:
                groups[gi].append(f)
                group_masks[gi] |= mask
                group_conflicts[gi] += conflict
                group_bins[gi] += mappers[f].num_bin - 1
                placed = True
                break
        if not placed:
            groups.append([f])
            group_masks.append(mask.copy())
            group_conflicts.append(0)
            group_bins.append(1 + mappers[f].num_bin - 1)
    out = [[f] for f in dense]
    out.extend(g for g in groups)
    # keep original feature order inside each bundle for determinism
    for g in out:
        g.sort()
    return out


class Dataset:
    """Binned dataset (internal core — the Python-facing wrapper with lazy
    construction lives in basic.py).

    Public state after construction:
      bins          : np.ndarray [num_groups, num_data] uint8/uint16
      groups        : List[FeatureGroup]
      feature_to_group : feature idx -> (group idx, member idx)
      mappers       : per-original-feature BinMapper
      metadata      : Metadata
    """

    def __init__(self, config: Optional[Config] = None) -> None:
        self.config = config or Config()
        self.num_data = 0
        self.num_total_features = 0
        self.mappers: List[BinMapper] = []
        self.groups: List[FeatureGroup] = []
        self.feature_to_group: Dict[int, Tuple[int, int]] = {}
        self.used_features: List[int] = []
        self.bins: Optional[np.ndarray] = None
        self.metadata = Metadata()
        self.feature_names: List[str] = []
        self.monotone_constraints: List[int] = []
        self._reference: Optional["Dataset"] = None

    # ------------------------------------------------------------ construction

    @classmethod
    def from_matrix(cls, data: np.ndarray, label=None, weight=None, group=None,
                    init_score=None, position=None,
                    config: Optional[Config] = None,
                    categorical_feature: Sequence[int] = (),
                    feature_names: Optional[Sequence[str]] = None,
                    reference: Optional["Dataset"] = None) -> "Dataset":
        config = config or Config()
        self = cls(config)
        if _is_sparse(data):
            # sparse path (DatasetLoader::ConstructFromSampleData with CSR
            # input, c_api.cpp LGBM_DatasetCreateFromCSR): keep the matrix
            # CSC and densify ONE COLUMN at a time — peak memory is the
            # uint8 bin matrix + a single f64 column, never the dense raw
            data = data.tocsc()
            if data.dtype not in (np.float32, np.float64):
                data = data.astype(np.float64)
        else:
            data = np.asarray(data)
            if data.dtype not in (np.float32, np.float64):
                data = data.astype(np.float64)
        n, f = data.shape
        self.num_data = n
        self.num_total_features = f
        self.metadata = Metadata(n)
        if label is not None:
            self.metadata.set_label(label)
        if weight is not None:
            self.metadata.set_weights(weight)
        if group is not None:
            self.metadata.set_query(group)
        if init_score is not None:
            self.metadata.set_init_score(init_score)
        if position is not None:
            self.metadata.set_positions(position)
        self.feature_names = (list(feature_names) if feature_names
                              else [f"Column_{i}" for i in range(f)])

        if reference is not None:
            # validation set: share the training BinMappers and group layout
            # (DatasetLoader::LoadFromFileAlignWithOtherDataset semantics)
            self._align_with(reference, data)
            return self

        group_lists = self._fit_layout(data, categorical_feature)
        self._build_groups_and_bins(group_lists, data)
        return self

    def _fit_layout(self, data, categorical_feature: Sequence[int] = ()
                    ) -> List[List[int]]:
        """Fit the bin layout (per-feature BinMappers, used features, EFB
        group lists) from `data` WITHOUT binning any rows. Split out of
        from_matrix so the streaming ingest path (streaming/ingest.py) can
        fit on a buffered sample prefix and then bin arbitrary row blocks
        through _bin_rows — concatenated block bins are identical to a
        one-shot construction over the same layout."""
        config = self.config
        n, f = data.shape
        self.num_total_features = f
        rng = np.random.RandomState(config.data_random_seed)
        sample_cnt = min(config.bin_construct_sample_cnt, n)
        cat_set = set(int(c) for c in categorical_feature)

        if config.max_bin_by_feature:
            # reference hard-checks these (dataset.cpp:416-420)
            if len(config.max_bin_by_feature) != f:
                Log.fatal("Size of max_bin_by_feature should be equal to max_feature_idx + 1")
            if min(config.max_bin_by_feature) <= 1:
                Log.fatal("max_bin_by_feature should be greater than 1")

        self.mappers = []
        sample_nonzero_masks: List[np.ndarray] = []
        sample_idx = (rng.choice(n, sample_cnt, replace=False)
                      if n > sample_cnt else np.arange(n))
        # forcedbins_filename (DatasetLoader::GetForcedBins,
        # src/io/dataset_loader.cpp): JSON [{"feature", "bin_upper_bound"}]
        forced_by_feature: Dict[int, List[float]] = {}
        if config.forcedbins_filename:
            import json as _json

            try:
                with open(config.forcedbins_filename) as fh:
                    for entry in _json.load(fh):
                        forced_by_feature[int(entry["feature"])] = [
                            float(v) for v in entry["bin_upper_bound"]]
            except OSError:
                Log.warning("Could not open %s", config.forcedbins_filename)
        for j in range(f):
            col = _column(data, j)[sample_idx]
            nonzero = col[(col != 0) | np.isnan(col)]
            mapper = BinMapper()
            bt = BIN_TYPE_CATEGORICAL if j in cat_set else BIN_TYPE_NUMERICAL
            mb = config.max_bin
            if config.max_bin_by_feature and j < len(config.max_bin_by_feature):
                mb = config.max_bin_by_feature[j]
            mapper.find_bin(nonzero, len(col), mb,
                            min_data_in_bin=config.min_data_in_bin,
                            min_split_data=config.min_data_in_leaf,
                            pre_filter=config.feature_pre_filter,
                            bin_type=bt,
                            use_missing=config.use_missing,
                            zero_as_missing=config.zero_as_missing,
                            forced_upper_bounds=forced_by_feature.get(j, ()))
            self.mappers.append(mapper)
            sample_nonzero_masks.append((col != 0) & ~np.isnan(col))

        self.used_features = [j for j in range(f) if not self.mappers[j].is_trivial]
        if not self.used_features:
            Log.warning("There are no meaningful features which satisfy "
                        "the provided configuration. Decreasing Dataset parameters "
                        "min_data_in_bin or min_data_in_leaf and re-constructing "
                        "Dataset might resolve this warning.")

        group_lists = find_feature_groups(
            self.mappers, sample_nonzero_masks, len(sample_idx),
            self.used_features, self.config.max_conflict_rate if hasattr(self.config, "max_conflict_rate") else 0.0,
            enable_bundle=self.config.enable_bundle)
        return group_lists

    def _build_groups_and_bins(self, group_lists: List[List[int]], data: np.ndarray) -> None:
        self._make_groups(group_lists)
        self.bins = self._bin_rows(data)

    def _make_groups(self, group_lists: List[List[int]]) -> None:
        """Materialize FeatureGroups + the feature->(group, member) map from
        fitted mappers; row-count independent (no bins touched)."""
        self.groups = []
        self.feature_to_group = {}
        for gi, feats in enumerate(group_lists):
            fg = FeatureGroup(feats, [self.mappers[j] for j in feats],
                              is_multi=len(feats) > 1)
            self.groups.append(fg)
            for mi, j in enumerate(feats):
                self.feature_to_group[j] = (gi, mi)

    def bins_dtype(self) -> np.dtype:
        max_bins = max((g.num_total_bin for g in self.groups), default=1)
        return np.dtype(np.uint8 if max_bins <= 256 else np.uint16)

    def _bin_rows(self, data) -> np.ndarray:
        """Bin an arbitrary row matrix against the FITTED layout into a
        [num_groups, n_rows] plane. Binning is per-row independent, so
        concatenating per-block planes equals one one-shot plane exactly —
        the invariant streaming ingest relies on."""
        n_rows = data.shape[0]
        dtype = self.bins_dtype()
        bins = np.zeros((len(self.groups), n_rows), dtype=dtype)
        for gi, fg in enumerate(self.groups):
            if not fg.is_multi:
                j = fg.feature_indices[0]
                bins[gi] = self.mappers[j].values_to_bins(
                    _column(data, j)).astype(dtype)
            else:
                acc = np.zeros(n_rows, dtype=np.int32)
                for mi, j in enumerate(fg.feature_indices):
                    raw = self.mappers[j].values_to_bins(_column(data, j))
                    gb = fg.bin_for_feature(mi, raw)
                    # exclusivity: at most one member non-default per row;
                    # on conflict the later feature wins (matches bundle
                    # push order semantics)
                    acc = np.where(gb != 0, gb, acc)
                bins[gi] = acc.astype(dtype)
        return bins

    @classmethod
    def from_layout(cls, layout: "Dataset", bins: np.ndarray, num_data: int,
                    label=None, weight=None, group=None, init_score=None,
                    position=None,
                    feature_names: Optional[Sequence[str]] = None) -> "Dataset":
        """Assemble a Dataset from a fitted layout prototype plus a
        pre-binned plane (streaming ingest: RowBlockStore.finalize). The
        layout's mappers/groups are shared, not copied."""
        self = cls(layout.config)
        self.num_data = int(num_data)
        self.num_total_features = layout.num_total_features
        self.mappers = layout.mappers
        self.groups = layout.groups
        self.feature_to_group = layout.feature_to_group
        self.used_features = layout.used_features
        self.monotone_constraints = list(layout.monotone_constraints)
        self.bins = bins
        self.metadata = Metadata(self.num_data)
        if label is not None:
            self.metadata.set_label(label)
        if weight is not None:
            self.metadata.set_weights(weight)
        if group is not None:
            self.metadata.set_query(group)
        if init_score is not None:
            self.metadata.set_init_score(init_score)
        if position is not None:
            self.metadata.set_positions(position)
        self.feature_names = (list(feature_names) if feature_names
                              else [f"Column_{i}"
                                    for i in range(self.num_total_features)])
        return self

    @classmethod
    def load_binary(cls, path: str,
                    config: Optional[Config] = None) -> "Dataset":
        """Rebuild a constructed Dataset from a save_binary npz cache
        (DatasetLoader::LoadFromBinFile analog): bins, mappers, groups, and
        metadata restore directly — no re-parse, no bin finding."""
        import json as _json

        from .binning import BinMapper

        z = np.load(path, allow_pickle=False)
        self = cls(config)
        self.bins = z["bins"]
        self.num_data = int(self.bins.shape[1])
        self.num_total_features = int(z["num_total_features"])
        self.mappers = [BinMapper.from_dict(d)
                        for d in _json.loads(str(z["mappers"]))]
        self.feature_names = _json.loads(str(z["feature_names"]))
        self.used_features = _json.loads(str(z["used_features"]))
        self.monotone_constraints = _json.loads(str(z["monotone"]))
        group_lists = _json.loads(str(z["group_lists"]))
        group_multi = _json.loads(str(z["group_is_multi"]))
        self.groups = []
        self.feature_to_group = {}
        for gi, (feats, multi) in enumerate(zip(group_lists, group_multi)):
            fg = FeatureGroup(feats, [self.mappers[f] for f in feats], multi)
            self.groups.append(fg)
            for mi, f in enumerate(feats):
                self.feature_to_group[f] = (gi, mi)
        self.metadata = Metadata(self.num_data)
        if z["label"].size:
            self.metadata.set_label(z["label"])
        if z["weight"].size:
            self.metadata.set_weights(z["weight"])
        if z["init_score"].size:
            self.metadata.set_init_score(z["init_score"])
        if z["query_boundaries"].size:
            qb = np.asarray(z["query_boundaries"], dtype=np.int32)
            self.metadata.query_boundaries = qb
        if z["positions"].size:
            self.metadata.positions = np.asarray(z["positions"], np.int32)
            self.metadata.position_ids = z["position_ids"]
        raw = z["raw"]
        self._loaded_raw = raw if raw.size else None  # single npz read
        return self

    def _align_with(self, reference: "Dataset", data: np.ndarray) -> None:
        self._reference = reference
        self.mappers = reference.mappers
        self.used_features = reference.used_features
        self.monotone_constraints = reference.monotone_constraints
        group_lists = [g.feature_indices for g in reference.groups]
        self._build_groups_and_bins(group_lists, data)

    # ---------------------------------------------------------------- queries

    @property
    def num_features(self) -> int:
        return len(self.used_features)

    @property
    def num_groups(self) -> int:
        return len(self.groups)

    def group_bin_counts(self) -> np.ndarray:
        return np.array([g.num_total_bin for g in self.groups], dtype=np.int32)

    def feature_num_bin(self, feature: int) -> int:
        return self.mappers[feature].num_bin

    def feature_infos(self) -> List[str]:
        return [m.bin_info_string() for m in self.mappers]

    def real_threshold(self, feature: int, bin_threshold: int) -> float:
        """Bin-space threshold -> raw-value threshold for the model tree."""
        return self.mappers[feature].bin_to_value(bin_threshold)

    def subset(self, indices: np.ndarray) -> "Dataset":
        out = Dataset(self.config)
        out.num_data = len(indices)
        out.num_total_features = self.num_total_features
        out.mappers = self.mappers
        out.groups = self.groups
        out.feature_to_group = self.feature_to_group
        out.used_features = self.used_features
        out.bins = self.bins[:, indices]
        out.metadata = self.metadata.subset(indices)
        out.feature_names = self.feature_names
        out.monotone_constraints = self.monotone_constraints
        return out

    # ------------------------------------------------- reference hist (tests)

    def construct_histogram_np(self, group: int, grad: np.ndarray, hess: np.ndarray,
                               row_indices: Optional[np.ndarray] = None) -> np.ndarray:
        """Numpy reference histogram [(num_total_bin), 3] for one group —
        the oracle the device kernels are tested against."""
        fg = self.groups[group]
        bins = self.bins[group]
        if row_indices is not None:
            bins = bins[row_indices]
            grad = grad[row_indices]
            hess = hess[row_indices]
        hist = np.zeros((fg.num_total_bin, 3), dtype=np.float64)
        np.add.at(hist[:, 0], bins, grad)
        np.add.at(hist[:, 1], bins, hess)
        np.add.at(hist[:, 2], bins, 1.0)
        return hist
