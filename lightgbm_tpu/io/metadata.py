"""Training metadata: labels, weights, init scores, query groups, positions.

Counterpart of the reference Metadata (include/LightGBM/dataset.h:48-397,
src/io/metadata.cpp): owns the per-row side information used by objectives,
metrics and the ranking machinery. Host numpy arrays; device copies are made
by the trainer once per run.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..utils.log import Log


class Metadata:
    def __init__(self, num_data: int = 0) -> None:
        self.num_data = num_data
        self.label: Optional[np.ndarray] = None  # float32 [N]
        self.weights: Optional[np.ndarray] = None  # float32 [N]
        self.init_score: Optional[np.ndarray] = None  # float64 [N * num_class]
        self.query_boundaries: Optional[np.ndarray] = None  # int32 [num_queries + 1]
        self.query_weights: Optional[np.ndarray] = None  # float32 [num_queries]
        self.positions: Optional[np.ndarray] = None  # int32 [N] (position-debiased ranking)
        self.position_ids: Optional[np.ndarray] = None

    # ------------------------------------------------------------------- sets

    def set_label(self, label) -> None:
        label = np.asarray(label, dtype=np.float32).ravel()
        if self.num_data and len(label) != self.num_data:
            Log.fatal("Length of label is not same with #data")
        self.num_data = len(label)
        self.label = label

    def set_weights(self, weights) -> None:
        if weights is None:
            self.weights = None
            return
        weights = np.asarray(weights, dtype=np.float32).ravel()
        if self.num_data and len(weights) != self.num_data:
            Log.fatal("Length of weights is not same with #data")
        if np.any(weights < 0):
            Log.fatal("Weights should be non-negative")
        self.weights = weights
        self._update_query_weights()

    def set_init_score(self, init_score) -> None:
        if init_score is None:
            self.init_score = None
            return
        self.init_score = np.asarray(init_score, dtype=np.float64).ravel(order="F")

    def set_query(self, group) -> None:
        """`group` is per-query sizes (like the reference .query files)."""
        if group is None:
            self.query_boundaries = None
            return
        group = np.asarray(group, dtype=np.int64).ravel()
        bounds = np.zeros(len(group) + 1, dtype=np.int32)
        np.cumsum(group, out=bounds[1:])
        if self.num_data and bounds[-1] != self.num_data:
            Log.fatal("Sum of query counts is not same with #data")
        self.query_boundaries = bounds
        self._update_query_weights()

    def set_positions(self, positions) -> None:
        if positions is None:
            self.positions = None
            return
        positions = np.asarray(positions)
        uniq, inv = np.unique(positions, return_inverse=True)
        self.position_ids = uniq
        self.positions = inv.astype(np.int32)

    def _update_query_weights(self) -> None:
        """metadata.cpp: query weight = mean of member weights."""
        if self.weights is None or self.query_boundaries is None:
            self.query_weights = None
            return
        nq = len(self.query_boundaries) - 1
        qw = np.zeros(nq, dtype=np.float32)
        for q in range(nq):
            lo, hi = self.query_boundaries[q], self.query_boundaries[q + 1]
            qw[q] = self.weights[lo:hi].mean() if hi > lo else 0.0
        self.query_weights = qw

    # ------------------------------------------------------------------ query

    @property
    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1

    def subset(self, indices: np.ndarray) -> "Metadata":
        out = Metadata(len(indices))
        if self.label is not None:
            out.label = self.label[indices]
        if self.weights is not None:
            out.weights = self.weights[indices]
        if self.init_score is not None:
            ns = len(self.init_score) // max(self.num_data, 1)
            mat = self.init_score.reshape(ns, self.num_data)
            out.init_score = mat[:, indices].ravel()
        if self.positions is not None:
            out.positions = self.positions[indices]
            out.position_ids = self.position_ids
        # query structure is not preserved under arbitrary row subsets
        out._update_query_weights()
        return out
