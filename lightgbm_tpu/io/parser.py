"""Text data parsers: CSV / TSV / LibSVM with format autodetection.

Counterpart of the reference Parser factory (include/LightGBM/dataset.h:401-482,
src/io/parser.cpp): detects the delimiter/format from the first data lines,
handles `label_column` (index or `name:` prefix), headers, ignore columns, and
the side files the CLI consumes (`<data>.weight`, `<data>.query` /
`<data>.group`, `<data>.position` — dataset_loader.cpp metadata loading).

Parsing happens on host with numpy; the result feeds Dataset.from_matrix.
A native (C++) fast-path parser for large files lives in native/ and is used
automatically when built.
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..utils.log import Log


def _detect_format(sample_lines: List[str]) -> Tuple[str, str]:
    """Returns (kind, delimiter) with kind in {csv, tsv, libsvm}."""
    for line in sample_lines:
        if not line.strip():
            continue
        tokens = line.replace("\t", " ").split()
        colon_tokens = sum(1 for t in tokens[1:] if ":" in t)
        if tokens and colon_tokens and colon_tokens >= max(1, (len(tokens) - 1) // 2):
            return "libsvm", " "
        if "\t" in line:
            return "tsv", "\t"
        if "," in line:
            return "csv", ","
        return "csv", " "
    return "csv", "\t"


def _is_number(tok: str) -> bool:
    try:
        float(tok)
        return True
    except ValueError:
        return tok.lower() in ("nan", "inf", "-inf", "na", "")


def _native_parse_dense(path: str, delim: str,
                        skip_header: int) -> Optional[np.ndarray]:
    """C++ fast path (native/parser.cpp); None -> caller falls back."""
    from ..native import get_parser

    native = get_parser()
    if native is None:
        return None
    try:
        buf, nrows, ncols = native.parse_dense(
            path, 0 if delim == " " else ord(delim), int(skip_header))
    except Exception:  # noqa: BLE001 - malformed file: numpy fallback
        return None
    if nrows == 0 or ncols == 0:
        return None
    return np.frombuffer(buf, dtype=np.float64).reshape(nrows, ncols)


def parse_file(path: str, header: bool = False, label_column: str = "0",
               ignore_columns: Sequence = (), max_rows: Optional[int] = None
               ) -> Tuple[np.ndarray, np.ndarray, List[str]]:
    """Parse a data file -> (X [N,F] float64, y [N] float64, feature_names).

    label_column follows the reference convention: an integer index into the
    file's columns, or "name:<colname>" with header=True. The label column is
    removed from X (Parser label_idx handling, parser.cpp).
    """
    with open(path) as fh:
        first = []
        for _ in range(3):
            line = fh.readline()
            if not line:
                break
            first.append(line.rstrip("\n"))
    kind, delim = _detect_format(first[1:] if header else first)

    names: List[str] = []
    if kind == "libsvm":
        return _parse_libsvm(path, header)

    skip = 1 if header else 0
    if header and first:
        names = [t.strip() for t in first[0].split(delim)]

    label_idx = 0
    if isinstance(label_column, str) and label_column.startswith("name:"):
        want = label_column[5:]
        if want not in names:
            Log.fatal("Could not find label column %s in data file", want)
        label_idx = names.index(want)
    elif label_column not in (None, ""):
        label_idx = int(label_column)

    raw = None
    if max_rows is None:
        raw = _native_parse_dense(path, delim, skip)
    if raw is None:
        raw = np.genfromtxt(path, delimiter=delim if delim != " " else None,
                            skip_header=skip, dtype=np.float64,
                            max_rows=max_rows, loose=True, invalid_raise=False)
    if raw.ndim == 1:
        raw = raw.reshape(-1, 1)
    ncol = raw.shape[1]

    ignore = set()
    for c in ignore_columns:
        if isinstance(c, str) and c.startswith("name:"):
            for nm in c[5:].split(","):
                if nm in names:
                    ignore.add(names.index(nm))
        else:
            ignore.add(int(c))

    y = raw[:, label_idx].copy() if label_idx >= 0 else np.zeros(len(raw))
    keep = [c for c in range(ncol) if c != label_idx and c not in ignore]
    X = raw[:, keep]
    if names:
        feature_names = [names[c] for c in keep]
    else:
        feature_names = [f"Column_{i}" for i in range(len(keep))]
    return X, y, feature_names


def _parse_libsvm(path: str, header: bool) -> Tuple[np.ndarray, np.ndarray, List[str]]:
    from ..native import get_parser

    native = get_parser()
    if native is not None:
        try:
            lab_buf, tri_buf, max_feat = native.parse_libsvm(path, int(header))
            y = np.frombuffer(lab_buf, dtype=np.float64).copy()
            trips = np.frombuffer(tri_buf, dtype=np.float64).reshape(-1, 3)
            X = np.zeros((len(y), int(max_feat) + 1), dtype=np.float64)
            X[trips[:, 0].astype(np.int64), trips[:, 1].astype(np.int64)] = \
                trips[:, 2]
            names = [f"Column_{i}" for i in range(int(max_feat) + 1)]
            return X, y, names
        except Exception:  # noqa: BLE001 - fall back to the python path
            pass
    rows: List[dict] = []
    labels: List[float] = []
    max_feat = -1
    with open(path) as fh:
        if header:
            fh.readline()
        for line in fh:
            toks = line.split()
            if not toks:
                continue
            labels.append(float(toks[0]))
            feats = {}
            for t in toks[1:]:
                if ":" not in t:
                    continue
                k, v = t.split(":", 1)
                k = int(k)
                feats[k] = float(v)
                max_feat = max(max_feat, k)
            rows.append(feats)
    X = np.zeros((len(rows), max_feat + 1), dtype=np.float64)
    for i, feats in enumerate(rows):
        for k, v in feats.items():
            X[i, k] = v
    names = [f"Column_{i}" for i in range(max_feat + 1)]
    return X, np.asarray(labels), names


def load_side_file(data_path: str, suffixes: Sequence[str], dtype) -> Optional[np.ndarray]:
    """Load `<data>.weight` / `<data>.query` style side files if present."""
    for suf in suffixes:
        p = data_path + suf
        if os.path.exists(p):
            return np.loadtxt(p, dtype=dtype).ravel()
    return None


def load_query_boundaries(data_path: str) -> Optional[np.ndarray]:
    return load_side_file(data_path, [".query", ".group"], np.int64)


def load_weights(data_path: str) -> Optional[np.ndarray]:
    return load_side_file(data_path, [".weight"], np.float64)


def load_positions(data_path: str) -> Optional[np.ndarray]:
    return load_side_file(data_path, [".position"], np.int64)
