"""Multi-process training launcher (the torchrun/dask-analog orchestrator).

The reference ships parallel orchestration through its socket machinery plus
external wrappers (Dask in python-package/lightgbm/dask.py, MPI via mpirun);
the TPU-native equivalent is one JAX process per host joined through
`jax.distributed`. This launcher covers the single-machine multi-process
case (simulating a multi-host cluster, or driving multiple local
accelerator processes):

    python -m lightgbm_tpu.launch -n 4 -- config=train.conf

spawns 4 worker processes with JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
JAX_PROCESS_ID set; each worker runs the normal CLI (lightgbm_tpu.cli), and
parallel/dist.py picks the env vars up in init_distributed. For a REAL
multi-host pod, run the same CLI once per host with those env vars (or a
machine-list conf) instead.

The gang is *supervised* (parallel/elastic.py): the moment one worker exits
nonzero or misses its liveness deadline, every sibling is reaped — a dead
rank must not leave the rest blocked in jax.distributed barriers forever.
With ``--elastic``, the launcher then relaunches the gang up to
``--max-restarts`` times, resuming from the newest valid
``output_model.snapshot_iter_<k>`` (arm ``snapshot_freq`` for that). The
restart keeps the SAME world size by default — the lost rank is respawned,
so the resumed run is bit-identical to an undisturbed one; pass
``--allow-shrink`` to instead continue at the surviving world size (see
docs/ROBUSTNESS.md, "Distributed fault domain", for what that trades away).
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import tempfile
from typing import List

from .parallel.elastic import GangSupervisor, latest_snapshot, worker_env


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _output_model(cli_args: List[str]) -> str:
    # config.py kv2map: first occurrence wins — mirror that here
    for a in cli_args:
        if a.startswith("output_model="):
            return a.split("=", 1)[1]
    return "LightGBM_model.txt"


def main(argv: List[str] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.launch",
        description="Spawn N jax.distributed worker processes running the "
                    "lightgbm_tpu CLI")
    parser.add_argument("-n", "--nproc", type=int, default=2,
                        help="number of worker processes")
    parser.add_argument("--port", type=int, default=0,
                        help="coordinator port (default: pick a free one)")
    parser.add_argument("--devices-per-proc", type=int, default=0,
                        help="force N virtual CPU devices per process "
                             "(local simulation)")
    parser.add_argument("--elastic", action="store_true",
                        help="relaunch the gang after a worker loss, "
                             "resuming from the newest snapshot")
    parser.add_argument("--max-restarts", type=int, default=2,
                        help="elastic relaunch budget (default 2)")
    parser.add_argument("--allow-shrink", action="store_true",
                        help="elastic restarts drop to the surviving world "
                             "size instead of respawning the lost rank")
    parser.add_argument("--liveness-timeout", type=float, default=0.0,
                        help="reap the gang when a worker's liveness file "
                             "goes stale this many seconds (0 = off)")
    parser.add_argument("--gang-dir", default=None,
                        help="directory for per-rank liveness files "
                             "(default: a fresh temp dir)")
    parser.add_argument("cli_args", nargs=argparse.REMAINDER,
                        help="arguments forwarded to lightgbm_tpu.cli "
                             "(prefix with --)")
    args = parser.parse_args(argv)
    cli_args = [a for a in args.cli_args if a != "--"]
    out_model = _output_model(cli_args)
    gang_dir = args.gang_dir or tempfile.mkdtemp(prefix="lgbm_gang_")

    # per-attempt state: each relaunch needs a fresh coordinator port (the
    # old one can sit in TIME_WAIT) and, past attempt 0, a resume arg
    attempt_state = {}

    def _attempt_args(attempt: int) -> tuple:
        if attempt in attempt_state:
            return attempt_state[attempt]
        port = (args.port or _free_port()) if attempt == 0 else _free_port()
        aargs = list(cli_args)
        if attempt > 0:
            snap = latest_snapshot(out_model)
            # kv2map takes the FIRST occurrence: strip any caller-supplied
            # input_model before appending the resume point
            aargs = [a for a in aargs if not a.startswith("input_model=")]
            if snap:
                aargs.append(f"input_model={snap}")
            else:
                print(f"launch: no valid snapshot beside {out_model}; "
                      "elastic restart retrains from scratch",
                      file=sys.stderr)
        attempt_state[attempt] = (port, aargs)
        return attempt_state[attempt]

    def spawn(world: int, rank: int, attempt: int) -> subprocess.Popen:
        port, aargs = _attempt_args(attempt)
        env = worker_env(port=port, world=world, rank=rank, attempt=attempt,
                         gang_dir=gang_dir, elastic=args.elastic,
                         devices_per_proc=args.devices_per_proc)
        return subprocess.Popen(
            [sys.executable, "-m", "lightgbm_tpu.cli", *aargs], env=env)

    sup = GangSupervisor(
        spawn, args.nproc, elastic=args.elastic,
        max_restarts=args.max_restarts, allow_shrink=args.allow_shrink,
        liveness_timeout_s=args.liveness_timeout, gang_dir=gang_dir)
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())
