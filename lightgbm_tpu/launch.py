"""Multi-process training launcher (the torchrun/dask-analog orchestrator).

The reference ships parallel orchestration through its socket machinery plus
external wrappers (Dask in python-package/lightgbm/dask.py, MPI via mpirun);
the TPU-native equivalent is one JAX process per host joined through
`jax.distributed`. This launcher covers the single-machine multi-process
case (simulating a multi-host cluster, or driving multiple local
accelerator processes):

    python -m lightgbm_tpu.launch -n 4 -- config=train.conf

spawns 4 worker processes with JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
JAX_PROCESS_ID set; each worker runs the normal CLI (lightgbm_tpu.cli), and
parallel/dist.py picks the env vars up in init_distributed. For a REAL
multi-host pod, run the same CLI once per host with those env vars (or a
machine-list conf) instead.
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
from typing import List


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main(argv: List[str] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.launch",
        description="Spawn N jax.distributed worker processes running the "
                    "lightgbm_tpu CLI")
    parser.add_argument("-n", "--nproc", type=int, default=2,
                        help="number of worker processes")
    parser.add_argument("--port", type=int, default=0,
                        help="coordinator port (default: pick a free one)")
    parser.add_argument("--devices-per-proc", type=int, default=0,
                        help="force N virtual CPU devices per process "
                             "(local simulation)")
    parser.add_argument("cli_args", nargs=argparse.REMAINDER,
                        help="arguments forwarded to lightgbm_tpu.cli "
                             "(prefix with --)")
    args = parser.parse_args(argv)
    cli_args = [a for a in args.cli_args if a != "--"]
    port = args.port or _free_port()

    procs = []
    for pid in range(args.nproc):
        env = dict(os.environ)
        env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["JAX_NUM_PROCESSES"] = str(args.nproc)
        env["JAX_PROCESS_ID"] = str(pid)
        if args.devices_per_proc:
            env["JAX_PLATFORMS"] = "cpu"
            flags = env.get("XLA_FLAGS", "")
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                f"{args.devices_per_proc}").strip()
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "lightgbm_tpu.cli", *cli_args], env=env))
    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    return rc


if __name__ == "__main__":
    sys.exit(main())
