from .registry import create_metric, Metric, METRIC_REGISTRY

__all__ = ["create_metric", "Metric", "METRIC_REGISTRY"]
