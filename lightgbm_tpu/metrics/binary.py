"""Binary metrics — counterpart of src/metric/binary_metric.hpp: logloss,
error rate, AUC, average precision. AUC/AP are device sort-based (jnp.argsort
then a weighted rank accumulation) — the analog of the reference's sorted-scan
(binary_metric.hpp AUCMetric::Eval)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import Metric, register_metric


@register_metric("binary_logloss", "binary")
class BinaryLoglossMetric(Metric):
    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self._is_pos = jnp.asarray((metadata.label > 0).astype(np.float32))
        self._w = (jnp.asarray(metadata.weights) if metadata.weights is not None else None)
        self._sumw = (float(np.sum(metadata.weights)) if metadata.weights is not None
                      else float(num_data))

    def eval(self, score, objective):
        prob = objective.convert_output(score) if objective is not None else score
        eps = 1e-15
        prob = jnp.clip(prob, eps, 1.0 - eps)
        loss = -(self._is_pos * jnp.log(prob) + (1.0 - self._is_pos) * jnp.log(1.0 - prob))
        if self._w is not None:
            loss = loss * self._w
        return [float(jnp.sum(loss)) / self._sumw]


@register_metric("binary_error")
class BinaryErrorMetric(BinaryLoglossMetric):
    def eval(self, score, objective):
        prob = objective.convert_output(score) if objective is not None else score
        pred_pos = prob > 0.5
        err = (pred_pos.astype(jnp.float32) != self._is_pos).astype(jnp.float32)
        if self._w is not None:
            err = err * self._w
        return [float(jnp.sum(err)) / self._sumw]


def _auc(score, is_pos, weights):
    """Weighted AUC via ranks: for each positive, count the fraction of
    negatives scored below it (ties get half credit)."""
    order = jnp.argsort(score)
    s = score[order]
    y = is_pos[order]
    w = weights[order] if weights is not None else jnp.ones_like(s)
    wneg = w * (1.0 - y)
    wpos = w * y
    cum_neg = jnp.cumsum(wneg)  # negatives with score <= s_i (inclusive)
    # tie handling: within equal-score runs use (neg_below + neg_tied/2)
    # compute run boundaries
    neg_below_excl = cum_neg - wneg
    # for ties: segment by equal score values
    is_new = jnp.concatenate([jnp.array([True]), s[1:] > s[:-1]])
    seg_id = jnp.cumsum(is_new.astype(jnp.int32)) - 1
    n_seg = s.shape[0]
    seg_neg = jax.ops.segment_sum(wneg, seg_id, num_segments=n_seg)
    seg_cum = jnp.cumsum(seg_neg)
    neg_in_seg = seg_neg[seg_id]
    neg_before_seg = seg_cum[seg_id] - neg_in_seg
    credit = neg_before_seg + 0.5 * neg_in_seg
    total_pos = jnp.sum(wpos)
    total_neg = jnp.sum(wneg)
    auc = jnp.sum(wpos * credit) / jnp.maximum(total_pos * total_neg, 1e-30)
    return auc


@register_metric("auc")
class AUCMetric(Metric):
    greater_is_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self._is_pos = jnp.asarray((metadata.label > 0).astype(np.float32))
        self._w = (jnp.asarray(metadata.weights) if metadata.weights is not None else None)

    def eval(self, score, objective):
        return [float(_auc(score, self._is_pos, self._w))]


@register_metric("average_precision")
class AveragePrecisionMetric(AUCMetric):
    def eval(self, score, objective):
        order = jnp.argsort(-score)
        y = self._is_pos[order]
        w = self._w[order] if self._w is not None else jnp.ones_like(y)
        wpos = w * y
        cum_pos = jnp.cumsum(wpos)
        cum_all = jnp.cumsum(w)
        precision = cum_pos / jnp.maximum(cum_all, 1e-30)
        total_pos = jnp.maximum(jnp.sum(wpos), 1e-30)
        return [float(jnp.sum(precision * wpos) / total_pos)]
