"""Multiclass metrics — counterpart of src/metric/multiclass_metric.hpp:
multi_logloss, multi_error (with multi_error_top_k), auc_mu."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import Metric, register_metric


class _MulticlassBase(Metric):
    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self._label = jnp.asarray(metadata.label.astype(np.int32))
        self._w = (jnp.asarray(metadata.weights) if metadata.weights is not None else None)
        self._sumw = (float(np.sum(metadata.weights)) if metadata.weights is not None
                      else float(num_data))

    def _probs(self, score, objective):
        # score [C, N] -> probabilities [N, C]
        if objective is not None:
            return objective.convert_output(score.T)
        return jax.nn.softmax(score.T, axis=-1)


@register_metric("multi_logloss", "multiclass", "softmax")
class MultiLoglossMetric(_MulticlassBase):
    def eval(self, score, objective):
        p = self._probs(score, objective)
        eps = 1e-15
        rows = jnp.arange(p.shape[0])
        loss = -jnp.log(jnp.clip(p[rows, self._label], eps, 1.0))
        if self._w is not None:
            loss = loss * self._w
        return [float(jnp.sum(loss)) / self._sumw]


@register_metric("multi_error")
class MultiErrorMetric(_MulticlassBase):
    def eval(self, score, objective):
        p = self._probs(score, objective)
        top_k = max(self.config.multi_error_top_k, 1)
        rows = jnp.arange(p.shape[0])
        true_p = p[rows, self._label]
        # correct if the true class prob is within the top k (ties count,
        # matching MultiErrorMetric::PointWiseLossCalculator)
        rank = jnp.sum(p > true_p[:, None], axis=1)
        correct = (rank < top_k).astype(jnp.float32)
        err = 1.0 - correct
        if self._w is not None:
            err = err * self._w
        return [float(jnp.sum(err)) / self._sumw]


@register_metric("auc_mu")
class AucMuMetric(_MulticlassBase):
    greater_is_better = True

    def eval(self, score, objective):
        """AUC-mu (Kleiman & Page 2019) — mean pairwise class separability
        (multiclass_metric.hpp auc_mu; uniform partition weights unless
        auc_mu_weights given)."""
        p = np.asarray(self._probs(score, objective))
        label = np.asarray(self._label)
        w = np.asarray(self._w) if self._w is not None else np.ones(len(label))
        C = p.shape[1]
        W = np.ones((C, C))
        if self.config.auc_mu_weights:
            W = np.asarray(self.config.auc_mu_weights, dtype=np.float64).reshape(C, C)
        total = 0.0
        count = 0
        for a in range(C):
            for b in range(a + 1, C):
                ia = label == a
                ib = label == b
                if not ia.any() or not ib.any():
                    continue
                va = p[ia, a] - p[ia, b]
                vb = p[ib, a] - p[ib, b]
                wa, wb = w[ia], w[ib]
                order = np.argsort(np.concatenate([va, vb]), kind="stable")
                y = np.concatenate([np.ones(len(va)), np.zeros(len(vb))])[order]
                ww = np.concatenate([wa, wb])[order]
                cum_neg = np.cumsum(ww * (1 - y))
                auc_num = float(np.sum(ww * y * cum_neg))
                denom = float(np.sum(wa) * np.sum(wb))
                if denom > 0:
                    total += W[a, b] * auc_num / denom
                    count += 1
        return [total / max(count, 1)]
