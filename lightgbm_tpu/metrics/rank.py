"""Ranking metrics: NDCG@k and MAP@k.

Counterpart of src/metric/rank_metric.hpp (NDCGMetric with eval_at positions,
DCGCalculator + label-gain table, per-query parallel evaluation, query-weight
support; queries with no relevant docs count as 1.0) and src/metric/
map_metric.hpp (MapMetric).

Device design: queries use the same padded [Q, L] bucket layout as the
ranking objectives; a bucket's NDCG@k for all its queries is one jitted
sort + gather + masked dot.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import Metric, register_metric
from ..objectives.rank import QueryLayout, default_label_gain, max_dcg_at_k


class _RankMetricBase(Metric):
    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            from ..utils.log import Log

            Log.fatal("The NDCG metric requires query information")
        self.layout = QueryLayout(metadata.query_boundaries, metadata.label, num_data)
        self.query_weights = metadata.query_weights
        self.eval_at = [int(k) for k in (self.config.eval_at or [1, 2, 3, 4, 5])]


@register_metric("ndcg")
class NDCGMetric(_RankMetricBase):
    greater_is_better = True

    @property
    def name(self):
        return [f"ndcg@{k}" for k in self.eval_at]

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        gains = (np.array(self.config.label_gain, dtype=np.float64)
                 if self.config.label_gain else default_label_gain())
        self.gains = gains
        self._gain_dev = jnp.asarray(gains, dtype=jnp.float32)
        qb = metadata.query_boundaries
        label = metadata.label
        # per (query, k): 1/maxDCG@k ; 0 marks "no relevant docs" -> ndcg 1
        inv = np.zeros((self.layout.num_queries, len(self.eval_at)))
        for q in range(self.layout.num_queries):
            srt = np.sort(label[qb[q]: qb[q + 1]])[::-1]
            for j, k in enumerate(self.eval_at):
                mx = max_dcg_at_k(srt, k, gains)
                inv[q, j] = 1.0 / mx if mx > 0 else 0.0
        for b in self.layout.buckets:
            b["ndcg_inv"] = jnp.asarray(inv[b["qids"]], dtype=jnp.float32)
        self._fns = {}

    def _bucket_fn(self, L: int, ks: tuple):
        key = (L, ks)
        if key in self._fns:
            return self._fns[key]
        gains = self._gain_dev

        def bucket(score_ext, doc_idx, lab, valid, inv):
            s = jnp.where(valid, score_ext[doc_idx], -jnp.inf)
            order = jnp.argsort(-s, axis=1, stable=True)
            ls = jnp.take_along_axis(lab, order, axis=1)
            vs = jnp.take_along_axis(valid, order, axis=1)
            g = jnp.where(vs, gains[ls.astype(jnp.int32)], 0.0)
            disc = 1.0 / jnp.log2(jnp.arange(L) + 2.0)
            out = []
            for j, k in enumerate(ks):
                mask = jnp.arange(L) < k
                dcg = jnp.sum(g * disc * mask, axis=1)
                ndcg = jnp.where(inv[:, j] > 0, dcg * inv[:, j], 1.0)
                out.append(ndcg)
            return jnp.stack(out, axis=1)  # [Qb, n_ks]

        fn = jax.jit(bucket)
        self._fns[key] = fn
        return fn

    def eval(self, score, objective):
        ks = tuple(self.eval_at)
        totals = np.zeros(len(ks))
        sumw = 0.0
        for b in self.layout.buckets:
            fn = self._bucket_fn(b["L"], ks)
            score_ext = jnp.concatenate([score, jnp.zeros(1, score.dtype)])
            ndcgs = np.asarray(fn(score_ext, b["doc_idx"], b["labels"],
                                  b["valid"], b["ndcg_inv"]))
            if self.query_weights is not None:
                w = self.query_weights[b["qids"]]
                totals += (ndcgs * w[:, None]).sum(axis=0)
                sumw += w.sum()
            else:
                totals += ndcgs.sum(axis=0)
                sumw += len(b["qids"])
        return [float(t / max(sumw, 1e-20)) for t in totals]


@register_metric("map", "mean_average_precision")
class MapMetric(_RankMetricBase):
    greater_is_better = True

    @property
    def name(self):
        return [f"map@{k}" for k in self.eval_at]

    def eval(self, score, objective):
        """MAP@k per map_metric.hpp: labels > 0 are relevant."""
        ks = self.eval_at
        totals = np.zeros(len(ks))
        sumw = 0.0
        score_np = np.asarray(score)
        for b in self.layout.buckets:
            doc = np.asarray(b["doc_idx"])
            lab = np.asarray(b["labels"])
            valid = np.asarray(b["valid"])
            s = np.where(valid, score_np[np.minimum(doc, len(score_np) - 1)], -np.inf)
            order = np.argsort(-s, axis=1, kind="stable")
            rel = np.take_along_axis((lab > 0) & valid, order, axis=1)
            cum_rel = np.cumsum(rel, axis=1)
            prec = cum_rel / (np.arange(rel.shape[1]) + 1.0)
            w = (self.query_weights[b["qids"]] if self.query_weights is not None
                 else np.ones(len(b["qids"])))
            for j, k in enumerate(ks):
                ap_num = (prec[:, :k] * rel[:, :k]).sum(axis=1)
                denom = np.minimum(cum_rel[:, -1], k)
                ap = np.where(denom > 0, ap_num / np.maximum(denom, 1), 1.0)
                totals[j] += (ap * w).sum()
            sumw += w.sum()
        return [float(t / max(sumw, 1e-20)) for t in totals]
