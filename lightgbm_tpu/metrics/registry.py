"""Metric interface + factory.

Counterpart of Metric (include/LightGBM/metric.h:24-60) and its factory
(src/metric/metric.cpp:21-120). Metrics evaluate device score arrays; the
objective's ConvertOutput is applied where the reference does.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Type

from ..config import Config
from ..io.metadata import Metadata
from ..utils.log import Log

METRIC_REGISTRY: Dict[str, Type] = {}


def register_metric(*names: str):
    def deco(cls):
        for n in names:
            METRIC_REGISTRY[n] = cls
        cls.names = names
        return cls

    return deco


class Metric:
    """Base: Init + Eval(score, objective) -> list of (name, value)."""

    greater_is_better = False

    def __init__(self, config: Config) -> None:
        self.config = config
        self.metadata: Optional[Metadata] = None
        self.num_data = 0

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.metadata = metadata
        self.num_data = num_data

    def eval(self, score, objective) -> List[float]:
        raise NotImplementedError

    @property
    def name(self) -> List[str]:
        return [self.names[0]]

    @property
    def factor_to_bigger_better(self) -> float:
        return 1.0 if self.greater_is_better else -1.0


def create_metric(name: str, config: Config) -> Optional[Metric]:
    from . import regression, binary, multiclass, rank, xentropy  # noqa: F401

    if name in ("custom", "none", "null", "na", ""):
        return None
    cls = METRIC_REGISTRY.get(name)
    if cls is None:
        Log.warning("Unknown metric type name: %s", name)
        return None
    return cls(config)
