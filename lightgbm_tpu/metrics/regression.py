"""Regression metrics — counterpart of src/metric/regression_metric.hpp."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .registry import Metric, register_metric


class _PointwiseRegression(Metric):
    """Weighted mean of a pointwise loss over converted outputs."""

    convert = True  # apply objective.convert_output first

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self._label = jnp.asarray(metadata.label, dtype=jnp.float32)
        self._w = (jnp.asarray(metadata.weights) if metadata.weights is not None else None)
        self._sumw = (float(np.sum(metadata.weights)) if metadata.weights is not None
                      else float(num_data))

    def point_loss(self, pred, label):
        raise NotImplementedError

    def transform(self, avg):
        return avg

    def eval(self, score, objective):
        pred = score
        if self.convert and objective is not None:
            pred = objective.convert_output(score)
        losses = self.point_loss(pred, self._label)
        if self._w is not None:
            losses = losses * self._w
        return [self.transform(float(jnp.sum(losses)) / self._sumw)]


@register_metric("l2", "mean_squared_error", "mse", "regression", "regression_l2")
class L2Metric(_PointwiseRegression):
    def point_loss(self, pred, label):
        return (pred - label) ** 2


@register_metric("rmse", "root_mean_squared_error", "l2_root")
class RMSEMetric(L2Metric):
    def transform(self, avg):
        return float(np.sqrt(avg))


@register_metric("l1", "mean_absolute_error", "mae", "regression_l1")
class L1Metric(_PointwiseRegression):
    def point_loss(self, pred, label):
        return jnp.abs(pred - label)


@register_metric("quantile")
class QuantileMetric(_PointwiseRegression):
    def point_loss(self, pred, label):
        alpha = self.config.alpha
        delta = label - pred
        return jnp.where(delta < 0, (alpha - 1.0) * delta, alpha * delta)


@register_metric("huber")
class HuberMetric(_PointwiseRegression):
    def point_loss(self, pred, label):
        alpha = self.config.alpha
        diff = jnp.abs(pred - label)
        return jnp.where(diff <= alpha, 0.5 * diff * diff,
                         alpha * (diff - 0.5 * alpha))


@register_metric("fair")
class FairMetric(_PointwiseRegression):
    def point_loss(self, pred, label):
        c = self.config.fair_c
        x = jnp.abs(pred - label)
        return c * x - c * c * jnp.log1p(x / c)


@register_metric("poisson")
class PoissonMetric(_PointwiseRegression):
    def point_loss(self, pred, label):
        eps = 1e-10
        pred = jnp.maximum(pred, eps)
        return pred - label * jnp.log(pred)


@register_metric("mape", "mean_absolute_percentage_error")
class MAPEMetric(_PointwiseRegression):
    def point_loss(self, pred, label):
        return jnp.abs((label - pred) / jnp.maximum(1.0, jnp.abs(label)))


@register_metric("gamma")
class GammaMetric(_PointwiseRegression):
    def point_loss(self, pred, label):
        psi = 1.0
        theta = -1.0 / jnp.maximum(pred, 1e-10)
        a = psi
        b = -jnp.log(-theta)
        c = 1.0 / psi * jnp.log(label / psi) - jnp.log(label) - 0.0
        return -((label * theta - b) / a + c)


@register_metric("gamma_deviance")
class GammaDevianceMetric(_PointwiseRegression):
    def point_loss(self, pred, label):
        epsilon = 1e-9
        tmp = label / (pred + epsilon)
        return tmp - jnp.log(tmp) - 1.0

    def transform(self, avg):
        return avg * 2.0 * self._sumw / self._sumw  # deviance uses sum*2

    def eval(self, score, objective):
        pred = score
        if objective is not None:
            pred = objective.convert_output(score)
        losses = self.point_loss(pred, self._label)
        if self._w is not None:
            losses = losses * self._w
        return [float(jnp.sum(losses)) * 2.0]


@register_metric("tweedie")
class TweedieMetric(_PointwiseRegression):
    def point_loss(self, pred, label):
        rho = self.config.tweedie_variance_power
        eps = 1e-10
        pred = jnp.maximum(pred, eps)
        a = label * jnp.exp((1.0 - rho) * jnp.log(pred)) / (1.0 - rho)
        b = jnp.exp((2.0 - rho) * jnp.log(pred)) / (2.0 - rho)
        return -a + b


@register_metric("r2")
class R2Metric(_PointwiseRegression):
    greater_is_better = True

    def eval(self, score, objective):
        pred = score
        if objective is not None:
            pred = objective.convert_output(score)
        label = self._label
        w = self._w if self._w is not None else jnp.ones_like(label)
        mean = jnp.sum(label * w) / jnp.sum(w)
        ss_res = jnp.sum(w * (label - pred) ** 2)
        ss_tot = jnp.sum(w * (label - mean) ** 2)
        return [float(1.0 - ss_res / jnp.maximum(ss_tot, 1e-30))]
