"""Cross-entropy metrics — counterpart of src/metric/xentropy_metric.hpp:
cross_entropy, cross_entropy_lambda, kullback_leibler."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .registry import Metric, register_metric

K_EPS = 1e-12


def _xent(label, prob):
    p = jnp.clip(prob, K_EPS, 1.0 - K_EPS)
    return -(label * jnp.log(p) + (1.0 - label) * jnp.log(1.0 - p))


class _XentMetricBase(Metric):
    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self._label = jnp.asarray(metadata.label, dtype=jnp.float32)
        self._w = (jnp.asarray(metadata.weights) if metadata.weights is not None else None)
        self._sumw = (float(np.sum(metadata.weights)) if metadata.weights is not None
                      else float(num_data))


@register_metric("cross_entropy", "xentropy")
class CrossEntropyMetric(_XentMetricBase):
    def eval(self, score, objective):
        prob = objective.convert_output(score) if objective is not None else \
            1.0 / (1.0 + jnp.exp(-score))
        loss = _xent(self._label, prob)
        if self._w is not None:
            loss = loss * self._w
        return [float(jnp.sum(loss)) / self._sumw]


@register_metric("cross_entropy_lambda", "xentlambda")
class CrossEntropyLambdaMetric(_XentMetricBase):
    def eval(self, score, objective):
        # z = 1 - exp(-w * log1p(exp(score))) — xentropy_metric.hpp xentlambda
        hhat = jnp.log1p(jnp.exp(score))
        w = self._w if self._w is not None else 1.0
        z = jnp.clip(1.0 - jnp.exp(-w * hhat), K_EPS, 1.0 - K_EPS)
        loss = -(self._label * jnp.log(z) + (1.0 - self._label) * jnp.log(1.0 - z))
        return [float(jnp.sum(loss)) / self._sumw]


@register_metric("kullback_leibler", "kldiv")
class KullbackLeiblerMetric(_XentMetricBase):
    def eval(self, score, objective):
        prob = objective.convert_output(score) if objective is not None else \
            1.0 / (1.0 + jnp.exp(-score))
        y = jnp.clip(self._label, K_EPS, 1.0 - K_EPS)
        # KL(y || p) = xent(y, p) - entropy(y)
        ent = -(y * jnp.log(y) + (1.0 - y) * jnp.log(1.0 - y))
        loss = _xent(self._label, prob) - ent
        if self._w is not None:
            loss = loss * self._w
        return [float(jnp.sum(loss)) / self._sumw]
