"""DART boosting: Dropouts meet Multiple Additive Regression Trees.

Counterpart of src/boosting/dart.hpp:23-211. Per iteration a random subset of
existing trees is dropped (uniform or weighted by tree weight, capped by
max_drop, skipped entirely with probability skip_drop); the new tree is fit
to gradients of the dropped score; then dropped + new trees are renormalized
(standard mode: new weight lr/(k+1), dropped shrink by k/(k+1);
xgboost_dart_mode: lr/(lr+k) and k/(lr+k)).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from .gbdt import GBDT, K_EPSILON


class DART(GBDT):
    def __init__(self, config, train_set, objective, train_raw=None) -> None:
        super().__init__(config, train_set, objective, train_raw)
        self._drop_rng = np.random.RandomState(config.drop_seed)
        self.tree_weight = []  # per-iteration weights (non-uniform drop)
        self.sum_weight = 0.0
        self.drop_index = []
        self._dropped_this_iter = False

    def prepare_training_score(self) -> None:
        """Drop once per iteration, before any gradient reads the score —
        custom objectives hit this via Booster.update (dart.hpp:78-88)."""
        if not self._dropped_this_iter:
            self._dropping_trees()
            self._dropped_this_iter = True

    def train_one_iter(self, gradients: Optional[np.ndarray] = None,
                       hessians: Optional[np.ndarray] = None) -> bool:
        self.prepare_training_score()
        self._dropped_this_iter = False  # re-arm for the next iteration
        stop = super().train_one_iter(gradients, hessians)
        if stop:
            return True
        self._normalize()
        if not self.config.uniform_drop:
            self.tree_weight.append(self.shrinkage_rate)
            self.sum_weight += self.shrinkage_rate
        return False

    # ------------------------------------------------------------- internals

    def _dropping_trees(self) -> None:
        cfg = self.config
        C = self.num_tree_per_iteration
        self.drop_index = []
        is_skip = self._drop_rng.rand() < cfg.skip_drop
        if not is_skip and self.iter_ > 0:
            drop_rate = cfg.drop_rate
            if not cfg.uniform_drop:
                if self.sum_weight > 0:
                    inv_avg = len(self.tree_weight) / self.sum_weight
                    if cfg.max_drop > 0:
                        drop_rate = min(
                            drop_rate, cfg.max_drop * inv_avg / self.sum_weight)
                    for i in range(self.iter_):
                        if (self._drop_rng.rand()
                                < drop_rate * self.tree_weight[i] * inv_avg):
                            self.drop_index.append(i)
                            if 0 < cfg.max_drop <= len(self.drop_index):
                                break
            else:
                if cfg.max_drop > 0:
                    drop_rate = min(drop_rate, cfg.max_drop / float(self.iter_))
                for i in range(self.iter_):
                    if self._drop_rng.rand() < drop_rate:
                        self.drop_index.append(i)
                        if 0 < cfg.max_drop <= len(self.drop_index):
                            break
        # remove dropped trees from the TRAIN score only (valid scores are
        # fixed up during Normalize, matching dart.hpp:131-137)
        for i in self.drop_index:
            for c in range(C):
                tree = self.models[i * C + c]
                tree.shrink(-1.0)
                self._add_tree_to_train_score(tree, c)
        k = float(len(self.drop_index))
        if not cfg.xgboost_dart_mode:
            self.shrinkage_rate = cfg.learning_rate / (1.0 + k)
        else:
            self.shrinkage_rate = (
                cfg.learning_rate if not self.drop_index
                else cfg.learning_rate / (cfg.learning_rate + k))

    def _normalize(self) -> None:
        cfg = self.config
        C = self.num_tree_per_iteration
        k = float(len(self.drop_index))
        for i in self.drop_index:
            for c in range(C):
                tree = self.models[i * C + c]
                if not cfg.xgboost_dart_mode:
                    # tree weight ends at old_weight * k/(k+1) (dart.hpp:149-158)
                    tree.shrink(1.0 / (k + 1.0))
                    self._update_valid_scores(tree, c)
                    tree.shrink(-k)
                    self._add_tree_to_train_score(tree, c)
                else:
                    tree.shrink(self.shrinkage_rate)
                    self._update_valid_scores(tree, c)
                    tree.shrink(-k / cfg.learning_rate)
                    self._add_tree_to_train_score(tree, c)
            if not cfg.uniform_drop:
                if not cfg.xgboost_dart_mode:
                    self.sum_weight -= self.tree_weight[i] * (1.0 / (k + 1.0))
                    self.tree_weight[i] *= k / (k + 1.0)
                else:
                    self.sum_weight -= self.tree_weight[i] * (
                        1.0 / (k + cfg.learning_rate))
                    self.tree_weight[i] *= k / (k + cfg.learning_rate)
        self._packed_cache = None
