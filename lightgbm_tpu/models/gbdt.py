"""GBDT boosting driver.

Counterpart of GBDT (src/boosting/gbdt.cpp): gradient boosting loop with
boost-from-average, per-class tree training, leaf-value renewal, shrinkage,
train/valid score maintenance, eval, and model export. The TrainOneIter
control flow mirrors gbdt.cpp:352-460 (init-score handling, constant trees,
should_continue semantics); score updates are device scatter-adds over the
partition's per-leaf index sets (the CUDAScoreUpdater analog).
"""
from __future__ import annotations

import os
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry
from ..config import Config
from ..health import create_monitor
from ..io.dataset import Dataset
from ..metrics import create_metric
from ..objectives import ObjectiveFunction
from ..ops.partition import bucket_size, pad_indices
from ..ops.predict import (PredictorCache, pack_ensemble, predict_dtype,
                           predict_raw, predict_raw_streamed,
                           stream_chunk_rows)
from ..ops.score import add_tree_to_score
from ..parallel import elastic
from ..treelearner import create_tree_learner
from ..utils import faults, sanitize
from ..utils.log import Log
from ..utils.timer import global_timer
from .sample_strategy import DeviceBag, create_sample_strategy
from .serialize import GBDTModel
from .tree import Tree

K_EPSILON = 1e-15


def _pack_gh(grad: jax.Array, hess: jax.Array) -> jax.Array:
    """[N] grad/hess -> [N+1, 3] with count channel and zero sentinel row."""
    gh = jnp.stack([grad, hess, jnp.ones_like(grad)], axis=1)
    return jnp.concatenate([gh, jnp.zeros((1, 3), gh.dtype)], axis=0)


# score is donated: the caller replaces it with the returned array, so XLA
# updates the [N] vector in place instead of double buffering it.
@partial(jax.jit, static_argnames=("num_leaves",), donate_argnums=(0,))
def _apply_split_log_to_score(score: jax.Array, rec_store: jax.Array,
                              leaf_ids: jax.Array, rate: jax.Array,
                              num_leaves: int) -> jax.Array:
    """Tree-t score update straight from the DEVICE split log — the async
    pipeline's replacement for the host-side leaf-value gather, applied
    before the log ever reaches the host.

    rec_store rows are [leaf, parent_output, depth, valid] + SPLIT_FIELDS;
    valid row t re-splits leaf `rec[0]` (left child keeps the id, right
    child becomes leaf t+1), so replaying left_output/right_output (store
    cols 14/15) into a leaf-value table reproduces tree.leaf_value exactly.
    Rows past the first invalid row are all-zero (valid == 0) and write to
    the dump slot. The f32 multiply by `rate` is bit-identical to the host
    path's f64 shrink + f32 cast whenever rate is exactly representable in
    f32 — _async_enabled gates on that. A stub tree (no valid rows) yields
    an all-zero table: the update is exactly a no-op."""
    L = num_leaves

    def body(t, lv):
        row = rec_store[t]
        valid = row[3] > 0.5
        wb = jnp.where(valid, row[0].astype(jnp.int32), L)
        wn = jnp.where(valid, t + 1, L)
        return lv.at[wb].set(row[14]).at[wn].set(row[15])

    lv = jax.lax.fori_loop(0, rec_store.shape[0], body,
                           jnp.zeros(L + 1, jnp.float32))
    lv = lv[:L] * rate
    return score + jnp.where(
        leaf_ids >= 0, lv[jnp.clip(leaf_ids, 0, L - 1)], 0.0)


def _colocate(arr: jax.Array, ref: jax.Array) -> jax.Array:
    """Move `arr` onto `ref`'s device when the two live on different device
    sets. The mesh-sharded tree learner hands back outputs spanning the whole
    mesh while the score vector lives on one device; jit refuses to mix the
    two. device_put here is an async transfer — it overlaps the host replay
    just like the copy_to_host_async pulls."""
    if not (isinstance(arr, jax.Array) and isinstance(ref, jax.Array)):
        return arr
    if not arr.is_fully_addressable:
        # multi-process mesh output: this process only holds its shards, so
        # device_put cannot assemble the value — allgather the global array
        # across the gang (every rank calls this in lockstep each iteration)
        from jax.experimental import multihost_utils

        host = multihost_utils.process_allgather(arr, tiled=True)
        return jax.device_put(jnp.asarray(host),
                              next(iter(ref.sharding.device_set)))
    if arr.sharding.device_set != ref.sharding.device_set:
        return jax.device_put(arr, next(iter(ref.sharding.device_set)))
    return arr


class _ValidData:
    """Holds one validation set's device raw matrix, metadata, score."""

    def __init__(self, dataset: Dataset, raw: np.ndarray, metrics) -> None:
        self.dataset = dataset
        self.raw = jnp.asarray(raw, dtype=jnp.float32)
        self.metrics = metrics
        self.score: Optional[jax.Array] = None


class GBDT:
    """The training driver. One instance per Booster."""

    def __init__(self, config: Config, train_set: Optional[Dataset],
                 objective: Optional[ObjectiveFunction],
                 train_raw: Optional[np.ndarray] = None) -> None:
        self.config = config
        self.train_set = train_set
        self.objective = objective
        self.iter_ = 0
        self.models: List[Tree] = []
        self.best_iteration = 0
        self.average_output = False  # RF sets True (rf.hpp)
        self.shrinkage_rate = config.learning_rate
        self.num_class = max(config.num_class, 1)
        if objective is not None:
            self.num_tree_per_iteration = objective.num_model_per_iteration
        else:
            self.num_tree_per_iteration = self.num_class if self.num_class > 1 else 1
        self.class_need_train = [True] * self.num_tree_per_iteration
        if objective is not None and hasattr(objective, "class_need_train"):
            pass  # resolved after objective.init (below)
        self._predictor = PredictorCache()
        self.valid_sets: List[_ValidData] = []
        self.valid_names: List[str] = []
        # async per-tree pipeline state (device learner only): the pending
        # handle of the last dispatched tree, finalized one iteration later
        self._pending = None
        self._async_stub_stop = False
        # numerical-health guardrails (None unless health_check_policy set)
        self._health = create_monitor(config)

        if train_set is not None:
            n = train_set.num_data
            self.num_data = n
            if objective is not None:
                objective.init(train_set.metadata, n)
                if hasattr(objective, "class_need_train"):
                    self.class_need_train = [
                        objective.class_need_train(c)
                        for c in range(self.num_tree_per_iteration)]
            self.tree_learner = create_tree_learner(
                config.tree_learner, config.device_type, config, train_set)
            self.sample_strategy = create_sample_strategy(
                config, n, train_set.metadata, self.num_tree_per_iteration)
            self._cur_bag: Optional[np.ndarray] = None
            self.train_metrics = [m for m in
                                  (create_metric(name, config) for name in config.metric)
                                  if m is not None]
            for m in self.train_metrics:
                m.init(train_set.metadata, n)
            # scores [C, N]
            self.score = jnp.zeros((self.num_tree_per_iteration, n), dtype=jnp.float32)
            init = train_set.metadata.init_score
            self._has_init_score = init is not None
            if self._has_init_score:
                self.score = jnp.asarray(
                    np.asarray(init, dtype=np.float32).reshape(
                        self.num_tree_per_iteration, n))
            if objective is None:
                self._grad_fn = None
            elif objective.jit_gradients:
                self._grad_fn = jax.jit(self._compute_gh)
            else:
                self._grad_fn = self._compute_gh
            self.train_raw = train_raw

    # ------------------------------------------------------------------ valid

    def add_valid(self, valid: Dataset, raw: np.ndarray, name: str) -> None:
        metrics = [m for m in (create_metric(nm, self.config) for nm in self.config.metric)
                   if m is not None]
        for m in metrics:
            m.init(valid.metadata, valid.num_data)
        vd = _ValidData(valid, raw, metrics)
        vd.score = jnp.zeros((self.num_tree_per_iteration, valid.num_data),
                             dtype=jnp.float32)
        if valid.metadata.init_score is not None:
            vd.score = jnp.asarray(np.asarray(valid.metadata.init_score, dtype=np.float32)
                                   .reshape(self.num_tree_per_iteration, valid.num_data))
        self.valid_sets.append(vd)
        self.valid_names.append(name)

    # --------------------------------------------------------------- boosting

    def _compute_gh(self, score):
        """score [N] (C==1) or [C, N] -> (grad, hess) matching shapes — the
        whole-iteration gradient pass (kept unpacked so the sample strategy
        can rescale GOSS's small-gradient rows before packing)."""
        return self.objective.get_gradients(score)

    def prepare_training_score(self) -> None:
        """Hook run before custom gradients read the training score
        (GetTrainingScore, boosting.h); DART drops trees here."""

    def boost_from_average(self, class_id: int) -> float:
        """gbdt.cpp:327-350."""
        if (not self.models and not self._has_init_score
                and self.objective is not None and self.config.boost_from_average):
            init = self.objective.boost_from_score(class_id)
            if abs(init) > K_EPSILON:
                self.score = self.score.at[class_id].add(init)
                for vd in self.valid_sets:
                    vd.score = vd.score.at[class_id].add(init)
                Log.info("Start training from score %f", init)
                return init
        return 0.0

    # --------------------------------------------------- async tree pipeline

    def _async_enabled(self) -> bool:
        """Eligibility gate for the async per-tree pipeline: the device
        learner's train_async/finalize split overlaps tree t's on-device
        growth with the host replay of tree t-1. Every condition below
        protects BIT-IDENTICAL semantics with the sync path:

        * plain GBDT, one tree per iteration, no linear leaves — subclasses
          (DART/RF) reorder score updates around training;
        * DeviceTreeLearner, unquantized — quantized renewal rewrites leaf
          values after replay and reads per-tree host state;
        * objective present with the BASE renew_tree_output no-op (L1-style
          objectives refit leaf values on the host before the score update);
        * the learning rate is exactly representable in f32, so the device
          f32 (leaf * rate) equals the host f64 shrink + f32 cast bit for
          bit. LGBM_TPU_ASYNC=1 forces the pipeline on regardless of the
          rate; LGBM_TPU_ASYNC=0 disables it."""
        env = os.environ.get("LGBM_TPU_ASYNC", "")
        if env == "0":
            return False
        from ..treelearner.device import DeviceTreeLearner

        learner = getattr(self, "tree_learner", None)
        if not isinstance(learner, DeviceTreeLearner) or learner.quantized:
            return False
        if type(self) is not GBDT:
            return False
        if self.num_tree_per_iteration != 1 or self.config.linear_tree:
            return False
        if not self.class_need_train[0] or self.train_set.num_features <= 0:
            return False
        obj = self.objective
        if obj is None or (type(obj).renew_tree_output
                           is not ObjectiveFunction.renew_tree_output):
            return False
        if env == "1":
            return True
        rate = float(self.shrinkage_rate)
        return float(np.float32(rate)) == rate

    def _flush_pending(self) -> None:
        """Finalize the in-flight tree, if any: replay its split log into
        the placeholder Tree already sitting in self.models, shrink it, and
        apply the deferred valid-score updates. A stub (no splits found)
        unwinds the whole iteration — the placeholder is removed and iter_
        decremented — and raises the _async_stub_stop flag so the next
        train_one_iter reports is_finished, matching the sync stop one
        iteration late. Called from every state reader (eval, predict,
        rollback, refit, export) and from the sync training path."""
        pending = self._pending
        if pending is None:
            return
        self._pending = None
        with global_timer.scope("tree_train"):
            tree = self.tree_learner.finalize(pending)
        if tree.num_leaves <= 1:
            for i in range(len(self.models) - 1, -1, -1):
                if self.models[i] is tree:
                    del self.models[i]
                    break
            self.iter_ -= 1
            self._predictor.invalidate()
            self._async_stub_stop = True
            return
        tree.shrink(self.shrinkage_rate)
        with global_timer.scope("update_score"):
            self._update_valid_scores(tree, 0)

    def train_one_iter(self, gradients: Optional[np.ndarray] = None,
                       hessians: Optional[np.ndarray] = None) -> bool:
        """Returns True when training should STOP (no more valid splits) —
        matching LGBM_BoosterUpdateOneIter's is_finished flag."""
        rt = elastic.active()
        if rt is not None:
            # beat the collective watchdog + (without a health monitor to
            # piggyback on) run the windowed heartbeat collective. The beat
            # precedes the fault hooks: a real worker enters the iteration
            # alive and blocks INSIDE it, so the last-good count the
            # watchdog reports equals the completed iterations (= the
            # snapshot a restarted gang resumes from).
            rt.on_iteration_start(self.iter_,
                                  piggyback=self._health is not None)
        faults.check_kill(self.iter_)
        faults.check_distributed(self.iter_)
        if self._async_stub_stop:
            self._async_stub_stop = False
            Log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            return True
        C = self.num_tree_per_iteration
        init_scores = [0.0] * C
        custom = gradients is not None
        if not custom:
            if self.objective is None:
                Log.fatal("No object function provided")
            for c in range(C):
                init_scores[c] = self.boost_from_average(c)
        should_continue = False
        with global_timer.scope("boosting"):
            if custom:
                grads = jnp.asarray(gradients, dtype=jnp.float32).reshape(
                    C, self.num_data)
                hesses = jnp.asarray(hessians, dtype=jnp.float32).reshape(
                    C, self.num_data)
                if C == 1:
                    grads, hesses = grads[0], hesses[0]
            else:
                grads, hesses = self._grad_fn(
                    self.score if C > 1 else self.score[0])
        grads, hesses = faults.maybe_poison_gh(grads, hesses, self.iter_)
        if self._health is not None:
            grads, hesses = self._health.admit(self, grads, hesses)
        with global_timer.scope("bagging"):
            bag, grads, hesses = self.sample_strategy.bagging(
                self.iter_, grads, hesses)
            self._refresh_bag_cache(bag)
        # async pipeline: not on the first iteration (its stub path seeds
        # init scores) and not under bagging (OOB updates need the host
        # tree before the next gradient pass)
        if (not custom and bag is None and len(self.models) >= C
                and self._async_enabled()):
            return self._train_one_iter_async(grads, hesses)
        self._flush_pending()
        if self._async_stub_stop:
            self._async_stub_stop = False
            Log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            return True
        for c in range(C):
            with global_timer.scope("boosting"):
                if C > 1:
                    gh_ext = _pack_gh(grads[c], hesses[c])
                else:
                    gh_ext = _pack_gh(grads, hesses)
            new_tree = Tree(2)
            if self.class_need_train[c] and self.train_set.num_features > 0:
                with global_timer.scope("tree_train"):
                    new_tree = self.tree_learner.train(gh_ext, bag)
            if new_tree.num_leaves > 1:
                should_continue = True
                if self._health is not None:
                    self._health.observe_tree(new_tree)
                if self.config.linear_tree:
                    from ..treelearner.linear import fit_leaf_linear_models

                    gvec = grads[c] if C > 1 else grads
                    hvec = hesses[c] if C > 1 else hesses
                    with global_timer.scope("linear_fit"):
                        fit_leaf_linear_models(
                            new_tree, self.train_set, self.train_raw,
                            self.tree_learner.partition,
                            np.asarray(gvec), np.asarray(hvec),
                            self.config.linear_lambda,
                            is_first_tree=len(self.models) < C)
                if self.objective is not None:
                    self.objective.renew_tree_output(
                        new_tree, self.score[c], self.tree_learner.partition)
                new_tree.shrink(self.shrinkage_rate)
                with global_timer.scope("update_score"):
                    self._update_train_score(new_tree, c)
                    self._update_valid_scores(new_tree, c)
                if abs(init_scores[c]) > K_EPSILON:
                    new_tree.add_bias(init_scores[c])
            else:
                if len(self.models) < C:
                    if (self.objective is not None and not self.config.boost_from_average
                            and not self._has_init_score):
                        init_scores[c] = self.objective.boost_from_score(c)
                        self.score = self.score.at[c].add(init_scores[c])
                        for vd in self.valid_sets:
                            vd.score = vd.score.at[c].add(init_scores[c])
                    new_tree.as_constant_tree(init_scores[c])
                else:
                    new_tree.as_constant_tree(0.0)
            self.models.append(new_tree)
        self._predictor.invalidate()
        if not should_continue:
            Log.warning("Stopped training because there are no more leaves that "
                        "meet the split requirements")
            if len(self.models) > C:
                del self.models[-C:]
            return True
        self.iter_ += 1
        if telemetry.enabled():
            telemetry.sample_hbm()  # per-tree HBM high-water
        return False

    def _train_one_iter_async(self, grads: jax.Array,
                              hesses: jax.Array) -> bool:
        """One async-pipelined iteration (eligibility checked by caller):
        dispatch tree t, apply its score update straight from the device
        split log, then — while the device is still growing tree t —
        host-replay tree t-1's log into its placeholder Tree. The only
        blocking transfer per iteration is t-1's split log, which has been
        copying since its dispatch. Semantics stay bit-identical to the
        sync path; only the stop on a no-split tree lands one iteration
        late (the extra dispatched tree is provably the same stub with a
        zero score delta, and is dropped)."""
        with global_timer.scope("boosting"):
            gh_ext = _pack_gh(grads, hesses)
        with global_timer.scope("tree_train"):
            pending = self.tree_learner.train_async(gh_ext, None)
        apply_log = sanitize.guard(
            _apply_split_log_to_score, (0,),
            "_apply_split_log_to_score (models/gbdt.py async score update)")
        with global_timer.scope("update_score"):
            self.score = self.score.at[0].set(apply_log(
                self.score[0], _colocate(pending.rec_store, self.score),
                _colocate(pending.leaf_id, self.score),
                jnp.float32(self.shrinkage_rate), self.config.num_leaves))
        self.models.append(pending.tree)
        self._predictor.invalidate()
        self._flush_pending()  # overlaps t-1's replay with t's growth
        if self._async_stub_stop:
            self._async_stub_stop = False
            self.models.pop()  # tree t: same gradients => the same stub
            Log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            return True
        self._pending = pending
        self.iter_ += 1
        return False

    # ------------------------------------------------------------------ score

    def _refresh_bag_cache(self, bag: Optional[np.ndarray]) -> None:
        """The bag is reused across bagging_freq iterations, so the padded
        out-of-bag index array is computed once per bag change."""
        if bag is self._cur_bag and getattr(self, "_oob_padded_ready", False):
            return
        self._cur_bag = bag
        self._oob_padded_ready = True
        if bag is None or len(bag) >= self.num_data:
            self._oob_padded = None
        elif isinstance(bag, DeviceBag):
            # device bag: build the padded OOB index set from the mask
            # without pulling it to host — sentinel rows (id == num_data,
            # same as pad_indices) sort past every real index
            n = self.num_data
            p = bucket_size(n - bag.n_bag)
            base = jnp.where(bag.mask, n,
                             jnp.arange(n, dtype=jnp.int32))
            if p > n:
                base = jnp.concatenate(
                    [base, jnp.full(p - n, n, dtype=jnp.int32)])
            self._oob_padded = jnp.sort(base)[:p]
        else:
            oob = np.setdiff1d(np.arange(self.num_data, dtype=np.int32), bag)
            self._oob_padded = jnp.asarray(pad_indices(oob, self.num_data))

    @property
    def _depth_bound(self) -> int:
        return (self.config.max_depth if self.config.max_depth > 0
                else self.config.num_leaves - 1)

    def _all_rows_padded(self) -> jax.Array:
        if getattr(self, "_all_rows_cache", None) is None:
            self._all_rows_cache = jnp.asarray(pad_indices(
                np.arange(self.num_data, dtype=np.int32), self.num_data))
        return self._all_rows_cache

    def _add_tree_to_train_score(self, tree: Tree, class_id: int) -> None:
        """Add an arbitrary (e.g. previously trained) tree's outputs to the
        train score of every row via bin-space traversal — the train-time
        ScoreUpdater::AddScore(tree) path DART/RF renormalization needs."""
        if tree.is_linear:
            self._add_linear_tree_score(tree, class_id)
            return
        score = self._score_tree_rows(tree, self.score[class_id],
                                      self._all_rows_padded())
        self.score = self.score.at[class_id].set(score)

    def _score_tree_rows(self, tree: Tree, score: jax.Array,
                         rows_padded: jax.Array) -> jax.Array:
        """Bin-space tree traversal over padded rows. A streamed learner
        keeps no device plane (bins_dev is None) — route through its
        block-sharded traversal, which is bitwise-equal (each valid row
        scattered exactly once with the identical leaf value)."""
        learner = self.tree_learner
        if getattr(learner, "bins_dev", None) is None:
            return learner.add_tree_to_score_blocked(
                tree, score, rows_padded, self._depth_bound)
        return add_tree_to_score(tree, self.train_set, learner.bins_dev,
                                 score, rows_padded, self.num_data,
                                 self._depth_bound)

    def _multiply_score(self, class_id: int, val: float) -> None:
        """ScoreUpdater::MultiplyScore on train + valid (RF averaging)."""
        self.score = self.score.at[class_id].multiply(val)
        for vd in self.valid_sets:
            vd.score = vd.score.at[class_id].multiply(val)

    def _train_raw_dev(self) -> jax.Array:
        if getattr(self, "_train_raw_dev_cache", None) is None:
            self._train_raw_dev_cache = jnp.asarray(self.train_raw,
                                                    dtype=jnp.float32)
        return self._train_raw_dev_cache

    def _add_linear_tree_score(self, tree: Tree, class_id: int) -> None:
        """Linear leaves need raw feature values, not leaf constants: score
        through the packed linear predictor (AddPredictionToScore with
        is_linear, gbdt.cpp)."""
        packed = pack_ensemble([tree], fixed_leaves=self.config.num_leaves,
                               fixed_depth=self._depth_bound)
        delta = predict_raw(packed, self._train_raw_dev())[:, 0]
        self.score = self.score.at[class_id].add(delta)

    def _update_train_score(self, tree: Tree, class_id: int) -> None:
        if tree.is_linear:
            self._add_linear_tree_score(tree, class_id)
            return
        part = self.tree_learner.partition
        score = self.score[class_id]
        ids_fn = getattr(part, "leaf_ids_dev", None)
        if ids_fn is not None:
            # vectorized path: one gather over the device leaf-id vector
            # (bagged-out rows carry -1 and contribute nothing)
            ids = _colocate(ids_fn(), score)
            lv = jnp.asarray(tree.leaf_value[: tree.num_leaves],
                             dtype=jnp.float32)
            score = score + jnp.where(
                ids >= 0, lv[jnp.clip(ids, 0, tree.num_leaves - 1)], 0.0)
        else:
            for leaf in range(tree.num_leaves):
                idx = part.indices(leaf)
                score = score.at[idx].add(tree.leaf_value[leaf], mode="drop")
        bag = self._cur_bag
        if bag is not None and self._oob_padded is not None:
            # out-of-bag rows: bin-space tree traversal (the train-time
            # AddPredictionToScore path, gbdt.cpp out_of_bag update)
            score = self._score_tree_rows(tree, score, self._oob_padded)
        self.score = self.score.at[class_id].set(score)

    def _update_valid_scores(self, tree: Tree, class_id: int) -> None:
        if not self.valid_sets:
            return
        depth_bound = (self.config.max_depth if self.config.max_depth > 0
                       else self.config.num_leaves - 1)
        packed = pack_ensemble([tree], fixed_leaves=self.config.num_leaves,
                               fixed_depth=depth_bound)
        for vd in self.valid_sets:
            delta = predict_raw(packed, vd.raw)[:, 0]
            vd.score = vd.score.at[class_id].add(delta)

    # ------------------------------------------------------------------- eval

    def eval_train(self) -> List[Tuple[str, str, float, bool]]:
        self._flush_pending()
        out = []
        for m in self.train_metrics:
            for name, val in zip(m.name, m.eval(self.score[0] if self.num_tree_per_iteration == 1
                                                else self.score, self.objective)):
                out.append(("training", name, val, m.greater_is_better))
        return out

    def eval_valid(self) -> List[Tuple[str, str, float, bool]]:
        self._flush_pending()
        out = []
        for vname, vd in zip(self.valid_names, self.valid_sets):
            for m in vd.metrics:
                score = vd.score[0] if self.num_tree_per_iteration == 1 else vd.score
                for name, val in zip(m.name, m.eval(score, self.objective)):
                    out.append((vname, name, val, m.greater_is_better))
        return out

    # ---------------------------------------------------------------- predict

    @staticmethod
    def _sharded_predict_enabled(n_rows: int,
                                 min_rows: Optional[int] = None) -> bool:
        from ..parallel.predict import sharded_predict_enabled

        return sharded_predict_enabled(n_rows, min_rows=min_rows)

    def _packed(self, num_iteration: int = 0, start_iteration: int = 0,
                dtype=jnp.float32):
        self._flush_pending()
        C = self.num_tree_per_iteration
        start = max(start_iteration, 0) * C
        n_trees = len(self.models)
        if num_iteration > 0:
            n_trees = min(n_trees, start + num_iteration * C)
        return self._predictor.get(self.models, start, n_trees, dtype=dtype)

    def predict(self, X: np.ndarray, raw_score: bool = False,
                num_iteration: int = 0, start_iteration: int = 0,
                early_stop: Optional[Tuple[int, float]] = None,
                chunk_rows: Optional[int] = None,
                shard_rows: Optional[int] = None) -> np.ndarray:
        dtype = predict_dtype(X)
        packed = self._packed(num_iteration, start_iteration, dtype=dtype)
        C = self.num_tree_per_iteration
        n = X.shape[0]
        chunk = stream_chunk_rows(n, chunk_rows)
        if early_stop is not None and packed.num_trees > 0:
            from ..ops.predict import predict_raw_early_stop

            freq, margin = early_stop
            out = predict_raw_early_stop(
                packed, jnp.asarray(X, dtype=dtype), C, freq, margin)
        elif packed.num_trees > 0 and chunk_rows is not None and chunk > 0:
            # explicit pred_chunk_rows wins over auto-sharding
            out = predict_raw_streamed(
                packed, np.asarray(X, dtype=np.dtype(dtype)), C, chunk, dtype)
        elif packed.num_trees > 0 and not packed.linear \
                and self._sharded_predict_enabled(n, shard_rows):
            # linear ensembles keep single-chip dispatch: their score math
            # runs eagerly for bit-stability (ops/predict.predict_raw)
            from ..parallel.predict import predict_raw_sharded

            out = predict_raw_sharded(
                packed, np.asarray(X, dtype=np.dtype(dtype)), C)
        elif chunk > 0 and packed.num_trees > 0:
            out = predict_raw_streamed(
                packed, np.asarray(X, dtype=np.dtype(dtype)), C, chunk, dtype)
        else:
            # serving warm start: a key-matched AOT executable answers
            # without consulting (or populating) the jit cache — a cold
            # replica's first bucket-shaped request skips the XLA compile
            fn = None
            if packed.num_trees > 0 and not packed.linear:
                from ..ops.predict import predict_pallas_enabled

                if not predict_pallas_enabled():
                    fn = self._predictor.aot_get(
                        packed, n, X.shape[1], C, np.dtype(dtype))
            if fn is not None:
                with global_timer.scope("predict_traverse"):
                    out = fn(packed, jnp.asarray(X, dtype=dtype))
            else:
                out = predict_raw(packed, jnp.asarray(X, dtype=dtype), C)
        if self.average_output and packed.num_trees > 0:
            out = out / (packed.num_trees // C)
        if not raw_score and self.objective is not None:
            out = self.objective.convert_output(out)
        res = np.asarray(out)
        return res[:, 0] if res.shape[1] == 1 else res

    def predict_leaf_index(self, X: np.ndarray, num_iteration: int = 0,
                           start_iteration: int = 0) -> np.ndarray:
        from ..ops.predict import predict_leaf_indices

        dtype = predict_dtype(X)
        packed = self._packed(num_iteration, start_iteration, dtype=dtype)
        return np.asarray(predict_leaf_indices(packed, jnp.asarray(X, dtype=dtype)))

    # ------------------------------------------------------------------ model

    def refit(self, pred_leaf: np.ndarray) -> None:
        """GBDT::RefitTree (gbdt.cpp:266-305): keep every tree's structure,
        refit the leaf outputs on the current training data. pred_leaf is
        [num_data, num_trees] leaf assignments of the OLD model on the new
        data; gradients are recomputed per iteration from the accumulating
        refit score, and each leaf output becomes

            refit_decay_rate * old + (1 - refit_decay_rate) * fit * shrinkage

        (SerialTreeLearner::FitByExistingTree, serial_tree_learner.cpp:250-283
        — per-leaf sums here are one device scatter-add per tree).
        """
        self._flush_pending()
        C = self.num_tree_per_iteration
        T = len(self.models)
        if pred_leaf.shape != (self.num_data, T):
            Log.fatal("Refit leaf predictions shape %s != (%d, %d)",
                      pred_leaf.shape, self.num_data, T)
        decay = self.config.refit_decay_rate
        cfg = self.config
        leaf_dev = jnp.asarray(pred_leaf.astype(np.int32))
        for it in range(T // C):
            grads, hesses = self._grad_fn(
                self.score if C > 1 else self.score[0])
            for c in range(C):
                m = it * C + c
                tree = self.models[m]
                g = grads[c] if C > 1 else grads
                h = hesses[c] if C > 1 else hesses
                leaf = leaf_dev[:, m]
                L = tree.num_leaves
                sum_g = np.asarray(jnp.zeros(L).at[leaf].add(g))
                sum_h = np.asarray(jnp.zeros(L).at[leaf].add(h))
                from ..treelearner.serial import _leaf_output_host

                for i in range(L):
                    out = _leaf_output_host(
                        float(sum_g[i]), float(sum_h[i]) + K_EPSILON,
                        cfg.lambda_l1, cfg.lambda_l2, cfg.max_delta_step)
                    tree.set_leaf_output(
                        i, decay * float(tree.leaf_value[i])
                        + (1.0 - decay) * out * tree.shrinkage)
                lv = jnp.asarray(tree.leaf_value[:L], dtype=jnp.float32)
                self.score = self.score.at[c].add(lv[leaf])
        self._predictor.invalidate()

    def rollback_one_iter(self) -> None:
        """RollbackOneIter (gbdt.cpp:462): drop the last iteration's trees and
        back out their score contributions."""
        self._flush_pending()
        if self.iter_ <= 0:
            return
        C = self.num_tree_per_iteration
        for c in range(C):
            tree = self.models[-C + c]
            inv = Tree(max(tree.max_leaves, 2))
            # subtract by re-adding the negated tree through the packed path
            tree.shrink(-1.0)
            self._update_train_score(tree, c)
            self._update_valid_scores(tree, c)
            tree.shrink(-1.0)
        del self.models[-C:]
        self.iter_ -= 1
        self._predictor.invalidate()

    def to_model(self) -> GBDTModel:
        self._flush_pending()
        ds = self.train_set
        model = GBDTModel()
        model.num_class = self.num_class
        model.num_tree_per_iteration = self.num_tree_per_iteration
        model.max_feature_idx = (ds.num_total_features - 1) if ds is not None else 0
        model.objective_str = self.objective.to_string() if self.objective else None
        model.feature_names = ds.feature_names if ds is not None else []
        model.feature_infos = ds.feature_infos() if ds is not None else []
        model.monotone_constraints = list(ds.monotone_constraints) if ds is not None else []
        model.trees = self.models
        model.best_iteration = self.best_iteration
        model.average_output = self.average_output
        model.parameters_str = self.config.to_string()
        return model


def create_boosting(config: Config, train_set: Optional[Dataset],
                    objective: Optional[ObjectiveFunction],
                    train_raw: Optional[np.ndarray] = None) -> GBDT:
    """Boosting factory (boosting.cpp:41-101): gbdt / dart / rf; the legacy
    boosting=goss spelling trains a GBDT with the GOSS sample strategy."""
    b = config.boosting
    if b == "dart":
        from .dart import DART

        return DART(config, train_set, objective, train_raw)
    if b in ("rf", "random_forest"):
        from .rf import RF

        return RF(config, train_set, objective, train_raw)
    if b in ("gbdt", "gbrt", "gbm", "goss"):
        return GBDT(config, train_set, objective, train_raw)
    Log.fatal("Unknown boosting type %s", b)
