"""Random-forest boosting mode.

Counterpart of src/boosting/rf.hpp:25-236: no shrinkage, bagging (or feature
subsampling) required, gradients computed ONCE from the constant
boost-from-average score (every tree fits the same residuals on its own
bag), and the maintained score is the running AVERAGE of tree outputs via
the multiply-update-multiply trick; prediction averages over iterations
(average_output).
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..utils.log import Log
from .gbdt import GBDT, K_EPSILON, _pack_gh
from .tree import Tree


class RF(GBDT):
    def __init__(self, config, train_set, objective, train_raw=None) -> None:
        if config.data_sample_strategy == "bagging":
            ok = (config.bagging_freq > 0 and 0.0 < config.bagging_fraction < 1.0) \
                or (0.0 < config.feature_fraction < 1.0)
            if not ok:
                Log.fatal("Random forest needs bagging (bagging_freq > 0 and "
                          "bagging_fraction < 1.0) or feature_fraction < 1.0")
        if objective is None:
            Log.fatal("RF mode do not support custom objective function, "
                      "please use built-in objectives.")
        if train_set is not None and train_set.metadata.init_score is not None:
            # the running-average score maintenance cannot absorb an additive
            # init score (rf.hpp:49 CHECK_EQ(init_score, nullptr))
            Log.fatal("Cannot use init_score in RF mode")
        super().__init__(config, train_set, objective, train_raw)
        self.average_output = True
        self.shrinkage_rate = 1.0
        # one-time gradient pass from the constant init score (rf.hpp Boosting)
        C = self.num_tree_per_iteration
        self.init_scores = [0.0] * C
        if self.objective is not None and config.boost_from_average:
            self.init_scores = [self.objective.boost_from_score(c)
                                for c in range(C)]
        if C > 1:
            base = jnp.asarray(np.asarray(self.init_scores, dtype=np.float32)
                               [:, None] * np.ones((C, self.num_data),
                                                   dtype=np.float32))
        else:
            base = jnp.full(self.num_data, self.init_scores[0],
                            dtype=jnp.float32)
        self._fixed_grads, self._fixed_hesses = self.objective.get_gradients(base)

    def train_one_iter(self, gradients: Optional[np.ndarray] = None,
                       hessians: Optional[np.ndarray] = None) -> bool:
        if gradients is not None or hessians is not None:
            Log.fatal("RF mode do not support custom objective function, "
                      "please use built-in objectives.")
        C = self.num_tree_per_iteration
        bag, grads, hesses = self.sample_strategy.bagging(
            self.iter_, self._fixed_grads, self._fixed_hesses)
        self._refresh_bag_cache(bag)
        for c in range(C):
            gh_ext = _pack_gh(grads[c] if C > 1 else grads,
                              hesses[c] if C > 1 else hesses)
            new_tree = Tree(2)
            if self.class_need_train[c] and self.train_set.num_features > 0:
                new_tree = self.tree_learner.train(gh_ext, bag)
            if new_tree.num_leaves > 1:
                if self.objective is not None:
                    # leaf refit residuals are label - init (rf.hpp:150-152)
                    self.objective.renew_tree_output(
                        new_tree,
                        jnp.full(self.num_data, self.init_scores[c],
                                 dtype=jnp.float32),
                        self.tree_learner.partition)
                if abs(self.init_scores[c]) > K_EPSILON:
                    new_tree.add_bias(self.init_scores[c])
                # running average: score = (score*iter + tree) / (iter+1)
                self._multiply_score(c, float(self.iter_))
                self._update_train_score(new_tree, c)
                self._update_valid_scores(new_tree, c)
                self._multiply_score(c, 1.0 / (self.iter_ + 1.0))
            else:
                if len(self.models) < C:
                    output = 0.0
                    if not self.class_need_train[c] and self.objective is not None:
                        output = self.objective.boost_from_score(c)
                    new_tree.as_constant_tree(output)
                    self._multiply_score(c, float(self.iter_))
                    self._update_train_score(new_tree, c)
                    self._update_valid_scores(new_tree, c)
                    self._multiply_score(c, 1.0 / (self.iter_ + 1.0))
            self.models.append(new_tree)
        self.iter_ += 1
        self._packed_cache = None
        return False

    def rollback_one_iter(self) -> None:
        if self.iter_ <= 0:
            return
        C = self.num_tree_per_iteration
        for c in range(C):
            tree = self.models[-C + c]
            tree.shrink(-1.0)
            self._multiply_score(c, float(self.iter_))
            self._add_tree_to_train_score(tree, c)
            self._update_valid_scores(tree, c)
            self._multiply_score(c, 1.0 / (self.iter_ - 1.0)
                                 if self.iter_ > 1 else 0.0)
            tree.shrink(-1.0)
        del self.models[-C:]
        self.iter_ -= 1
        self._packed_cache = None
