"""Row sampling strategies: bagging and GOSS.

Counterpart of src/boosting/sample_strategy.{h,cpp} (factory), bagging.hpp
(BaggingSampleStrategy) and goss.hpp (GOSSStrategy). The strategy runs on
host once per iteration over the gradient arrays (GOSS needs |g·h| scores)
and hands the tree learner a bag index set; gradient rescaling for GOSS's
small-gradient sample happens on device.
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import numpy as np

from ..config import Config
from ..utils.log import Log


class SampleStrategy:
    """Base: no sampling (full data every iteration)."""

    is_use_subset = False

    def __init__(self, config: Config, num_data: int, metadata,
                 num_tree_per_iteration: int) -> None:
        self.config = config
        self.num_data = num_data
        self.metadata = metadata
        self.num_tree_per_iteration = num_tree_per_iteration

    def bagging(self, iteration: int, grad, hess
                ) -> Tuple[Optional[np.ndarray], object, object]:
        """Returns (bag_indices or None for full data, grad, hess) — the
        gradients are passed through so GOSS can rescale them."""
        return None, grad, hess


class BaggingSampleStrategy(SampleStrategy):
    """bagging_fraction / bagging_freq (+ pos/neg fractions for binary)
    — bagging.hpp:30-296. The bag is resampled every `bagging_freq`
    iterations and reused in between."""

    def __init__(self, config: Config, num_data: int, metadata,
                 num_tree_per_iteration: int) -> None:
        super().__init__(config, num_data, metadata, num_tree_per_iteration)
        self.balanced = (config.pos_bagging_fraction < 1.0
                         or config.neg_bagging_fraction < 1.0)
        self.need = config.bagging_freq > 0 and (
            config.bagging_fraction < 1.0 or self.balanced)
        if self.balanced and config.objective not in ("binary",):
            Log.warning("Only can use pos/neg bagging with binary objective")
            self.balanced = False
            self.need = config.bagging_freq > 0 and config.bagging_fraction < 1.0
        self._bag: Optional[np.ndarray] = None

    def bagging(self, iteration: int, grad, hess):
        if not self.need:
            return None, grad, hess
        freq = self.config.bagging_freq
        if self._bag is None or iteration % freq == 0:
            rng = np.random.RandomState(self.config.bagging_seed + iteration)
            if self.balanced:
                label = np.asarray(self.metadata.label)
                pos = label > 0
                keep = np.where(
                    pos, rng.rand(self.num_data) < self.config.pos_bagging_fraction,
                    rng.rand(self.num_data) < self.config.neg_bagging_fraction)
                self._bag = np.nonzero(keep)[0].astype(np.int32)
            else:
                cnt = int(round(self.config.bagging_fraction * self.num_data))
                cnt = max(min(cnt, self.num_data), 1)
                self._bag = np.sort(rng.choice(
                    self.num_data, cnt, replace=False)).astype(np.int32)
        return self._bag, grad, hess


class GOSSStrategy(SampleStrategy):
    """Gradient-based One-Side Sampling — goss.hpp:30-172.

    Keeps the top `top_rate` fraction of rows by sum_c |g_c·h_c|, samples
    `other_rate` of the rest, and scales the sampled small-gradient rows'
    grad/hess by (1-top_rate)/other_rate. Inactive during the warm-up
    (iteration < 1/learning_rate, goss.hpp) like the reference.
    """

    def __init__(self, config: Config, num_data: int, metadata,
                 num_tree_per_iteration: int) -> None:
        super().__init__(config, num_data, metadata, num_tree_per_iteration)
        if config.top_rate + config.other_rate > 1.0:
            Log.fatal("The sum of top_rate and other_rate cannot be greater than 1.0")
        if config.top_rate <= 0.0 or config.other_rate <= 0.0:
            # goss.hpp CHECK: both subsample fractions must be positive
            Log.fatal("top_rate and other_rate must be positive in GOSS")
        if config.bagging_freq > 0 and config.bagging_fraction < 1.0:
            Log.warning("Cannot use bagging in GOSS")

    def bagging(self, iteration: int, grad, hess):
        lr = max(self.config.learning_rate, 1e-12)
        if iteration < int(1.0 / lr):
            return None, grad, hess
        import jax.numpy as jnp

        g = np.asarray(grad, dtype=np.float64)
        h = np.asarray(hess, dtype=np.float64)
        if g.ndim == 1:
            score = np.abs(g * h)
        else:
            score = np.abs(g * h).sum(axis=0)
        n = self.num_data
        top_k = max(int(math.ceil(n * self.config.top_rate)), 1)
        other_k = int(math.ceil(n * self.config.other_rate))
        order = np.argsort(-score, kind="stable")
        top = order[:top_k]
        rest = order[top_k:]
        rng = np.random.RandomState(self.config.bagging_seed + iteration)
        if other_k > 0 and len(rest) > 0:
            sampled = rng.choice(rest, min(other_k, len(rest)), replace=False)
        else:
            sampled = np.empty(0, dtype=np.int64)
        multiplier = (1.0 - self.config.top_rate) / max(
            self.config.other_rate, 1e-12)
        if len(sampled) > 0:
            sampled_dev = jnp.asarray(np.sort(sampled).astype(np.int32))
            if g.ndim == 1:
                grad = grad.at[sampled_dev].mul(multiplier)
                hess = hess.at[sampled_dev].mul(multiplier)
            else:
                grad = grad.at[:, sampled_dev].mul(multiplier)
                hess = hess.at[:, sampled_dev].mul(multiplier)
        bag = np.sort(np.concatenate([top, sampled])).astype(np.int32)
        return bag, grad, hess


def create_sample_strategy(config: Config, num_data: int, metadata,
                           num_tree_per_iteration: int) -> SampleStrategy:
    """sample_strategy.cpp:27: data_sample_strategy ∈ {bagging, goss}; the
    legacy boosting=goss spelling is normalized by the config layer."""
    strategy = config.data_sample_strategy
    if strategy == "goss" or config.boosting == "goss":
        return GOSSStrategy(config, num_data, metadata, num_tree_per_iteration)
    if strategy == "bagging":
        return BaggingSampleStrategy(config, num_data, metadata,
                                     num_tree_per_iteration)
    Log.fatal("Unknown data sample strategy: %s", strategy)
