"""Row sampling strategies: bagging and GOSS.

Counterpart of src/boosting/sample_strategy.{h,cpp} (factory), bagging.hpp
(BaggingSampleStrategy) and goss.hpp (GOSSStrategy). Bagging runs on host
once per iteration; GOSS has two equivalent homes for its |g·h| top-rate
selection:

* host (the original path, and the default off-accelerator): pull the
  gradients, argsort on host, hand the learner a host index bag;
* device (LGBM_TPU_GOSS_DEVICE, default auto = on for tpu/axon backends):
  a jitted score + stable-argsort + scatter keeps the gradients and the
  bag membership mask on device — the only host work per iteration is the
  MT19937 position draw, which consumes the generator exactly like the
  host path's `choice(rest, ...)` (both reduce to `permutation(n)[:k]`),
  so the two paths pick bit-identical bags.

Both paths score in f32 with the multiclass per-class terms added in class
order (a fixed association), so the sort keys — and therefore the stable
argsort permutation — match bit for bit.
"""
from __future__ import annotations

import math
import os
from functools import partial
from typing import Optional, Tuple

import numpy as np

from ..config import Config
from ..utils.log import Log
from ..utils.timer import global_timer


class SampleStrategy:
    """Base: no sampling (full data every iteration)."""

    is_use_subset = False

    def __init__(self, config: Config, num_data: int, metadata,
                 num_tree_per_iteration: int) -> None:
        self.config = config
        self.num_data = num_data
        self.metadata = metadata
        self.num_tree_per_iteration = num_tree_per_iteration

    def bagging(self, iteration: int, grad, hess
                ) -> Tuple[Optional[np.ndarray], object, object]:
        """Returns (bag_indices or None for full data, grad, hess) — the
        gradients are passed through so GOSS can rescale them."""
        return None, grad, hess


class BaggingSampleStrategy(SampleStrategy):
    """bagging_fraction / bagging_freq (+ pos/neg fractions for binary)
    — bagging.hpp:30-296. The bag is resampled every `bagging_freq`
    iterations and reused in between."""

    def __init__(self, config: Config, num_data: int, metadata,
                 num_tree_per_iteration: int) -> None:
        super().__init__(config, num_data, metadata, num_tree_per_iteration)
        self.balanced = (config.pos_bagging_fraction < 1.0
                         or config.neg_bagging_fraction < 1.0)
        self.need = config.bagging_freq > 0 and (
            config.bagging_fraction < 1.0 or self.balanced)
        if self.balanced and config.objective not in ("binary",):
            Log.warning("Only can use pos/neg bagging with binary objective")
            self.balanced = False
            self.need = config.bagging_freq > 0 and config.bagging_fraction < 1.0
        self._bag: Optional[np.ndarray] = None

    def bagging(self, iteration: int, grad, hess):
        if not self.need:
            return None, grad, hess
        freq = self.config.bagging_freq
        if self._bag is None or iteration % freq == 0:
            rng = np.random.RandomState(self.config.bagging_seed + iteration)
            if self.balanced:
                label = np.asarray(self.metadata.label)
                pos = label > 0
                keep = np.where(
                    pos, rng.rand(self.num_data) < self.config.pos_bagging_fraction,
                    rng.rand(self.num_data) < self.config.neg_bagging_fraction)
                self._bag = np.nonzero(keep)[0].astype(np.int32)
            else:
                cnt = int(round(self.config.bagging_fraction * self.num_data))
                cnt = max(min(cnt, self.num_data), 1)
                self._bag = np.sort(rng.choice(
                    self.num_data, cnt, replace=False)).astype(np.int32)
        return self._bag, grad, hess


class DeviceBag:
    """A bag that lives on device: membership as a bool mask, the count
    known host-side from shapes alone. Consumers that genuinely need host
    indices (the serial learner's RowPartition, the distributed learners)
    materialize them lazily through `.indices` — one pull per bag, outside
    the per-iteration sampling path."""

    def __init__(self, mask, n_bag: int, num_data: int) -> None:
        self.mask = mask  # device bool [num_data]
        self.n_bag = int(n_bag)
        self.num_data = int(num_data)
        self._host: Optional[np.ndarray] = None

    def __len__(self) -> int:
        return self.n_bag

    @property
    def indices(self) -> np.ndarray:
        if self._host is None:
            self._host = np.nonzero(np.asarray(self.mask))[0].astype(np.int32)
        return self._host


def host_bag_indices(bag):
    """Normalize a bag to host int32 indices (identity for host bags)."""
    if isinstance(bag, DeviceBag):
        return bag.indices
    return bag


def use_device_goss() -> bool:
    """LGBM_TPU_GOSS_DEVICE: 1/on forces the device selection, 0/off the
    host path; auto (default) enables it on accelerator backends where the
    per-iteration gradient pull is the cost being removed."""
    mode = os.environ.get("LGBM_TPU_GOSS_DEVICE", "auto").lower()
    if mode in ("0", "false", "off", "host"):
        return False
    if mode in ("1", "true", "on", "device"):
        return True
    import jax

    backend = jax.default_backend()
    return "tpu" in backend or backend == "axon"


def _goss_select(grad, hess, sampled_pos, multiplier, top_k: int):
    """Device half of GOSS: f32 |g·h| score, stable argsort (identical
    permutation to the host np stable sort — stability uniquely determines
    the output for equal keys), top-`top_k` kept, `sampled_pos` indexes the
    REST segment of the order (the host RNG drew positions, not rows), and
    the sampled small-gradient rows are rescaled in place. Returns the
    in-bag mask and the rescaled gradients; nothing touches the host."""
    import jax.numpy as jnp

    if grad.ndim == 1:
        score = jnp.abs(grad * hess)
    else:
        # fixed class-order association — mirrors the host loop bit for bit
        score = jnp.abs(grad[0] * hess[0])
        for c in range(1, grad.shape[0]):
            score = score + jnp.abs(grad[c] * hess[c])
    order = jnp.argsort(-score, stable=True)
    mask = jnp.zeros(score.shape[0], dtype=jnp.bool_)
    mask = mask.at[order[:top_k]].set(True)
    if sampled_pos.shape[0] > 0:
        sampled = order[top_k:][sampled_pos]
        mask = mask.at[sampled].set(True)
        mult = jnp.asarray(multiplier, dtype=jnp.float32)
        if grad.ndim == 1:
            grad = grad.at[sampled].mul(mult)
            hess = hess.at[sampled].mul(mult)
        else:
            grad = grad.at[:, sampled].mul(mult)
            hess = hess.at[:, sampled].mul(mult)
    return mask, grad, hess


class GOSSStrategy(SampleStrategy):
    """Gradient-based One-Side Sampling — goss.hpp:30-172.

    Keeps the top `top_rate` fraction of rows by sum_c |g_c·h_c|, samples
    `other_rate` of the rest, and scales the sampled small-gradient rows'
    grad/hess by (1-top_rate)/other_rate. Inactive during the warm-up
    (iteration < 1/learning_rate, goss.hpp) like the reference.
    """

    def __init__(self, config: Config, num_data: int, metadata,
                 num_tree_per_iteration: int) -> None:
        super().__init__(config, num_data, metadata, num_tree_per_iteration)
        if config.top_rate + config.other_rate > 1.0:
            Log.fatal("The sum of top_rate and other_rate cannot be greater than 1.0")
        if config.top_rate <= 0.0 or config.other_rate <= 0.0:
            # goss.hpp CHECK: both subsample fractions must be positive
            Log.fatal("top_rate and other_rate must be positive in GOSS")
        if config.bagging_freq > 0 and config.bagging_fraction < 1.0:
            Log.warning("Cannot use bagging in GOSS")
        self._select_jit = None

    def _sizes(self) -> Tuple[int, int, int]:
        n = self.num_data
        top_k = max(int(math.ceil(n * self.config.top_rate)), 1)
        other_k = int(math.ceil(n * self.config.other_rate))
        n_rest = n - top_k
        n_sampled = min(other_k, n_rest) if (other_k > 0 and n_rest > 0) else 0
        return top_k, n_rest, n_sampled

    def _bagging_device(self, iteration: int, grad, hess):
        """Device-resident selection: the host draws sample POSITIONS from
        the same MT19937 stream (`choice(n_rest, k)` and the host path's
        `choice(rest, k)` both reduce to `permutation(n_rest)[:k]`), the
        jitted kernel turns them into rows of the device-side order."""
        import jax
        import jax.numpy as jnp

        top_k, n_rest, n_sampled = self._sizes()
        rng = np.random.RandomState(self.config.bagging_seed + iteration)
        if n_sampled > 0:
            pos = rng.choice(n_rest, n_sampled, replace=False)
        else:
            pos = np.empty(0, dtype=np.int64)
        multiplier = (1.0 - self.config.top_rate) / max(
            self.config.other_rate, 1e-12)
        if self._select_jit is None:
            self._select_jit = jax.jit(
                partial(_goss_select, top_k=top_k))
        with global_timer.scope("goss_device_select"):
            mask, grad, hess = self._select_jit(
                grad, hess, jnp.asarray(pos.astype(np.int32)),
                jnp.float32(multiplier))
        return DeviceBag(mask, top_k + n_sampled, self.num_data), grad, hess

    def bagging(self, iteration: int, grad, hess):
        lr = max(self.config.learning_rate, 1e-12)
        if iteration < int(1.0 / lr):
            return None, grad, hess
        if use_device_goss():
            return self._bagging_device(iteration, grad, hess)
        import jax.numpy as jnp

        g = np.asarray(grad, dtype=np.float32)
        h = np.asarray(hess, dtype=np.float32)
        if g.ndim == 1:
            score = np.abs(g * h)
        else:
            # per-class terms added in class order: the same f32 value
            # chain as the device kernel, so the sort keys match bitwise
            score = np.abs(g[0] * h[0])
            for c in range(1, g.shape[0]):
                score = score + np.abs(g[c] * h[c])
        top_k, n_rest, n_sampled = self._sizes()
        order = np.argsort(-score, kind="stable")
        top = order[:top_k]
        rest = order[top_k:]
        rng = np.random.RandomState(self.config.bagging_seed + iteration)
        if n_sampled > 0:
            sampled = rng.choice(rest, n_sampled, replace=False)
        else:
            sampled = np.empty(0, dtype=np.int64)
        multiplier = (1.0 - self.config.top_rate) / max(
            self.config.other_rate, 1e-12)
        if len(sampled) > 0:
            sampled_dev = jnp.asarray(np.sort(sampled).astype(np.int32))
            if g.ndim == 1:
                grad = grad.at[sampled_dev].mul(multiplier)
                hess = hess.at[sampled_dev].mul(multiplier)
            else:
                grad = grad.at[:, sampled_dev].mul(multiplier)
                hess = hess.at[:, sampled_dev].mul(multiplier)
        bag = np.sort(np.concatenate([top, sampled])).astype(np.int32)
        return bag, grad, hess


def create_sample_strategy(config: Config, num_data: int, metadata,
                           num_tree_per_iteration: int) -> SampleStrategy:
    """sample_strategy.cpp:27: data_sample_strategy ∈ {bagging, goss}; the
    legacy boosting=goss spelling is normalized by the config layer."""
    strategy = config.data_sample_strategy
    if strategy == "goss" or config.boosting == "goss":
        return GOSSStrategy(config, num_data, metadata, num_tree_per_iteration)
    if strategy == "bagging":
        return BaggingSampleStrategy(config, num_data, metadata,
                                     num_tree_per_iteration)
    Log.fatal("Unknown data sample strategy: %s", strategy)
