"""Whole-model text/JSON serialization, reference format.

Counterpart of src/boosting/gbdt_model_text.cpp: SaveModelToString (:314-413),
LoadModelFromString (:424+), DumpModel JSON (:26-123). The text model file is
the checkpoint + interchange format; matching it field-for-field lets models
round-trip with the reference implementation.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from .tree import Tree
from ..checkpoint import atomic_write_text
from ..utils.log import Log

MODEL_VERSION = "v4"


class GBDTModel:
    """The serializable state of a boosted ensemble."""

    def __init__(self) -> None:
        self.name = "tree"  # SubModelName: "tree" for gbdt/rf/dart
        self.num_class = 1
        self.num_tree_per_iteration = 1
        self.label_index = 0
        self.max_feature_idx = 0
        self.objective_str: Optional[str] = None
        self.average_output = False
        self.feature_names: List[str] = []
        self.monotone_constraints: List[int] = []
        self.feature_infos: List[str] = []
        self.trees: List[Tree] = []
        self.best_iteration = 0
        self.parameters_str = ""  # `parameters:` section payload
        self.loaded_parameters = ""  # params recovered from a loaded file
        # per-categorical-column pandas category lists (python-package
        # appends `pandas_categorical:<json>` after the parameters section)
        self.pandas_categorical = None

    # ------------------------------------------------------------- properties

    @property
    def num_iterations(self) -> int:
        if self.num_tree_per_iteration <= 0:
            return 0
        return len(self.trees) // self.num_tree_per_iteration

    def feature_importance(self, importance_type: str = "split",
                           num_iteration: int = 0) -> np.ndarray:
        """GBDT::FeatureImportance: split counts or total gains per feature."""
        n_trees = len(self.trees) if num_iteration <= 0 else min(
            len(self.trees), num_iteration * self.num_tree_per_iteration)
        imp = np.zeros(self.max_feature_idx + 1, dtype=np.float64)
        for tree in self.trees[:n_trees]:
            ni = tree.num_leaves - 1
            for node in range(ni):
                # reference counts/accumulates only splits with positive gain
                if tree.split_gain[node] <= 0:
                    continue
                f = int(tree.split_feature[node])
                if importance_type == "split":
                    imp[f] += 1.0
                else:
                    imp[f] += float(tree.split_gain[node])
        return imp

    # ------------------------------------------------------------------- save

    def to_string(self, start_iteration: int = 0, num_iteration: int = -1,
                  importance_type: str = "split") -> str:
        lines = [self.name, f"version={MODEL_VERSION}",
                 f"num_class={self.num_class}",
                 f"num_tree_per_iteration={self.num_tree_per_iteration}",
                 f"label_index={self.label_index}",
                 f"max_feature_idx={self.max_feature_idx}"]
        if self.objective_str:
            lines.append(f"objective={self.objective_str}")
        if self.average_output:
            lines.append("average_output")
        lines.append("feature_names=" + " ".join(self.feature_names))
        if self.monotone_constraints:
            lines.append("monotone_constraints=" + " ".join(str(c) for c in self.monotone_constraints))
        lines.append("feature_infos=" + " ".join(self.feature_infos))

        total_iteration = self.num_iterations
        start_iteration = min(max(start_iteration, 0), total_iteration)
        num_used_model = len(self.trees)
        if num_iteration > 0:
            num_used_model = min((start_iteration + num_iteration) * self.num_tree_per_iteration,
                                 num_used_model)
        start_model = start_iteration * self.num_tree_per_iteration

        tree_strs = []
        for idx, tree in enumerate(self.trees[start_model:num_used_model]):
            tree_strs.append(f"Tree={idx}\n" + tree.to_string() + "\n")
        lines.append("tree_sizes=" + " ".join(str(len(s)) for s in tree_strs))
        lines.append("")
        out = "\n".join(lines) + "\n"
        out += "".join(tree_strs)
        out += "end of trees\n"

        imp = self.feature_importance(importance_type, num_iteration if num_iteration > 0 else 0)
        pairs = [(int(imp[i]), self.feature_names[i]) for i in range(len(imp)) if int(imp[i]) > 0]
        pairs.sort(key=lambda p: -p[0])
        out += "\nfeature_importances:\n"
        for count, fname in pairs:
            out += f"{fname}={count}\n"
        params = self.parameters_str or self.loaded_parameters
        if params:
            out += "\nparameters:\n" + params + "\nend of parameters\n"
        if self.pandas_categorical is not None:
            out += ("pandas_categorical:"
                    + json.dumps(self.pandas_categorical, default=str) + "\n")
        return out

    def save_to_file(self, filename: str, start_iteration: int = 0,
                     num_iteration: int = -1, importance_type: str = "split") -> None:
        # atomic (temp + fsync + os.replace): a crash mid-save can never
        # leave a truncated model file behind
        atomic_write_text(filename,
                          self.to_string(start_iteration, num_iteration,
                                         importance_type))

    # ------------------------------------------------------------------- load

    @classmethod
    def from_string(cls, text: str, source: str = "<string>") -> "GBDTModel":
        model = cls()
        lines = text.split("\n")
        i = 0
        key_vals: Dict[str, str] = {}
        while i < len(lines):
            line = lines[i].rstrip("\r")
            if line.startswith("Tree="):
                break
            if line:
                if "=" in line:
                    key, val = line.split("=", 1)
                    key_vals[key] = val
                else:
                    key_vals[line] = ""
            i += 1
        if "num_class" not in key_vals:
            Log.fatal("Model file %s is truncated or corrupt: missing "
                      "header key num_class", source)
        if "max_feature_idx" not in key_vals:
            Log.fatal("Model file %s is truncated or corrupt: missing "
                      "header key max_feature_idx", source)
        model.name = lines[0].strip() or "tree"
        try:
            model.num_class = int(key_vals["num_class"])
            model.num_tree_per_iteration = int(key_vals.get("num_tree_per_iteration", model.num_class))
            model.label_index = int(key_vals.get("label_index", 0))
            model.max_feature_idx = int(key_vals["max_feature_idx"])
        except ValueError as exc:
            Log.fatal("Model file %s is truncated or corrupt: garbled "
                      "header value (%s)", source, exc)
        model.average_output = "average_output" in key_vals
        model.objective_str = key_vals.get("objective") or None
        model.feature_names = key_vals.get("feature_names", "").split()
        if len(model.feature_names) != model.max_feature_idx + 1:
            Log.fatal("Model file %s: wrong size of feature_names (%d names "
                      "for max_feature_idx=%d)", source,
                      len(model.feature_names), model.max_feature_idx)
        model.feature_infos = key_vals.get("feature_infos", "").split()
        if "monotone_constraints" in key_vals and key_vals["monotone_constraints"]:
            model.monotone_constraints = [int(x) for x in key_vals["monotone_constraints"].split()]

        # tree sections
        saw_end = False
        while i < len(lines):
            line = lines[i].rstrip("\r")
            if line.startswith("end of trees"):
                saw_end = True
                i += 1
                break
            if line.startswith("Tree="):
                i += 1
                tree_kv: Dict[str, str] = {}
                while i < len(lines):
                    tline = lines[i].rstrip("\r")
                    if not tline or tline.startswith("Tree=") or tline.startswith("end of trees"):
                        break
                    if "=" in tline:
                        k, v = tline.split("=", 1)
                        tree_kv[k] = v
                    i += 1
                try:
                    model.trees.append(Tree.from_key_values(tree_kv))
                except (KeyError, ValueError, IndexError) as exc:
                    Log.fatal("Model file %s is truncated or corrupt: tree "
                              "%d has a missing or garbled key (%s)",
                              source, len(model.trees), exc)
            else:
                i += 1
        expected_trees = len(key_vals.get("tree_sizes", "").split())
        if not saw_end or len(model.trees) != expected_trees:
            Log.fatal("Model file %s is truncated or corrupt: header "
                      "declares %d trees but %d parsed%s", source,
                      expected_trees, len(model.trees),
                      "" if saw_end else " and the 'end of trees' marker "
                      "is missing")
        # parameters section
        if "parameters:" in text:
            start = text.index("parameters:") + len("parameters:")
            end = text.find("end of parameters", start)
            if end >= 0:
                model.loaded_parameters = text[start:end].strip()
        # python-package pandas category lists (trailing json line)
        marker = "pandas_categorical:"
        pos = text.rfind("\n" + marker)
        if pos >= 0:
            line = text[pos + 1 + len(marker):].splitlines()[0].strip()
            if line and line != "null":
                try:
                    model.pandas_categorical = json.loads(line)
                except ValueError:
                    pass
        return model

    @classmethod
    def from_file(cls, filename: str) -> "GBDTModel":
        try:
            with open(filename) as fh:
                text = fh.read()
        except OSError as exc:
            Log.fatal("Cannot read model file %s: %s", filename, exc)
        return cls.from_string(text, source=filename)

    # ------------------------------------------------------------------- JSON

    def dump_json(self, start_iteration: int = 0, num_iteration: int = -1,
                  importance_type: str = "split") -> str:
        num_used_model = len(self.trees)
        if num_iteration > 0:
            num_used_model = min((start_iteration + num_iteration) * self.num_tree_per_iteration,
                                 num_used_model)
        start_model = start_iteration * self.num_tree_per_iteration
        tree_infos = []
        for idx in range(start_model, num_used_model):
            tree_infos.append('{"tree_index":%d,%s}' % (idx - start_model,
                                                        self.trees[idx].to_json()[1:-1] + ""))
        imp = self.feature_importance(importance_type,
                                      num_iteration if num_iteration > 0 else 0)
        pairs = [(int(imp[i]), self.feature_names[i]) for i in range(len(imp)) if int(imp[i]) > 0]
        pairs.sort(key=lambda p: -p[0])
        feat_imp = ",".join(f'"{n}":{c}' for c, n in pairs)
        feature_infos_json = []
        for info in self.feature_infos:
            if info.startswith("["):
                lo, hi = info[1:-1].split(":")
                feature_infos_json.append({"min_value": float(lo), "max_value": float(hi), "values": []})
            elif info == "none":
                feature_infos_json.append({"min_value": 0, "max_value": 0, "values": []})
            else:
                vals = [int(float(x)) for x in info.split(":")]
                feature_infos_json.append({"min_value": min(vals), "max_value": max(vals), "values": vals})
        head = {
            "name": self.name,
            "version": MODEL_VERSION,
            "num_class": self.num_class,
            "num_tree_per_iteration": self.num_tree_per_iteration,
            "label_index": self.label_index,
            "max_feature_idx": self.max_feature_idx,
        }
        if self.objective_str:
            head["objective"] = self.objective_str
        head["average_output"] = self.average_output
        head["feature_names"] = self.feature_names
        head["monotone_constraints"] = self.monotone_constraints
        head["feature_infos"] = {n: fi for n, fi in zip(self.feature_names, feature_infos_json)}
        body = json.dumps(head)[:-1]
        out = body + ',"tree_info":[' + ",".join(tree_infos) + '],'
        out += '"feature_importances":{' + feat_imp + "}}"
        return out
