"""Decision tree model: flat arrays, reference-compatible text format.

TPU-native counterpart of the reference Tree (include/LightGBM/tree.h:26,
src/io/tree.cpp). A tree with `num_leaves` leaves is stored as parallel arrays
of length num_leaves-1 (internal nodes) / num_leaves (leaves). Child indices
use the reference's encoding: internal node j >= 0, leaf i encoded as ~i
(negative). decision_type packs [bit0: categorical, bit1: default_left,
bits2-3: missing_type] (tree.h:20-21,274-281).

Construction happens on host (numpy); inference packs tree arrays into padded
device tensors traversed by a vectorized gather loop (ops/predict.py).

Text format matches the reference Tree::ToString (src/io/tree.cpp:349-410)
field-for-field so models interchange with the reference's model files.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np

from ..common import MISSING_NONE, MISSING_ZERO, MISSING_NAN, K_ZERO_THRESHOLD

_CATEGORICAL_MASK = 1  # tree.h:20
_DEFAULT_LEFT_MASK = 2  # tree.h:21

_EPS = K_ZERO_THRESHOLD  # Tree::IsZero band used for zero-as-missing comparisons


def _fmt(x: float) -> str:
    """%.17g-style shortest-roundtrip double formatting (Common::DoubleToStr)."""
    if math.isnan(x):
        return "nan"
    if math.isinf(x):
        return "inf" if x > 0 else "-inf"
    return np.format_float_scientific(x, trim="-") if (x != 0 and (abs(x) < 1e-4 or abs(x) >= 1e17)) else repr(float(x))


class Tree:
    """A single decision tree under construction or loaded from a model file."""

    def __init__(self, max_leaves: int, track_branch_features: bool = False,
                 is_linear: bool = False) -> None:
        self.max_leaves = max_leaves
        self.num_leaves = 1
        self.num_cat = 0
        n_int = max(max_leaves - 1, 1)
        self.left_child = np.zeros(n_int, dtype=np.int32)
        self.right_child = np.zeros(n_int, dtype=np.int32)
        self.split_feature_inner = np.zeros(n_int, dtype=np.int32)
        self.split_feature = np.zeros(n_int, dtype=np.int32)
        self.split_gain = np.zeros(n_int, dtype=np.float32)
        self.threshold_in_bin = np.zeros(n_int, dtype=np.int32)
        self.threshold = np.zeros(n_int, dtype=np.float64)
        self.decision_type = np.zeros(n_int, dtype=np.int8)
        self.internal_value = np.zeros(n_int, dtype=np.float64)
        self.internal_weight = np.zeros(n_int, dtype=np.float64)
        self.internal_count = np.zeros(n_int, dtype=np.int64)
        self.leaf_value = np.zeros(max_leaves, dtype=np.float64)
        self.leaf_weight = np.zeros(max_leaves, dtype=np.float64)
        self.leaf_count = np.zeros(max_leaves, dtype=np.int64)
        self.leaf_parent = np.full(max_leaves, -1, dtype=np.int32)
        self.leaf_depth = np.zeros(max_leaves, dtype=np.int32)
        # categorical split storage (tree.h cat_boundaries_/cat_threshold_)
        self.cat_boundaries: List[int] = [0]
        self.cat_threshold: List[int] = []  # uint32 bitset words over real values
        self.cat_boundaries_inner: List[int] = [0]
        self.cat_threshold_inner: List[int] = []  # bitset words over bins
        self.shrinkage = 1.0
        self.is_linear = is_linear
        self.track_branch_features = track_branch_features
        self.branch_features: List[List[int]] = [[] for _ in range(max_leaves)] if track_branch_features else []
        # linear-tree per-leaf models
        self.leaf_const = np.zeros(max_leaves, dtype=np.float64) if is_linear else None
        self.leaf_coeff: List[List[float]] = [[] for _ in range(max_leaves)] if is_linear else []
        self.leaf_features: List[List[int]] = [[] for _ in range(max_leaves)] if is_linear else []
        self.leaf_features_inner: List[List[int]] = [[] for _ in range(max_leaves)] if is_linear else []

    # ------------------------------------------------------------------ build

    def split(self, leaf: int, feature_inner: int, real_feature: int,
              threshold_bin: int, threshold_double: float, default_left: bool,
              missing_type: int, gain: float,
              left_value: float, right_value: float,
              left_count: int, right_count: int,
              left_weight: float, right_weight: float,
              parent_value: float) -> int:
        """Numerical split of `leaf`; returns the index of the new right leaf.

        Mirrors Tree::Split (tree.h:79-88 + tree.cpp): the split leaf keeps its
        id as the left child; the new leaf id is the current num_leaves.
        """
        new_node = self.num_leaves - 1
        new_leaf = self.num_leaves
        parent = self.leaf_parent[leaf]
        if parent >= 0:
            if self.left_child[parent] == ~leaf:
                self.left_child[parent] = new_node
            else:
                self.right_child[parent] = new_node
        self.split_feature_inner[new_node] = feature_inner
        self.split_feature[new_node] = real_feature
        self.split_gain[new_node] = gain
        self.threshold_in_bin[new_node] = threshold_bin
        self.threshold[new_node] = threshold_double
        dt = np.int8(0)
        if default_left:
            dt |= _DEFAULT_LEFT_MASK
        dt |= np.int8((missing_type & 3) << 2)
        self.decision_type[new_node] = dt
        self._finish_split(new_node, leaf, new_leaf, left_value, right_value,
                           left_count, right_count, left_weight, right_weight,
                           parent_value, real_feature)
        return new_leaf

    def split_categorical(self, leaf: int, feature_inner: int, real_feature: int,
                          bin_bitset: List[int], value_bitset: List[int],
                          missing_type: int, gain: float,
                          left_value: float, right_value: float,
                          left_count: int, right_count: int,
                          left_weight: float, right_weight: float,
                          parent_value: float) -> int:
        """Categorical split: membership in bitset -> left (tree.h:89-95)."""
        new_node = self.num_leaves - 1
        new_leaf = self.num_leaves
        parent = self.leaf_parent[leaf]
        if parent >= 0:
            if self.left_child[parent] == ~leaf:
                self.left_child[parent] = new_node
            else:
                self.right_child[parent] = new_node
        self.split_feature_inner[new_node] = feature_inner
        self.split_feature[new_node] = real_feature
        self.split_gain[new_node] = gain
        self.threshold_in_bin[new_node] = self.num_cat
        self.threshold[new_node] = float(self.num_cat)
        dt = np.int8(_CATEGORICAL_MASK)
        dt |= np.int8((missing_type & 3) << 2)
        self.decision_type[new_node] = dt
        self.cat_boundaries_inner.append(self.cat_boundaries_inner[-1] + len(bin_bitset))
        self.cat_threshold_inner.extend(int(w) for w in bin_bitset)
        self.cat_boundaries.append(self.cat_boundaries[-1] + len(value_bitset))
        self.cat_threshold.extend(int(w) for w in value_bitset)
        self.num_cat += 1
        self._finish_split(new_node, leaf, new_leaf, left_value, right_value,
                           left_count, right_count, left_weight, right_weight,
                           parent_value, real_feature)
        return new_leaf

    def _finish_split(self, new_node: int, leaf: int, new_leaf: int,
                      left_value: float, right_value: float,
                      left_count: int, right_count: int,
                      left_weight: float, right_weight: float,
                      parent_value: float, real_feature: int) -> None:
        self.left_child[new_node] = ~leaf
        self.right_child[new_node] = ~new_leaf
        self.internal_value[new_node] = parent_value
        self.internal_weight[new_node] = left_weight + right_weight
        self.internal_count[new_node] = left_count + right_count
        self.leaf_parent[new_leaf] = new_node
        self.leaf_parent[leaf] = new_node
        self.leaf_value[leaf] = 0.0 if math.isnan(left_value) else left_value
        self.leaf_value[new_leaf] = 0.0 if math.isnan(right_value) else right_value
        self.leaf_weight[leaf] = left_weight
        self.leaf_weight[new_leaf] = right_weight
        self.leaf_count[leaf] = left_count
        self.leaf_count[new_leaf] = right_count
        self.leaf_depth[new_leaf] = self.leaf_depth[leaf] + 1
        self.leaf_depth[leaf] += 1
        if self.track_branch_features:
            self.branch_features[new_leaf] = self.branch_features[leaf] + [real_feature]
            self.branch_features[leaf] = self.branch_features[leaf] + [real_feature]
        self.num_leaves += 1

    def set_leaf_output(self, leaf: int, value: float) -> None:
        self.leaf_value[leaf] = 0.0 if math.isnan(value) else value

    def shrink(self, rate: float) -> None:
        """Tree::Shrinkage (tree.h:188): scale all outputs by `rate`."""
        self.leaf_value[: self.num_leaves] *= rate
        self.internal_value[: max(self.num_leaves - 1, 0)] *= rate
        if self.is_linear and self.leaf_const is not None:
            self.leaf_const[: self.num_leaves] *= rate
            for i in range(self.num_leaves):
                self.leaf_coeff[i] = [c * rate for c in self.leaf_coeff[i]]
        self.shrinkage *= rate

    def add_bias(self, val: float) -> None:
        self.leaf_value[: self.num_leaves] += val
        self.internal_value[: max(self.num_leaves - 1, 0)] += val
        if self.is_linear and self.leaf_const is not None:
            self.leaf_const[: self.num_leaves] += val
        self.shrinkage = 1.0

    def as_constant_tree(self, val: float) -> None:
        self.num_leaves = 1
        self.leaf_value[0] = val

    @property
    def max_depth(self) -> int:
        if self.num_leaves <= 1:
            return 0
        return int(self.leaf_depth[: self.num_leaves].max())

    def expected_value(self) -> float:
        """Weighted mean output over the training distribution (for SHAP)."""
        if self.num_leaves == 1:
            return float(self.leaf_value[0])
        total = float(self.internal_count[0])
        if total <= 0:
            return float(self.leaf_value[0])
        return float(np.dot(self.leaf_value[: self.num_leaves],
                            self.leaf_count[: self.num_leaves]) / total)

    # -------------------------------------------------------------- inference

    def _decide_numerical(self, fval: float, node: int) -> int:
        missing_type = (int(self.decision_type[node]) >> 2) & 3
        if math.isnan(fval) and missing_type != MISSING_NAN:
            fval = 0.0
        if ((missing_type == MISSING_ZERO and abs(fval) <= _EPS)
                or (missing_type == MISSING_NAN and math.isnan(fval))):
            if int(self.decision_type[node]) & _DEFAULT_LEFT_MASK:
                return int(self.left_child[node])
            return int(self.right_child[node])
        if fval <= self.threshold[node]:
            return int(self.left_child[node])
        return int(self.right_child[node])

    def _decide_categorical(self, fval: float, node: int) -> int:
        if math.isnan(fval):
            return int(self.right_child[node])
        int_fval = int(fval)
        if int_fval < 0:
            return int(self.right_child[node])
        cat_idx = int(self.threshold[node])
        lo, hi = self.cat_boundaries[cat_idx], self.cat_boundaries[cat_idx + 1]
        word, bit = int_fval // 32, int_fval % 32
        if word < hi - lo and (self.cat_threshold[lo + word] >> bit) & 1:
            return int(self.left_child[node])
        return int(self.right_child[node])

    def predict_leaf_index(self, row: np.ndarray) -> int:
        if self.num_leaves <= 1:
            return 0
        node = 0
        while node >= 0:
            if int(self.decision_type[node]) & _CATEGORICAL_MASK:
                node = self._decide_categorical(float(row[self.split_feature[node]]), node)
            else:
                node = self._decide_numerical(float(row[self.split_feature[node]]), node)
        return ~node

    def predict(self, row: np.ndarray) -> float:
        leaf = self.predict_leaf_index(row)
        out = float(self.leaf_value[leaf])
        if self.is_linear:
            out = float(self.leaf_const[leaf])
            ok = True
            for feat, coef in zip(self.leaf_features[leaf], self.leaf_coeff[leaf]):
                v = float(row[feat])
                if math.isnan(v) or math.isinf(v):
                    ok = False
                    break
                out += coef * v
            if not ok:
                out = float(self.leaf_value[leaf])
        return out

    # ---------------------------------------------------------- serialization

    def to_string(self) -> str:
        """Reference text format (tree.cpp:349-410)."""
        n = self.num_leaves
        ni = max(n - 1, 0)

        def ints(a, k):
            return " ".join(str(int(x)) for x in a[:k])

        def floats(a, k):
            return " ".join(_fmt(float(x)) for x in a[:k])

        lines = [f"num_leaves={n}", f"num_cat={self.num_cat}"]
        lines.append("split_feature=" + ints(self.split_feature, ni))
        lines.append("split_gain=" + " ".join(_fmt(float(x)) for x in self.split_gain[:ni]))
        lines.append("threshold=" + floats(self.threshold, ni))
        lines.append("decision_type=" + ints(self.decision_type, ni))
        lines.append("left_child=" + ints(self.left_child, ni))
        lines.append("right_child=" + ints(self.right_child, ni))
        lines.append("leaf_value=" + floats(self.leaf_value, n))
        lines.append("leaf_weight=" + floats(self.leaf_weight, n))
        lines.append("leaf_count=" + ints(self.leaf_count, n))
        lines.append("internal_value=" + floats(self.internal_value, ni))
        lines.append("internal_weight=" + floats(self.internal_weight, ni))
        lines.append("internal_count=" + ints(self.internal_count, ni))
        if self.num_cat > 0:
            lines.append("cat_boundaries=" + " ".join(str(x) for x in self.cat_boundaries))
            lines.append("cat_threshold=" + " ".join(str(x) for x in self.cat_threshold))
        lines.append(f"is_linear={1 if self.is_linear else 0}")
        if self.is_linear:
            lines.append("leaf_const=" + floats(self.leaf_const, n))
            lines.append("num_features=" + " ".join(str(len(self.leaf_features[i])) for i in range(n)))
            lines.append("leaf_features=" + " ".join(
                (" ".join(str(f) for f in self.leaf_features[i]) + " ") if self.leaf_features[i] else " "
                for i in range(n)).rstrip() )
            lines.append("leaf_coeff=" + " ".join(
                (" ".join(_fmt(c) for c in self.leaf_coeff[i]) + " ") if self.leaf_coeff[i] else " "
                for i in range(n)).rstrip())
        shr = self.shrinkage
        lines.append("shrinkage=" + (_fmt(shr) if shr != int(shr) else str(int(shr))))
        lines.append("")
        return "\n".join(lines) + "\n"

    @classmethod
    def from_key_values(cls, kv: Dict[str, str]) -> "Tree":
        """Build from a parsed `Tree=i` section (Tree::Tree(const char*, ...))."""
        num_leaves = int(kv["num_leaves"])
        tree = cls(max(num_leaves, 2))
        tree.num_leaves = num_leaves
        tree.num_cat = int(kv.get("num_cat", "0"))
        ni = max(num_leaves - 1, 0)

        def geti(key, k, dtype=np.int64):
            if k == 0 or key not in kv or kv[key] == "":
                return np.zeros(k, dtype=dtype)
            return np.array([int(x) for x in kv[key].split()], dtype=dtype)[:k]

        def getf(key, k):
            if k == 0 or key not in kv or kv[key] == "":
                return np.zeros(k, dtype=np.float64)
            return np.array([float(x) for x in kv[key].split()], dtype=np.float64)[:k]

        if ni > 0:
            tree.split_feature[:ni] = geti("split_feature", ni)
            tree.split_feature_inner[:ni] = tree.split_feature[:ni]
            tree.split_gain[:ni] = getf("split_gain", ni) if "split_gain" in kv else 0
            tree.threshold[:ni] = getf("threshold", ni)
            tree.decision_type[:ni] = geti("decision_type", ni, np.int8)
            tree.left_child[:ni] = geti("left_child", ni)
            tree.right_child[:ni] = geti("right_child", ni)
            tree.internal_value[:ni] = getf("internal_value", ni)
            tree.internal_weight[:ni] = getf("internal_weight", ni)
            tree.internal_count[:ni] = geti("internal_count", ni)
        tree.leaf_value[:num_leaves] = getf("leaf_value", num_leaves)
        tree.leaf_weight[:num_leaves] = getf("leaf_weight", num_leaves)
        tree.leaf_count[:num_leaves] = geti("leaf_count", num_leaves)
        if tree.num_cat > 0:
            tree.cat_boundaries = [int(x) for x in kv["cat_boundaries"].split()]
            tree.cat_threshold = [int(x) for x in kv["cat_threshold"].split()]
            tree.cat_boundaries_inner = list(tree.cat_boundaries)
            tree.cat_threshold_inner = list(tree.cat_threshold)
        tree.is_linear = kv.get("is_linear", "0").strip() == "1"
        if tree.is_linear:
            tree.leaf_const = np.zeros(tree.max_leaves, dtype=np.float64)
            tree.leaf_const[:num_leaves] = getf("leaf_const", num_leaves)
            nf = geti("num_features", num_leaves)
            feats = [int(x) for x in kv.get("leaf_features", "").split()]
            coefs = [float(x) for x in kv.get("leaf_coeff", "").split()]
            tree.leaf_features = []
            tree.leaf_coeff = []
            pos = 0
            for i in range(num_leaves):
                k = int(nf[i])
                tree.leaf_features.append(feats[pos: pos + k])
                tree.leaf_coeff.append(coefs[pos: pos + k])
                pos += k
            tree.leaf_features_inner = [list(f) for f in tree.leaf_features]
        tree.shrinkage = float(kv.get("shrinkage", "1"))
        # recompute leaf depth/parents from children arrays
        if num_leaves > 1:
            stack = [(0, 0)]
            while stack:
                node, depth = stack.pop()
                for child in (int(tree.left_child[node]), int(tree.right_child[node])):
                    if child < 0:
                        tree.leaf_parent[~child] = node
                        tree.leaf_depth[~child] = depth + 1
                    else:
                        stack.append((child, depth + 1))
        return tree

    # ------------------------------------------------------------------ JSON

    def to_json(self) -> str:
        import json

        def node_json(node: int, depth: int):
            if node < 0:
                leaf = ~node
                d = {"leaf_index": leaf, "leaf_value": self.leaf_value[leaf],
                     "leaf_weight": self.leaf_weight[leaf],
                     "leaf_count": int(self.leaf_count[leaf])}
                return d
            dt = int(self.decision_type[node])
            is_cat = bool(dt & _CATEGORICAL_MASK)
            missing = ["None", "Zero", "NaN"][(dt >> 2) & 3]
            if is_cat:
                cat_idx = int(self.threshold[node])
                lo, hi = self.cat_boundaries[cat_idx], self.cat_boundaries[cat_idx + 1]
                cats = []
                for w in range(lo, hi):
                    for b in range(32):
                        if (self.cat_threshold[w] >> b) & 1:
                            cats.append((w - lo) * 32 + b)
                threshold = "||".join(str(c) for c in cats)
                decision = "=="
            else:
                threshold = self.threshold[node]
                decision = "<="
            return {
                "split_index": node,
                "split_feature": int(self.split_feature[node]),
                "split_gain": float(self.split_gain[node]),
                "threshold": threshold,
                "decision_type": decision,
                "default_left": bool(dt & _DEFAULT_LEFT_MASK),
                "missing_type": missing,
                "internal_value": self.internal_value[node],
                "internal_weight": self.internal_weight[node],
                "internal_count": int(self.internal_count[node]),
                "left_child": node_json(int(self.left_child[node]), depth + 1),
                "right_child": node_json(int(self.right_child[node]), depth + 1),
            }

        body = {"num_leaves": self.num_leaves, "num_cat": self.num_cat,
                "shrinkage": self.shrinkage}
        if self.num_leaves == 1:
            body["tree_structure"] = {"leaf_value": self.leaf_value[0],
                                      "leaf_count": int(self.leaf_count[0])}
        else:
            body["tree_structure"] = node_json(0, 0)
        return json.dumps(body)
