"""On-demand build + load of the native C++ parser extension.

The reference ships its parser stack as C++ (src/io/parser.cpp); here the
native module is compiled once per interpreter ABI with plain g++ against
the CPython headers (no pybind11 dependency) into this package directory,
then dlopen'd as a normal extension module. Every caller treats a missing
toolchain or failed build as "no native parser" and falls back to the
pure-numpy path in io/parser.py.
"""
from __future__ import annotations

import importlib.util
import os
import subprocess
import sys
import sysconfig
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "parser.cpp")
_cached = None  # None = not tried, False = unavailable, module otherwise


def _so_path() -> str:
    tag = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return os.path.join(_DIR, f"_lgbt_parser{tag}")


def _build() -> Optional[str]:
    so = _so_path()
    if (os.path.exists(so)
            and os.path.getmtime(so) >= os.path.getmtime(_SRC)):
        return so
    include = sysconfig.get_paths()["include"]
    # build to a per-process temp file + atomic rename: concurrent workers
    # (lightgbm_tpu.launch) must never dlopen a half-written .so
    tmp = f"{so}.build.{os.getpid()}"
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-pthread", f"-I{include}",
           _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
    except Exception:  # noqa: BLE001 - toolchain missing/failed: no native
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return so


def get_parser():
    """The compiled _lgbt_parser module, or None when unavailable."""
    global _cached
    if _cached is not None:
        return _cached or None
    if os.environ.get("LIGHTGBM_TPU_NO_NATIVE"):
        _cached = False
        return None
    so = _build()
    if so is None:
        _cached = False
        return None
    try:
        spec = importlib.util.spec_from_file_location("_lgbt_parser", so)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        sys.modules["_lgbt_parser"] = mod
        _cached = mod
    except Exception:  # noqa: BLE001
        _cached = False
        return None
    return _cached
