// Native text parser for lightgbm_tpu.
//
// TPU-native counterpart of the reference's C++ Parser stack
// (src/io/parser.cpp CSVParser/TSVParser/LibSVMParser + the OMP block
// parsing in src/io/dataset_loader.cpp LoadTextDataToMemory): tokenizes
// CSV/TSV (single-char or whitespace delimited) and LibSVM files with
// strtod. Dense parsing is PIPELINED: the buffer splits at line boundaries
// into one shard per hardware thread, shards parse concurrently with the
// GIL released, results concatenate in order — the std::thread analog of
// the reference's `#pragma omp parallel for` over line blocks. Exposed as
// a tiny CPython extension module (no pybind11 — plain Python C API)
// returning raw double buffers the Python side wraps with np.frombuffer;
// built on demand by __init__.py.
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cctype>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

bool read_file(const char* path, std::string* out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  out->resize(static_cast<size_t>(size));
  size_t got = size ? std::fread(&(*out)[0], 1, static_cast<size_t>(size), f)
                    : 0;
  std::fclose(f);
  out->resize(got);
  return true;
}

inline double parse_token(const char* tok, const char* end) {
  if (tok == end) return NAN;
  char* stop = nullptr;
  double v = std::strtod(tok, &stop);
  if (stop == tok) {
    // na / NA / ? / empty -> NaN (reference Atof NaN semantics)
    return NAN;
  }
  return v;
}

struct ShardResult {
  std::vector<double> values;
  long rows = 0;
  long ncols = -1;
  bool bad = false;  // inconsistent column count inside this shard
};

// parse one [p, fend) line-aligned shard; delim == 0 means "any whitespace"
void parse_dense_range(const char* p, const char* fend, char delim,
                       ShardResult* out) {
  while (p < fend) {
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(fend - p)));
    if (!line_end) line_end = fend;
    const char* q = p;
    const char* qe = line_end;
    if (qe > q && qe[-1] == '\r') --qe;
    if (q == qe) {  // blank line
      p = line_end + 1;
      continue;
    }
    long row_cols = 0;
    if (delim == 0) {
      while (q < qe) {
        while (q < qe && std::isspace(static_cast<unsigned char>(*q))) ++q;
        if (q >= qe) break;
        const char* tok = q;
        while (q < qe && !std::isspace(static_cast<unsigned char>(*q))) ++q;
        out->values.push_back(parse_token(tok, q));
        ++row_cols;
      }
    } else {
      const char* tok = q;
      for (;; ++q) {
        if (q == qe || *q == delim) {
          out->values.push_back(parse_token(tok, q));
          ++row_cols;
          if (q == qe) break;
          tok = q + 1;
        }
      }
    }
    if (out->ncols < 0) {
      out->ncols = row_cols;
    } else if (row_cols != out->ncols) {
      out->bad = true;
      return;
    }
    ++out->rows;
    p = line_end + 1;
  }
}

// dense CSV/TSV: pipelined over hardware threads, GIL released
PyObject* parse_dense(PyObject*, PyObject* args) {
  const char* path;
  int delim_int, skip_header;
  if (!PyArg_ParseTuple(args, "sii", &path, &delim_int, &skip_header)) {
    return nullptr;
  }
  const char delim = static_cast<char>(delim_int);
  std::string buf;
  if (!read_file(path, &buf)) {
    PyErr_SetString(PyExc_OSError, "cannot open data file");
    return nullptr;
  }
  const char* p = buf.data();
  const char* fend = p + buf.size();
  if (skip_header && p < fend) {  // drop the first line
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(fend - p)));
    p = line_end ? line_end + 1 : fend;
  }

  unsigned hw = std::thread::hardware_concurrency();
  size_t n_shards = hw ? (hw > 16 ? 16 : hw) : 1;
  if (static_cast<size_t>(fend - p) < (4u << 20)) n_shards = 1;
  std::vector<ShardResult> shards(n_shards);
  {
    // shard boundaries snapped forward to the next newline
    std::vector<const char*> starts(n_shards + 1);
    size_t span = static_cast<size_t>(fend - p) / n_shards;
    starts[0] = p;
    for (size_t s = 1; s < n_shards; ++s) {
      const char* cut = p + s * span;
      if (cut >= fend) {
        cut = fend;
      } else {
        const char* nl = static_cast<const char*>(
            std::memchr(cut, '\n', static_cast<size_t>(fend - cut)));
        cut = nl ? nl + 1 : fend;
      }
      starts[s] = cut < starts[s - 1] ? starts[s - 1] : cut;
    }
    starts[n_shards] = fend;

    Py_BEGIN_ALLOW_THREADS;
    std::vector<std::thread> workers;
    for (size_t s = 1; s < n_shards; ++s) {
      workers.emplace_back(parse_dense_range, starts[s], starts[s + 1],
                           delim, &shards[s]);
    }
    parse_dense_range(starts[0], starts[1], delim, &shards[0]);
    for (auto& w : workers) w.join();
    Py_END_ALLOW_THREADS;
  }

  Py_ssize_t nrows = 0, ncols = -1;
  size_t total_values = 0;
  for (const auto& sh : shards) {
    if (sh.bad) {
      PyErr_SetString(PyExc_ValueError, "inconsistent column count");
      return nullptr;
    }
    if (sh.ncols >= 0) {
      if (ncols < 0) {
        ncols = sh.ncols;
      } else if (sh.ncols != ncols) {
        PyErr_SetString(PyExc_ValueError, "inconsistent column count");
        return nullptr;
      }
    }
    nrows += sh.rows;
    total_values += sh.values.size();
  }
  if (ncols < 0) ncols = 0;
  PyObject* bytes = PyBytes_FromStringAndSize(
      nullptr, static_cast<Py_ssize_t>(total_values * sizeof(double)));
  if (!bytes) return nullptr;
  char* dst = PyBytes_AS_STRING(bytes);
  for (const auto& sh : shards) {
    std::memcpy(dst, sh.values.data(), sh.values.size() * sizeof(double));
    dst += sh.values.size() * sizeof(double);
  }
  return Py_BuildValue("(Nnn)", bytes, nrows, ncols);
}

// LibSVM: label idx:val idx:val ... -> (labels, triplets of (row, col, val))
PyObject* parse_libsvm(PyObject*, PyObject* args) {
  const char* path;
  int skip_header;
  if (!PyArg_ParseTuple(args, "si", &path, &skip_header)) return nullptr;
  std::string buf;
  if (!read_file(path, &buf)) {
    PyErr_SetString(PyExc_OSError, "cannot open data file");
    return nullptr;
  }
  std::vector<double> labels;
  std::vector<double> trips;  // row, col, val
  long max_feat = -1;
  const char* p = buf.data();
  const char* fend = p + buf.size();
  int line_no = 0;
  long row = 0;
  while (p < fend) {
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(fend - p)));
    if (!line_end) line_end = fend;
    const char* q = p;
    const char* qe = line_end;
    if (qe > q && qe[-1] == '\r') --qe;
    ++line_no;
    if ((skip_header && line_no == 1) || q == qe) {
      p = line_end + 1;
      continue;
    }
    bool first = true;
    while (q < qe) {
      while (q < qe && std::isspace(static_cast<unsigned char>(*q))) ++q;
      if (q >= qe) break;
      const char* tok = q;
      while (q < qe && !std::isspace(static_cast<unsigned char>(*q))) ++q;
      const char* colon = static_cast<const char*>(
          std::memchr(tok, ':', static_cast<size_t>(q - tok)));
      if (first && !colon) {
        labels.push_back(parse_token(tok, q));
        first = false;
      } else if (colon) {
        if (first) {  // qid-less line starting with idx:val -> label 0
          labels.push_back(0.0);
          first = false;
        }
        // the index must be purely numeric: `qid:3`-style tokens are NOT
        // silently coerced (strtol would map them to feature 0) — error out
        // so the caller surfaces the same failure as the python parser
        for (const char* c = tok; c < colon; ++c) {
          if (!std::isdigit(static_cast<unsigned char>(*c))) {
            PyErr_Format(PyExc_ValueError,
                         "non-numeric feature index in libsvm token at "
                         "line %d", line_no);
            return nullptr;
          }
        }
        long idx = std::strtol(tok, nullptr, 10);
        double val = parse_token(colon + 1, q);
        if (idx > max_feat) max_feat = idx;
        trips.push_back(static_cast<double>(row));
        trips.push_back(static_cast<double>(idx));
        trips.push_back(val);
      }
    }
    if (first) labels.push_back(0.0);
    ++row;
    p = line_end + 1;
  }
  PyObject* lab = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(labels.data()),
      static_cast<Py_ssize_t>(labels.size() * sizeof(double)));
  PyObject* tri = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(trips.data()),
      static_cast<Py_ssize_t>(trips.size() * sizeof(double)));
  if (!lab || !tri) return nullptr;
  return Py_BuildValue("(NNl)", lab, tri, max_feat);
}

PyMethodDef methods[] = {
    {"parse_dense", parse_dense, METH_VARARGS,
     "parse_dense(path, delim_ord, skip_header) -> (bytes, nrows, ncols)"},
    {"parse_libsvm", parse_libsvm, METH_VARARGS,
     "parse_libsvm(path, skip_header) -> (labels, triplets, max_feat)"},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef moduledef = {PyModuleDef_HEAD_INIT, "_lgbt_parser",
                         "native text parser", -1, methods,
                         nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__lgbt_parser(void) {
  return PyModule_Create(&moduledef);
}
