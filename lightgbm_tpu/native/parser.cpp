// Native text parser for lightgbm_tpu.
//
// TPU-native counterpart of the reference's C++ Parser stack
// (src/io/parser.cpp CSVParser/TSVParser/LibSVMParser): tokenizes CSV/TSV
// (single-char or whitespace delimited) and LibSVM files with strtod in one
// pass over a buffered read. Exposed as a tiny CPython extension module
// (no pybind11 — plain Python C API) returning raw double buffers the
// Python side wraps with np.frombuffer; built on demand by build.py.
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cctype>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

bool read_file(const char* path, std::string* out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  out->resize(static_cast<size_t>(size));
  size_t got = size ? std::fread(&(*out)[0], 1, static_cast<size_t>(size), f)
                    : 0;
  std::fclose(f);
  out->resize(got);
  return true;
}

inline double parse_token(const char* tok, const char* end) {
  if (tok == end) return NAN;
  char* stop = nullptr;
  double v = std::strtod(tok, &stop);
  if (stop == tok) {
    // na / NA / ? / empty -> NaN (reference Atof NaN semantics)
    return NAN;
  }
  return v;
}

// dense CSV/TSV: delim == 0 means "any whitespace run"
PyObject* parse_dense(PyObject*, PyObject* args) {
  const char* path;
  int delim_int, skip_header;
  if (!PyArg_ParseTuple(args, "sii", &path, &delim_int, &skip_header)) {
    return nullptr;
  }
  const char delim = static_cast<char>(delim_int);
  std::string buf;
  if (!read_file(path, &buf)) {
    PyErr_SetString(PyExc_OSError, "cannot open data file");
    return nullptr;
  }
  std::vector<double> values;
  values.reserve(1 << 20);
  Py_ssize_t nrows = 0, ncols = -1;
  const char* p = buf.data();
  const char* fend = p + buf.size();
  int line_no = 0;
  while (p < fend) {
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(fend - p)));
    if (!line_end) line_end = fend;
    const char* q = p;
    const char* qe = line_end;
    if (qe > q && qe[-1] == '\r') --qe;
    ++line_no;
    if (skip_header && line_no == 1) {
      p = line_end + 1;
      continue;
    }
    if (q == qe) {  // blank line
      p = line_end + 1;
      continue;
    }
    Py_ssize_t row_cols = 0;
    if (delim == 0) {
      while (q < qe) {
        while (q < qe && std::isspace(static_cast<unsigned char>(*q))) ++q;
        if (q >= qe) break;
        const char* tok = q;
        while (q < qe && !std::isspace(static_cast<unsigned char>(*q))) ++q;
        values.push_back(parse_token(tok, q));
        ++row_cols;
      }
    } else {
      const char* tok = q;
      for (;; ++q) {
        if (q == qe || *q == delim) {
          values.push_back(parse_token(tok, q));
          ++row_cols;
          if (q == qe) break;
          tok = q + 1;
        }
      }
    }
    if (ncols < 0) {
      ncols = row_cols;
    } else if (row_cols != ncols) {
      PyErr_SetString(PyExc_ValueError, "inconsistent column count");
      return nullptr;
    }
    ++nrows;
    p = line_end + 1;
  }
  if (ncols < 0) ncols = 0;
  PyObject* bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(values.data()),
      static_cast<Py_ssize_t>(values.size() * sizeof(double)));
  if (!bytes) return nullptr;
  return Py_BuildValue("(Nnn)", bytes, nrows, ncols);
}

// LibSVM: label idx:val idx:val ... -> (labels, triplets of (row, col, val))
PyObject* parse_libsvm(PyObject*, PyObject* args) {
  const char* path;
  int skip_header;
  if (!PyArg_ParseTuple(args, "si", &path, &skip_header)) return nullptr;
  std::string buf;
  if (!read_file(path, &buf)) {
    PyErr_SetString(PyExc_OSError, "cannot open data file");
    return nullptr;
  }
  std::vector<double> labels;
  std::vector<double> trips;  // row, col, val
  long max_feat = -1;
  const char* p = buf.data();
  const char* fend = p + buf.size();
  int line_no = 0;
  long row = 0;
  while (p < fend) {
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(fend - p)));
    if (!line_end) line_end = fend;
    const char* q = p;
    const char* qe = line_end;
    if (qe > q && qe[-1] == '\r') --qe;
    ++line_no;
    if ((skip_header && line_no == 1) || q == qe) {
      p = line_end + 1;
      continue;
    }
    bool first = true;
    while (q < qe) {
      while (q < qe && std::isspace(static_cast<unsigned char>(*q))) ++q;
      if (q >= qe) break;
      const char* tok = q;
      while (q < qe && !std::isspace(static_cast<unsigned char>(*q))) ++q;
      const char* colon = static_cast<const char*>(
          std::memchr(tok, ':', static_cast<size_t>(q - tok)));
      if (first && !colon) {
        labels.push_back(parse_token(tok, q));
        first = false;
      } else if (colon) {
        if (first) {  // qid-less line starting with idx:val -> label 0
          labels.push_back(0.0);
          first = false;
        }
        // the index must be purely numeric: `qid:3`-style tokens are NOT
        // silently coerced (strtol would map them to feature 0) — error out
        // so the caller surfaces the same failure as the python parser
        for (const char* c = tok; c < colon; ++c) {
          if (!std::isdigit(static_cast<unsigned char>(*c))) {
            PyErr_Format(PyExc_ValueError,
                         "non-numeric feature index in libsvm token at "
                         "line %d", line_no);
            return nullptr;
          }
        }
        long idx = std::strtol(tok, nullptr, 10);
        double val = parse_token(colon + 1, q);
        if (idx > max_feat) max_feat = idx;
        trips.push_back(static_cast<double>(row));
        trips.push_back(static_cast<double>(idx));
        trips.push_back(val);
      }
    }
    if (first) labels.push_back(0.0);
    ++row;
    p = line_end + 1;
  }
  PyObject* lab = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(labels.data()),
      static_cast<Py_ssize_t>(labels.size() * sizeof(double)));
  PyObject* tri = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(trips.data()),
      static_cast<Py_ssize_t>(trips.size() * sizeof(double)));
  if (!lab || !tri) return nullptr;
  return Py_BuildValue("(NNl)", lab, tri, max_feat);
}

PyMethodDef methods[] = {
    {"parse_dense", parse_dense, METH_VARARGS,
     "parse_dense(path, delim_ord, skip_header) -> (bytes, nrows, ncols)"},
    {"parse_libsvm", parse_libsvm, METH_VARARGS,
     "parse_libsvm(path, skip_header) -> (labels, triplets, max_feat)"},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef moduledef = {PyModuleDef_HEAD_INIT, "_lgbt_parser",
                         "native text parser", -1, methods,
                         nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__lgbt_parser(void) {
  return PyModule_Create(&moduledef);
}
