from .registry import create_objective, ObjectiveFunction, OBJECTIVE_REGISTRY

__all__ = ["create_objective", "ObjectiveFunction", "OBJECTIVE_REGISTRY"]
