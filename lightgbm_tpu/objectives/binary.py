"""Binary classification objective.

Counterpart of BinaryLogloss (src/objective/binary_objective.hpp): sigmoid-
scaled logistic loss with is_unbalance / scale_pos_weight class weighting,
boost-from-average init score, and sigmoid output conversion.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from .registry import ObjectiveFunction, register_objective
from ..utils.log import Log

K_EPS = 1e-15


@register_objective("binary")
class BinaryLogloss(ObjectiveFunction):
    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = config.sigmoid
        if self.sigmoid <= 0:
            Log.fatal("Sigmoid parameter %f should be greater than zero", self.sigmoid)
        self.is_unbalance = config.is_unbalance
        self.scale_pos_weight = config.scale_pos_weight
        if self.is_unbalance and abs(self.scale_pos_weight - 1.0) > 1e-6:
            Log.fatal("Cannot set is_unbalance and scale_pos_weight at the same time")

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        label = metadata.label
        self.is_pos = (label > 0).astype(np.float64)
        cnt_pos = int(self.is_pos.sum())
        cnt_neg = num_data - cnt_pos
        self.need_train = True
        if cnt_pos == 0 or cnt_neg == 0:
            Log.warning("Contains only one class")
            self.need_train = False
        w_pos, w_neg = 1.0, 1.0
        if self.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        w_pos *= self.scale_pos_weight
        self.w_pos, self.w_neg = w_pos, w_neg
        # signed labels {-1, +1} and per-row class weights
        self._sign = jnp.asarray(np.where(self.is_pos > 0, 1.0, -1.0), dtype=jnp.float32)
        lw = np.where(self.is_pos > 0, w_pos, w_neg)
        if metadata.weights is not None:
            lw = lw * metadata.weights
        self._lw = jnp.asarray(lw, dtype=jnp.float32)

    def get_gradients(self, score):
        # response = -y*sigma / (1 + exp(y*sigma*score))  (binary_objective.hpp:117)
        response = -self._sign * self.sigmoid / (1.0 + jnp.exp(self._sign * self.sigmoid * score))
        abs_r = jnp.abs(response)
        grad = response * self._lw
        hess = abs_r * (self.sigmoid - abs_r) * self._lw
        return grad, hess

    def boost_from_score(self, class_id=0):
        if self.metadata.weights is not None:
            suml = float(np.sum(self.is_pos * self.metadata.weights))
            sumw = float(np.sum(self.metadata.weights))
        else:
            suml = float(self.is_pos.sum())
            sumw = float(self.num_data)
        pavg = min(max(suml / max(sumw, K_EPS), K_EPS), 1.0 - K_EPS)
        init = math.log(pavg / (1.0 - pavg)) / self.sigmoid
        Log.info("[binary:BoostFromScore]: pavg=%f -> initscore=%f", pavg, init)
        return init

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * raw))

    def to_string(self):
        return f"binary sigmoid:{self.sigmoid:g}"
