"""Multiclass objectives: softmax and one-vs-all.

Counterpart of src/objective/multiclass_objective.hpp: MulticlassSoftmax
(grad = p - y, hess = K/(K-1) * p * (1-p), :86-107,31) and MulticlassOVA
(per-class BinaryLogloss, :228-268). num_class trees per iteration.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .binary import BinaryLogloss
from .registry import ObjectiveFunction, register_objective
from ..utils.log import Log

K_EPS = 1e-15


@register_objective("multiclass", "softmax")
class MulticlassSoftmax(ObjectiveFunction):
    def __init__(self, config):
        super().__init__(config)
        self.num_class_ = config.num_class
        if self.num_class_ < 2:
            Log.fatal("Number of classes should be specified and greater than 1 for multiclass training")
        self.factor = self.num_class_ / (self.num_class_ - 1.0)

    @property
    def num_model_per_iteration(self):
        return self.num_class_

    @property
    def num_class(self):
        return self.num_class_

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        label_int = metadata.label.astype(np.int32)
        if label_int.min() < 0 or label_int.max() >= self.num_class_:
            Log.fatal("Label must be in [0, %d), but found %d in label",
                      self.num_class_, int(label_int.max()))
        onehot = np.zeros((self.num_class_, num_data), dtype=np.float32)
        onehot[label_int, np.arange(num_data)] = 1.0
        self._onehot = jnp.asarray(onehot)
        self._w = (jnp.asarray(metadata.weights) if metadata.weights is not None else None)
        probs = onehot.sum(axis=1) if metadata.weights is None else \
            (onehot * np.asarray(metadata.weights)[None, :]).sum(axis=1)
        self.class_init_probs = probs / probs.sum()

    def get_gradients(self, score):
        """score [C, N] -> (grad [C, N], hess [C, N]) — softmax over classes."""
        p = jax.nn.softmax(score, axis=0)
        grad = p - self._onehot
        hess = self.factor * p * (1.0 - p)
        if self._w is not None:
            grad = grad * self._w[None, :]
            hess = hess * self._w[None, :]
        return grad, hess

    def boost_from_score(self, class_id=0):
        init = math.log(max(K_EPS, float(self.class_init_probs[class_id])))
        Log.info("[multiclass:BoostFromScore]: class %d init=%f", class_id, init)
        return init

    def class_need_train(self, class_id):
        p = float(self.class_init_probs[class_id])
        return K_EPS < abs(p) < 1.0 - K_EPS

    def convert_output(self, raw):
        """Softmax over the class axis; raw is [N, C]."""
        return jax.nn.softmax(raw, axis=-1)

    def to_string(self):
        return f"multiclass num_class:{self.num_class_}"


@register_objective("multiclassova", "multiclass_ova", "ova", "ovr")
class MulticlassOVA(ObjectiveFunction):
    def __init__(self, config):
        super().__init__(config)
        self.num_class_ = config.num_class
        if self.num_class_ < 2:
            Log.fatal("Number of classes should be specified and greater than 1 for multiclass training")
        self.sigmoid = config.sigmoid
        self._binaries = [BinaryLogloss(config) for _ in range(self.num_class_)]

    @property
    def num_model_per_iteration(self):
        return self.num_class_

    @property
    def num_class(self):
        return self.num_class_

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        from ..io.metadata import Metadata

        label = metadata.label
        for k, b in enumerate(self._binaries):
            md = Metadata(num_data)
            md.label = (label.astype(np.int32) == k).astype(np.float32)
            md.weights = metadata.weights
            b.init(md, num_data)

    def get_gradients(self, score):
        grads, hesses = [], []
        for k, b in enumerate(self._binaries):
            g, h = b.get_gradients(score[k])
            grads.append(g)
            hesses.append(h)
        return jnp.stack(grads), jnp.stack(hesses)

    def boost_from_score(self, class_id=0):
        return self._binaries[class_id].boost_from_score(0)

    def class_need_train(self, class_id):
        return self._binaries[class_id].need_train

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * raw))

    def to_string(self):
        return f"multiclassova num_class:{self.num_class_} sigmoid:{self.sigmoid:g}"
