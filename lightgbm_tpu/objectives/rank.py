"""Learning-to-rank objectives: lambdarank and rank_xendcg.

Counterpart of src/objective/rank_objective.hpp: RankingObjective (per-query
gradient computation, :25-100), LambdarankNDCG (:138-290: |ΔNDCG|-weighted
pairwise lambdas with truncation, sigmoid scaling, and lambda normalization)
and RankXENDCG (:300+).

TPU design: the reference parallelizes with one OpenMP task per query over
ragged boundaries. Here queries are padded into dense [Q, L] blocks bucketed
by length (powers of two), and the whole pairwise lambda computation for a
bucket is one jitted tensor program: sort by score, build the [L, L] pairwise
ΔNDCG/sigmoid matrices, reduce rows, and scatter back to the flat row space.
Pad slots carry score = -inf so they sort last and are masked out of pairs.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .registry import ObjectiveFunction, register_objective
from ..utils.log import Log

K_MIN_SCORE = -1e30


def default_label_gain(max_label: int = 31) -> np.ndarray:
    """DCGCalculator::DefaultLabelGain (dcg_calculator.cpp:33-42): 2^i - 1."""
    g = [0.0]
    for i in range(1, max_label):
        g.append(float((1 << i) - 1))
    return np.array(g)


class QueryLayout:
    """Padded per-bucket query layout shared by ranking objectives/metrics.

    For each power-of-two length bucket: doc_idx [Qb, Lb] (global row ids,
    pad = num_data), labels [Qb, Lb], valid mask, and the query ids.
    """

    def __init__(self, query_boundaries: np.ndarray, labels: np.ndarray,
                 num_data: int, min_bucket: int = 8) -> None:
        self.num_data = num_data
        self.num_queries = len(query_boundaries) - 1
        lengths = np.diff(query_boundaries)
        buckets: Dict[int, List[int]] = {}
        for q, ln in enumerate(lengths):
            b = min_bucket
            while b < ln:
                b <<= 1
            buckets.setdefault(b, []).append(q)
        self.buckets = []
        for L, qids in sorted(buckets.items()):
            Qb = len(qids)
            doc_idx = np.full((Qb, L), num_data, dtype=np.int32)
            lab = np.zeros((Qb, L), dtype=np.float32)
            for r, q in enumerate(qids):
                lo, hi = query_boundaries[q], query_boundaries[q + 1]
                doc_idx[r, : hi - lo] = np.arange(lo, hi)
                lab[r, : hi - lo] = labels[lo:hi]
            valid = doc_idx < num_data
            self.buckets.append({
                "L": L,
                "qids": np.array(qids),
                "doc_idx": jnp.asarray(doc_idx),
                "labels": jnp.asarray(lab),
                "valid": jnp.asarray(valid),
            })


def max_dcg_at_k(labels_sorted_desc: np.ndarray, k: int, gains: np.ndarray) -> float:
    """DCGCalculator::CalMaxDCGAtK."""
    n = min(len(labels_sorted_desc), k)
    disc = 1.0 / np.log2(np.arange(n) + 2.0)
    return float(np.sum(gains[labels_sorted_desc[:n].astype(int)] * disc))


@register_objective("lambdarank")
class LambdarankNDCG(ObjectiveFunction):
    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = config.sigmoid
        if self.sigmoid <= 0:
            Log.fatal("Sigmoid param %f should be greater than zero", self.sigmoid)
        self.norm = config.lambdarank_norm
        self.truncation_level = config.lambdarank_truncation_level
        gains = np.array(config.label_gain, dtype=np.float64) if config.label_gain \
            else default_label_gain()
        self.label_gain = gains

    jit_gradients = False  # manages per-bucket jits internally

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            Log.fatal("Ranking tasks require query information")
        qb = metadata.query_boundaries
        label = metadata.label
        if label.max() >= len(self.label_gain):
            Log.fatal("Label %d is not less than the number of label mappings (%d)",
                      int(label.max()), len(self.label_gain))
        self.layout = QueryLayout(qb, label, num_data)
        # per-query 1/maxDCG@trunc
        inv = np.zeros(self.layout.num_queries)
        for q in range(self.layout.num_queries):
            lo, hi = qb[q], qb[q + 1]
            srt = np.sort(label[lo:hi])[::-1]
            mx = max_dcg_at_k(srt, self.truncation_level, self.label_gain)
            inv[q] = 1.0 / mx if mx > 0 else 0.0
        for b in self.layout.buckets:
            b["inv_max_dcg"] = jnp.asarray(inv[b["qids"]], dtype=jnp.float32)
        self._w = (jnp.asarray(metadata.weights) if metadata.weights is not None else None)
        self._gain_dev = jnp.asarray(self.label_gain, dtype=jnp.float32)
        self._fns = {}
        # position debias state (rank_objective.hpp:43-90, 296-340): per-
        # position-id bias factors, Newton-updated from the lambdas each
        # iteration; gradients are computed on bias-adjusted scores
        self._positions = None
        if metadata.positions is not None:
            self._positions = jnp.asarray(metadata.positions)
            P = len(metadata.position_ids)
            self._num_positions = P
            self._pos_biases = jnp.zeros(P, dtype=jnp.float32)
            self._pos_counts = jnp.zeros(P, jnp.float32).at[self._positions].add(1.0)
            self._bias_reg = jnp.float32(
                self.config.lambdarank_position_bias_regularization)
            self._bias_lr = jnp.float32(self.config.learning_rate)

            @jax.jit
            def _update_biases(biases, grad, hess, positions, counts):
                fd = -(jnp.zeros_like(biases).at[positions].add(grad))
                sd = -(jnp.zeros_like(biases).at[positions].add(hess))
                fd = fd - biases * self._bias_reg * counts
                sd = sd - self._bias_reg * counts
                return biases + self._bias_lr * fd / (jnp.abs(sd) + 0.001)

            self._update_biases = _update_biases

    def _bucket_fn(self, L: int):
        if L in self._fns:
            return self._fns[L]
        sigmoid = self.sigmoid
        trunc = self.truncation_level
        norm = self.norm
        gains = self._gain_dev

        def per_query(s, lab, valid, inv_max_dcg):
            # s, lab, valid: [L]
            s_pad = jnp.where(valid, s, K_MIN_SCORE)
            order = jnp.argsort(-s_pad, stable=True)
            ss = s_pad[order]
            ls = lab[order]
            vs = valid[order]
            g = gains[ls.astype(jnp.int32)]
            pos = jnp.arange(L)
            disc = jnp.where(vs, 1.0 / jnp.log2(pos + 2.0), 0.0)
            best = ss[0]
            cnt = vs.sum()
            worst = jnp.where(cnt > 0, ss[jnp.maximum(cnt - 1, 0)], 0.0)
            # pairwise matrices over sorted positions
            ds = ss[:, None] - ss[None, :]
            sign = jnp.sign(ls[:, None] - ls[None, :])
            delta_hl = sign * ds  # score(high-label) - score(low-label)
            dcg_gap = jnp.abs(g[:, None] - g[None, :])
            paired_disc = jnp.abs(disc[:, None] - disc[None, :])
            delta_ndcg = dcg_gap * paired_disc * inv_max_dcg
            if norm:
                delta_ndcg = jnp.where(best != worst,
                                       delta_ndcg / (0.01 + jnp.abs(ds)), delta_ndcg)
            p = 1.0 / (1.0 + jnp.exp(delta_hl * sigmoid))
            pair_ok = (vs[:, None] & vs[None, :] & (sign != 0)
                       & ((jnp.minimum(pos[:, None], pos[None, :])) < trunc)
                       & (pos[:, None] != pos[None, :]))
            p_lambda = jnp.where(pair_ok, -sigmoid * delta_ndcg * p, 0.0)
            p_hess = jnp.where(pair_ok, sigmoid * sigmoid * delta_ndcg * p * (1.0 - p), 0.0)
            lam_sorted = jnp.sum(sign * p_lambda, axis=1)
            hes_sorted = jnp.sum(p_hess, axis=1)
            sum_lambdas = -jnp.sum(p_lambda)
            if norm:
                factor = jnp.where(sum_lambdas > 0,
                                   jnp.log2(1.0 + sum_lambdas) / jnp.maximum(sum_lambdas, 1e-20),
                                   1.0)
                lam_sorted = lam_sorted * factor
                hes_sorted = hes_sorted * factor
            # unsort back to query-local order
            lam = jnp.zeros(L).at[order].set(lam_sorted)
            hes = jnp.zeros(L).at[order].set(hes_sorted)
            return lam, hes

        def bucket(score_ext, doc_idx, lab, valid, inv_max_dcg):
            s = score_ext[doc_idx]  # [Qb, L]
            if L >= 512:
                lam, hes = jax.lax.map(
                    lambda args: per_query(*args), (s, lab, valid, inv_max_dcg))
            else:
                lam, hes = jax.vmap(per_query)(s, lab, valid, inv_max_dcg)
            return lam, hes

        fn = jax.jit(bucket)
        self._fns[L] = fn
        return fn

    def get_gradients(self, score):
        n = self.num_data
        if self._positions is not None:
            # lambdas come from bias-adjusted scores; the model score itself
            # is untouched (rank_objective.hpp:66-74 score_adjusted)
            score = score + self._pos_biases[self._positions]
        score_ext = jnp.concatenate([score, jnp.zeros(1, score.dtype)])
        grad = jnp.zeros(n, dtype=jnp.float32)
        hess = jnp.zeros(n, dtype=jnp.float32)
        for b in self.layout.buckets:
            fn = self._bucket_fn(b["L"])
            lam, hes = fn(score_ext, b["doc_idx"], b["labels"], b["valid"],
                          b["inv_max_dcg"])
            grad = grad.at[b["doc_idx"].ravel()].set(lam.ravel(), mode="drop")
            hess = hess.at[b["doc_idx"].ravel()].set(hes.ravel(), mode="drop")
        if self._w is not None:
            grad = grad * self._w
            hess = hess * self._w
        if self._positions is not None:
            self._pos_biases = self._update_biases(
                self._pos_biases, grad, hess, self._positions,
                self._pos_counts)
        return grad, hess

    def to_string(self):
        return "lambdarank"


@register_objective("rank_xendcg")
class RankXENDCG(ObjectiveFunction):
    """XE-NDCG (Bruch et al. 2019, 'An Alternative Cross Entropy Loss for
    Learning-to-Rank'): listwise softmax cross-entropy with randomly
    perturbed relevance gains (rank_objective.hpp RankXENDCG)."""

    jit_gradients = False  # stateful per-iteration RNG + per-bucket jits

    def __init__(self, config):
        super().__init__(config)
        self.seed = config.objective_seed

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            Log.fatal("Ranking tasks require query information")
        self.layout = QueryLayout(metadata.query_boundaries, metadata.label, num_data)
        self._w = (jnp.asarray(metadata.weights) if metadata.weights is not None else None)
        self._iter = 0
        self._fns = {}

    def _bucket_fn(self, L: int):
        if L in self._fns:
            return self._fns[L]

        def per_query(s, lab, valid, seed):
            s_masked = jnp.where(valid, s, -jnp.inf)
            key = jax.random.PRNGKey(seed.astype(jnp.uint32))
            # phi: gumbel-perturbed gains, normalized (the paper's sampling)
            gumbel = jax.random.uniform(key, (L,), minval=1e-6, maxval=1.0)
            gain = jnp.where(valid, (2.0 ** lab - 1.0) - jnp.log(-jnp.log(gumbel)), 0.0)
            gain = jnp.maximum(gain, 0.0)
            rho = jax.nn.softmax(s_masked)
            rho = jnp.where(valid, rho, 0.0)
            gsum = jnp.maximum(gain.sum(), 1e-20)
            phi = gain / gsum
            lam = rho - phi
            hes = jnp.maximum(rho * (1.0 - rho), 1e-16)
            return jnp.where(valid, lam, 0.0), jnp.where(valid, hes, 0.0)

        def bucket(score_ext, doc_idx, lab, valid, seeds):
            s = score_ext[doc_idx]
            return jax.vmap(per_query)(s, lab, valid, seeds)

        fn = jax.jit(bucket)
        self._fns[L] = fn
        return fn

    def get_gradients(self, score):
        n = self.num_data
        score_ext = jnp.concatenate([score, jnp.zeros(1, score.dtype)])
        grad = jnp.zeros(n, dtype=jnp.float32)
        hess = jnp.zeros(n, dtype=jnp.float32)
        self._iter += 1
        for b in self.layout.buckets:
            fn = self._bucket_fn(b["L"])
            seeds = jnp.asarray(
                (b["qids"].astype(np.int64) * 9973 + self._iter * 31 + self.seed)
                % (2 ** 31), dtype=jnp.int32)
            lam, hes = fn(score_ext, b["doc_idx"], b["labels"], b["valid"], seeds)
            grad = grad.at[b["doc_idx"].ravel()].set(lam.ravel(), mode="drop")
            hess = hess.at[b["doc_idx"].ravel()].set(hes.ravel(), mode="drop")
        if self._w is not None:
            grad = grad * self._w
            hess = hess * self._w
        return grad, hess

    def to_string(self):
        return "rank_xendcg"
