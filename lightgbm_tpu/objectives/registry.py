"""Objective function interface + factory.

Counterpart of ObjectiveFunction (include/LightGBM/objective_function.h:19-90)
and its factory (src/objective/objective_function.cpp:71-119). Objectives are
per-row gradient/hessian producers; on TPU they are pure jitted elementwise
functions over the device score/label arrays (the analog of the CUDA objective
kernels in src/objective/cuda/).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Type

from ..config import Config
from ..io.metadata import Metadata
from ..utils.log import Log

OBJECTIVE_REGISTRY: Dict[str, Type] = {}


def register_objective(*names: str):
    def deco(cls):
        for n in names:
            OBJECTIVE_REGISTRY[n] = cls
        cls.names = names
        return cls

    return deco


class ObjectiveFunction:
    """Base interface (objective_function.h:29-90)."""

    is_constant_hessian = False
    need_accurate_gradients = False
    # whether get_gradients is a pure traceable function safe to wrap in an
    # outer jit (stateful objectives like rank_xendcg manage their own jits)
    jit_gradients = True

    def __init__(self, config: Config) -> None:
        self.config = config
        self.metadata: Optional[Metadata] = None
        self.num_data = 0

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.metadata = metadata
        self.num_data = num_data

    # device: score [N, C] -> (grad [N, C], hess [N, C])
    def get_gradients(self, score):
        raise NotImplementedError

    def boost_from_score(self, class_id: int = 0) -> float:
        """Initial raw score (BoostFromScore, objective_function.h:65)."""
        return 0.0

    def convert_output(self, raw):
        """Raw score -> output space (sigmoid/exp/identity)."""
        return raw

    def renew_tree_output(self, tree, score, partition) -> None:
        """Leaf-value refitting hook (RenewTreeOutput) for percentile-style
        objectives (L1/quantile/MAPE); default no-op."""
        return None

    @property
    def num_model_per_iteration(self) -> int:
        return 1

    @property
    def num_class(self) -> int:
        return 1

    def to_string(self) -> str:
        return self.names[0]


def create_objective(name: str, config: Config) -> Optional[ObjectiveFunction]:
    from . import regression, binary, multiclass, rank, xentropy  # noqa: F401

    if name in ("custom", "none", "null", "na") or not name:
        return None
    cls = OBJECTIVE_REGISTRY.get(name)
    if cls is None:
        Log.fatal("Unknown objective type name: %s", name)
    return cls(config)
