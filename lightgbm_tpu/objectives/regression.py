"""Regression-family objectives.

Counterpart of src/objective/regression_objective.hpp: l2, l1, huber, fair,
poisson, quantile, mape, gamma, tweedie. Gradients are jitted elementwise
device functions; percentile-style leaf refits (RenewTreeOutput for
l1/quantile/mape, regression_objective.hpp RenewTreeOutput) run on device with
per-leaf gathered residual sorts.
"""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .registry import ObjectiveFunction, register_objective
from ..utils.log import Log


def _weighted(grad, hess, w):
    if w is None:
        return grad, hess
    return grad * w, hess * w


def _percentile_refit(tree, score, labels, weights, partition, alpha_fn):
    """Recompute each leaf output as a (weighted) percentile of residuals —
    the RenewTreeOutput machinery for L1/quantile/MAPE objectives
    (regression_objective.hpp RenewTreeOutput; runs before shrinkage)."""
    score_np = np.asarray(score)
    for leaf in range(tree.num_leaves):
        idx = np.asarray(partition.indices(leaf))
        cnt = partition.count(leaf)
        idx = idx[:cnt]
        if cnt == 0:
            continue
        resid = labels[idx] - score_np[idx]
        w = weights[idx] if weights is not None else None
        tree.set_leaf_output(leaf, float(alpha_fn(resid, w)))


def _weighted_percentile(values: np.ndarray, weights, alpha: float) -> float:
    """PercentileFun / WeightedPercentileFun (regression_objective.hpp:23-60)."""
    if len(values) == 0:
        return 0.0
    order = np.argsort(values)
    if weights is None:
        n = len(values)
        pos = alpha * n
        k = int(math.floor(pos))
        if k >= n:
            return float(values[order[-1]])
        if abs(pos - k) < 1e-12 and k > 0:
            return float(values[order[k - 1]] + values[order[k]]) / 2.0
        return float(values[order[k]])
    w = weights[order]
    cum = np.cumsum(w)
    target = alpha * cum[-1]
    k = int(np.searchsorted(cum, target))
    k = min(k, len(values) - 1)
    return float(values[order[k]])


@register_objective("regression", "regression_l2", "l2", "mean_squared_error", "mse")
class RegressionL2(ObjectiveFunction):
    is_constant_hessian = True

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = config.reg_sqrt

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.label = metadata.label.astype(np.float64)
        if self.sqrt:
            self.trans_label = np.sign(self.label) * np.sqrt(np.abs(self.label))
        else:
            self.trans_label = self.label
        self._label_dev = jnp.asarray(self.trans_label, dtype=jnp.float32)
        self._w_dev = (jnp.asarray(metadata.weights) if metadata.weights is not None
                       else None)

    def get_gradients(self, score):
        grad = score - self._label_dev
        hess = jnp.ones_like(score)
        return _weighted(grad, hess, self._w_dev)

    def boost_from_score(self, class_id=0):
        if self.metadata.weights is not None:
            suml = float(np.sum(self.trans_label * self.metadata.weights))
            sumw = float(np.sum(self.metadata.weights))
        else:
            suml = float(np.sum(self.trans_label))
            sumw = float(self.num_data)
        init = suml / sumw if sumw > 0 else 0.0
        Log.info("[regression:BoostFromScore]: pavg=%f -> initscore=%f", init, init)
        return init

    def convert_output(self, raw):
        if self.sqrt:
            return jnp.sign(raw) * raw * raw
        return raw

    def to_string(self):
        return "regression"


@register_objective("regression_l1", "l1", "mean_absolute_error", "mae")
class RegressionL1(RegressionL2):
    is_constant_hessian = True

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = False

    def get_gradients(self, score):
        diff = score - self._label_dev
        grad = jnp.sign(diff)
        hess = jnp.ones_like(score)
        return _weighted(grad, hess, self._w_dev)

    def boost_from_score(self, class_id=0):
        return _weighted_percentile(self.label, self.metadata.weights, 0.5)

    def renew_tree_output(self, tree, score, partition):
        _percentile_refit(tree, score, self.label, self.metadata.weights, partition,
                          lambda r, w: _weighted_percentile(r, w, 0.5))

    def to_string(self):
        return "regression_l1"


@register_objective("huber")
class RegressionHuber(RegressionL2):
    is_constant_hessian = True

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = False
        self.alpha = config.alpha

    def get_gradients(self, score):
        diff = score - self._label_dev
        grad = jnp.clip(diff, -self.alpha, self.alpha)
        hess = jnp.ones_like(score)
        return _weighted(grad, hess, self._w_dev)

    def to_string(self):
        return "huber"


@register_objective("fair")
class RegressionFair(RegressionL2):
    def __init__(self, config):
        super().__init__(config)
        self.sqrt = False
        self.c = config.fair_c

    def get_gradients(self, score):
        diff = score - self._label_dev
        grad = self.c * diff / (jnp.abs(diff) + self.c)
        hess = self.c * self.c / ((jnp.abs(diff) + self.c) ** 2)
        return _weighted(grad, hess, self._w_dev)

    def to_string(self):
        return "fair"


@register_objective("poisson")
class RegressionPoisson(RegressionL2):
    is_constant_hessian = False

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = False
        self.max_delta_step = config.poisson_max_delta_step

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if np.any(self.label < 0):
            Log.fatal("[poisson]: at least one target label is negative")

    def get_gradients(self, score):
        exp_s = jnp.exp(score)
        grad = exp_s - self._label_dev
        hess = jnp.exp(score + self.max_delta_step)
        return _weighted(grad, hess, self._w_dev)

    def boost_from_score(self, class_id=0):
        mean = super().boost_from_score(class_id)
        return math.log(max(mean, 1e-15))

    def convert_output(self, raw):
        return jnp.exp(raw)

    def to_string(self):
        return "poisson"


@register_objective("quantile")
class RegressionQuantile(RegressionL2):
    is_constant_hessian = True

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = False
        self.alpha = config.alpha

    def get_gradients(self, score):
        diff = score - self._label_dev
        grad = jnp.where(diff >= 0, 1.0 - self.alpha, -self.alpha)
        hess = jnp.ones_like(score)
        return _weighted(grad, hess, self._w_dev)

    def boost_from_score(self, class_id=0):
        return _weighted_percentile(self.label, self.metadata.weights, self.alpha)

    def renew_tree_output(self, tree, score, partition):
        _percentile_refit(tree, score, self.label, self.metadata.weights, partition,
                          lambda r, w: _weighted_percentile(r, w, self.alpha))

    def to_string(self):
        return "quantile"


@register_objective("mape", "mean_absolute_percentage_error")
class RegressionMAPE(RegressionL2):
    is_constant_hessian = True

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = False

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.label_weight = 1.0 / np.maximum(1.0, np.abs(self.label))
        if metadata.weights is not None:
            self.label_weight = self.label_weight * metadata.weights
        self._lw_dev = jnp.asarray(self.label_weight, dtype=jnp.float32)

    def get_gradients(self, score):
        diff = score - self._label_dev
        grad = jnp.sign(diff) * self._lw_dev
        hess = self._lw_dev
        return grad, hess

    def boost_from_score(self, class_id=0):
        return _weighted_percentile(self.label, self.label_weight, 0.5)

    def renew_tree_output(self, tree, score, partition):
        _percentile_refit(tree, score, self.label, self.label_weight, partition,
                          lambda r, w: _weighted_percentile(r, w, 0.5))

    def to_string(self):
        return "mape"


@register_objective("gamma")
class RegressionGamma(RegressionPoisson):
    def __init__(self, config):
        super().__init__(config)

    def get_gradients(self, score):
        exp_ns = jnp.exp(-score)
        grad = 1.0 - self._label_dev * exp_ns
        hess = self._label_dev * exp_ns
        return _weighted(grad, hess, self._w_dev)

    def to_string(self):
        return "gamma"


@register_objective("tweedie")
class RegressionTweedie(RegressionPoisson):
    def __init__(self, config):
        super().__init__(config)
        self.rho = config.tweedie_variance_power

    def get_gradients(self, score):
        a = jnp.exp((1.0 - self.rho) * score)
        b = jnp.exp((2.0 - self.rho) * score)
        grad = -self._label_dev * a + b
        hess = (-self._label_dev * (1.0 - self.rho) * a
                + (2.0 - self.rho) * b)
        return _weighted(grad, hess, self._w_dev)

    def to_string(self):
        return "tweedie"
