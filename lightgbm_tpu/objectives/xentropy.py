"""Cross-entropy objectives for continuous labels in [0, 1] / intensities.

Counterpart of src/objective/xentropy_objective.hpp: CrossEntropy (alias
xentropy, :77-145) and CrossEntropyLambda (alias xentlambda, :223-268) with
their weighted parameterizations, boost-from-average inits, and output
conversions (sigmoid / log1p(exp)).
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from .registry import ObjectiveFunction, register_objective
from ..utils.log import Log

K_EPS = 1e-15


class _XentBase(ObjectiveFunction):
    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        label = metadata.label.astype(np.float64)
        if np.any(label < 0):
            Log.fatal("[%s]: label should be non-negative", self.to_string())
        self.label = label
        self._label_dev = jnp.asarray(label, dtype=jnp.float32)
        self._w_dev = (jnp.asarray(metadata.weights)
                       if metadata.weights is not None else None)

    def _avg_label(self):
        if self.metadata.weights is not None:
            suml = float(np.sum(self.label * self.metadata.weights))
            sumw = float(np.sum(self.metadata.weights))
        else:
            suml = float(self.label.sum())
            sumw = float(self.num_data)
        return suml / max(sumw, K_EPS)


@register_objective("cross_entropy", "xentropy")
class CrossEntropy(_XentBase):
    def get_gradients(self, score):
        z = 1.0 / (1.0 + jnp.exp(-score))
        grad = z - self._label_dev
        hess = z * (1.0 - z)
        if self._w_dev is not None:
            grad = grad * self._w_dev
            hess = hess * self._w_dev
        return grad, hess

    def boost_from_score(self, class_id=0):
        pavg = min(max(self._avg_label(), K_EPS), 1.0 - K_EPS)
        init = math.log(pavg / (1.0 - pavg))
        Log.info("[cross_entropy:BoostFromScore]: pavg = %f -> initscore = %f", pavg, init)
        return init

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-raw))

    def to_string(self):
        return "cross_entropy"


@register_objective("cross_entropy_lambda", "xentlambda")
class CrossEntropyLambda(_XentBase):
    """Poisson-process parameterization: yhat = log1p(exp(score))
    (xentropy_objective.hpp:223-268)."""

    def get_gradients(self, score):
        if self._w_dev is None:
            z = 1.0 / (1.0 + jnp.exp(-score))
            grad = z - self._label_dev
            hess = z * (1.0 - z)
            return grad, hess
        w = self._w_dev
        y = self._label_dev
        epf = jnp.exp(score)
        hhat = jnp.log1p(epf)
        z = 1.0 - jnp.exp(-w * hhat)
        enf = 1.0 / epf
        grad = (1.0 - y / jnp.maximum(z, K_EPS)) * w / (1.0 + enf)
        c = 1.0 / jnp.maximum(1.0 - z, K_EPS)
        d1 = 1.0 + epf
        a = w * epf / (d1 * d1)
        d = c - 1.0
        b = (c / jnp.maximum(d * d, K_EPS)) * (1.0 + w * epf - c)
        hess = a * (1.0 + y * b)
        return grad, hess

    def boost_from_score(self, class_id=0):
        havg = self._avg_label()
        init = math.log(max(math.expm1(havg), K_EPS))
        Log.info("[cross_entropy_lambda:BoostFromScore]: havg = %f -> initscore = %f",
                 havg, init)
        return init

    def convert_output(self, raw):
        return jnp.log1p(jnp.exp(raw))

    def to_string(self):
        return "cross_entropy_lambda"
