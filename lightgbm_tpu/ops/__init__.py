from .predict import PackedEnsemble, pack_ensemble, predict_raw

__all__ = ["PackedEnsemble", "pack_ensemble", "predict_raw"]
