"""Pallas TPU leaf-contiguous row compaction (stable 2-way partition).

The device tree learner keeps every per-row array (bin columns + gradient
rows) in a LEAF-CONTIGUOUS permutation so each histogram wave can read only
the rows of the leaves it is splitting (ops/hist_pallas.py ragged tiles)
instead of all N rows. This module moves the rows: given the forward
destination map of a stable 2-way partition restricted to a set of disjoint
leaf ranges, it produces the re-permuted arrays in one sequential-grid
Pallas pass.

Counterpart of CUDADataPartition::SplitInner (cuda_data_partition.cu):
there, a bitvector + block prefix-scan + global scatter. TPUs have no fast
global scatter, so the same data movement is phrased as dense tile algebra:

  1. XLA side (range_partition_dst): per-range stable left/right ranks via
     two global exclusive scans + a [N, K] range-membership matmul for the
     per-row destination base -> forward map dst[j] (a permutation of
     [0, N); rows outside every range keep their position).
  2. XLA side (build_pair_tables): each INPUT tile's rows land in at most a
     handful of OUTPUT tiles — per (range, side) the destinations are
     contiguous, so a tile's class rows span <= 2 output tiles. The pair
     list (in_tile -> out_tile), sorted by out_tile, is the kernel's grid.
  3. Pallas kernel (pallas_compact): sequential grid over pairs; per pair
     build the in-tile one-hot P[i, o] = (dst[i] - out*T == o) and
     accumulate out_block += P^T @ rows (and bins @ P). Consecutive pairs
     share the output block (sorted order), so accumulation stays in VMEM;
     a scalar-prefetched copy flag routes untouched tiles through a plain
     VPU copy with no matmul.

Exactness: values transit the MXU as four 8-bit limbs of their raw bits
(bf16 operands — 0/1 one-hot and limbs <= 255 are exact in bf16, and each
output row receives exactly ONE source row), so arbitrary f32/int32 payloads
are moved bit-exactly at full bf16 MXU rate. No lax.sort anywhere: at 10.5M
rows a global sort costs more than the histograms it would save
(docs/PERF_NOTES.md).
"""
from __future__ import annotations

from functools import partial
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Compaction tile: independent of the histogram tile (DEFAULT_TILE_ROWS);
# the one-hot P is [tile, tile] so smaller tiles keep VMEM + per-pair FLOPs
# down. N must be padded to a multiple of lcm(COMPACT_TILE, hist tile).
COMPACT_TILE = 512


def exclusive_cumsum(x: jax.Array) -> jax.Array:
    """[N] -> [N] exclusive prefix sum (int32)."""
    x = x.astype(jnp.int32)
    return jnp.cumsum(x) - x


def range_partition_dst(go_left: jax.Array, match: jax.Array,
                        starts: jax.Array, counts: jax.Array,
                        valid: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Forward destination map of a stable 2-way partition of K disjoint
    position ranges.

    go_left [N] bool, match [N, K] bool (row-in-range membership, already
    masked by `valid`), starts/counts [K] int32, valid [K] bool.
    Returns (dst [N] int32, n_left [K] int32). Rows outside every valid
    range keep their position; rows of range k land stably in
    [starts[k], starts[k]+n_left[k]) or [starts[k]+n_left[k], ends[k]).

    All vectorized: two global scans, K-sized gathers, one [N, K] matmul
    for the per-row base (gathers at N scale serialize on TPU; the matmul
    does not). Positions must be < 2**24 (exact in f32).
    """
    N, K = match.shape
    pos = jnp.arange(N, dtype=jnp.int32)
    in_any = match.any(axis=1)
    lmask = in_any & go_left
    rmask = in_any & ~go_left
    lcum = exclusive_cumsum(lmask)
    rcum = exclusive_cumsum(rmask)
    # length-(N+1) inclusive tails so ends[k] == N indexes safely
    lext = jnp.concatenate(
        [lcum, (lcum[-1] + lmask[-1].astype(jnp.int32))[None]])
    rext = jnp.concatenate(
        [rcum, (rcum[-1] + rmask[-1].astype(jnp.int32))[None]])
    ends = starts + counts
    n_left = jnp.take(lext, ends) - jnp.take(lext, starts)
    base_l = starts - jnp.take(lext, starts)
    base_r = starts + n_left - jnp.take(rext, starts)
    bases = jax.lax.dot(match.astype(jnp.float32),
                        jnp.stack([base_l, base_r], axis=1)
                        .astype(jnp.float32),
                        precision=jax.lax.Precision.HIGHEST)  # [N, 2]
    dst = jnp.where(
        lmask, bases[:, 0].astype(jnp.int32) + lcum,
        jnp.where(rmask, bases[:, 1].astype(jnp.int32) + rcum, pos))
    return dst, jnp.where(valid, n_left, 0)


def max_pairs_bound(n_tiles: int, n_classes: int) -> int:
    """Static upper bound on the pair-list length for DISJOINT class masks.

    identity pairs: n_tiles. Per class, (in_tile, out_tile) adjacencies of a
    contiguous destination run <= tiles_touched + out_tiles; summed over
    disjoint classes both terms are <= n_tiles + 2*n_classes.
    """
    return 3 * n_tiles + 4 * n_classes + 8


def build_pair_tables(dst: jax.Array, class_masks: Sequence[jax.Array],
                      moved: jax.Array, tile: int):
    """Pair list (in_tile -> out_tile) covering every row movement.

    dst [N] int32 forward permutation; class_masks: disjoint row sets whose
    destinations are contiguous PER TILE (e.g. left rows of one range);
    moved [N] bool = union of class masks (rows whose dst may differ from
    their position). Returns (pair_in, pair_out, is_copy, n_pairs[1]) with
    static length max_pairs_bound(T, len(class_masks)); entries past
    n_pairs repeat the last real pair (same blocks -> the kernel skips DMA
    and compute for them). Sorted by out_tile so the kernel revisits each
    output block in one consecutive run.
    """
    N = dst.shape[0]
    T = N // tile
    if T * T + T >= 2 ** 30:
        raise ValueError("pair sort key would overflow int32; use a larger "
                         "compaction tile for this row count")
    dstT = dst.reshape(T, tile)
    big = jnp.int32(2 ** 30)
    ids = jnp.arange(T, dtype=jnp.int32)
    cands = [ids[:, None]]  # identity pair for every tile: full coverage
    for m in class_masks:
        mT = m.reshape(T, tile)
        any_m = mT.any(axis=1)
        dmin = jnp.min(jnp.where(mT, dstT, big), axis=1) // tile
        dmax = jnp.max(jnp.where(mT, dstT, -1), axis=1) // tile
        c0 = jnp.where(any_m, dmin, T)
        c1 = jnp.where(any_m & (dmax > dmin), dmax, T)
        cands.append(jnp.stack([c0, c1], axis=1))
    cand = jnp.concatenate(cands, axis=1)  # [T, 1 + 2*len(masks)]
    # de-duplicate per input tile (duplicate pairs would double-count rows)
    cs = jnp.sort(cand, axis=1)
    dup = jnp.concatenate([jnp.zeros((T, 1), bool), cs[:, 1:] == cs[:, :-1]],
                          axis=1)
    cs = jnp.where(dup | (cs >= T), T, cs)
    out_flat = cs.reshape(-1)
    in_flat = jnp.repeat(ids, cs.shape[1])
    ok = out_flat < T
    key = jnp.where(ok, out_flat * T + in_flat, big)
    key = jax.lax.sort(key)
    n_pairs = ok.sum().astype(jnp.int32)
    mp = max_pairs_bound(T, len(class_masks))
    if key.shape[0] < mp:
        key = jnp.concatenate([key, jnp.full(mp - key.shape[0], big,
                                             jnp.int32)])
    key = key[:mp]
    last = jnp.take(key, jnp.maximum(n_pairs - 1, 0))
    key = jnp.where(jnp.arange(mp, dtype=jnp.int32) < n_pairs, key, last)
    pair_in = key % T
    pair_out = key // T
    # untouched tiles: identity pair does a raw block copy, no matmul.
    # (A tile receiving rows from elsewhere necessarily lost rows too —
    # dst is a permutation — so untouched tiles exchange nothing.)
    touched = moved.reshape(T, tile).any(axis=1)
    is_copy = ((pair_in == pair_out)
               & ~jnp.take(touched, pair_in)).astype(jnp.int32)
    return pair_in, pair_out, is_copy, n_pairs[None]


def _limbs(x_int: jax.Array, n: int, axis: int) -> jax.Array:
    """Split int32 values into n 8-bit limbs concatenated along `axis`
    (each limb <= 255: exact as a bf16 matmul operand)."""
    parts = [jnp.bitwise_and(jax.lax.shift_right_logical(x_int, 8 * i), 255)
             for i in range(n)]
    return jnp.concatenate(parts, axis=axis)


def _make_compact_kernel(tile: int, gp: int, rc: int):
    def kernel(pin_ref, pout_ref, pcopy_ref, npair_ref,
               bins_ref, row_ref, dst_ref, bins_out, row_out):
        p = pl.program_id(0)
        out_t = pout_ref[p]
        first = (p == 0) | (out_t != pout_ref[jnp.maximum(p - 1, 0)])
        active = p < npair_ref[0]
        is_copy = pcopy_ref[p] > 0

        @pl.when(active & is_copy)
        def _copy():  # untouched tile: single pair for this block, plain copy
            bins_out[...] = bins_ref[...]
            row_out[...] = row_ref[...]

        @pl.when(active & jnp.logical_not(is_copy))
        def _permute():
            @pl.when(first)
            def _zero():
                bins_out[...] = jnp.zeros_like(bins_out)
                row_out[...] = jnp.zeros_like(row_out)

            rel = dst_ref[...][:, 0] - out_t * tile  # [tile] int32
            iota = jax.lax.broadcasted_iota(jnp.int32, (tile, tile), 1)
            # P[i, o] = 1 iff in-row i lands at out-row o of this block.
            # dst is injective => every column has at most one 1, so each
            # output row below receives exactly one source row: the limb
            # matmuls are exact bit transport, not sums.
            P = (rel[:, None] == iota).astype(jnp.bfloat16)
            rbits = jax.lax.bitcast_convert_type(row_ref[...], jnp.int32)
            rl = _limbs(rbits, 4, axis=1).astype(jnp.bfloat16)  # [tile, 4*rc]
            orl = jax.lax.dot_general(
                P, rl, dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).astype(jnp.int32)
            obits = (orl[:, :rc]
                     | (orl[:, rc:2 * rc] << 8)
                     | (orl[:, 2 * rc:3 * rc] << 16)
                     | (orl[:, 3 * rc:] << 24))
            # rows not sourced by this pair recombine to bits 0 == +0.0f;
            # f32 += 0.0 is exact, so cross-pair accumulation is bit-exact
            row_out[...] += jax.lax.bitcast_convert_type(obits, jnp.float32)
            bl = _limbs(bins_ref[...], 2, axis=0).astype(jnp.bfloat16)
            obl = jax.lax.dot_general(
                bl, P, dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).astype(jnp.int32)
            bins_out[...] += obl[:gp] | (obl[gp:] << 8)

    return kernel


@partial(jax.jit, static_argnames=("tile", "interpret"))
def _pallas_compact_call(bins_p, row_p, dst, pair_in, pair_out, is_copy,
                         n_pairs, tile: int, interpret: bool):
    Gp, N = bins_p.shape
    rc = row_p.shape[1]
    mp = pair_in.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(mp,),
        in_specs=[
            pl.BlockSpec((Gp, tile), lambda p, pi, po, pc, npr: (0, pi[p])),
            pl.BlockSpec((tile, rc), lambda p, pi, po, pc, npr: (pi[p], 0)),
            pl.BlockSpec((tile, 1), lambda p, pi, po, pc, npr: (pi[p], 0)),
        ],
        out_specs=[
            pl.BlockSpec((Gp, tile), lambda p, pi, po, pc, npr: (0, po[p])),
            pl.BlockSpec((tile, rc), lambda p, pi, po, pc, npr: (po[p], 0)),
        ],
    )
    return pl.pallas_call(
        _make_compact_kernel(tile, Gp, rc),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((Gp, N), jnp.int32),
            jax.ShapeDtypeStruct((N, rc), jnp.float32),
        ],
        interpret=interpret,
    )(pair_in, pair_out, is_copy, n_pairs, bins_p, row_p,
      dst.reshape(N, 1))


def compact_rows(bins_p: jax.Array, row_p: jax.Array, dst: jax.Array,
                 class_masks: Sequence[jax.Array], moved: jax.Array,
                 *, tile: int = COMPACT_TILE, use_pallas: bool = True,
                 interpret: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Apply the forward permutation dst to bins_p [Gp, N] (int32,
    values < 2**16) and row_p [N, rc] (f32 payload, moved bit-exactly).

    Pallas path requirements: N % tile == 0, Gp % 8 == 0, class_masks
    disjoint with per-tile-contiguous destinations (range_partition_dst
    output qualifies), moved == union(class_masks). The XLA path is a plain
    permutation scatter — exact on CPU, used when no TPU backend is live.
    """
    if not use_pallas:
        bins_o = jnp.zeros_like(bins_p).at[:, dst].set(
            bins_p, unique_indices=True)
        row_o = jnp.zeros_like(row_p).at[dst].set(row_p, unique_indices=True)
        return bins_o, row_o
    pair_in, pair_out, is_copy, n_pairs = build_pair_tables(
        dst, class_masks, moved, tile)
    return _pallas_compact_call(bins_p, row_p.astype(jnp.float32),
                                dst.astype(jnp.int32), pair_in, pair_out,
                                is_copy, n_pairs, tile, interpret)
