"""Pallas TPU leaf-contiguous row compaction (stable 2-way partition).

The device tree learner keeps every per-row array (bin columns + gradient
rows) in a LEAF-CONTIGUOUS permutation so each histogram wave can read only
the rows of the leaves it is splitting (ops/hist_pallas.py ragged tiles)
instead of all N rows. This module moves the rows: given the forward
destination map of a stable 2-way partition restricted to a set of disjoint
leaf ranges, it produces the re-permuted arrays in one sequential-grid
Pallas pass.

Counterpart of CUDADataPartition::SplitInner (cuda_data_partition.cu):
there, a bitvector + block prefix-scan + global scatter. TPUs have no fast
global scatter, so the same data movement is phrased as dense tile algebra:

  1. XLA side (range_partition_dst): per-range stable left/right ranks via
     two global exclusive scans + a [N, K] range-membership matmul for the
     per-row destination base -> forward map dst[j] (a permutation of
     [0, N); rows outside every range keep their position).
  2. XLA side (build_pair_tables): each INPUT tile's rows land in at most a
     handful of OUTPUT tiles — per (range, side) the destinations are
     contiguous, so a tile's class rows span <= 2 output tiles. The pair
     list (in_tile -> out_tile), sorted by out_tile, is the kernel's grid.
  3. Pallas kernel (pallas_compact): sequential grid over pairs; per pair
     build the in-tile one-hot P[i, o] = (dst[i] - out*T == o) and
     accumulate out_block += P^T @ rows (and bins @ P). Consecutive pairs
     share the output block (sorted order), so accumulation stays in VMEM;
     a scalar-prefetched copy flag routes untouched tiles through a plain
     VPU copy with no matmul.

Exactness: values transit the MXU as 8-bit limbs of their raw bits (bf16
operands — 0/1 one-hot and limbs <= 255 are exact in bf16, and each output
row receives exactly ONE source row), so payloads are moved bit-exactly at
full bf16 MXU rate: f32 rows as four limbs, the bin plane as two limbs for
int32 (values < 2**16) or ONE limb when the plane is already 8-bit (uint8
bins, values <= 255) — a 2x cut in the plane's transport matmuls on top of
the 4x HBM cut of the narrow plane itself. The only lax.sort is the single
composite-key sort ordering the pair list; no row-wise sort anywhere — at
10.5M rows a global row sort costs more than the histograms it would save
(docs/PERF_NOTES.md).
"""
from __future__ import annotations

import os
from functools import partial
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import perfmodel, telemetry

# Compaction tile: independent of the histogram tile (DEFAULT_TILE_ROWS);
# the one-hot P is [tile, tile] so smaller tiles keep VMEM + per-pair FLOPs
# down. N must be padded to a multiple of lcm(COMPACT_TILE, hist tile).
COMPACT_TILE = 512

# the recompile watcher splits this entry's cache misses into the
# kernel_compiles counter (kernel-flag experiments show their compile cost)
telemetry.register_kernel_fn("_pallas_compact_call")


def exclusive_cumsum(x: jax.Array) -> jax.Array:
    """[N] -> [N] exclusive prefix sum (int32)."""
    x = x.astype(jnp.int32)
    return jnp.cumsum(x) - x


def range_partition_dst(go_left: jax.Array, match: jax.Array,
                        starts: jax.Array, counts: jax.Array,
                        valid: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Forward destination map of a stable 2-way partition of K disjoint
    position ranges.

    go_left [N] bool, match [N, K] bool (row-in-range membership, already
    masked by `valid`), starts/counts [K] int32, valid [K] bool.
    Returns (dst [N] int32, n_left [K] int32). Rows outside every valid
    range keep their position; rows of range k land stably in
    [starts[k], starts[k]+n_left[k]) or [starts[k]+n_left[k], ends[k]).

    All vectorized: two global scans, K-sized gathers, one [N, K] matmul
    for the per-row base (gathers at N scale serialize on TPU; the matmul
    does not). Positions must be < 2**24 (exact in f32).
    """
    N, K = match.shape
    pos = jnp.arange(N, dtype=jnp.int32)
    in_any = match.any(axis=1)
    lmask = in_any & go_left
    rmask = in_any & ~go_left
    lcum = exclusive_cumsum(lmask)
    rcum = exclusive_cumsum(rmask)
    # length-(N+1) inclusive tails so ends[k] == N indexes safely
    lext = jnp.concatenate(
        [lcum, (lcum[-1] + lmask[-1].astype(jnp.int32))[None]])
    rext = jnp.concatenate(
        [rcum, (rcum[-1] + rmask[-1].astype(jnp.int32))[None]])
    ends = starts + counts
    n_left = jnp.take(lext, ends) - jnp.take(lext, starts)
    base_l = starts - jnp.take(lext, starts)
    base_r = starts + n_left - jnp.take(rext, starts)
    bases = jax.lax.dot(match.astype(jnp.float32),
                        jnp.stack([base_l, base_r], axis=1)
                        .astype(jnp.float32),
                        precision=jax.lax.Precision.HIGHEST)  # [N, 2]
    dst = jnp.where(
        lmask, bases[:, 0].astype(jnp.int32) + lcum,
        jnp.where(rmask, bases[:, 1].astype(jnp.int32) + rcum, pos))
    return dst, jnp.where(valid, n_left, 0)


def max_pairs_bound(n_tiles: int, n_classes: int) -> int:
    """Static upper bound on the pair-list length for DISJOINT class masks.

    identity pairs: n_tiles. Per class, (in_tile, out_tile) adjacencies of a
    contiguous destination run <= tiles_touched + out_tiles; summed over
    disjoint classes both terms are <= n_tiles + 2*n_classes.
    """
    return 3 * n_tiles + 4 * n_classes + 8


def build_pair_tables(dst: jax.Array, class_masks: Sequence[jax.Array],
                      moved: jax.Array, tile: int):
    """Pair list (in_tile -> out_tile) covering every row movement.

    dst [N] int32 forward permutation; class_masks: disjoint row sets whose
    destinations are contiguous PER TILE (e.g. left rows of one range);
    moved [N] bool = union of class masks (rows whose dst may differ from
    their position). Returns (pair_in, pair_out, pcopy, n_pairs[1]) with
    static length max_pairs_bound(T, len(class_masks)); entries past
    n_pairs repeat the last real pair (same blocks -> the kernel skips DMA
    and compute for them). pcopy per pair: 0 = one-hot permute, 1 = raw
    block copy (untouched identity tile), 2 = SKIP (duplicate of the
    previous pair — processing it would double-count rows). Sorted by
    out_tile so the kernel revisits each output block in one consecutive
    run.

    One fused lax.sort: candidate pairs (with duplicates still in) are
    sorted by the composite key out_tile*T + in_tile, so duplicates —
    which always share an input tile AND an output tile — land adjacent
    and are demoted to skip pairs by one post-sort compare. The previous
    formulation pre-deduplicated with a second per-tile jnp.sort of the
    candidate matrix; the fused key sort removes that whole pass.
    """
    N = dst.shape[0]
    T = N // tile
    if T * T + T >= 2 ** 30:
        raise ValueError("pair sort key would overflow int32; use a larger "
                         "compaction tile for this row count")
    dstT = dst.reshape(T, tile)
    big = jnp.int32(2 ** 30)
    ids = jnp.arange(T, dtype=jnp.int32)
    cands = [ids[:, None]]  # identity pair for every tile: full coverage
    for m in class_masks:
        mT = m.reshape(T, tile)
        any_m = mT.any(axis=1)
        dmin = jnp.min(jnp.where(mT, dstT, big), axis=1) // tile
        dmax = jnp.max(jnp.where(mT, dstT, -1), axis=1) // tile
        c0 = jnp.where(any_m, dmin, T)
        c1 = jnp.where(any_m & (dmax > dmin), dmax, T)
        cands.append(jnp.stack([c0, c1], axis=1))
    cand = jnp.concatenate(cands, axis=1)  # [T, 1 + 2*len(masks)]
    out_flat = cand.reshape(-1)
    in_flat = jnp.repeat(ids, cand.shape[1])
    ok = out_flat < T
    key = jnp.where(ok, out_flat * T + in_flat, big)
    key = jax.lax.sort(key)
    n_pairs = ok.sum().astype(jnp.int32)
    # duplicate pairs (same in AND out tile => equal keys, now adjacent)
    # become skip pairs: they stay in the list so the length stays static,
    # but the kernel must not process them (double-counted rows). They
    # share both blocks with their predecessor, so they cost no extra DMA.
    dup = jnp.concatenate([jnp.zeros(1, bool), key[1:] == key[:-1]])
    mp = max_pairs_bound(T, len(class_masks))
    if key.shape[0] < mp:
        pad_n = mp - key.shape[0]
        key = jnp.concatenate([key, jnp.full(pad_n, big, jnp.int32)])
        dup = jnp.concatenate([dup, jnp.zeros(pad_n, bool)])
    key = key[:mp]
    dup = dup[:mp]
    last = jnp.take(key, jnp.maximum(n_pairs - 1, 0))
    live = jnp.arange(mp, dtype=jnp.int32) < n_pairs
    key = jnp.where(live, key, last)
    pair_in = key % T
    pair_out = key // T
    # untouched tiles: identity pair does a raw block copy, no matmul.
    # (A tile receiving rows from elsewhere necessarily lost rows too —
    # dst is a permutation — so untouched tiles exchange nothing.)
    touched = moved.reshape(T, tile).any(axis=1)
    is_copy = (pair_in == pair_out) & ~jnp.take(touched, pair_in)
    pcopy = jnp.where(dup & live, 2, is_copy.astype(jnp.int32))
    return pair_in, pair_out, pcopy, n_pairs[None]


def _limbs(x_int: jax.Array, n: int, axis: int) -> jax.Array:
    """Split int32 values into n 8-bit limbs concatenated along `axis`
    (each limb <= 255: exact as a bf16 matmul operand)."""
    parts = [jnp.bitwise_and(jax.lax.shift_right_logical(x_int, 8 * i), 255)
             for i in range(n)]
    return jnp.concatenate(parts, axis=axis)


def _make_compact_kernel(tile: int, gp: int, rc: int, plane8: bool):
    """plane8: the bin plane is an 8-bit dtype (uint8). Its values fit one
    bf16 limb, so the plane transports through ONE matmul instead of two,
    and the accumulate widens to i32 in-register (Mosaic has no elementwise
    8-bit vectors) before narrowing back to the 8-bit output block."""

    def kernel(pin_ref, pout_ref, pcopy_ref, npair_ref,
               bins_ref, row_ref, dst_ref, bins_out, row_out):
        p = pl.program_id(0)
        out_t = pout_ref[p]
        first = (p == 0) | (out_t != pout_ref[jnp.maximum(p - 1, 0)])
        # pcopy == 2: duplicate pair demoted to a skip by build_pair_tables
        # (a duplicate is never the first pair of its output block, so the
        # zero-init below cannot be skipped by accident)
        active = (p < npair_ref[0]) & (pcopy_ref[p] < 2)
        is_copy = pcopy_ref[p] == 1

        @pl.when(active & is_copy)
        def _copy():  # untouched tile: single pair for this block, plain copy
            bins_out[...] = bins_ref[...]
            row_out[...] = row_ref[...]

        @pl.when(active & jnp.logical_not(is_copy))
        def _permute():
            @pl.when(first)
            def _zero():
                bins_out[...] = jnp.zeros_like(bins_out)
                row_out[...] = jnp.zeros_like(row_out)

            rel = dst_ref[...][:, 0] - out_t * tile  # [tile] int32
            iota = jax.lax.broadcasted_iota(jnp.int32, (tile, tile), 1)
            # P[i, o] = 1 iff in-row i lands at out-row o of this block.
            # dst is injective => every column has at most one 1, so each
            # output row below receives exactly one source row: the limb
            # matmuls are exact bit transport, not sums.
            P = (rel[:, None] == iota).astype(jnp.bfloat16)
            rbits = jax.lax.bitcast_convert_type(row_ref[...], jnp.int32)
            rl = _limbs(rbits, 4, axis=1).astype(jnp.bfloat16)  # [tile, 4*rc]
            orl = jax.lax.dot_general(
                P, rl, dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).astype(jnp.int32)
            obits = (orl[:, :rc]
                     | (orl[:, rc:2 * rc] << 8)
                     | (orl[:, 2 * rc:3 * rc] << 16)
                     | (orl[:, 3 * rc:] << 24))
            # rows not sourced by this pair recombine to bits 0 == +0.0f;
            # f32 += 0.0 is exact, so cross-pair accumulation is bit-exact
            row_out[...] += jax.lax.bitcast_convert_type(obits, jnp.float32)
            if plane8:
                # single limb: values <= 255 are exact bf16 operands
                bl = bins_ref[...].astype(jnp.int32).astype(jnp.bfloat16)
                obl = jax.lax.dot_general(
                    bl, P, dimension_numbers=(((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32).astype(jnp.int32)
                bins_out[...] = (bins_out[...].astype(jnp.int32)
                                 + obl).astype(bins_out.dtype)
            else:
                bl = _limbs(bins_ref[...], 2, axis=0).astype(jnp.bfloat16)
                obl = jax.lax.dot_general(
                    bl, P, dimension_numbers=(((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32).astype(jnp.int32)
                bins_out[...] += obl[:gp] | (obl[gp:] << 8)

    return kernel


@partial(jax.jit, static_argnames=("tile", "interpret", "alias"))
def _pallas_compact_call(bins_p, row_p, dst, pair_in, pair_out, is_copy,
                         n_pairs, tile: int, interpret: bool,
                         alias: bool = False):
    Gp, N = bins_p.shape
    rc = row_p.shape[1]
    mp = pair_in.shape[0]
    plane8 = bins_p.dtype.itemsize == 1
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(mp,),
        in_specs=[
            pl.BlockSpec((Gp, tile), lambda p, pi, po, pc, npr: (0, pi[p])),
            pl.BlockSpec((tile, rc), lambda p, pi, po, pc, npr: (pi[p], 0)),
            pl.BlockSpec((tile, 1), lambda p, pi, po, pc, npr: (pi[p], 0)),
        ],
        out_specs=[
            pl.BlockSpec((Gp, tile), lambda p, pi, po, pc, npr: (0, po[p])),
            pl.BlockSpec((tile, rc), lambda p, pi, po, pc, npr: (po[p], 0)),
        ],
    )
    kwargs = {}
    if alias:
        # LGBM_TPU_COMPACT_ALIAS=1: reuse the bins/row input buffers as the
        # outputs (no double buffering of the two largest carries). Indices
        # count the 4 scalar-prefetch operands first. UNSAFE in general: a
        # pair whose in_tile < out_tile reads its input tile after the
        # aliased output tile has already been flushed over it. Safe only
        # when the runtime keeps a private copy or the permutation never
        # moves rows to a later tile than any unread source — hence opt-in.
        kwargs["input_output_aliases"] = {4: 0, 5: 1}
    return pl.pallas_call(
        _make_compact_kernel(tile, Gp, rc, plane8),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((Gp, N), bins_p.dtype),
            jax.ShapeDtypeStruct((N, rc), jnp.float32),
        ],
        interpret=interpret,
        **kwargs,
    )(pair_in, pair_out, is_copy, n_pairs, bins_p, row_p,
      dst.reshape(N, 1))


def compact_rows(bins_p: jax.Array, row_p: jax.Array, dst: jax.Array,
                 class_masks: Sequence[jax.Array], moved: jax.Array,
                 *, tile: int = COMPACT_TILE, use_pallas: bool = True,
                 interpret: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Apply the forward permutation dst to bins_p [Gp, N] (uint8, or int32
    with values < 2**16) and row_p [N, rc] (f32 payload, moved bit-exactly).
    The output bin plane keeps bins_p's dtype.

    Pallas path requirements: N % tile == 0, Gp % 8 == 0 for int32 planes
    and Gp % 32 == 0 for 8-bit planes (Mosaic (32, 128) tiling),
    class_masks disjoint with per-tile-contiguous destinations
    (range_partition_dst output qualifies), moved == union(class_masks).
    The XLA path is a plain permutation scatter — exact on CPU, used when
    no TPU backend is live. LGBM_TPU_COMPACT_ALIAS=1 opts in to
    input/output buffer aliasing on the pallas_call (see
    _pallas_compact_call for the hazard).
    """
    if not use_pallas:
        bins_o = jnp.zeros_like(bins_p).at[:, dst].set(
            bins_p, unique_indices=True)
        row_o = jnp.zeros_like(row_p).at[dst].set(row_p, unique_indices=True)
        return bins_o, row_o
    pair_in, pair_out, is_copy, n_pairs = build_pair_tables(
        dst, class_masks, moved, tile)
    alias = os.environ.get("LGBM_TPU_COMPACT_ALIAS", "") == "1"
    row_f32 = row_p.astype(jnp.float32)
    dst_i32 = dst.astype(jnp.int32)
    if telemetry.enabled():
        # one-time capture (works at trace time too: tracers carry the
        # shape/dtype perfmodel's AOT cost_analysis re-lower needs)
        perfmodel.note_dispatch("compact", _pallas_compact_call,
                                bins_p, row_f32, dst_i32, pair_in, pair_out,
                                is_copy, n_pairs, tile, interpret, alias)
    return _pallas_compact_call(bins_p, row_f32, dst_i32, pair_in, pair_out,
                                is_copy, n_pairs, tile, interpret, alias)
