"""Pallas TPU histogram kernel: one-hot stays in VMEM.

The XLA formulation in ops/histogram.py materializes the [G, chunk, B]
one-hot operand of the contraction unless XLA fuses it into the dot; at
HIGGS scale (N=10.5M, B=256) a materialized one-hot costs G*N*B*4 bytes of
HBM traffic per histogram — catastrophically bandwidth-bound. This kernel
generates each [TN, B] one-hot tile INSIDE the kernel (VMEM-resident, never
touches HBM) and feeds the MXU directly, so HBM traffic drops to the
irreducible G*N*(bins + gh) bytes:

    grid (G, N/TN); per step:
        onehot[TN, B] = (bins_tile[:, None] == iota)      # VPU, VMEM only
        out[g] += onehot^T @ gh_tile                      # MXU, [B, 3]

The output block for group g is revisited across the N tiles (TPU grids run
sequentially), accumulating in VMEM; step 0 zero-initializes.

Counterpart of the CUDA shared-memory scatter kernels
(src/treelearner/cuda/cuda_histogram_constructor.cu:20-513) — same
"accumulate in fast memory, flush once" structure, with the TPU twist that
the accumulation is an MXU contraction instead of atomic scatters.

Used automatically on TPU backends (ops/histogram.py routes here); the XLA
path remains for CPU and as the LGBM_TPU_HIST=xla escape hatch. Correctness
is pinned by tests running this kernel in interpret mode against the XLA
path and the numpy reference.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DEFAULT_TILE_ROWS = 2048


def _make_kernel(num_bins: int, tile_rows: int, compute_dtype, acc_dtype):
    def kernel(bins_ref, gh_ref, out_ref):
        @pl.when(pl.program_id(1) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        b = bins_ref[0, :]  # [TN] int32
        iota = jax.lax.broadcasted_iota(jnp.int32, (tile_rows, num_bins), 1)
        onehot = (b[:, None] == iota).astype(compute_dtype)  # VMEM only
        acc = jax.lax.dot_general(
            onehot, gh_ref[...].astype(compute_dtype),
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=acc_dtype)  # [B, CH]
        out_ref[0] += acc

    return kernel


@partial(jax.jit, static_argnames=("num_bins", "tile_rows", "quantized",
                                   "interpret"))
def pallas_histogram(bins: jax.Array, gh: jax.Array, num_bins: int,
                     tile_rows: int = DEFAULT_TILE_ROWS,
                     quantized: bool = False,
                     interpret: bool = False) -> jax.Array:
    """[G, N] bins + [N, CH] gh -> [G, num_bins, CH] histogram.

    quantized: int8 one-hot x int8 gh with exact int32 accumulation
    (MXU-native); otherwise f32 throughout. Rows are padded to the tile
    size with zero gh (contributes nothing).
    """
    G, N = bins.shape
    CH = gh.shape[1]
    compute_dtype = jnp.int8 if quantized else jnp.float32
    acc_dtype = jnp.int32 if quantized else jnp.float32
    n_tiles = max(-(-N // tile_rows), 1)
    pad = n_tiles * tile_rows - N
    bins = bins.astype(jnp.int32)
    if pad:
        bins = jnp.pad(bins, ((0, 0), (0, pad)), constant_values=0)
        gh = jnp.pad(gh, ((0, pad), (0, 0)))  # zero gh => no contribution
    out = pl.pallas_call(
        _make_kernel(num_bins, tile_rows, compute_dtype, acc_dtype),
        grid=(G, n_tiles),
        in_specs=[
            pl.BlockSpec((1, tile_rows), lambda g, t: (g, t)),
            pl.BlockSpec((tile_rows, CH), lambda g, t: (t, 0)),
        ],
        out_specs=pl.BlockSpec((1, num_bins, CH), lambda g, t: (g, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((G, num_bins, CH), acc_dtype),
        interpret=interpret,
    )(bins, gh)
    return out
