"""Pallas TPU histogram kernel: one-hot stays in VMEM.

The XLA formulation in ops/histogram.py materializes the [G, chunk, B]
one-hot operand of the contraction unless XLA fuses it into the dot; at
HIGGS scale (N=10.5M, B=256) a materialized one-hot costs G*N*B*4 bytes of
HBM traffic per histogram — catastrophically bandwidth-bound. This kernel
generates each [TN, B] one-hot tile INSIDE the kernel (VMEM-resident, never
touches HBM) and feeds the MXU directly, so HBM traffic drops to the
irreducible G*N*(bins + gh) bytes:

    grid (G/GB, N/TN); per step, for each of the GB groups in the block:
        onehot[TN, B] = (bins_tile[g][:, None] == iota)   # VPU, VMEM only
        out[g] += gh_tile^T @ onehot                      # MXU, [CH, B]

GB is chosen per call by _prep_bins/_group_block: as large as the output
block fits comfortably in VMEM (32 -> 16 -> 8; bigger blocks amortize
per-grid-step work), never below 8 — Mosaic requires the second-to-last
block dim to be a multiple of 8 (or the full array dim); a (1, TN) bins
block fails to lower on real TPU hardware. 8-bit bin planes (uint8) pass
through unwidened — 4x less HBM traffic for the dominant [G, N] array —
with GB pinned to 32 (Mosaic tiles 8-bit as (32, 128)) and the group row
widened to i32 in-register for the compare. The output block for a group
slab is revisited across the N tiles (TPU grids run sequentially),
accumulating in VMEM; step 0 zero-initializes.

Counterpart of the CUDA shared-memory scatter kernels
(src/treelearner/cuda/cuda_histogram_constructor.cu:20-513) — same
"accumulate in fast memory, flush once" structure, with the TPU twist that
the accumulation is an MXU contraction instead of atomic scatters.

Used automatically on TPU backends (ops/histogram.py routes here); the XLA
path remains for CPU and as the LGBM_TPU_HIST=xla escape hatch. Correctness
is pinned by tests running this kernel in interpret mode against the XLA
path and the numpy reference.
"""
from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import telemetry

# classify these entries' jit cache misses as kernel compiles (telemetry's
# recompile watcher keeps them in a counter separate from XLA churn)
for _fn in ("pallas_histogram", "pallas_histogram_slots",
            "pallas_histogram_slots_ragged"):
    telemetry.register_kernel_fn(_fn)

DEFAULT_TILE_ROWS = 1024  # best of {512, 1024, 2048, 4096} on v5e
MIN_GROUP_BLOCK = 8  # Mosaic minimum for the second-to-last block dim


def _group_block(n_groups: int, n_channels: int, num_bins: int,
                 acc_bytes: int = 4) -> int:
    """Largest useful group block whose output block stays comfortably in
    VMEM. Bigger blocks amortize the per-grid-step work (the slot-expanded
    gradient build runs once per (block, tile)): 8 -> 32 measured +13%
    end-to-end training throughput on v5e. Clamped to the group count
    rounded up to 8 so small-G datasets don't pay for dead padded groups."""
    cap = max(-(-n_groups // MIN_GROUP_BLOCK) * MIN_GROUP_BLOCK,
              MIN_GROUP_BLOCK)
    for gb in (32, 16):
        if gb <= cap and gb * n_channels * num_bins * acc_bytes <= (4 << 20):
            return gb
    return MIN_GROUP_BLOCK


def _prep_bins(bins: jax.Array, n_channels: int, num_bins: int):
    """Bin-plane dtype + group-block policy shared by the three wrappers.

    8-bit planes (uint8 bins) pass through UNWIDENED — the dominant [G, N]
    array moves 4x fewer HBM bytes — and the kernels widen each group row
    to i32 in-register for the one-hot compare (Mosaic has no elementwise
    8-bit vectors). Mosaic tiles 8-bit arrays as (32, 128), so the bins
    block's group dim is pinned to 32; when the matching (32, SC, B) f32
    output block would blow the VMEM budget, widen to int32 up front and
    let _group_block pick a smaller block instead."""
    if (bins.dtype.itemsize == 1
            and 32 * n_channels * num_bins * 4 <= (4 << 20)):
        return bins, 32
    return bins.astype(jnp.int32), _group_block(
        bins.shape[0], n_channels, num_bins)


def _make_kernel(num_bins: int, tile_rows: int, compute_dtype, acc_dtype,
                 group_block: int):
    def kernel(bins_ref, gh_ref, out_ref):
        @pl.when(pl.program_id(1) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        gh = gh_ref[...].astype(compute_dtype)
        iota = jax.lax.broadcasted_iota(jnp.int32, (tile_rows, num_bins), 1)
        for gi in range(group_block):  # unrolled: static VMEM indices
            b = bins_ref[gi, :].astype(jnp.int32)  # widen 8-bit in-register
            onehot = (b[:, None] == iota).astype(compute_dtype)  # VMEM only
            # [CH, B] orientation: B rides the 128-lane dim. The [B, CH]
            # orientation pads CH (2-6) up to 128 output lanes — a 20x+ FLOP
            # inflation that made histogram time scale with num_bins*128
            # instead of num_bins*CH.
            acc = jax.lax.dot_general(
                gh, onehot,
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=acc_dtype,
                # without HIGHEST the MXU decomposes f32 operands into bf16
                # passes, silently giving f32-mode the bf16 noise floor
                precision=(jax.lax.Precision.HIGHEST
                           if compute_dtype == jnp.float32 else
                           jax.lax.Precision.DEFAULT))  # [CH, B]
            out_ref[gi] += acc

    return kernel


def hist_force_f32() -> bool:
    """LGBM_TPU_HIST_F32=1 forces f32 operands. Resolved by the unjitted
    dispatch wrappers in ops.histogram so it enters the jit cache key as the
    `f32` static arg — but outer jitted callers (grow_tree_on_device) bake
    the value into their own trace, so set it BEFORE the first training
    call, not mid-run."""
    return os.environ.get("LGBM_TPU_HIST_F32", "").lower() not in (
        "", "0", "false", "off")


@partial(jax.jit, static_argnames=("num_bins", "tile_rows", "quantized",
                                   "f32", "interpret"))
def pallas_histogram(bins: jax.Array, gh: jax.Array, num_bins: int,
                     tile_rows: int = DEFAULT_TILE_ROWS,
                     quantized: bool = False,
                     f32: bool = False,
                     interpret: bool = False) -> jax.Array:
    """[G, N] bins + [N, CH] gh -> [G, num_bins, CH] histogram.

    quantized: int8 one-hot x int8 gh with exact int32 accumulation
    (MXU-native). Float path: bf16 operands with f32 accumulation — the MXU
    runs bf16 at full rate while f32 matmuls cost multiple passes; the
    one-hot is exactly representable and only the gh operand rounds (well
    under the reference's own single-precision histogram noise floor,
    feature_histogram.hpp hist_t=float). f32=True forces f32 operands.
    Rows are padded to the tile size with zero gh (contributes nothing).
    """
    G, N = bins.shape
    CH = gh.shape[1]
    if quantized:
        compute_dtype, acc_dtype = jnp.int8, jnp.int32
    elif f32:
        compute_dtype, acc_dtype = jnp.float32, jnp.float32
    else:
        compute_dtype, acc_dtype = jnp.bfloat16, jnp.float32
    n_tiles = max(-(-N // tile_rows), 1)
    pad = n_tiles * tile_rows - N
    bins, GB = _prep_bins(bins, CH, num_bins)
    if pad:
        bins = jnp.pad(bins, ((0, 0), (0, pad)), constant_values=0)
        gh = jnp.pad(gh, ((0, pad), (0, 0)))  # zero gh => no contribution
    g_blocks = max(-(-G // GB), 1)
    g_pad = g_blocks * GB - G
    if g_pad:  # padded groups accumulate into rows sliced off below
        bins = jnp.pad(bins, ((0, g_pad), (0, 0)), constant_values=0)
    out = pl.pallas_call(
        _make_kernel(num_bins, tile_rows, compute_dtype, acc_dtype, GB),
        grid=(g_blocks, n_tiles),
        in_specs=[
            pl.BlockSpec((GB, tile_rows), lambda g, t: (g, t)),
            pl.BlockSpec((tile_rows, CH), lambda g, t: (t, 0)),
        ],
        out_specs=pl.BlockSpec((GB, CH, num_bins),
                               lambda g, t: (g, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((g_blocks * GB, CH, num_bins),
                                       acc_dtype),
        interpret=interpret,
    )(bins, gh)
    return out[:G].transpose(0, 2, 1)  # [G, B, CH]; 172KB, free vs the dot


def _make_slots_kernel(num_bins: int, tile_rows: int, n_slots: int,
                       ch: int, compute_dtype, acc_dtype, group_block: int):
    SC = n_slots * ch

    def kernel(bins_ref, gh_ref, slot_ref, out_ref):
        @pl.when(pl.program_id(1) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        s = slot_ref[...]  # [TN, 1] int32
        ghc = gh_ref[...]  # [TN, ch]
        # flat 2D build of the slot-expanded gradient tile — column
        # j = slot*ch + channel. Strictly 2D broadcasts: per-channel masked
        # adds instead of a concat/tile (an n_slots-way concat lowers to a
        # serial copy chain in Mosaic; measured ~2x slower end to end), and
        # the whole [TN, SC] tile lives only in VMEM (the XLA-side
        # materialization of this matrix cost ~18 ms/wave of HBM traffic).
        # Mosaic has no elementwise int8 vectors ("only vector<i16/i32>"),
        # so the quantized build runs in int32 and casts to int8 only at
        # the matmul operand.
        build_dtype = (jnp.int32 if jnp.issubdtype(jnp.dtype(compute_dtype),
                                                   jnp.integer)
                       else ghc.dtype)
        ghb = ghc.astype(build_dtype)
        col = jax.lax.broadcasted_iota(jnp.int32, (1, SC), 1)
        colslot, colch = col // ch, col % ch
        gsum = jnp.zeros((tile_rows, SC), build_dtype)
        for c in range(ch):
            gsum += ghb[:, c:c + 1] * (colch == c).astype(build_dtype)
        ghK = (gsum * (colslot == s).astype(build_dtype)).astype(compute_dtype)
        iota = jax.lax.broadcasted_iota(jnp.int32, (tile_rows, num_bins), 1)
        for gi in range(group_block):
            b = bins_ref[gi, :].astype(jnp.int32)
            onehot = (b[:, None] == iota).astype(compute_dtype)
            acc = jax.lax.dot_general(
                ghK, onehot,
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=acc_dtype,
                precision=(jax.lax.Precision.HIGHEST
                           if compute_dtype == jnp.float32 else
                           jax.lax.Precision.DEFAULT))  # [SC, B]
            out_ref[gi] += acc

    return kernel


@partial(jax.jit, static_argnames=("num_bins", "n_slots", "tile_rows",
                                   "quantized", "f32", "interpret"))
def pallas_histogram_slots(bins: jax.Array, gh: jax.Array, slot: jax.Array,
                           num_bins: int, n_slots: int,
                           tile_rows: int = DEFAULT_TILE_ROWS,
                           quantized: bool = False,
                           f32: bool = False,
                           interpret: bool = False) -> jax.Array:
    """Slot-expanded histogram: [G, N] bins + [N, CH] gh + [N] slot ids ->
    [G, num_bins, n_slots*CH], where row n contributes its gh to channel
    block slot[n] (rows with slot outside [0, n_slots) contribute nowhere).

    This is the wave histogram of the batched device learner: building the
    [N, n_slots*CH] slot-expanded gradient matrix in XLA costs a full HBM
    round trip of n_slots*CH f32 per row (~10 ms/wave at 1M rows); here the
    expansion happens per-tile in VMEM for free. Dtype policy matches
    pallas_histogram."""
    G, N = bins.shape
    CH = gh.shape[1]
    SC = n_slots * CH
    if quantized:
        compute_dtype, acc_dtype = jnp.int8, jnp.int32
    elif f32:
        compute_dtype, acc_dtype = jnp.float32, jnp.float32
    else:
        compute_dtype, acc_dtype = jnp.bfloat16, jnp.float32
    n_tiles = max(-(-N // tile_rows), 1)
    pad = n_tiles * tile_rows - N
    bins, GB = _prep_bins(bins, SC, num_bins)
    slot = slot.reshape(N, 1).astype(jnp.int32)
    if pad:
        bins = jnp.pad(bins, ((0, 0), (0, pad)), constant_values=0)
        gh = jnp.pad(gh, ((0, pad), (0, 0)))  # zero gh => no contribution
        slot = jnp.pad(slot, ((0, pad), (0, 0)), constant_values=n_slots)
    g_blocks = max(-(-G // GB), 1)
    g_pad = g_blocks * GB - G
    if g_pad:
        bins = jnp.pad(bins, ((0, g_pad), (0, 0)), constant_values=0)
    out = pl.pallas_call(
        _make_slots_kernel(num_bins, tile_rows, n_slots, CH, compute_dtype,
                           acc_dtype, GB),
        grid=(g_blocks, n_tiles),
        in_specs=[
            pl.BlockSpec((GB, tile_rows), lambda g, t: (g, t)),
            pl.BlockSpec((tile_rows, CH), lambda g, t: (t, 0)),
            pl.BlockSpec((tile_rows, 1), lambda g, t: (t, 0)),
        ],
        out_specs=pl.BlockSpec((GB, SC, num_bins),
                               lambda g, t: (g, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((g_blocks * GB, SC, num_bins),
                                       acc_dtype),
        interpret=interpret,
    )(bins, gh, slot)
    return out[:G].transpose(0, 2, 1)  # [G, B, SC]


def active_tile_table(starts: jax.Array, ends: jax.Array, valid: jax.Array,
                      n_tiles: int, tile_rows: int):
    """Row-tile indirection table for the ragged wave histogram.

    starts/ends [K] int32 half-open row ranges (leaf-contiguous layout),
    valid [K] bool. Returns (tiles [n_tiles] int32, n_active [1] int32):
    the ascending indices of every tile overlapping a valid range, padded
    past n_active by repeating the last active tile (same block index =>
    the kernel pipeline skips the redundant DMA and pl.when skips compute).
    """
    t = jnp.arange(n_tiles, dtype=jnp.int32)
    lo = t * tile_rows
    act = (((lo[:, None] < ends[None, :])
            & (lo[:, None] + tile_rows > starts[None, :]))
           & valid[None, :]).any(axis=1)
    order = jnp.argsort(~act, stable=True).astype(jnp.int32)  # actives first
    n_act = act.sum().astype(jnp.int32)
    last = jnp.take(order, jnp.maximum(n_act - 1, 0))
    tiles = jnp.where(t < n_act, order, last)
    return tiles, n_act[None]


def _make_slots_ragged_kernel(num_bins: int, tile_rows: int, n_slots: int,
                              ch: int, compute_dtype, acc_dtype,
                              group_block: int):
    SC = n_slots * ch
    quantized = jnp.issubdtype(jnp.dtype(acc_dtype), jnp.integer)

    def kernel(tiles_ref, nact_ref, bins_ref, gh_ref, slot_ref, out_ref):
        t = pl.program_id(1)

        @pl.when(t == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        @pl.when(t < nact_ref[0])
        def _acc():
            s = slot_ref[...]  # [TN, 1] int32
            ghc = gh_ref[...]  # [TN, ch] f32 (quantized: exact small ints)
            col = jax.lax.broadcasted_iota(jnp.int32, (1, SC), 1)
            colslot, colch = col // ch, col % ch
            gsum = jnp.zeros((tile_rows, SC), jnp.float32)
            for c in range(ch):
                gsum += ghc[:, c:c + 1] * (colch == c).astype(jnp.float32)
            ghK = (gsum * (colslot == s).astype(jnp.float32)
                   ).astype(compute_dtype)
            iota = jax.lax.broadcasted_iota(jnp.int32,
                                            (tile_rows, num_bins), 1)
            for gi in range(group_block):
                b = bins_ref[gi, :].astype(jnp.int32)
                onehot = (b[:, None] == iota).astype(compute_dtype)
                acc = jax.lax.dot_general(
                    ghK, onehot,
                    dimension_numbers=(((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=(jax.lax.Precision.HIGHEST
                               if compute_dtype == jnp.float32 else
                               jax.lax.Precision.DEFAULT))  # [SC, B]
                # quantized: per-tile partial sums are exact ints in f32
                # (<= tile_rows * 127 * 255 < 2**24); accumulate int32
                out_ref[gi] += acc.astype(acc_dtype) if quantized else acc

    return kernel


@partial(jax.jit, static_argnames=("num_bins", "n_slots", "tile_rows",
                                   "quantized", "f32", "interpret"))
def pallas_histogram_slots_ragged(bins: jax.Array, gh: jax.Array,
                                  slot: jax.Array, tiles: jax.Array,
                                  n_active: jax.Array,
                                  num_bins: int, n_slots: int,
                                  tile_rows: int = DEFAULT_TILE_ROWS,
                                  quantized: bool = False,
                                  f32: bool = False,
                                  interpret: bool = False) -> jax.Array:
    """pallas_histogram_slots restricted to an indirected set of row tiles.

    The rows-in-leaf wave histogram: `tiles` (from active_tile_table) names
    the row tiles overlapping the wave's selected leaf ranges; the grid
    walks ONLY those via scalar-prefetched index maps (MoE-style ragged
    blocks), so per-wave cost is O(rows in selected leaves) instead of
    O(N). Rows inside a listed tile but outside every selected range must
    carry slot >= n_slots (the dump slot). `n_active` is a traced [1]
    int32 — inactive tail entries of `tiles` repeat the last active tile
    and are skipped.

    gh is ALWAYS [N, CH] f32 here (the leaf-contiguous row payload).
    quantized=True means gh holds small exact ints; the build stays f32,
    operands go bf16 (exact <= 255), per-tile partials are exact in f32
    and accumulate int32 — bit-identical to the int8 dense path.
    """
    G, N = bins.shape
    CH = gh.shape[1]
    SC = n_slots * CH
    if N % tile_rows:
        raise ValueError("ragged histogram requires N padded to tile_rows")
    if quantized:
        compute_dtype, acc_dtype = jnp.bfloat16, jnp.int32
    elif f32:
        compute_dtype, acc_dtype = jnp.float32, jnp.float32
    else:
        compute_dtype, acc_dtype = jnp.bfloat16, jnp.float32
    T = tiles.shape[0]
    bins, GB = _prep_bins(bins, SC, num_bins)
    slot = slot.reshape(N, 1).astype(jnp.int32)
    g_blocks = max(-(-G // GB), 1)
    g_pad = g_blocks * GB - G
    if g_pad:
        bins = jnp.pad(bins, ((0, g_pad), (0, 0)), constant_values=0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(g_blocks, T),
        in_specs=[
            pl.BlockSpec((GB, tile_rows), lambda g, t, tr, na: (g, tr[t])),
            pl.BlockSpec((tile_rows, CH), lambda g, t, tr, na: (tr[t], 0)),
            pl.BlockSpec((tile_rows, 1), lambda g, t, tr, na: (tr[t], 0)),
        ],
        out_specs=pl.BlockSpec((GB, SC, num_bins),
                               lambda g, t, tr, na: (g, 0, 0)),
    )
    out = pl.pallas_call(
        _make_slots_ragged_kernel(num_bins, tile_rows, n_slots, CH,
                                  compute_dtype, acc_dtype, GB),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((g_blocks * GB, SC, num_bins),
                                       acc_dtype),
        interpret=interpret,
    )(tiles.astype(jnp.int32), n_active.astype(jnp.int32),
      bins, gh.astype(jnp.float32), slot)
    return out[:G].transpose(0, 2, 1)  # [G, B, SC]
