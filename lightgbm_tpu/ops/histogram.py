"""Histogram construction as batched one-hot MXU contractions.

TPU-native replacement for the reference's histogram kernels:
  * CPU: DenseBin::ConstructHistogram gather-add loops (src/io/dense_bin.hpp)
  * CUDA: CUDAHistogramConstructor shared-memory scatter kernels
    (src/treelearner/cuda/cuda_histogram_constructor.cu:20-513)

TPUs have no fast arbitrary scatter; the idiomatic formulation is a one-hot
contraction that runs on the MXU: for each feature group g,

    hist[g, b, c] = sum_p [bins[g, p] == b] * gh[p, c]

i.e. an einsum('gpb,pc->gbc') where the one-hot tensor is generated on the
fly from an iota comparison. XLA tiles this onto the systolic array; rows are
processed in chunks via lax.scan so the transient one-hot stays small (VMEM-
friendly) and the accumulator lives in f32.

Leaf-restricted histograms use gather-by-index: the trainer keeps per-leaf
padded row-index arrays (ops/partition.py); `gh` is stored with a zero
sentinel row at index N so padded indices contribute nothing.

The channel layout is [grad, hess, count].
"""
from __future__ import annotations

import os
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from .. import perfmodel, telemetry

DEFAULT_ROW_CHUNK = 16384


def _use_pallas() -> bool:
    """Pallas kernel on TPU ONLY (the XLA one-hot contraction risks
    materializing the [G, chunk, B] one-hot in HBM); the kernel's
    revisited-output accumulation relies on TPU's sequential grid, so other
    backends (cpu, gpu) always take the XLA path. LGBM_TPU_HIST=xla|pallas
    overrides, resolved at CALL time (the public entry points are unjitted
    wrappers so the env var participates in dispatch, not a baked trace)."""
    mode = os.environ.get("LGBM_TPU_HIST", "auto")
    if mode == "xla":
        return False
    if mode == "pallas":
        return True
    try:
        backend = jax.default_backend().lower()
        return "tpu" in backend or "axon" in backend
    except RuntimeError:
        return False


def _acc_dtype(compute_dtype):
    """Accumulator dtype: int32 for integer (quantized-gradient) histograms
    — exact, and int8 x int8 -> int32 contractions are MXU-native — f32
    otherwise."""
    return (jnp.int32 if jnp.issubdtype(jnp.dtype(compute_dtype), jnp.integer)
            else jnp.float32)


def _hist_chunk(bins_c: jax.Array, gh_c: jax.Array, num_bins: int,
                compute_dtype) -> jax.Array:
    """One chunk: bins_c [G, C] int32, gh_c [C, 3] -> [G, num_bins, 3]."""
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, 1, num_bins), 2)
    onehot = (bins_c[:, :, None] == iota).astype(compute_dtype)  # [G, C, B]
    return jax.lax.dot_general(
        onehot, gh_c.astype(compute_dtype),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=_acc_dtype(compute_dtype),
    )  # [G, B, 3]


def build_histogram(bins: jax.Array, gh: jax.Array, num_bins: int,
                    row_chunk: int = DEFAULT_ROW_CHUNK,
                    compute_dtype=jnp.float32,
                    use_pallas: bool = None) -> jax.Array:
    """Full-data histogram.

    bins: [G, N] integer bin matrix (any int dtype)
    gh:   [N, 3] float (grad, hess, 1.0)
    Returns [G, num_bins, 3] float32.

    Unjitted dispatch wrapper: the backend choice (Pallas on TPU, XLA
    elsewhere / LGBM_TPU_HIST override) resolves per call, then routes to a
    jitted implementation. Inside an outer jit the choice is baked at that
    trace's creation, as any Python-level branch must be.
    """
    if use_pallas is None:
        use_pallas = _use_pallas()
    if use_pallas:
        from .hist_pallas import hist_force_f32, pallas_histogram

        # native dtype pass-through: 8-bit planes stay narrow on the wire
        # (the kernel wrapper widens only when its VMEM policy demands it)
        return pallas_histogram(
            bins, gh, num_bins,
            quantized=jnp.issubdtype(jnp.dtype(compute_dtype), jnp.integer),
            f32=hist_force_f32())
    return _build_histogram_xla(bins, gh, num_bins, row_chunk, compute_dtype)


@partial(jax.jit, static_argnames=("num_bins", "row_chunk", "compute_dtype"))
def _build_histogram_xla(bins: jax.Array, gh: jax.Array, num_bins: int,
                         row_chunk: int = DEFAULT_ROW_CHUNK,
                         compute_dtype=jnp.float32) -> jax.Array:
    G, N = bins.shape
    bins = bins.astype(jnp.int32)
    if N <= row_chunk:
        return _hist_chunk(bins, gh, num_bins, compute_dtype)
    n_chunks = (N + row_chunk - 1) // row_chunk
    pad = n_chunks * row_chunk - N
    if pad:
        bins = jnp.pad(bins, ((0, 0), (0, pad)))
        gh = jnp.pad(gh, ((0, pad), (0, 0)))  # zero gh => no contribution
    bins_s = bins.reshape(G, n_chunks, row_chunk).transpose(1, 0, 2)
    gh_s = gh.reshape(n_chunks, row_chunk, gh.shape[1])

    def step(acc, xs):
        b_c, g_c = xs
        return acc + _hist_chunk(b_c, g_c, num_bins, compute_dtype), None

    init = jnp.zeros((G, num_bins, gh.shape[1]), dtype=_acc_dtype(compute_dtype))
    hist, _ = jax.lax.scan(step, init, (bins_s, gh_s))
    return hist


def build_histogram_rows(bins: jax.Array, gh_ext: jax.Array, row_idx: jax.Array,
                         num_bins: int, row_chunk: int = DEFAULT_ROW_CHUNK,
                         compute_dtype=jnp.float32,
                         use_pallas: bool = None) -> jax.Array:
    """Leaf histogram over a padded row-index set (unjitted dispatch wrapper
    like build_histogram).

    bins:    [G, N] full bin matrix
    gh_ext:  [N+1, 3] gradients with a ZERO sentinel row at index N
    row_idx: [P] row indices, padded with N (the sentinel)
    Returns [G, num_bins, 3] float32.

    Padded entries gather gh == 0 so they contribute nothing; their bins
    gather is clamped (any bin works since the weight is zero).
    """
    if use_pallas is None:
        use_pallas = _use_pallas()
    if use_pallas:
        from .hist_pallas import hist_force_f32, pallas_histogram

        G, N = bins.shape
        bins_leaf = jnp.take(bins, jnp.minimum(row_idx, N - 1), axis=1)
        gh_leaf = jnp.take(gh_ext, row_idx, axis=0)
        quantized = jnp.issubdtype(jnp.dtype(compute_dtype), jnp.integer)
        f32 = hist_force_f32()
        if telemetry.enabled():
            # one-time capture for perfmodel's AOT cost_analysis; the dict
            # check keeps the per-leaf hot path O(1) afterwards
            perfmodel.note_dispatch("histogram", pallas_histogram,
                                    bins_leaf, gh_leaf, num_bins,
                                    quantized=quantized, f32=f32)
        return pallas_histogram(bins_leaf, gh_leaf, num_bins,
                                quantized=quantized, f32=f32)
    if telemetry.enabled():
        perfmodel.note_dispatch("histogram", _build_histogram_rows_xla,
                                bins, gh_ext, row_idx, num_bins,
                                row_chunk, compute_dtype)
    return _build_histogram_rows_xla(bins, gh_ext, row_idx, num_bins,
                                     row_chunk, compute_dtype)


@partial(jax.jit, static_argnames=("num_bins", "row_chunk", "compute_dtype"))
def _build_histogram_rows_xla(bins: jax.Array, gh_ext: jax.Array,
                              row_idx: jax.Array, num_bins: int,
                              row_chunk: int = DEFAULT_ROW_CHUNK,
                              compute_dtype=jnp.float32) -> jax.Array:
    G, N = bins.shape
    bins_leaf = jnp.take(bins, jnp.minimum(row_idx, N - 1), axis=1).astype(jnp.int32)
    gh_leaf = jnp.take(gh_ext, row_idx, axis=0)  # idx==N hits the zero row
    P = row_idx.shape[0]
    if P <= row_chunk:
        return _hist_chunk(bins_leaf, gh_leaf, num_bins, compute_dtype)
    n_chunks = (P + row_chunk - 1) // row_chunk
    pad = n_chunks * row_chunk - P
    if pad:
        bins_leaf = jnp.pad(bins_leaf, ((0, 0), (0, pad)))
        gh_leaf = jnp.pad(gh_leaf, ((0, pad), (0, 0)))
    bins_s = bins_leaf.reshape(G, n_chunks, row_chunk).transpose(1, 0, 2)
    gh_s = gh_leaf.reshape(n_chunks, row_chunk, gh_leaf.shape[1])

    def step(acc, xs):
        b_c, g_c = xs
        return acc + _hist_chunk(b_c, g_c, num_bins, compute_dtype), None

    init = jnp.zeros((G, num_bins, gh_leaf.shape[1]),
                     dtype=_acc_dtype(compute_dtype))
    hist, _ = jax.lax.scan(step, init, (bins_s, gh_s))
    return hist


@jax.jit
def subtract_histogram(parent: jax.Array, sibling: jax.Array) -> jax.Array:
    """The histogram-subtraction trick (FeatureHistogram::Subtract,
    src/treelearner/feature_histogram.hpp:99; CUDA SubtractHistogramForLeaf):
    larger child = parent - smaller child, skipping a full construction pass.
    """
    return parent - sibling
