"""Leaf row partition: per-leaf padded index sets on device.

TPU-native replacement for DataPartition (src/treelearner/data_partition.hpp)
and CUDADataPartition's bitvector + prefix-scan compaction
(src/treelearner/cuda/cuda_data_partition.cu, GenDataToLeftBitVector/
SplitInner).

Design: instead of one globally permuted index array with host-tracked leaf
ranges (awkward under XLA's static shapes), each leaf owns a padded device
index array. Padding uses the sentinel index N, which

  * gathers the zero row of the extended gradient array (histograms), and
  * is dropped by scatter-adds with mode="drop" (score updates).

A split evaluates the bin-level decision (NumericalDecisionInner semantics,
include/LightGBM/tree.h:357-371) over the parent's indices, then performs a
stable partition via argsort on a 3-way key (left < right < padding) — the
XLA-friendly equivalent of the CUDA prefix-scan compaction. Children reuse
power-of-two padded buffers so jit caches stay bounded (one compiled kernel
per bucket size).
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..common import MISSING_NAN, MISSING_NONE, MISSING_ZERO


def bucket_size(n: int, minimum: int = 256) -> int:
    """Power-of-two padded size for a leaf of n rows."""
    p = minimum
    while p < n:
        p <<= 1
    return p


def pad_indices(idx: np.ndarray, n_data: int, minimum: int = 256) -> np.ndarray:
    """Pad a host index array with the sentinel N to its bucket size."""
    p = bucket_size(len(idx), minimum)
    out = np.full(p, n_data, dtype=np.int32)
    out[: len(idx)] = idx
    return out


@jax.jit
def split_decision_bins(group_bins: jax.Array, decision: jax.Array) -> jax.Array:
    """go_left for raw GROUP bins of the split group.

    decision: device vector
      [0]=threshold (feature-bin space), [1]=default_left, [2]=missing_type,
      [3]=feature default_bin, [4]=feature nbins, [5]=efb_lo, [6]=efb_hi,
      [7]=is_efb (group bins need translation to feature bins)
    Implements NumericalDecisionInner: missing bin -> default side, otherwise
    bin <= threshold.
    """
    thresh = decision[0].astype(jnp.int32)
    default_left = decision[1] > 0.5
    missing_type = decision[2].astype(jnp.int32)
    default_bin = decision[3].astype(jnp.int32)
    nbins = decision[4].astype(jnp.int32)
    lo = decision[5].astype(jnp.int32)
    hi = decision[6].astype(jnp.int32)
    is_efb = decision[7] > 0.5

    gb = group_bins.astype(jnp.int32)
    # EFB translation: group bin in [lo, hi) -> natural feature bin
    # (undo the default-bin removal shift); anything else -> default bin
    in_range = (gb >= lo) & (gb < hi)
    shifted = gb - lo
    natural = shifted + (shifted >= default_bin).astype(jnp.int32)
    fbin = jnp.where(is_efb, jnp.where(in_range, natural, default_bin), gb)

    is_missing = jnp.where(
        missing_type == MISSING_NAN, fbin == nbins - 1,
        jnp.where(missing_type == MISSING_ZERO, fbin == default_bin, False))
    return jnp.where(is_missing, default_left, fbin <= thresh)


@jax.jit
def split_decision_bins_cat(group_bins: jax.Array, decision: jax.Array,
                            cat_mask: jax.Array) -> jax.Array:
    """go_left for a categorical split: membership of the (EFB-translated)
    feature bin in the chosen bin set (CategoricalDecisionInner,
    include/LightGBM/tree.h:375-388; unseen bins go right)."""
    default_bin = decision[3].astype(jnp.int32)
    lo = decision[5].astype(jnp.int32)
    hi = decision[6].astype(jnp.int32)
    is_efb = decision[7] > 0.5

    gb = group_bins.astype(jnp.int32)
    in_range = (gb >= lo) & (gb < hi)
    shifted = gb - lo
    natural = shifted + (shifted >= default_bin).astype(jnp.int32)
    fbin = jnp.where(is_efb, jnp.where(in_range, natural, default_bin), gb)
    B = cat_mask.shape[0]
    return cat_mask[jnp.clip(fbin, 0, B - 1)] & (fbin < B)


@jax.jit
def partition_rows_cat(bins_row: jax.Array, row_idx: jax.Array,
                       count: jax.Array, decision: jax.Array,
                       cat_mask: jax.Array, n_data: int
                       ) -> Tuple[jax.Array, jax.Array]:
    """partition_rows with a categorical bin-set decision."""
    P = row_idx.shape[0]
    valid = jnp.arange(P, dtype=jnp.int32) < count
    gb = jnp.take(bins_row, jnp.minimum(row_idx, n_data - 1))
    go_left = split_decision_bins_cat(gb, decision, cat_mask) & valid
    key = jnp.where(go_left, 0, jnp.where(valid, 1, 2)).astype(jnp.int32)
    order = jnp.argsort(key, stable=True)
    sorted_idx = jnp.where(jnp.arange(P, dtype=jnp.int32) < count, row_idx[order], n_data)
    return sorted_idx, go_left.sum()


@jax.jit
def partition_rows(bins_row: jax.Array, row_idx: jax.Array, count: jax.Array,
                   decision: jax.Array, n_data: int
                   ) -> Tuple[jax.Array, jax.Array]:
    """Stable-partition a leaf's padded indices by the split decision.

    bins_row: [N] group-bin column of the split group
    row_idx:  [P] padded leaf indices (sentinel = n_data)
    count:    scalar actual row count
    Returns (sorted_idx [P] — left rows first, then right, then sentinel
    padding — and left_count).
    """
    P = row_idx.shape[0]
    valid = jnp.arange(P, dtype=jnp.int32) < count
    gb = jnp.take(bins_row, jnp.minimum(row_idx, n_data - 1))
    go_left = split_decision_bins(gb, decision) & valid
    key = jnp.where(go_left, 0, jnp.where(valid, 1, 2)).astype(jnp.int32)
    order = jnp.argsort(key, stable=True)
    sorted_idx = jnp.where(jnp.arange(P, dtype=jnp.int32) < count, row_idx[order], n_data)
    return sorted_idx, go_left.sum()


class RowPartition:
    """Host orchestrator of per-leaf device index arrays.

    leaf -> (device idx array padded to a power-of-two bucket, host count).
    The root leaf starts with all rows. One device->host sync per split (the
    left count), mirroring the CUDA learner's per-split scalar sync
    (cuda_single_gpu_tree_learner.cpp:291-330).
    """

    def __init__(self, num_data: int, min_bucket: int = 256) -> None:
        self.num_data = num_data
        self.min_bucket = min_bucket
        root = np.arange(num_data, dtype=np.int32)
        self.leaf_idx = {0: jnp.asarray(pad_indices(root, num_data, min_bucket),
                                        dtype=jnp.int32)}
        self.leaf_count = {0: num_data}

    def indices(self, leaf: int) -> jax.Array:
        return self.leaf_idx[leaf]

    def count(self, leaf: int) -> int:
        return self.leaf_count[leaf]

    def split(self, leaf: int, new_leaf: int, bins_row: jax.Array,
              decision: jax.Array,
              cat_mask: Optional[jax.Array] = None) -> Tuple[int, int]:
        """Split `leaf` in place; left stays as `leaf`, right becomes
        `new_leaf`. Returns (left_count, right_count). cat_mask selects the
        categorical bin-membership decision."""
        idx = self.leaf_idx[leaf]
        cnt = self.leaf_count[leaf]
        if cat_mask is not None:
            sorted_idx, left_cnt_dev = partition_rows_cat(
                bins_row, idx, jnp.asarray(cnt, dtype=jnp.int32), decision,
                cat_mask, self.num_data)
        else:
            sorted_idx, left_cnt_dev = partition_rows(
                bins_row, idx, jnp.asarray(cnt, dtype=jnp.int32), decision,
                self.num_data)
        left_cnt = int(left_cnt_dev)  # the one host sync per split
        right_cnt = cnt - left_cnt
        lp = bucket_size(left_cnt, self.min_bucket)
        rp = bucket_size(right_cnt, self.min_bucket)
        left_idx = sorted_idx[:lp]
        left_idx = jnp.where(jnp.arange(lp, dtype=jnp.int32) < left_cnt, left_idx,
                             self.num_data)
        # pad before slicing: dynamic_slice clamps its start index when
        # start+size exceeds the array, which would silently hand left rows
        # to the right child
        padded = jnp.concatenate([
            sorted_idx, jnp.full(rp, self.num_data, sorted_idx.dtype)])
        right_idx = jax.lax.dynamic_slice(padded, (left_cnt,), (rp,))
        right_idx = jnp.where(jnp.arange(rp, dtype=jnp.int32) < right_cnt, right_idx,
                              self.num_data)
        self.leaf_idx[leaf] = left_idx
        self.leaf_count[leaf] = left_cnt
        self.leaf_idx[new_leaf] = right_idx
        self.leaf_count[new_leaf] = right_cnt
        return left_cnt, right_cnt

    def set_used_indices(self, indices: np.ndarray) -> None:
        """Restrict the root to a bagging subset (SetUsedDataIndices)."""
        self.leaf_idx = {0: jnp.asarray(pad_indices(indices.astype(np.int32),
                                                    self.num_data, self.min_bucket),
                                        dtype=jnp.int32)}
        self.leaf_count = {0: len(indices)}
