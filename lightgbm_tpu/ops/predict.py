"""Vectorized tree-ensemble inference on TPU.

TPU-native replacement for the reference's per-row recursive traversal
(Tree::Predict / NumericalDecision, include/LightGBM/tree.h:338-420, and
GBDT::PredictRaw, src/boosting/gbdt_prediction.cpp:15-56). Instead of
pointer-chasing per row, all trees are packed into padded [T, nodes] tensors
and traversed with a depth-synchronous gather loop under jit: every row of
every tree advances one level per step; rows that reached a leaf (negative
node id) freeze. This keeps shapes static and the whole ensemble evaluation a
single fused XLA computation, vmapped over trees.
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common import MISSING_NAN, MISSING_ZERO, K_ZERO_THRESHOLD
from ..models.tree import Tree

_EPS = K_ZERO_THRESHOLD


@dataclass
class PackedEnsemble:
    """Device-resident padded arrays for a list of trees.

    Shapes: T = number of trees, I = max internal nodes, L = max leaves,
    W = total categorical bitset words (>=1).
    """

    split_feature: jax.Array  # [T, I] int32
    threshold: jax.Array  # [T, I] float
    decision_type: jax.Array  # [T, I] int32
    left_child: jax.Array  # [T, I] int32
    right_child: jax.Array  # [T, I] int32
    leaf_value: jax.Array  # [T, L] float
    cat_words: jax.Array  # [W] uint32 bitset words (real-value space)
    cat_offset: jax.Array  # [T, I] int32 word offset for categorical nodes
    cat_n_words: jax.Array  # [T, I] int32
    num_leaves: jax.Array  # [T] int32
    max_depth: int
    num_trees: int
    # linear-tree per-leaf models (tree.h leaf_const_/leaf_coeff_/leaf_features_)
    linear: bool = False  # static: gates the linear output path
    lin_const: Optional[jax.Array] = None  # [T, L] (leaf_value for non-linear trees)
    lin_feat: Optional[jax.Array] = None  # [T, L, K] int32, -1 padding
    lin_coeff: Optional[jax.Array] = None  # [T, L, K]

    def tree_slice(self, start: int, end: int) -> "PackedEnsemble":
        return PackedEnsemble(
            split_feature=self.split_feature[start:end],
            threshold=self.threshold[start:end],
            decision_type=self.decision_type[start:end],
            left_child=self.left_child[start:end],
            right_child=self.right_child[start:end],
            leaf_value=self.leaf_value[start:end],
            cat_words=self.cat_words,
            cat_offset=self.cat_offset[start:end],
            cat_n_words=self.cat_n_words[start:end],
            num_leaves=self.num_leaves[start:end],
            max_depth=self.max_depth,
            num_trees=end - start,
            linear=self.linear,
            lin_const=self.lin_const[start:end] if self.linear else None,
            lin_feat=self.lin_feat[start:end] if self.linear else None,
            lin_coeff=self.lin_coeff[start:end] if self.linear else None,
        )


jax.tree_util.register_pytree_node(
    PackedEnsemble,
    lambda p: ((p.split_feature, p.threshold, p.decision_type, p.left_child,
                p.right_child, p.leaf_value, p.cat_words, p.cat_offset,
                p.cat_n_words, p.num_leaves, p.lin_const, p.lin_feat,
                p.lin_coeff), (p.max_depth, p.num_trees, p.linear)),
    lambda aux, ch: PackedEnsemble(
        *ch[:10], max_depth=aux[0], num_trees=aux[1], linear=aux[2],
        lin_const=ch[10], lin_feat=ch[11], lin_coeff=ch[12]),
)


def pack_ensemble(trees: Sequence[Tree], dtype=jnp.float32,
                  fixed_leaves: int = 0, fixed_depth: int = 0) -> PackedEnsemble:
    """Pack host Tree objects into padded device tensors.

    fixed_leaves / fixed_depth force the padded node count and traversal
    depth, keeping shapes stable across repeated packs (per-iteration
    validation scoring) so jit caches are reused.
    """
    T = max(len(trees), 1)
    I = max(max((t.num_leaves - 1 for t in trees), default=1), 1,
            fixed_leaves - 1)
    L = max(max((t.num_leaves for t in trees), default=1), 1, fixed_leaves)
    sf = np.zeros((T, I), dtype=np.int32)
    th = np.zeros((T, I), dtype=np.float64)
    dt = np.zeros((T, I), dtype=np.int32)
    lc = np.full((T, I), -1, dtype=np.int32)
    rc = np.full((T, I), -1, dtype=np.int32)
    lv = np.zeros((T, L), dtype=np.float64)
    nl = np.ones(T, dtype=np.int32)
    co = np.zeros((T, I), dtype=np.int32)
    cw_n = np.zeros((T, I), dtype=np.int32)
    cat_words: List[int] = []
    max_depth = 1
    for k, tree in enumerate(trees):
        ni = tree.num_leaves - 1
        nl[k] = tree.num_leaves
        if ni > 0:
            sf[k, :ni] = tree.split_feature[:ni]
            th[k, :ni] = tree.threshold[:ni]
            dt[k, :ni] = tree.decision_type[:ni].astype(np.int32) & 0xFF
            lc[k, :ni] = tree.left_child[:ni]
            rc[k, :ni] = tree.right_child[:ni]
            max_depth = max(max_depth, tree.max_depth)
            for node in range(ni):
                if dt[k, node] & 1:  # categorical
                    cat_idx = int(tree.threshold[node])
                    lo, hi = tree.cat_boundaries[cat_idx], tree.cat_boundaries[cat_idx + 1]
                    co[k, node] = len(cat_words)
                    cw_n[k, node] = hi - lo
                    cat_words.extend(tree.cat_threshold[lo:hi])
        lv[k, : tree.num_leaves] = tree.leaf_value[: tree.num_leaves]
    any_linear = any(t.is_linear for t in trees)
    lin_const = lin_feat = lin_coeff = None
    if any_linear:
        K = max((len(t.leaf_features[i]) for t in trees if t.is_linear
                 for i in range(t.num_leaves)), default=0)
        lin_const = lv.copy()  # non-linear trees fall through to leaf_value
        lin_feat = np.full((T, L, K), -1, dtype=np.int32)
        lin_coeff = np.zeros((T, L, K), dtype=np.float64)
        for k, tree in enumerate(trees):
            if not tree.is_linear or tree.leaf_const is None:
                continue
            lin_const[k, : tree.num_leaves] = tree.leaf_const[: tree.num_leaves]
            for i in range(tree.num_leaves):
                nf = len(tree.leaf_features[i])
                if nf:
                    lin_feat[k, i, :nf] = tree.leaf_features[i]
                    lin_coeff[k, i, :nf] = tree.leaf_coeff[i]
    if not cat_words:
        cat_words = [0]
    # float64 thresholds only take effect with jax x64 enabled; otherwise
    # jnp.asarray would silently round-to-nearest down to f32, so route through
    # the decision-preserving round-toward--inf downcast instead.
    f64_effective = dtype == jnp.float64 and jax.config.jax_enable_x64
    if not f64_effective:
        # Round thresholds toward -inf when downcasting: for any float32 x,
        # (x <= t64) == (x <= rounddown32(t64)), so device decisions over
        # float32 inputs exactly match the float64 reference semantics.
        th32 = th.astype(np.float32)
        over = th32.astype(np.float64) > th
        th32[over] = np.nextafter(th32[over], -np.inf)
        th = th32
    return PackedEnsemble(
        split_feature=jnp.asarray(sf, dtype=jnp.int32),
        threshold=jnp.asarray(th, dtype=jnp.float64 if f64_effective else jnp.float32),
        decision_type=jnp.asarray(dt, dtype=jnp.int32),
        left_child=jnp.asarray(lc, dtype=jnp.int32),
        right_child=jnp.asarray(rc, dtype=jnp.int32),
        leaf_value=jnp.asarray(lv, dtype=dtype),
        cat_words=jnp.asarray(np.array(cat_words, dtype=np.uint32),
                              dtype=jnp.uint32),
        cat_offset=jnp.asarray(co, dtype=jnp.int32),
        cat_n_words=jnp.asarray(cw_n, dtype=jnp.int32),
        num_leaves=jnp.asarray(nl, dtype=jnp.int32),
        max_depth=max(int(max_depth), fixed_depth),
        num_trees=len(trees),
        linear=any_linear,
        lin_const=jnp.asarray(lin_const, dtype=dtype) if any_linear else None,
        lin_feat=jnp.asarray(lin_feat, dtype=jnp.int32) if any_linear else None,
        lin_coeff=jnp.asarray(lin_coeff, dtype=dtype) if any_linear else None,
    )


def _tree_leaf_index(packed: PackedEnsemble, tree_idx, X: jax.Array, max_depth: int):
    """Leaf index [N] for one tree over row-major X [N, F]."""
    sf = packed.split_feature[tree_idx]
    th = packed.threshold[tree_idx]
    dt = packed.decision_type[tree_idx]
    lc = packed.left_child[tree_idx]
    rc = packed.right_child[tree_idx]
    co = packed.cat_offset[tree_idx]
    cn = packed.cat_n_words[tree_idx]
    n = X.shape[0]
    single_leaf = packed.num_leaves[tree_idx] <= 1

    def body(_, node):
        active = node >= 0
        nd = jnp.maximum(node, 0)
        feat = sf[nd]
        fval = jnp.take_along_axis(X, feat[:, None], axis=1)[:, 0]
        d = dt[nd]
        is_cat = (d & 1) > 0
        default_left = (d & 2) > 0
        missing_type = (d >> 2) & 3
        # --- numerical decision (tree.h:338-355)
        is_nan = jnp.isnan(fval)
        fval_num = jnp.where(is_nan & (missing_type != MISSING_NAN), 0.0, fval)
        is_missing = ((missing_type == MISSING_ZERO) & (jnp.abs(fval_num) <= _EPS)) | (
            (missing_type == MISSING_NAN) & jnp.isnan(fval_num))
        go_left_num = jnp.where(is_missing, default_left, fval_num <= th[nd])
        # --- categorical decision (tree.h:375-388)
        int_fval = jnp.where(is_nan, -1, fval.astype(jnp.int32))
        word_idx = jnp.clip(int_fval, 0, None) // 32
        bit_idx = jnp.clip(int_fval, 0, None) % 32
        in_range = (int_fval >= 0) & (word_idx < cn[nd])
        word = packed.cat_words[jnp.clip(co[nd] + word_idx, 0, packed.cat_words.shape[0] - 1)]
        go_left_cat = in_range & (((word >> bit_idx.astype(jnp.uint32)) & 1) > 0)
        go_left = jnp.where(is_cat, go_left_cat, go_left_num)
        nxt = jnp.where(go_left, lc[nd], rc[nd])
        return jnp.where(active, nxt, node)

    node0 = jnp.zeros(n, dtype=jnp.int32)
    node = jax.lax.fori_loop(0, max_depth, body, node0)
    leaf = jnp.where(single_leaf, 0, ~node)
    return leaf


def predict_leaf_indices(packed: PackedEnsemble, X: jax.Array) -> jax.Array:
    """[N, T] leaf index per row per tree."""
    T = packed.num_trees
    leaf_fn = jax.vmap(lambda k: _tree_leaf_index(packed, k, X, packed.max_depth))
    return leaf_fn(jnp.arange(T, dtype=jnp.int32)).T


def predict_raw(packed: PackedEnsemble, X: jax.Array, num_tree_per_iteration: int = 1) -> jax.Array:
    """Raw scores [N, num_tree_per_iteration] summed over iterations."""
    T = packed.num_trees
    if T == 0:
        return jnp.zeros((X.shape[0], num_tree_per_iteration), dtype=X.dtype)

    def tree_score(k):
        leaf = _tree_leaf_index(packed, k, X, packed.max_depth)
        base = packed.leaf_value[k][leaf]
        if not packed.linear:
            return base
        # linear leaf output: const + coeffs . raw features, falling back to
        # the constant leaf value when any model feature is NaN/inf
        # (Tree::PredictByMap linear path, src/io/tree.cpp)
        feats = packed.lin_feat[k][leaf]  # [N, K]
        used = feats >= 0
        fv = jnp.take_along_axis(
            X, jnp.clip(feats, 0, X.shape[1] - 1), axis=1)
        bad = (used & ~jnp.isfinite(fv)).any(axis=1)
        fv = jnp.where(used, fv, 0.0)
        lin = packed.lin_const[k][leaf] + jnp.where(
            used, packed.lin_coeff[k][leaf] * fv, 0.0).sum(axis=1)
        return jnp.where(bad, base, lin)

    scores = jax.vmap(tree_score)(jnp.arange(T, dtype=jnp.int32))  # [T, N]
    scores = scores.reshape(T // num_tree_per_iteration, num_tree_per_iteration, X.shape[0])
    return scores.sum(axis=0).T  # [N, C]


def predict_raw_early_stop(packed: PackedEnsemble, X: jax.Array,
                           num_tree_per_iteration: int, round_period: int,
                           margin_threshold: float) -> np.ndarray:
    """Raw scores with prediction early stopping
    (src/boosting/prediction_early_stop.cpp): every `round_period`
    iterations, rows whose margin — |score| for binary, top-2 class gap for
    multiclass — exceeds `margin_threshold` stop traversing further trees.

    TPU formulation: the reference's per-row sequential check becomes
    host-chunked batches — still-active rows are compacted (power-of-two
    padded so jit caches stay bounded) and only they evaluate the next tree
    block. Batch workloads with confident rows skip most of the ensemble.
    """
    from .partition import bucket_size

    C = num_tree_per_iteration
    T = packed.num_trees
    N = X.shape[0]
    out = np.zeros((N, C), dtype=np.float64)
    active = np.ones(N, dtype=bool)
    block = max(round_period, 1) * C
    for start in range(0, T, block):
        idx = np.nonzero(active)[0]
        if idx.size == 0:
            break
        pad = bucket_size(idx.size, 256)
        idx_pad = np.zeros(pad, dtype=np.int64)
        idx_pad[: idx.size] = idx
        # graftlint: disable=implicit-dtype -- X keeps its caller dtype (f32 or f64)
        Xa = jnp.asarray(X)[jnp.asarray(idx_pad, dtype=jnp.int32)]
        sl = packed.tree_slice(start, min(start + block, T))
        delta = np.asarray(predict_raw(sl, Xa, C))[: idx.size]
        out[idx] += delta
        scores = out[idx]
        if C == 1:
            # binary margin is 2*|pred| (prediction_early_stop.cpp:65)
            stop = 2.0 * np.abs(scores[:, 0]) > margin_threshold
        else:
            top2 = np.partition(scores, -2, axis=1)[:, -2:]
            stop = (top2[:, 1] - top2[:, 0]) > margin_threshold
        active[idx[stop]] = False
    return out
