"""Vectorized tree-ensemble inference on TPU.

TPU-native replacement for the reference's per-row recursive traversal
(Tree::Predict / NumericalDecision, include/LightGBM/tree.h:338-420, and
GBDT::PredictRaw, src/boosting/gbdt_prediction.cpp:15-56). All trees are
packed into padded [T, nodes] SoA tensors and traversed with ONE
level-synchronous gather loop over the whole forest: every (row, tree)
pair advances one level per step, rows that reached a leaf (negative node
id) freeze, and each level issues a single X gather for all T trees (the
per-tree formulation would issue T). Scores accumulate in-register — the
[T, N] per-tree score matrix is never materialized.

Serving-path machinery on top of the traversal:

  * `PredictorCache` — packs the ensemble once per (model version, tree
    slice, dtype) and keeps it device-resident across Booster.predict
    calls; training/refit/rollback/model-load invalidate it.
  * `predict_raw_streamed` — power-of-two row chunks with
    copy_to_host_async double buffering for large N.
  * `predict_raw_early_stop` — device-resident: scores and the active-row
    mask stay on device; the only per-block host sync is one scalar.
  * optional Pallas row-tile traversal behind LGBM_TPU_PREDICT_PALLAS=1
    (ops/predict_pallas.py, interpret-tested like hist_pallas.py).
"""
from __future__ import annotations

import os
import threading
from collections import OrderedDict, deque
from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import perfmodel, telemetry
from ..common import MISSING_NAN, MISSING_ZERO, K_ZERO_THRESHOLD
from ..models.tree import Tree
from ..utils.log import Log
from ..utils.timer import global_timer

_EPS = K_ZERO_THRESHOLD


@dataclass
class PackedEnsemble:
    """Device-resident padded arrays for a list of trees.

    Shapes: T = number of trees, I = max internal nodes, L = max leaves,
    W = total categorical bitset words (>=1).
    """

    split_feature: jax.Array  # [T, I] int32
    threshold: jax.Array  # [T, I] float
    decision_type: jax.Array  # [T, I] int32
    left_child: jax.Array  # [T, I] int32
    right_child: jax.Array  # [T, I] int32
    leaf_value: jax.Array  # [T, L] float
    cat_words: jax.Array  # [W] uint32 bitset words (real-value space)
    cat_offset: jax.Array  # [T, I] int32 word offset for categorical nodes
    cat_n_words: jax.Array  # [T, I] int32
    num_leaves: jax.Array  # [T] int32
    max_depth: int
    num_trees: int
    # linear-tree per-leaf models (tree.h leaf_const_/leaf_coeff_/leaf_features_)
    linear: bool = False  # static: gates the linear output path
    lin_const: Optional[jax.Array] = None  # [T, L] (leaf_value for non-linear trees)
    lin_feat: Optional[jax.Array] = None  # [T, L, K] int32, -1 padding
    lin_coeff: Optional[jax.Array] = None  # [T, L, K]

    def tree_slice(self, start: int, end: int) -> "PackedEnsemble":
        return PackedEnsemble(
            split_feature=self.split_feature[start:end],
            threshold=self.threshold[start:end],
            decision_type=self.decision_type[start:end],
            left_child=self.left_child[start:end],
            right_child=self.right_child[start:end],
            leaf_value=self.leaf_value[start:end],
            cat_words=self.cat_words,
            cat_offset=self.cat_offset[start:end],
            cat_n_words=self.cat_n_words[start:end],
            num_leaves=self.num_leaves[start:end],
            max_depth=self.max_depth,
            num_trees=end - start,
            linear=self.linear,
            lin_const=self.lin_const[start:end] if self.linear else None,
            lin_feat=self.lin_feat[start:end] if self.linear else None,
            lin_coeff=self.lin_coeff[start:end] if self.linear else None,
        )


jax.tree_util.register_pytree_node(
    PackedEnsemble,
    lambda p: ((p.split_feature, p.threshold, p.decision_type, p.left_child,
                p.right_child, p.leaf_value, p.cat_words, p.cat_offset,
                p.cat_n_words, p.num_leaves, p.lin_const, p.lin_feat,
                p.lin_coeff), (p.max_depth, p.num_trees, p.linear)),
    lambda aux, ch: PackedEnsemble(
        *ch[:10], max_depth=aux[0], num_trees=aux[1], linear=aux[2],
        lin_const=ch[10], lin_feat=ch[11], lin_coeff=ch[12]),
)


def pack_ensemble(trees: Sequence[Tree], dtype=jnp.float32,
                  fixed_leaves: int = 0, fixed_depth: int = 0) -> PackedEnsemble:
    """Pack host Tree objects into padded device tensors.

    fixed_leaves / fixed_depth force the padded node count and traversal
    depth, keeping shapes stable across repeated packs (per-iteration
    validation scoring) so jit caches are reused.
    """
    with global_timer.scope("predict_pack"):
        T = max(len(trees), 1)
        I = max(max((t.num_leaves - 1 for t in trees), default=1), 1,
                fixed_leaves - 1)
        L = max(max((t.num_leaves for t in trees), default=1), 1, fixed_leaves)
        sf = np.zeros((T, I), dtype=np.int32)
        th = np.zeros((T, I), dtype=np.float64)
        dt = np.zeros((T, I), dtype=np.int32)
        lc = np.full((T, I), -1, dtype=np.int32)
        rc = np.full((T, I), -1, dtype=np.int32)
        lv = np.zeros((T, L), dtype=np.float64)
        nl = np.ones(T, dtype=np.int32)
        co = np.zeros((T, I), dtype=np.int32)
        cw_n = np.zeros((T, I), dtype=np.int32)
        cat_words: List[int] = []
        max_depth = 1
        for k, tree in enumerate(trees):
            ni = tree.num_leaves - 1
            nl[k] = tree.num_leaves
            if ni > 0:
                sf[k, :ni] = tree.split_feature[:ni]
                th[k, :ni] = tree.threshold[:ni]
                dt[k, :ni] = tree.decision_type[:ni].astype(np.int32) & 0xFF
                lc[k, :ni] = tree.left_child[:ni]
                rc[k, :ni] = tree.right_child[:ni]
                max_depth = max(max_depth, tree.max_depth)
                for node in range(ni):
                    if dt[k, node] & 1:  # categorical
                        cat_idx = int(tree.threshold[node])
                        lo, hi = tree.cat_boundaries[cat_idx], tree.cat_boundaries[cat_idx + 1]
                        co[k, node] = len(cat_words)
                        cw_n[k, node] = hi - lo
                        cat_words.extend(tree.cat_threshold[lo:hi])
            lv[k, : tree.num_leaves] = tree.leaf_value[: tree.num_leaves]
        any_linear = any(t.is_linear for t in trees)
        lin_const = lin_feat = lin_coeff = None
        if any_linear:
            K = max((len(t.leaf_features[i]) for t in trees if t.is_linear
                     for i in range(t.num_leaves)), default=0)
            lin_const = lv.copy()  # non-linear trees fall through to leaf_value
            lin_feat = np.full((T, L, K), -1, dtype=np.int32)
            lin_coeff = np.zeros((T, L, K), dtype=np.float64)
            for k, tree in enumerate(trees):
                if not tree.is_linear or tree.leaf_const is None:
                    continue
                lin_const[k, : tree.num_leaves] = tree.leaf_const[: tree.num_leaves]
                for i in range(tree.num_leaves):
                    nf = len(tree.leaf_features[i])
                    if nf:
                        lin_feat[k, i, :nf] = tree.leaf_features[i]
                        lin_coeff[k, i, :nf] = tree.leaf_coeff[i]
        if not cat_words:
            cat_words = [0]
        # float64 thresholds only take effect with jax x64 enabled; otherwise
        # jnp.asarray would silently round-to-nearest down to f32, so route through
        # the decision-preserving round-toward--inf downcast instead.
        f64_effective = dtype == jnp.float64 and jax.config.jax_enable_x64
        if not f64_effective:
            # Round thresholds toward -inf when downcasting: for any float32 x,
            # (x <= t64) == (x <= rounddown32(t64)), so device decisions over
            # float32 inputs exactly match the float64 reference semantics.
            th32 = th.astype(np.float32)
            over = th32.astype(np.float64) > th
            th32[over] = np.nextafter(th32[over], -np.inf)
            th = th32
        return PackedEnsemble(
            split_feature=jnp.asarray(sf, dtype=jnp.int32),
            threshold=jnp.asarray(th, dtype=jnp.float64 if f64_effective else jnp.float32),
            decision_type=jnp.asarray(dt, dtype=jnp.int32),
            left_child=jnp.asarray(lc, dtype=jnp.int32),
            right_child=jnp.asarray(rc, dtype=jnp.int32),
            leaf_value=jnp.asarray(lv, dtype=dtype),
            cat_words=jnp.asarray(np.array(cat_words, dtype=np.uint32),
                                  dtype=jnp.uint32),
            cat_offset=jnp.asarray(co, dtype=jnp.int32),
            cat_n_words=jnp.asarray(cw_n, dtype=jnp.int32),
            num_leaves=jnp.asarray(nl, dtype=jnp.int32),
            max_depth=max(int(max_depth), fixed_depth),
            num_trees=len(trees),
            linear=any_linear,
            lin_const=jnp.asarray(lin_const, dtype=dtype) if any_linear else None,
            lin_feat=jnp.asarray(lin_feat, dtype=jnp.int32) if any_linear else None,
            lin_coeff=jnp.asarray(lin_coeff, dtype=dtype) if any_linear else None,
        )


def predict_dtype(X):
    """Device dtype for a predict input: f64 inputs keep f64 when jax x64
    is enabled (models whose thresholds need the full mantissa); everything
    else runs f32 — safe because pack_ensemble's round-toward--inf
    threshold downcast keeps f32 decisions identical to the f64 reference."""
    if getattr(X, "dtype", None) == np.float64 and jax.config.jax_enable_x64:
        return jnp.float64
    return jnp.float32


# --------------------------------------------------------------- traversal


def forest_level_step(X: jax.Array, node: jax.Array, sf: jax.Array,
                      th: jax.Array, dt: jax.Array, lc: jax.Array,
                      rc: jax.Array, co: jax.Array, cn: jax.Array,
                      cat_words: jax.Array) -> jax.Array:
    """Advance every (row, tree) pair one level: node [N, T] -> [N, T].

    Node attributes for ALL T trees' current nodes gather from the
    flattened [T*I] tables in one shot, and the feature values for the
    whole forest come from ONE take_along_axis over X — the per-tree
    formulation issued T X-gathers per level. Shared verbatim by the XLA
    path and the Pallas row-tile kernel (ops/predict_pallas.py)."""
    I = sf.shape[1]
    T = sf.shape[0]
    tree_base = jnp.arange(T, dtype=jnp.int32)[None, :] * I
    active = node >= 0
    nd = tree_base + jnp.maximum(node, 0)  # flat [N, T] into [T*I] tables
    feat = sf.reshape(-1)[nd]
    d = dt.reshape(-1)[nd]
    fval = jnp.take_along_axis(X, feat, axis=1)  # ONE X gather per level
    is_cat = (d & 1) > 0
    default_left = (d & 2) > 0
    missing_type = (d >> 2) & 3
    # --- numerical decision (tree.h:338-355)
    is_nan = jnp.isnan(fval)
    fval_num = jnp.where(is_nan & (missing_type != MISSING_NAN), 0.0, fval)
    is_missing = ((missing_type == MISSING_ZERO) & (jnp.abs(fval_num) <= _EPS)) | (
        (missing_type == MISSING_NAN) & jnp.isnan(fval_num))
    go_left_num = jnp.where(is_missing, default_left,
                            fval_num <= th.reshape(-1)[nd])
    # --- categorical decision (tree.h:375-388)
    int_fval = jnp.where(is_nan, -1, fval.astype(jnp.int32))
    word_idx = jnp.clip(int_fval, 0, None) // 32
    bit_idx = jnp.clip(int_fval, 0, None) % 32
    in_range = (int_fval >= 0) & (word_idx < cn.reshape(-1)[nd])
    word = cat_words[jnp.clip(co.reshape(-1)[nd] + word_idx, 0,
                              cat_words.shape[0] - 1)]
    go_left_cat = in_range & (((word >> bit_idx.astype(jnp.uint32)) & 1) > 0)
    go_left = jnp.where(is_cat, go_left_cat, go_left_num)
    nxt = jnp.where(go_left, lc.reshape(-1)[nd], rc.reshape(-1)[nd])
    return jnp.where(active, nxt, node)


def _traverse_leaves(packed: PackedEnsemble, X: jax.Array) -> jax.Array:
    """[N, T] leaf index per row per tree, level-synchronous over the
    whole forest."""
    n = X.shape[0]
    T = packed.split_feature.shape[0]
    node0 = jnp.zeros((n, T), dtype=jnp.int32)

    def body(_, node):
        return forest_level_step(
            X, node, packed.split_feature, packed.threshold,
            packed.decision_type, packed.left_child, packed.right_child,
            packed.cat_offset, packed.cat_n_words, packed.cat_words)

    node = jax.lax.fori_loop(0, packed.max_depth, body, node0)
    # a leaf id is the bitwise complement of the (negative) frozen node;
    # single-leaf (constant) trees sit at leaf 0
    return jnp.where(packed.num_leaves[None, :] <= 1, 0, ~node)


def _leaf_scores(packed: PackedEnsemble, X: jax.Array,
                 leaf: jax.Array) -> jax.Array:
    """Per-(row, tree) scores [N, T] from leaf assignments. Linear-tree
    ensembles evaluate const + coeffs . raw features, falling back to the
    constant leaf value when any model feature is NaN/inf
    (Tree::PredictByMap linear path, src/io/tree.cpp) — vectorized across
    trees with one [N, T*K] X gather."""
    T, L = packed.leaf_value.shape
    flat = jnp.arange(T, dtype=jnp.int32)[None, :] * L + leaf  # [N, T]
    base = packed.leaf_value.reshape(-1)[flat]
    if not packed.linear:
        return base
    n = X.shape[0]
    K = packed.lin_feat.shape[2]
    feats = packed.lin_feat.reshape(T * L, K)[flat]  # [N, T, K]
    used = feats >= 0
    fv = jnp.take_along_axis(
        X, jnp.clip(feats, 0, X.shape[1] - 1).reshape(n, T * K),
        axis=1).reshape(n, T, K)
    bad = (used & ~jnp.isfinite(fv)).any(axis=2)
    fv = jnp.where(used, fv, 0.0)
    lin = packed.lin_const.reshape(-1)[flat] + jnp.where(
        used, packed.lin_coeff.reshape(T * L, K)[flat] * fv, 0.0).sum(axis=2)
    return jnp.where(bad, base, lin)


@partial(jax.jit, static_argnames=("num_tree_per_iteration",))
def _predict_raw_fused(packed: PackedEnsemble, X: jax.Array,
                       num_tree_per_iteration: int) -> jax.Array:
    """Fused traverse + score + per-class accumulate: [N, C] without ever
    materializing the [T, N] per-tree score matrix."""
    leaf = _traverse_leaves(packed, X)
    vals = _leaf_scores(packed, X, leaf)
    n, T = vals.shape
    return vals.reshape(n, T // num_tree_per_iteration,
                        num_tree_per_iteration).sum(axis=1)


_leaf_indices_fused = jax.jit(_traverse_leaves)


def predict_leaf_indices(packed: PackedEnsemble, X: jax.Array) -> jax.Array:
    """[N, T] leaf index per row per tree."""
    if packed.num_trees == 0:
        return jnp.zeros((X.shape[0], 0), dtype=jnp.int32)
    with global_timer.scope("predict_traverse"):
        return _leaf_indices_fused(packed, X)


def validate_tree_count(packed: PackedEnsemble,
                        num_tree_per_iteration: int) -> None:
    """The packed tree count must cover whole iterations: a ragged slice
    would mis-assign trees to classes in the per-class accumulate."""
    if num_tree_per_iteration > 0 \
            and packed.num_trees % num_tree_per_iteration != 0:
        Log.fatal(
            "Cannot predict with %d trees grouped %d per iteration: the "
            "slice does not cover whole iterations (check num_iteration / "
            "start_iteration against the model's tree count)",
            packed.num_trees, num_tree_per_iteration)


def predict_pallas_enabled() -> bool:
    return os.environ.get("LGBM_TPU_PREDICT_PALLAS", "").lower() in (
        "1", "true", "on")


def predict_raw(packed: PackedEnsemble, X: jax.Array,
                num_tree_per_iteration: int = 1) -> jax.Array:
    """Raw scores [N, num_tree_per_iteration] summed over iterations."""
    T = packed.num_trees
    if T == 0:
        return jnp.zeros((X.shape[0], num_tree_per_iteration), dtype=X.dtype)
    validate_tree_count(packed, num_tree_per_iteration)
    if predict_pallas_enabled() and not packed.linear:
        from .predict_pallas import pallas_predict_raw

        # Mosaic compiles on TPU only; elsewhere (CPU tests, GPU) the
        # opt-in still works end to end through interpret mode
        interp = jax.default_backend() != "tpu"
        with global_timer.scope("predict_traverse"):
            return pallas_predict_raw(packed, X, num_tree_per_iteration,
                                      interpret=interp)
    with global_timer.scope("predict_traverse"):
        if packed.linear:
            # under jit XLA contracts the linear mul+sum into fmas, a 1-ulp
            # drift vs the eager reference arithmetic; keep the score math
            # eager (the traversal is integer-only and stays jitted)
            leaf = _leaf_indices_fused(packed, X)
            vals = _leaf_scores(packed, X, leaf)
            n, T = vals.shape
            return vals.reshape(n, T // num_tree_per_iteration,
                                num_tree_per_iteration).sum(axis=1)
        if telemetry.enabled():
            # one-time dispatch capture for perfmodel's AOT cost_analysis
            perfmodel.note_dispatch("predict", _predict_raw_fused,
                                    packed, X, num_tree_per_iteration)
        return _predict_raw_fused(packed, X, num_tree_per_iteration)


# --------------------------------------------------------------------- aot
#
# Ahead-of-time compiled predict executables for serving warm start.
# A warm writer lowers + compiles the fused traversal for each micro-batch
# bucket shape, serializes the executables (jax.experimental.
# serialize_executable), and the bundle persists next to the model
# checkpoint (checkpoint.write_aot_sidecar). A cold replica deserializes
# in milliseconds instead of paying one XLA compile per bucket before its
# first answer. Safety: an executable is specialized on SHAPES only — the
# packed ensemble is a runtime argument — so a loaded executable can never
# produce a wrong answer for a key-matched call; staleness is an
# ENVIRONMENT property (jax/jaxlib build, backend, device kind), checked
# against the bundle's fingerprint at load, and any mismatch falls back
# to a fresh compile with a warning.

AOT_FORMAT_VERSION = 1


def aot_environment() -> dict:
    """The environment fingerprint an AOT bundle is valid for. XLA
    executables are build- and target-specific: every field here must
    match between writer and loader or deserialization is refused."""
    import jaxlib

    try:
        dev = jax.devices()[0]
        kind, platform = str(dev.device_kind), str(dev.platform)
    except Exception:  # noqa: BLE001 - no backend: still fingerprintable
        kind, platform = "", ""
    return {
        "format": AOT_FORMAT_VERSION,
        "jax": jax.__version__,
        "jaxlib": getattr(jaxlib.version, "__version__", ""),
        "backend": jax.default_backend(),
        "platform": platform,
        "device_kind": kind,
    }


def aot_call_key(packed: PackedEnsemble, n_rows: int, n_cols: int,
                 num_tree_per_iteration: int, x_dtype) -> tuple:
    """Exact dispatch key: every packed leaf's (shape, dtype) plus the
    input block shape/dtype and the static tree grouping. Matching this
    key guarantees the executable's input avals match the call."""
    leaves = jax.tree_util.tree_leaves(packed)
    return (tuple((tuple(int(s) for s in leaf.shape), str(leaf.dtype))
                  for leaf in leaves),
            (int(n_rows), int(n_cols)), np.dtype(x_dtype).name,
            int(num_tree_per_iteration))


def aot_compile(packed: PackedEnsemble, n_rows: int, n_cols: int,
                num_tree_per_iteration: int, x_dtype=np.float32):
    """Lower + compile the fused traversal for one bucket shape without
    touching (or populating) the jit dispatch cache."""
    xs = jax.ShapeDtypeStruct((int(n_rows), int(n_cols)),
                              np.dtype(x_dtype))
    return _predict_raw_fused.lower(
        packed, xs, num_tree_per_iteration=num_tree_per_iteration).compile()


def aot_serialize_bundle(packed: PackedEnsemble, n_cols: int,
                         num_tree_per_iteration: int,
                         buckets: Sequence[int], x_dtype=np.float32,
                         model_sha256: str = "") -> bytes:
    """Compile and serialize one executable per bucket row count into a
    self-describing bundle (environment fingerprint + model hash +
    keyed payloads). Linear packs are refused: their score math runs
    eagerly for bit-stability (see predict_raw), so there is no single
    executable to persist."""
    import pickle

    from jax.experimental.serialize_executable import serialize

    if packed.linear:
        raise ValueError("AOT bundles cover the fused traversal only; "
                         "linear-tree ensembles keep eager score math")
    entries = []
    with global_timer.scope("predict_aot_export"):
        for rows in buckets:
            compiled = aot_compile(packed, rows, n_cols,
                                   num_tree_per_iteration, x_dtype)
            payload, in_tree, out_tree = serialize(compiled)
            entries.append({
                "key": aot_call_key(packed, rows, n_cols,
                                    num_tree_per_iteration, x_dtype),
                "rows": int(rows),
                "payload": payload,
                "in_tree": in_tree,
                "out_tree": out_tree,
            })
    return pickle.dumps({
        "environment": aot_environment(),
        "model_sha256": model_sha256,
        "entries": entries,
    }, protocol=pickle.HIGHEST_PROTOCOL)


def aot_load_bundle(blob: bytes, model_sha256: Optional[str] = None):
    """Deserialize a bundle into {call_key: loaded_executable}.

    Returns (executables, problems). A non-empty `problems` list means the
    bundle was REFUSED (environment fingerprint mismatch, wrong model
    hash, damaged payload) and the mapping is empty — the caller logs the
    reasons and falls back to fresh compilation; a stale bundle can cost a
    compile, never a wrong answer."""
    import pickle

    from jax.experimental.serialize_executable import deserialize_and_load

    problems: List[str] = []
    try:
        obj = pickle.loads(blob)
    except Exception as exc:  # noqa: BLE001 - any damage -> refuse
        return {}, [f"undecodable AOT bundle: {exc!r}"]
    env, want = aot_environment(), obj.get("environment")
    if want != env:
        diff = sorted(k for k in set(env) | set(want or {})
                      if (want or {}).get(k) != env.get(k))
        problems.append(
            "environment fingerprint mismatch on "
            + ", ".join(f"{k}: bundle {((want or {}).get(k))!r} != "
                        f"here {env.get(k)!r}" for k in diff))
    if model_sha256 and obj.get("model_sha256") \
            and obj["model_sha256"] != model_sha256:
        problems.append(
            f"bundle was exported for model sha "
            f"{str(obj['model_sha256'])[:12]}.., loading {model_sha256[:12]}..")
    if problems:
        return {}, problems
    out = {}
    with global_timer.scope("predict_aot_load"):
        for ent in obj.get("entries", ()):
            try:
                out[ent["key"]] = deserialize_and_load(
                    ent["payload"], ent["in_tree"], ent["out_tree"])
            except Exception as exc:  # noqa: BLE001 - refuse the bundle
                return {}, [f"executable for {ent.get('rows')} rows failed "
                            f"to deserialize: {exc!r}"]
    return out, []


# ------------------------------------------------------------------- cache


class PredictorCache:
    """Device-resident PackedEnsemble cache for the serving path.

    Repeated Booster.predict calls reuse the packed arrays already on
    device instead of re-packing and re-uploading the ensemble per call.
    Keys are (model version, tree slice, dtype); any mutation of the model
    list — training an iteration, refit, rollback, loading a model — must
    call invalidate(), which bumps the version and drops every entry. A
    small LRU bound keeps sliced predicts (num_iteration / staged CV
    evaluation) from pinning unbounded HBM.

    Thread safety: the serving layer hammers `get` from batcher threads
    while hot-swap / training calls `invalidate` — both mutate the
    OrderedDict (move_to_end, insert, popitem), so every access holds one
    lock. The version snapshot is taken INSIDE the lock: a get racing an
    invalidate either sees the old version's entry (still bit-correct for
    the tree list it was packed from) or packs fresh under the new version,
    never a half-evicted entry. Packing on a miss happens under the lock
    too — concurrent misses for one key must not upload the ensemble
    twice."""

    def __init__(self, capacity: int = 4) -> None:
        self.capacity = capacity
        self._version = 0
        self._entries: "OrderedDict[tuple, PackedEnsemble]" = OrderedDict()
        # AOT warm-start executables (aot_load_bundle), keyed by the exact
        # aot_call_key. Shape-specialized, value-free: any key-matched call
        # is correct by construction. Dropped on invalidate with the packs
        # — a mutated model changes pack shapes, so stale keys would only
        # miss, but holding dead executables pins memory for nothing.
        self._aot: dict = {}
        self._lock = threading.Lock()

    def invalidate(self) -> None:
        with self._lock:
            self._version += 1
            self._entries.clear()
            self._aot.clear()

    # ------------------------------------------------------------- aot

    def install_aot(self, executables: dict) -> int:
        """Install {aot_call_key: loaded_executable} (serving warm start).
        Returns the number now installed."""
        with self._lock:
            self._aot.update(executables)
            return len(self._aot)

    def aot_get(self, packed: PackedEnsemble, n_rows: int, n_cols: int,
                num_tree_per_iteration: int, x_dtype):
        """The installed executable exactly matching this dispatch, or
        None (caller falls through to the jit path)."""
        if not self._aot:
            return None
        key = aot_call_key(packed, n_rows, n_cols,
                           num_tree_per_iteration, x_dtype)
        with self._lock:
            fn = self._aot.get(key)
        if fn is not None:
            global_timer.add_count("predict_aot_hits", 1)
        return fn

    def aot_rows(self) -> List[int]:
        """Row counts (bucket sizes) with an installed executable."""
        with self._lock:
            return sorted({key[1][0] for key in self._aot})

    def get(self, trees: Sequence[Tree], start: int, end: int,
            dtype=jnp.float32) -> PackedEnsemble:
        with self._lock:
            key = (self._version, start, end, np.dtype(dtype).name)
            hit = self._entries.get(key)
            if hit is not None:
                self._entries.move_to_end(key)
                global_timer.add_count("predict_pack_hits", 1)
                return hit
            packed = pack_ensemble(trees[start:end], dtype=dtype)
            self._entries[key] = packed
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
            return packed


# --------------------------------------------------------------- streaming

_CHUNK_ENV = "LGBM_TPU_PREDICT_CHUNK"
_AUTO_CHUNK_ROWS = 1 << 18       # 256k-row device chunks
_AUTO_STREAM_MIN_ROWS = 1 << 19  # stream once the batch is >= two chunks


def stream_chunk_rows(n_rows: int, requested: Optional[int] = None) -> int:
    """Row-chunk size for streamed predict; 0 means run single-shot.

    `requested` (the pred_chunk_rows param) wins; then the
    LGBM_TPU_PREDICT_CHUNK env var; then auto (256k chunks once the batch
    is at least two of them). Chunks round up to a power of two
    (ops/partition.bucket_size) so the jit cache holds one traversal per
    bucket, not one per batch size."""
    from .partition import bucket_size

    chunk = requested
    if chunk is None:
        env = os.environ.get(_CHUNK_ENV, "")
        if env:
            try:
                chunk = int(env)
            except ValueError:
                chunk = None
        if chunk is None:
            chunk = _AUTO_CHUNK_ROWS if n_rows >= _AUTO_STREAM_MIN_ROWS else 0
    if chunk <= 0 or n_rows <= chunk:
        return 0
    return bucket_size(chunk, 256)


def predict_raw_streamed(packed: PackedEnsemble, X: np.ndarray,
                         num_tree_per_iteration: int, chunk: int,
                         dtype) -> np.ndarray:
    """Chunked double-buffered raw predict for large N, on host arrays.

    Each chunk uploads, traverses, and starts its device->host copy
    (copy_to_host_async) before the next chunk is touched, so H2D,
    compute, and D2H overlap; the host blocks only when more than two
    results are in flight. The tail chunk pads to its own power-of-two
    bucket (bounded jit cache). Returns a host [N, C] array."""
    from .partition import bucket_size

    validate_tree_count(packed, num_tree_per_iteration)
    n = X.shape[0]
    n_chunks = -(-n // chunk)
    out_parts: List[Optional[np.ndarray]] = [None] * n_chunks
    inflight: deque = deque()
    with global_timer.scope("predict_stream"):
        for i in range(n_chunks):
            start = i * chunk
            stop = min(start + chunk, n)
            rows = stop - start
            xc = X[start:stop]
            pad = chunk if rows == chunk else bucket_size(rows, 256)
            if rows < pad:  # tail chunk: pad to its own bucket
                xc = np.concatenate(
                    [xc, np.zeros((pad - rows, X.shape[1]), dtype=X.dtype)])
            xd = jnp.asarray(xc, dtype=dtype)
            yd = predict_raw(packed, xd, num_tree_per_iteration)
            yd.copy_to_host_async()
            if telemetry.enabled():
                telemetry.emit("predict_chunk", index=i, rows=rows, pad=pad)
            inflight.append((i, rows, yd))
            while len(inflight) > 2:
                j, r, y = inflight.popleft()
                out_parts[j] = np.asarray(y)[:r]
        while inflight:
            j, r, y = inflight.popleft()
            out_parts[j] = np.asarray(y)[:r]
        global_timer.add_count("predict_stream_chunks", n_chunks)
    return np.concatenate(out_parts, axis=0)


# -------------------------------------------------------------- early stop


@partial(jax.jit, static_argnames=("bucket",))
def _compact_active(active: jax.Array, bucket: int) -> jax.Array:
    """Indices of active rows first (stable argsort over the 2-way key —
    the ops/partition compaction idiom), truncated to `bucket`."""
    key = jnp.where(active, 0, 1).astype(jnp.int32)
    return jnp.argsort(key).astype(jnp.int32)[:bucket]


@partial(jax.jit, static_argnames=("num_tree_per_iteration",))
def _early_stop_block(packed_sl: PackedEnsemble, X: jax.Array,
                      scores: jax.Array, active: jax.Array, idx: jax.Array,
                      cnt: jax.Array, margin: jax.Array,
                      num_tree_per_iteration: int):
    """One tree block of device-resident early stopping: gather the
    still-active rows, add the block's raw scores, and deactivate rows
    whose margin clears the threshold — all without leaving the device."""
    C = num_tree_per_iteration
    P = idx.shape[0]
    valid = jnp.arange(P, dtype=jnp.int32) < cnt  # rows past cnt are padding
    Xa = X[idx]
    leaf = _traverse_leaves(packed_sl, Xa)
    delta = _leaf_scores(packed_sl, Xa, leaf).reshape(P, -1, C).sum(axis=1)
    scores = scores.at[idx].add(
        jnp.where(valid[:, None], delta, jnp.zeros((), delta.dtype)))
    sc = scores[idx]
    if C == 1:
        # binary margin is 2*|pred| (prediction_early_stop.cpp:65)
        margin_val = 2.0 * jnp.abs(sc[:, 0])
    else:
        top2 = jax.lax.top_k(sc, 2)[0]
        margin_val = top2[:, 0] - top2[:, 1]
    stop = (margin_val > margin) & valid
    active = active.at[idx].set(active[idx] & ~stop)
    return scores, active


def predict_raw_early_stop(packed: PackedEnsemble, X: jax.Array,
                           num_tree_per_iteration: int, round_period: int,
                           margin_threshold: float) -> np.ndarray:
    """Raw scores with prediction early stopping
    (src/boosting/prediction_early_stop.cpp): every `round_period`
    iterations, rows whose margin — |score| for binary, top-2 class gap for
    multiclass — exceeds `margin_threshold` stop traversing further trees.

    Device-resident formulation: the score matrix and the active-row mask
    live on device; per block the still-active rows are compacted by a
    stable argsort (power-of-two padded so jit caches stay bounded) and
    only they evaluate the next tree block. The ONLY host sync per block
    is the active-count scalar that picks the bucket size — the previous
    implementation pulled the whole per-block delta matrix through
    np.asarray and recomputed the compaction with np.nonzero on host.
    """
    from .partition import bucket_size

    C = num_tree_per_iteration
    T = packed.num_trees
    validate_tree_count(packed, C)
    N = X.shape[0]
    # graftlint: disable=implicit-dtype -- X keeps its caller dtype (f32 or f64)
    X_dev = jnp.asarray(X)
    scores = jnp.zeros((N, C), dtype=packed.leaf_value.dtype)
    active = jnp.ones(N, dtype=jnp.bool_)
    block = max(round_period, 1) * C
    with global_timer.scope("predict_early_stop"):
        for start in range(0, T, block):
            # the one intended sync per block: a scalar count picks the
            # power-of-two bucket, keeping compiled shapes bounded
            cnt_dev = jnp.sum(active, dtype=jnp.int32)
            cnt = int(cnt_dev)
            if cnt == 0:
                break
            bucket = min(bucket_size(cnt, 256), N)
            idx = _compact_active(active, bucket)
            sl = packed.tree_slice(start, min(start + block, T))
            scores, active = _early_stop_block(
                sl, X_dev, scores, active, idx, cnt_dev, margin_threshold, C)
    return np.asarray(scores, dtype=np.float64)
