"""Pallas TPU row-tile kernel for ensemble inference.

The fused XLA traversal (ops/predict.py:_predict_raw_fused) already does
one X-gather per level, but XLA stages each level's [N, T] gather results
through HBM. This kernel pins a row tile of X plus the whole packed node
table in VMEM and runs ALL max_depth levels and the leaf-value gather for
that tile before touching HBM again — HBM traffic becomes the irreducible
read of X and node tables plus the [N, C] output write.

    grid (N / tile_rows,); per step:
        node[tile, T] level loop (forest_level_step, shared verbatim with
        the XLA path — bit-identical decisions by construction)
        out[tile, C] = leaf_value gather, per-class sum

Tables replicate into every grid step via constant index maps; the node
table for serving-size ensembles (T*I ints) is a few MB — comfortably
VMEM-resident next to a 512-row X tile. Linear-tree ensembles keep the
XLA path (the [N, T, K] coefficient gather does not tile this way).

Enabled by LGBM_TPU_PREDICT_PALLAS=1 (ops/predict.py:predict_raw);
correctness pinned by interpret-mode tests against the XLA path, like
hist_pallas.py.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .. import telemetry
from .predict import PackedEnsemble, forest_level_step

# kernel-compile classification for the recompile watcher's split counter
telemetry.register_kernel_fn("pallas_predict_raw")

PREDICT_TILE_ROWS = 512


def _make_kernel(num_tree_per_iteration: int, max_depth: int):
    def kernel(sf_ref, th_ref, dt_ref, lc_ref, rc_ref, co_ref, cn_ref,
               cw_ref, nl_ref, lv_ref, x_ref, out_ref):
        X = x_ref[...]
        sf = sf_ref[...]
        th = th_ref[...]
        dt = dt_ref[...]
        lc = lc_ref[...]
        rc = rc_ref[...]
        co = co_ref[...]
        cn = cn_ref[...]
        cw = cw_ref[...]
        nl = nl_ref[...]
        lv = lv_ref[...]
        rows = X.shape[0]
        T, L = lv.shape
        node0 = jnp.zeros((rows, T), dtype=jnp.int32)

        def body(_, node):
            return forest_level_step(X, node, sf, th, dt, lc, rc, co, cn, cw)

        node = jax.lax.fori_loop(0, max_depth, body, node0)
        leaf = jnp.where(nl[None, :] <= 1, 0, ~node)
        flat = jnp.arange(T, dtype=jnp.int32)[None, :] * L + leaf
        vals = lv.reshape(-1)[flat]
        out_ref[...] = vals.reshape(
            rows, T // num_tree_per_iteration, num_tree_per_iteration
        ).sum(axis=1)

    return kernel


def _replicated_spec(shape):
    """Full-array block replicated into every grid step."""
    return pl.BlockSpec(shape, lambda t: (0,) * len(shape))


@partial(jax.jit, static_argnames=("num_tree_per_iteration", "tile_rows",
                                   "interpret"))
def pallas_predict_raw(packed: PackedEnsemble, X: jax.Array,
                       num_tree_per_iteration: int,
                       tile_rows: int = PREDICT_TILE_ROWS,
                       interpret: bool = False) -> jax.Array:
    """Raw scores [N, num_tree_per_iteration] via the row-tile kernel."""
    n, F = X.shape
    C = num_tree_per_iteration
    n_tiles = max(-(-n // tile_rows), 1)
    n_pad = n_tiles * tile_rows
    if n_pad > n:
        X = jnp.concatenate(
            [X, jnp.zeros((n_pad - n, F), dtype=X.dtype)], axis=0)
    kernel = _make_kernel(C, packed.max_depth)
    out = pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        in_specs=[
            _replicated_spec(packed.split_feature.shape),
            _replicated_spec(packed.threshold.shape),
            _replicated_spec(packed.decision_type.shape),
            _replicated_spec(packed.left_child.shape),
            _replicated_spec(packed.right_child.shape),
            _replicated_spec(packed.cat_offset.shape),
            _replicated_spec(packed.cat_n_words.shape),
            _replicated_spec(packed.cat_words.shape),
            _replicated_spec(packed.num_leaves.shape),
            _replicated_spec(packed.leaf_value.shape),
            pl.BlockSpec((tile_rows, F), lambda t: (t, 0)),
        ],
        out_specs=pl.BlockSpec((tile_rows, C), lambda t: (t, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, C), packed.leaf_value.dtype),
        interpret=interpret,
    )(packed.split_feature, packed.threshold, packed.decision_type,
      packed.left_child, packed.right_child, packed.cat_offset,
      packed.cat_n_words, packed.cat_words, packed.num_leaves,
      packed.leaf_value, X)
    return out[:n]
