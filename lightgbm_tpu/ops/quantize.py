"""Gradient discretization for quantized-histogram training.

Counterpart of GradientDiscretizer
(src/treelearner/gradient_discretizer.{hpp,cpp}): gradients/hessians are
linearly quantized to small signed integers,

    grad_scale = max|g| / (num_grad_quant_bins / 2)
    hess_scale = max|h| / num_grad_quant_bins      (max|h| if constant hess)
    g_int = trunc(g / grad_scale +- r)   (r ~ U[0,1) stochastic rounding,
                                          0.5 for nearest rounding)

and histograms accumulate the integers exactly in int32 via the one-hot MXU
contraction (ops/histogram.py with an int8 compute dtype — int8 x int8 ->
int32 is MXU-native). The split scan rescales integer sums back to float.

TPU-first notes vs the reference: the int8/int16/int32 per-leaf histogram
bit-width machinery (gradient_discretizer.hpp:60-90, bin.h:63-81) exists to
save CPU cache; the TPU formulation always accumulates int32 (exact, no
overflow for any leaf below 2^23 rows per bin at 4-bit quantization) and
instead narrows the DISTRIBUTED reduction to int16 when the per-device shard
provably fits (parallel/learners.py), halving psum_scatter bytes — the
analog of the reference's int16 histogram reduction
(data_parallel_tree_learner.cpp:285-297).
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp


def int16_reduction_safe(row_count: int, num_grad_quant_bins: int) -> bool:
    """True when a quantized histogram bin over `row_count` rows provably
    fits int16, so the cross-device reduction can ship int16 instead of
    int32 (the reference's int16 histogram reduction,
    data_parallel_tree_learner.cpp:285-297). Conservative: assumes every
    row lands in one bin at the max quantized magnitude, with headroom
    under 2^15."""
    return row_count * num_grad_quant_bins < 32000


@partial(jax.jit, static_argnames=("num_bins", "stochastic"))
def discretize_gradients(grad: jax.Array, hess: jax.Array, key: jax.Array,
                         num_bins: int = 4, stochastic: bool = True
                         ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """[N] float grad/hess -> ([N] int8 g_int, [N] int8 h_int, g_scale, h_scale).

    GradientDiscretizer::DiscretizeGradients (gradient_discretizer.cpp:70-160).
    The hessian is quantized over [0, num_bins]; a constant-hessian objective
    (max == min) degenerates to h_int == 1 with hess_scale = max|h|, matching
    the reference's is_constant_hessian branch.
    """
    eps = jnp.float32(1e-35)
    max_g = jnp.maximum(jnp.max(jnp.abs(grad)), eps)
    max_h = jnp.maximum(jnp.max(jnp.abs(hess)), eps)
    min_h = jnp.min(hess)
    const_hess = (max_h - min_h) <= 1e-12 * max_h
    g_scale = max_g / (num_bins // 2)
    h_scale = jnp.where(const_hess, max_h, max_h / num_bins)
    inv_g = 1.0 / g_scale
    inv_h = 1.0 / h_scale
    if stochastic:
        kg, kh = jax.random.split(key)
        rg = jax.random.uniform(kg, grad.shape, dtype=jnp.float32)
        rh = jax.random.uniform(kh, hess.shape, dtype=jnp.float32)
    else:
        rg = rh = jnp.float32(0.5)
    g_int = jnp.trunc(
        jnp.where(grad >= 0, grad * inv_g + rg, grad * inv_g - rg)
    ).astype(jnp.int8)
    h_int = jnp.where(const_hess, jnp.int8(1),
                      jnp.trunc(hess * inv_h + rh).astype(jnp.int8))
    return g_int, h_int, g_scale, h_scale
