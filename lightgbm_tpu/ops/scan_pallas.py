"""Pallas TPU fused split-scan kernel: the per-feature gain search in one pass.

The XLA formulation in ops/split.py (per_feature_best) materializes every
stage of the search — cumsum over bins, two left/right aggregate lanes, the
masked gain surfaces, the lane-major argmax operand — as separate [K, F_pad,
Bmax{,3}] tensors through HBM. Per wave that is ~10 round trips of the
histogram working set for a computation whose arithmetic intensity is tiny;
BENCH_r05's attribution pins the wave loop's non-histogram remainder as the
single-chip frontier. This kernel fuses the whole pipeline over a feature
tile so every intermediate lives in VMEM:

    grid (F_pad / FT,); per step, for an [FT, B] feature slab:
        pull the missing bin out of the ordered scan        # VPU
        cumsum over bins -> left aggregates (both lanes)    # VPU scan
        right aggregates, validity masks, regularized gains # VPU
        lane-major argmax + masked-max stat extraction      # VPU reduce
        packed [FT, REC_PAD] split records                  # one HBM write

The per-tile scan + carry decomposition of arxiv 2505.15112 degenerates to
its single-tile case here on purpose: Bmax <= 256 always (max_bin caps at
255), so the whole bin axis rides the lane dimension of one block and the
tile-parallel axis is features. Keeping the bin axis unsplit is also what
makes bit-identity cheap: the in-kernel jnp.cumsum sees exactly the same
length-Bmax scan the XLA path runs, so interpret mode reproduces the XLA
records bit-for-bit (pinned by tests/test_scan_pallas.py). The two exact-
value extractions (missing bin, picked threshold stats) use masked-max
instead of gather — a max over {v, -inf, ...} returns v's bits unchanged,
while a masked sum would lose the sign of a -0.0 aggregate.

The identity contract is jit-vs-jit AT THE DISPATCH BOUNDARY. Embedded in
a larger jit (the device learner's fused tree growth), the XLA body is not
even stable against ITSELF: XLA fuses the gain arithmetic differently in
the big-jit context and drifts 1 ULP from its standalone compilation —
the standalone value being the one this kernel reproduces (the
`best_gain - gain_shift` cancellation then amplifies that one rounding to
a few ULP of the result). In practice that surfaces only as a tiny wobble
in the stored split_gain metadata between LGBM_TPU_SCAN_PALLAS on/off
end-to-end runs; decisions, thresholds, counts and leaf outputs stay
byte-equal (pinned by test_train_bit_identical_fused_vs_xla).

Scope: numeric/default-direction lanes only. Categorical and CTR lanes stay
on the XLA path behind the same find_best_split dispatch, as does any scan
with monotone constraints (the clamped-output gain variant). Used
automatically on TPU backends; LGBM_TPU_SCAN_PALLAS=0 restores the XLA scan
byte-for-byte, =1 forces the kernel (tests run it with
LGBM_TPU_PALLAS_INTERPRET=1 on CPU).
"""
from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .. import telemetry
from ..common import MISSING_NAN, MISSING_NONE

# classify this entry's jit cache misses as kernel compiles (telemetry's
# recompile watcher keeps them separate from XLA churn)
for _fn in ("fused_split_scan",):
    telemetry.register_kernel_fn(_fn)

SCAN_TILE_FEATURES = 8  # feature-tile sublane dim (Mosaic f32 tile is (8, 128))
REC_PAD = 128  # record columns padded to one lane tile; cols 14+ are zero
N_REC = 14  # == len(ops.split.SPLIT_FIELDS); pinned by test_scan_pallas
K_EPSILON = 1e-15  # == ops.split.K_EPSILON; pinned by test_scan_pallas

# meta_cols column layout (one row per feature, padded to REC_PAD lanes)
_MC_MISSING_POS = 0
_MC_HAS_MISSING = 1
_MC_NBINS = 2
_MC_GATE = 3  # numeric-lane feature gate: ~is_categorical & feature_mask
_MC_PENALTY = 4
_MC_PARAMS = 5  # l1, l2, min_data, min_hess, min_gain, max_delta
_MC_TOTALS = 11  # total_g, total_h, total_count
_MC_COLS = 14


def use_scan_pallas() -> bool:
    """Fused scan on TPU backends; LGBM_TPU_SCAN_PALLAS=0|xla and =1|pallas
    override. Resolved at trace time of the enclosing jit (find_best_split /
    grow_tree_on_device), so flip it before the first training call — tests
    that toggle mid-process clear the jit caches."""
    mode = os.environ.get("LGBM_TPU_SCAN_PALLAS", "auto").lower()
    if mode in ("0", "false", "off", "xla"):
        return False
    if mode in ("1", "true", "on", "pallas"):
        return True
    try:
        backend = jax.default_backend().lower()
        return "tpu" in backend or "axon" in backend
    except RuntimeError:
        return False


def interpret_mode() -> bool:
    """Interpret off-TPU (Mosaic only lowers on real hardware);
    LGBM_TPU_PALLAS_INTERPRET=1 forces it everywhere."""
    if os.environ.get("LGBM_TPU_PALLAS_INTERPRET", "").lower() in (
            "1", "true", "on"):
        return True
    try:
        return "tpu" not in jax.default_backend().lower()
    except RuntimeError:
        return True


# graftlint: disable=untimed-hot-func -- traced kernel body; the jitted call site owns the timer scope
def _make_scan_kernel(n_bins: int, feat_tile: int, barrier: bool):
    neg_inf = float("-inf")  # python float: weak-typed, not a captured array

    def fused_scan_kernel(hist_ref, meta_ref, valid_ref, out_ref):
        g = hist_ref[0]  # [FT, B] f32 grad sums
        h = hist_ref[1]
        c = hist_ref[2]
        valid = valid_ref[...] > 0.0  # [FT, B]

        mpos = meta_ref[:, _MC_MISSING_POS:_MC_MISSING_POS + 1]
        mpos = mpos.astype(jnp.int32)  # [FT, 1]
        has_missing = meta_ref[:, _MC_HAS_MISSING:_MC_HAS_MISSING + 1] > 0.0
        nbins = meta_ref[:, _MC_NBINS:_MC_NBINS + 1].astype(jnp.int32)
        gate = meta_ref[:, _MC_GATE:_MC_GATE + 1] > 0.0
        penalty = meta_ref[:, _MC_PENALTY:_MC_PENALTY + 1]
        l1 = meta_ref[:, _MC_PARAMS:_MC_PARAMS + 1]
        l2 = meta_ref[:, _MC_PARAMS + 1:_MC_PARAMS + 2]
        min_data = meta_ref[:, _MC_PARAMS + 2:_MC_PARAMS + 3]
        min_hess = meta_ref[:, _MC_PARAMS + 3:_MC_PARAMS + 4]
        min_gain = meta_ref[:, _MC_PARAMS + 4:_MC_PARAMS + 5]
        max_delta = meta_ref[:, _MC_PARAMS + 5:_MC_PARAMS + 6]
        total_g = meta_ref[:, _MC_TOTALS:_MC_TOTALS + 1]
        total_h = meta_ref[:, _MC_TOTALS + 1:_MC_TOTALS + 2]
        total_c = meta_ref[:, _MC_TOTALS + 2:_MC_TOTALS + 3]

        def soft_l1(s):
            # threshold_l1: in interpret mode the barrier is required for
            # bit-identity (it stops XLA reassociating the sign/abs/divide
            # chain, exactly as in the XLA scan); Mosaic has no lowering for
            # optimization_barrier, so the hardware kernel runs the plain
            # arithmetic and owns its own instruction schedule.
            t = jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)
            return jax.lax.optimization_barrier(t) if barrier else t

        def out_of(sg, sh):
            out = -soft_l1(sg) / jnp.maximum(sh + l2, K_EPSILON)
            return jnp.where(max_delta > 0,
                             jnp.clip(out, -max_delta, max_delta), out)

        def gain_given(sg, sh, out):
            gg = soft_l1(sg)
            return -(2.0 * gg * out + (sh + l2) * out * out)

        def gain_of(sg, sh):
            return gain_given(sg, sh, out_of(sg, sh))

        tpos = jax.lax.broadcasted_iota(jnp.int32, (feat_tile, n_bins), 1)
        slot = tpos == mpos  # the missing bin's scan slot
        at_missing = slot & has_missing

        def extract(x):  # exact-value gather of the missing bin (keeps -0.0)
            return jnp.max(jnp.where(slot, x, neg_inf), axis=1,
                           keepdims=True)

        miss_g = jnp.where(has_missing, extract(g), 0.0)
        miss_h = jnp.where(has_missing, extract(h), 0.0)
        miss_c = jnp.where(has_missing, extract(c), 0.0)

        cum_g = jnp.cumsum(jnp.where(at_missing, 0.0, g), axis=1)
        cum_h = jnp.cumsum(jnp.where(at_missing, 0.0, h), axis=1)
        cum_c = jnp.cumsum(jnp.where(at_missing, 0.0, c), axis=1)

        # lane 0: missing goes right (natural); lane 1: missing goes left
        lg0, lh0, lc0 = cum_g, cum_h, cum_c
        lg1, lh1, lc1 = cum_g + miss_g, cum_h + miss_h, cum_c + miss_c

        def lane(lg, lh, lc, lane1):
            rg, rh, rc = total_g - lg, total_h - lh, total_c - lc
            ok = (lc >= min_data) & (rc >= min_data) & \
                 (lh >= min_hess) & (rh >= min_hess)
            ok &= tpos < (nbins - 1)
            ok &= valid
            ok &= gate
            if lane1:
                ok &= has_missing
            gain = gain_of(lg, lh) + gain_of(rg, rh)
            return jnp.where(ok, gain, neg_inf), rg, rh, rc

        gain0, rg0, rh0, rc0 = lane(lg0, lh0, lc0, False)
        gain1, rg1, rh1, rc1 = lane(lg1, lh1, lc1, True)

        gain_shift = gain_of(total_g, total_h) + min_gain

        per_f = jnp.concatenate([gain0, gain1], axis=1)  # [FT, 2B] lane-major
        bf = jnp.argmax(per_f, axis=1, keepdims=True).astype(jnp.int32)
        lane_b = bf // n_bins
        t_b = bf - lane_b * n_bins
        best_gain = jnp.max(per_f, axis=1, keepdims=True)

        sel = tpos == t_b  # the winning threshold's bin column

        def pick(a0, a1):  # exact-value stat extraction at (lane_b, t_b)
            v0 = jnp.max(jnp.where(sel, a0, neg_inf), axis=1, keepdims=True)
            v1 = jnp.max(jnp.where(sel, a1, neg_inf), axis=1, keepdims=True)
            return jnp.where(lane_b == 0, v0, v1)

        lg = pick(lg0, lg1)
        lh = pick(lh0, lh1)
        lc = pick(lc0, lc1)
        rg = pick(rg0, rg1)
        rh = pick(rh0, rh1)
        rc = pick(rc0, rc1)

        is_valid = jnp.isfinite(best_gain) & (best_gain > gain_shift)
        out_gain = jnp.where(is_valid, best_gain - gain_shift, neg_inf)
        out_gain = jnp.where(is_valid, out_gain - penalty, neg_inf)
        lout = out_of(lg, lh)
        rout = out_of(rg, rh)
        rows = (pl.program_id(0) * feat_tile
                + jax.lax.broadcasted_iota(jnp.int32, (feat_tile, 1), 0))
        feat = jnp.where(is_valid, rows.astype(jnp.float32), -1.0)
        zero = jnp.zeros_like(out_gain)
        rec = jnp.concatenate(
            [out_gain, feat, t_b.astype(jnp.float32),
             lane_b.astype(jnp.float32), lg, lh, lc, rg, rh, rc,
             lout, rout, zero, zero], axis=1)  # [FT, N_REC]
        out_ref[...] = jnp.concatenate(
            [rec, jnp.zeros((feat_tile, REC_PAD - N_REC), jnp.float32)],
            axis=1)

    return fused_scan_kernel


@partial(jax.jit, static_argnames=("interpret",))
def fused_split_scan(hist3: jax.Array, meta_cols: jax.Array,
                     valid: jax.Array, interpret: bool = False) -> jax.Array:
    """[3, F_pad, B] channel-major feature hists + [F_pad, REC_PAD] packed
    per-feature meta columns + [F_pad, B] valid-slot mask -> [F_pad, REC_PAD]
    split records (cols N_REC+ zero). F_pad must be a multiple of
    SCAN_TILE_FEATURES; the bin axis is never split (see module docstring)."""
    _, f_pad, n_bins = hist3.shape
    grid = (f_pad // SCAN_TILE_FEATURES,)
    return pl.pallas_call(
        _make_scan_kernel(n_bins, SCAN_TILE_FEATURES, barrier=interpret),
        grid=grid,
        in_specs=[
            pl.BlockSpec((3, SCAN_TILE_FEATURES, n_bins),
                         lambda i: (0, i, 0)),
            pl.BlockSpec((SCAN_TILE_FEATURES, REC_PAD), lambda i: (i, 0)),
            pl.BlockSpec((SCAN_TILE_FEATURES, n_bins), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((SCAN_TILE_FEATURES, REC_PAD),
                               lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((f_pad, REC_PAD), jnp.float32),
        interpret=interpret,
    )(hist3, meta_cols, valid)


def per_feature_best_fused(fh, totals, meta, params, feature_mask=None,
                           penalty=None, interpret=False):
    """Drop-in for ops.split.per_feature_best (numeric lanes, no monotone
    constraint): [F, Bmax, 3] feature hists -> [F, N_REC] records. Builds the
    kernel operands (channel-major hist, packed meta columns) and slices the
    padded record block back to the caller's shape."""
    F, _, _ = fh.shape
    f_pad = -(-F // SCAN_TILE_FEATURES) * SCAN_TILE_FEATURES
    missing_pos = jnp.where(meta.missing_type == MISSING_NAN,
                            meta.nbins - 1, meta.default_bin)
    has_missing = meta.missing_type != MISSING_NONE
    gate = ~meta.is_categorical
    if feature_mask is not None:
        gate = gate & feature_mask
    pen = penalty if penalty is not None \
        else jnp.zeros((F,), jnp.float32)
    cols = [missing_pos.astype(jnp.float32),
            has_missing.astype(jnp.float32),
            meta.nbins.astype(jnp.float32),
            gate.astype(jnp.float32),
            pen.astype(jnp.float32)]
    cols += [jnp.broadcast_to(params[i].astype(jnp.float32), (F,))
             for i in range(6)]
    cols += [jnp.broadcast_to(totals[i].astype(jnp.float32), (F,))
             for i in range(3)]
    meta_cols = jnp.stack(cols, axis=1)  # [F, _MC_COLS]
    meta_cols = jnp.pad(meta_cols,
                        ((0, f_pad - F), (0, REC_PAD - _MC_COLS)))
    hist3 = jnp.pad(jnp.moveaxis(fh, -1, 0), ((0, 0), (0, f_pad - F), (0, 0)))
    valid = jnp.pad(meta.valid_slot.astype(jnp.float32),
                    ((0, f_pad - F), (0, 0)))
    rec = fused_split_scan(hist3, meta_cols, valid, interpret=interpret)
    return rec[:F, :N_REC]
