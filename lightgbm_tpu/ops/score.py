"""Train-time score updates via bin-space tree traversal.

Counterpart of Tree::AddPredictionToScore over the training dataset
(include/LightGBM/tree.h:104-132, train-time path using bin thresholds) and
CUDAScoreUpdater. Bagged training needs it for out-of-bag rows: those rows
never enter the leaf partition, so their new-tree contribution is computed by
traversing the tree directly over the binned matrix (exactly the decisions
the partition made for in-bag rows — threshold_in_bin comparisons, EFB
group-bin translation, missing direction).
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..common import MISSING_NAN, MISSING_NONE, MISSING_ZERO


class BinnedTreeArrays(NamedTuple):
    """Per-internal-node decision fields in bin space + leaf values."""

    group: jax.Array  # [I] int32 feature-group row in the bin matrix
    threshold: jax.Array  # [I] int32 bin threshold
    default_left: jax.Array  # [I] bool
    missing_type: jax.Array  # [I] int32
    default_bin: jax.Array  # [I] int32 (feature-bin space)
    nbins: jax.Array  # [I] int32
    efb_lo: jax.Array  # [I] int32 group-bin range for EFB members
    efb_hi: jax.Array  # [I] int32
    is_efb: jax.Array  # [I] bool
    left_child: jax.Array  # [I] int32
    right_child: jax.Array  # [I] int32
    leaf_value: jax.Array  # [L] float32


def binned_tree_arrays(tree, dataset) -> BinnedTreeArrays:
    """Host-side packing of a trained tree's decisions into bin space."""
    ni = max(tree.num_leaves - 1, 1)
    gi = np.zeros(ni, dtype=np.int32)
    th = np.zeros(ni, dtype=np.int32)
    dl = np.zeros(ni, dtype=bool)
    mt = np.zeros(ni, dtype=np.int32)
    db = np.zeros(ni, dtype=np.int32)
    nb = np.full(ni, 2, dtype=np.int32)
    lo = np.zeros(ni, dtype=np.int32)
    hi = np.zeros(ni, dtype=np.int32)
    ie = np.zeros(ni, dtype=bool)
    for n in range(tree.num_leaves - 1):
        f = int(tree.split_feature[n])
        mapper = dataset.mappers[f]
        g, mi = dataset.feature_to_group[f]
        fg = dataset.groups[g]
        l, h, _ = fg.feature_bin_range(mi)
        gi[n] = g
        th[n] = tree.threshold_in_bin[n]
        dt = int(tree.decision_type[n])
        dl[n] = bool(dt & 2)
        mt[n] = (dt >> 2) & 3
        db[n] = mapper.default_bin
        nb[n] = mapper.num_bin
        lo[n], hi[n], ie[n] = l, h, fg.is_multi
    return BinnedTreeArrays(
        group=jnp.asarray(gi, dtype=jnp.int32),
        threshold=jnp.asarray(th, dtype=jnp.int32),
        default_left=jnp.asarray(dl, dtype=jnp.bool_),
        missing_type=jnp.asarray(mt, dtype=jnp.int32),
        default_bin=jnp.asarray(db, dtype=jnp.int32),
        nbins=jnp.asarray(nb, dtype=jnp.int32),
        efb_lo=jnp.asarray(lo, dtype=jnp.int32),
        efb_hi=jnp.asarray(hi, dtype=jnp.int32),
        is_efb=jnp.asarray(ie, dtype=jnp.bool_),
        left_child=jnp.asarray(tree.left_child[:ni], dtype=jnp.int32),
        right_child=jnp.asarray(tree.right_child[:ni], dtype=jnp.int32),
        leaf_value=jnp.asarray(tree.leaf_value[: tree.num_leaves],
                               dtype=jnp.float32),
    )


@partial(jax.jit, static_argnames=("max_depth",))
def binned_leaf_index(ta: BinnedTreeArrays, bins: jax.Array, row_idx: jax.Array,
                      num_data: int, max_depth: int) -> jax.Array:
    """Leaf index [P] for padded row indices (sentinel num_data -> clamped
    gather; caller drops its scatter)."""
    rows = jnp.minimum(row_idx, num_data - 1)

    def body(_, node):
        active = node >= 0
        nd = jnp.maximum(node, 0)
        gb = bins[ta.group[nd], rows].astype(jnp.int32)
        # EFB translation: group bin -> natural feature bin (split_decision_bins)
        in_range = (gb >= ta.efb_lo[nd]) & (gb < ta.efb_hi[nd])
        shifted = gb - ta.efb_lo[nd]
        natural = shifted + (shifted >= ta.default_bin[nd]).astype(jnp.int32)
        fbin = jnp.where(ta.is_efb[nd],
                         jnp.where(in_range, natural, ta.default_bin[nd]), gb)
        mt = ta.missing_type[nd]
        is_missing = jnp.where(
            mt == MISSING_NAN, fbin == ta.nbins[nd] - 1,
            jnp.where(mt == MISSING_ZERO, fbin == ta.default_bin[nd], False))
        go_left = jnp.where(is_missing, ta.default_left[nd],
                            fbin <= ta.threshold[nd])
        nxt = jnp.where(go_left, ta.left_child[nd], ta.right_child[nd])
        return jnp.where(active, nxt, node)

    node0 = jnp.zeros(row_idx.shape[0], dtype=jnp.int32)
    node = jax.lax.fori_loop(0, max_depth, body, node0)
    return ~node


def add_tree_to_score(tree, dataset, bins_dev: jax.Array, score: jax.Array,
                      row_idx: jax.Array, num_data: int,
                      max_depth: int = 0) -> jax.Array:
    """score[row] += tree.leaf_value[leaf(row)] for the given padded rows.

    max_depth should be a CONFIG-derived bound, not the tree's actual depth —
    per-tree depths would recompile the traversal for every distinct value.
    Extra iterations freeze at the leaf, so over-bounding is free.
    """
    if tree.num_leaves <= 1:
        return score.at[row_idx].add(float(tree.leaf_value[0]), mode="drop")
    ta = binned_tree_arrays(tree, dataset)
    bound = max_depth if max_depth > 0 else int(tree.max_depth)
    leaf = binned_leaf_index(ta, bins_dev, row_idx, num_data, bound)
    return score.at[row_idx].add(ta.leaf_value[leaf], mode="drop")
