"""Vectorized best-split search over histograms.

TPU-native replacement for FeatureHistogram::FindBestThreshold /
FindBestThresholdSequentially (src/treelearner/feature_histogram.hpp:165,832)
and the CUDA per-(leaf,feature) scan kernels (CUDABestSplitFinder,
src/treelearner/cuda/cuda_best_split_finder.cu).

Instead of the reference's per-feature sequential bidirectional scans, the
whole search is one fused computation over a dense [F, Bmax, 3] feature-
histogram tensor:

    cumsum over bins -> left/right aggregates for every threshold
    -> regularized gains for both missing directions -> masked argmax.

Missing-value directionality (the reference's templated REVERSE / NA_AS_MISSING
scan variants) becomes two gain lanes: the missing bin's mass (NaN bin for
MissingType::NaN, default/zero bin for MissingType::Zero) is pulled out of the
ordered scan and added to the left side in the "default-left" lane only.

Bundled features (EFB) omit their default bin in group storage; it is
reconstructed here from the leaf totals exactly like Dataset::FixHistogram
(include/LightGBM/dataset.h:770).

Gain/output formulas mirror feature_histogram.hpp GetSplitGains /
CalculateSplittedLeafOutput: L1 soft-thresholding, L2 shrinkage,
max_delta_step clamping.
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..common import MISSING_NAN, MISSING_NONE, MISSING_ZERO

K_EPSILON = 1e-15
K_MIN_GAIN = -np.inf


@dataclass
class FeatureMeta:
    """Device-side per-feature split metadata, precomputed once per dataset."""

    gather_index: jax.Array  # [F, Bmax] int32 into flattened group-hist rows (+ sentinel)
    valid_slot: jax.Array  # [F, Bmax] bool
    default_bin: jax.Array  # [F] int32 zero/default bin (feature-bin space)
    efb_omitted: jax.Array  # [F] bool: default bin omitted in storage (EFB bundle)
    missing_type: jax.Array  # [F] int32
    nbins: jax.Array  # [F] int32 bins per feature
    is_categorical: jax.Array  # [F] bool
    monotone: jax.Array  # [F] int32 (-1/0/+1)
    penalty: jax.Array  # [F] float32 per-feature split gain penalty (CEGB lazy)
    # host-side
    real_feature: List[int]  # dense idx -> original feature index
    max_bins: int
    hist_rows: int  # rows in the flattened group-hist (without sentinel)

    def tree_flatten(self):
        return ((self.gather_index, self.valid_slot, self.default_bin,
                 self.efb_omitted, self.missing_type, self.nbins,
                 self.is_categorical, self.monotone, self.penalty),
                (self.real_feature, self.max_bins, self.hist_rows))


jax.tree_util.register_pytree_node(
    FeatureMeta,
    FeatureMeta.tree_flatten,
    lambda aux, ch: FeatureMeta(*ch, real_feature=aux[0], max_bins=aux[1],
                                hist_rows=aux[2]),
)


def make_feature_meta(dataset, group_bin_padded: int) -> FeatureMeta:
    """Build FeatureMeta from a constructed io.dataset.Dataset.

    group_bin_padded is the per-group bin-axis padding used by the histogram
    kernel (hist shape [G, group_bin_padded, 3]); the flat row index of group
    g bin b is g * group_bin_padded + b.
    """
    feats = dataset.used_features
    F = len(feats)
    Bmax = max((dataset.mappers[f].num_bin for f in feats), default=2)
    gather = np.zeros((F, Bmax), dtype=np.int32)
    valid = np.zeros((F, Bmax), dtype=bool)
    default_bin = np.zeros(F, dtype=np.int32)
    efb_omitted = np.zeros(F, dtype=bool)
    missing = np.zeros(F, dtype=np.int32)
    nbins = np.zeros(F, dtype=np.int32)
    is_cat = np.zeros(F, dtype=bool)
    mono = np.zeros(F, dtype=np.int32)
    penalty = np.zeros(F, dtype=np.float32)
    G = dataset.num_groups
    sentinel = G * group_bin_padded  # flat index of the all-zero sentinel row
    for k, f in enumerate(feats):
        m = dataset.mappers[f]
        gi, mi = dataset.feature_to_group[f]
        fg = dataset.groups[gi]
        nb = m.num_bin
        nbins[k] = nb
        missing[k] = m.missing_type
        is_cat[k] = m.bin_type == 1
        if dataset.monotone_constraints:
            mono[k] = dataset.monotone_constraints[f]
        lo, hi, dbin = fg.feature_bin_range(mi)
        gather[k, :] = sentinel
        default_bin[k] = m.default_bin
        if not fg.is_multi:
            for b in range(nb):
                gather[k, b] = gi * group_bin_padded + b
                valid[k, b] = True
        else:
            # bundle member: natural bin b != default lives at
            # lo + b - (b > default); default bin is reconstructed
            for b in range(nb):
                valid[k, b] = True
                if b == dbin:
                    continue
                slot = lo + b - (1 if b > dbin else 0)
                gather[k, b] = gi * group_bin_padded + slot
            efb_omitted[k] = True
    return FeatureMeta(
        gather_index=jnp.asarray(gather),
        valid_slot=jnp.asarray(valid),
        default_bin=jnp.asarray(default_bin),
        efb_omitted=jnp.asarray(efb_omitted),
        missing_type=jnp.asarray(missing),
        nbins=jnp.asarray(nbins),
        is_categorical=jnp.asarray(is_cat),
        monotone=jnp.asarray(mono),
        penalty=jnp.asarray(penalty),
        real_feature=list(feats),
        max_bins=Bmax,
        hist_rows=G * group_bin_padded,
    )


class ScanMeta(NamedTuple):
    """The FeatureMeta subset the split scan reads — a plain pytree so
    distributed learners can shard it along the feature axis."""

    valid_slot: jax.Array  # [F, Bmax] bool
    default_bin: jax.Array  # [F] int32
    missing_type: jax.Array  # [F] int32
    nbins: jax.Array  # [F] int32
    is_categorical: jax.Array  # [F] bool


def scan_meta_of(meta: FeatureMeta) -> ScanMeta:
    return ScanMeta(meta.valid_slot, meta.default_bin, meta.missing_type,
                    meta.nbins, meta.is_categorical)


def pad_feature_meta(meta: FeatureMeta, f_pad: int) -> FeatureMeta:
    """Pad the feature axis to f_pad with inert rows (valid_slot all False,
    gather hitting the zero sentinel) so it divides a mesh axis evenly."""
    F = meta.gather_index.shape[0]
    if f_pad == F:
        return meta
    pad = f_pad - F
    return FeatureMeta(
        gather_index=jnp.concatenate([
            meta.gather_index,
            jnp.full((pad, meta.max_bins), meta.hist_rows, jnp.int32)]),
        valid_slot=jnp.concatenate([
            meta.valid_slot, jnp.zeros((pad, meta.max_bins), bool)]),
        default_bin=jnp.concatenate([meta.default_bin, jnp.zeros(pad, jnp.int32)]),
        efb_omitted=jnp.concatenate([meta.efb_omitted, jnp.zeros(pad, bool)]),
        missing_type=jnp.concatenate([meta.missing_type, jnp.zeros(pad, jnp.int32)]),
        nbins=jnp.concatenate([meta.nbins, jnp.ones(pad, jnp.int32)]),
        is_categorical=jnp.concatenate([meta.is_categorical, jnp.zeros(pad, bool)]),
        monotone=jnp.concatenate([meta.monotone, jnp.zeros(pad, jnp.int32)]),
        penalty=jnp.concatenate([meta.penalty, jnp.zeros(pad, jnp.float32)]),
        real_feature=list(meta.real_feature) + [-1] * pad,
        max_bins=meta.max_bins,
        hist_rows=meta.hist_rows,
    )


def threshold_l1(s, l1):
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_output(sum_grad, sum_hess, l1, l2, max_delta_step):
    """CalculateSplittedLeafOutput (feature_histogram.hpp)."""
    num = -threshold_l1(sum_grad, l1)
    out = num / jnp.maximum(sum_hess + l2, K_EPSILON)
    return jnp.where(max_delta_step > 0,
                     jnp.clip(out, -max_delta_step, max_delta_step), out)


def leaf_gain_given_output(sum_grad, sum_hess, l1, l2, output):
    g = threshold_l1(sum_grad, l1)
    return -(2.0 * g * output + (sum_hess + l2) * output * output)


def leaf_gain(sum_grad, sum_hess, l1, l2, max_delta_step):
    out = leaf_output(sum_grad, sum_hess, l1, l2, max_delta_step)
    return leaf_gain_given_output(sum_grad, sum_hess, l1, l2, out)


# Packed best-split record layout (device -> host, one sync per leaf):
SPLIT_FIELDS = ["gain", "feature", "threshold_bin", "default_left",
                "left_sum_g", "left_sum_h", "left_count",
                "right_sum_g", "right_sum_h", "right_count",
                "left_output", "right_output"]


@dataclass
class SplitInfo:
    """Host-side split record (counterpart of split_info.hpp SplitInfo)."""

    gain: float = -np.inf
    feature: int = -1  # dense (used-feature) index
    threshold_bin: int = 0
    default_left: bool = False
    left_sum_g: float = 0.0
    left_sum_h: float = 0.0
    left_count: int = 0
    right_sum_g: float = 0.0
    right_sum_h: float = 0.0
    right_count: int = 0
    left_output: float = 0.0
    right_output: float = 0.0
    is_categorical: bool = False
    cat_bitset_bins: Optional[List[int]] = None  # bin-space bitset words

    @property
    def valid(self) -> bool:
        return self.feature >= 0 and np.isfinite(self.gain) and self.gain > 0

    @classmethod
    def from_packed(cls, vec: np.ndarray) -> "SplitInfo":
        return cls(gain=float(vec[0]), feature=int(vec[1]),
                   threshold_bin=int(vec[2]), default_left=bool(vec[3] > 0.5),
                   left_sum_g=float(vec[4]), left_sum_h=float(vec[5]),
                   left_count=int(round(vec[6])), right_sum_g=float(vec[7]),
                   right_sum_h=float(vec[8]), right_count=int(round(vec[9])),
                   left_output=float(vec[10]), right_output=float(vec[11]))


@partial(jax.jit, static_argnames=())
def gather_feature_hist(hist: jax.Array, meta: FeatureMeta,
                        totals: jax.Array) -> jax.Array:
    """[G, Bg, 3] group hist -> [F, Bmax, 3] feature hist with EFB default
    reconstruction (FixHistogram)."""
    flat = hist.reshape(-1, hist.shape[-1])
    flat = jnp.concatenate([flat, jnp.zeros((1, hist.shape[-1]), flat.dtype)], axis=0)
    fh = flat[meta.gather_index]  # [F, Bmax, 3]
    fh = fh * meta.valid_slot[:, :, None]
    # EFB default-bin reconstruction: default = leaf totals - sum(other bins)
    missing_mass = totals[None, :] - fh.sum(axis=1)  # [F, 3]
    add = jnp.where(meta.efb_omitted[:, None], missing_mass, 0.0)
    fh = fh.at[jnp.arange(fh.shape[0]), meta.default_bin].add(add)
    return fh


def per_feature_best(fh: jax.Array, totals: jax.Array, meta: FeatureMeta,
                     params: jax.Array) -> jax.Array:
    """Best split per feature: [F, len(SPLIT_FIELDS)] records.

    fh:     [F, Bmax, 3] feature histograms (after gather_feature_hist)
    totals: [3] leaf (sum_grad, sum_hess, count)
    params: [lambda_l1, lambda_l2, min_data_in_leaf, min_sum_hessian_in_leaf,
             min_gain_to_split, max_delta_step] as a device vector

    The `feature` field is the LOCAL row index into fh (invalid rows get -1);
    distributed feature shards offset it by their block start. This is the
    core scan shared by the serial learner and the data/feature/voting
    parallel learners (the reference runs FindBestThresholdSequentially per
    rank feature block, data_parallel_tree_learner.cpp:305+).
    """
    l1, l2, min_data, min_hess, min_gain, max_delta = (
        params[0], params[1], params[2], params[3], params[4], params[5])
    F, Bmax, _ = fh.shape

    total_g, total_h, total_cnt = totals[0], totals[1], totals[2]

    # pull the missing bin out of the ordered scan: the NaN bin is the last
    # bin for MissingType::NaN, the zero/default bin for MissingType::Zero
    missing_pos = jnp.where(meta.missing_type == MISSING_NAN,
                            meta.nbins - 1, meta.default_bin)
    has_missing = meta.missing_type != MISSING_NONE
    rows = jnp.arange(F)
    missing_vals = jnp.where(has_missing[:, None],
                             fh[rows, missing_pos], 0.0)  # [F, 3]
    scan_hist = jnp.where(
        (has_missing[:, None] & (jnp.arange(Bmax)[None, :] == missing_pos[:, None]))[:, :, None],
        0.0, fh)

    cum = jnp.cumsum(scan_hist, axis=1)  # [F, Bmax, 3]

    # lane 0: missing goes right (natural);  lane 1: missing goes left
    left0 = cum
    left1 = cum + missing_vals[:, None, :]
    results = []
    for lane, left in enumerate((left0, left1)):
        lg, lh, lc = left[..., 0], left[..., 1], left[..., 2]
        rg, rh, rc = total_g - lg, total_h - lh, total_cnt - lc
        ok = (lc >= min_data) & (rc >= min_data) & \
             (lh >= min_hess) & (rh >= min_hess)
        # threshold t must leave at least one real bin on the right
        tpos = jnp.arange(Bmax)[None, :]
        ok &= tpos < (meta.nbins[:, None] - 1)
        ok &= meta.valid_slot
        ok &= ~meta.is_categorical[:, None]
        if lane == 1:
            ok &= has_missing[:, None]
        gain = (leaf_gain(lg, lh, l1, l2, max_delta)
                + leaf_gain(rg, rh, l1, l2, max_delta))
        gain = jnp.where(ok, gain, -jnp.inf)
        results.append((gain, lg, lh, lc, rg, rh, rc))

    gain_shift = leaf_gain(total_g, total_h, l1, l2, max_delta) + min_gain
    g0, g1 = results[0][0], results[1][0]
    per_f = jnp.stack([g0, g1], axis=1).reshape(F, 2 * Bmax)  # lane-major
    best_flat = jnp.argmax(per_f, axis=1)  # [F]
    lane_b = best_flat // Bmax
    t_b = best_flat % Bmax
    best_gain = jnp.take_along_axis(per_f, best_flat[:, None], axis=1)[:, 0]

    def pick(a0, a1):
        stack = jnp.stack([a0, a1])  # [2, F, Bmax]
        return stack[lane_b, rows, t_b]

    lg = pick(results[0][1], results[1][1])
    lh = pick(results[0][2], results[1][2])
    lc = pick(results[0][3], results[1][3])
    rg = pick(results[0][4], results[1][4])
    rh = pick(results[0][5], results[1][5])
    rc = pick(results[0][6], results[1][6])

    is_valid = jnp.isfinite(best_gain) & (best_gain > gain_shift)
    out_gain = jnp.where(is_valid, best_gain - gain_shift, -jnp.inf)
    lout = leaf_output(lg, lh, l1, l2, max_delta)
    rout = leaf_output(rg, rh, l1, l2, max_delta)
    # default_left lane semantics: lane 1 sends the missing bin left
    return jnp.stack([
        out_gain,
        jnp.where(is_valid, rows.astype(jnp.float32), -1.0),
        t_b.astype(jnp.float32),
        lane_b.astype(jnp.float32),
        lg, lh, lc, rg, rh, rc, lout, rout,
    ], axis=1)


def reduce_best_record(recs: jax.Array) -> jax.Array:
    """[K, len(SPLIT_FIELDS)] -> [len(SPLIT_FIELDS)] by max gain (ties: first,
    matching the reference's SplitInfo operator> sweep order)."""
    return recs[jnp.argmax(recs[:, 0])]


@partial(jax.jit, static_argnames=())
def find_best_split(hist: jax.Array, totals: jax.Array, meta: FeatureMeta,
                    params: jax.Array) -> jax.Array:
    """Best numerical split across all features for one leaf.

    hist:   [G, Bg, 3] group histogram for the leaf
    totals: [3] leaf (sum_grad, sum_hess, count)
    Returns packed split record [len(SPLIT_FIELDS)] float32.
    """
    fh = gather_feature_hist(hist, meta, totals)  # [F, Bmax, 3]
    recs = per_feature_best(fh, totals, meta, params)
    return reduce_best_record(recs)
