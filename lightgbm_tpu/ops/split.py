"""Vectorized best-split search over histograms.

TPU-native replacement for FeatureHistogram::FindBestThreshold /
FindBestThresholdSequentially (src/treelearner/feature_histogram.hpp:165,832)
and the CUDA per-(leaf,feature) scan kernels (CUDABestSplitFinder,
src/treelearner/cuda/cuda_best_split_finder.cu).

Instead of the reference's per-feature sequential bidirectional scans, the
whole search is one fused computation over a dense [F, Bmax, 3] feature-
histogram tensor:

    cumsum over bins -> left/right aggregates for every threshold
    -> regularized gains for both missing directions -> masked argmax.

Missing-value directionality (the reference's templated REVERSE / NA_AS_MISSING
scan variants) becomes two gain lanes: the missing bin's mass (NaN bin for
MissingType::NaN, default/zero bin for MissingType::Zero) is pulled out of the
ordered scan and added to the left side in the "default-left" lane only.

Bundled features (EFB) omit their default bin in group storage; it is
reconstructed here from the leaf totals exactly like Dataset::FixHistogram
(include/LightGBM/dataset.h:770).

Gain/output formulas mirror feature_histogram.hpp GetSplitGains /
CalculateSplittedLeafOutput: L1 soft-thresholding, L2 shrinkage,
max_delta_step clamping.
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..common import MISSING_NAN, MISSING_NONE, MISSING_ZERO

K_EPSILON = 1e-15
K_MIN_GAIN = -np.inf


@dataclass
class FeatureMeta:
    """Device-side per-feature split metadata, precomputed once per dataset."""

    gather_index: jax.Array  # [F, Bmax] int32 into flattened group-hist rows (+ sentinel)
    valid_slot: jax.Array  # [F, Bmax] bool
    default_bin: jax.Array  # [F] int32 zero/default bin (feature-bin space)
    efb_omitted: jax.Array  # [F] bool: default bin omitted in storage (EFB bundle)
    missing_type: jax.Array  # [F] int32
    nbins: jax.Array  # [F] int32 bins per feature
    is_categorical: jax.Array  # [F] bool
    monotone: jax.Array  # [F] int32 (-1/0/+1)
    # host-side
    real_feature: List[int]  # dense idx -> original feature index
    max_bins: int
    hist_rows: int  # rows in the flattened group-hist (without sentinel)
    has_categorical: bool = False  # static: gates the categorical scan

    def tree_flatten(self):
        return ((self.gather_index, self.valid_slot, self.default_bin,
                 self.efb_omitted, self.missing_type, self.nbins,
                 self.is_categorical, self.monotone),
                (self.real_feature, self.max_bins, self.hist_rows,
                 self.has_categorical))


jax.tree_util.register_pytree_node(
    FeatureMeta,
    FeatureMeta.tree_flatten,
    lambda aux, ch: FeatureMeta(*ch, real_feature=aux[0], max_bins=aux[1],
                                hist_rows=aux[2], has_categorical=aux[3]),
)


def make_feature_meta(dataset, group_bin_padded: int) -> FeatureMeta:
    """Build FeatureMeta from a constructed io.dataset.Dataset.

    group_bin_padded is the per-group bin-axis padding used by the histogram
    kernel (hist shape [G, group_bin_padded, 3]); the flat row index of group
    g bin b is g * group_bin_padded + b.
    """
    feats = dataset.used_features
    F = len(feats)
    Bmax = max((dataset.mappers[f].num_bin for f in feats), default=2)
    gather = np.zeros((F, Bmax), dtype=np.int32)
    valid = np.zeros((F, Bmax), dtype=bool)
    default_bin = np.zeros(F, dtype=np.int32)
    efb_omitted = np.zeros(F, dtype=bool)
    missing = np.zeros(F, dtype=np.int32)
    nbins = np.zeros(F, dtype=np.int32)
    is_cat = np.zeros(F, dtype=bool)
    mono = np.zeros(F, dtype=np.int32)
    G = dataset.num_groups
    sentinel = G * group_bin_padded  # flat index of the all-zero sentinel row
    for k, f in enumerate(feats):
        m = dataset.mappers[f]
        gi, mi = dataset.feature_to_group[f]
        fg = dataset.groups[gi]
        nb = m.num_bin
        nbins[k] = nb
        missing[k] = m.missing_type
        is_cat[k] = m.bin_type == 1
        if dataset.monotone_constraints:
            mono[k] = dataset.monotone_constraints[f]
        lo, hi, dbin = fg.feature_bin_range(mi)
        gather[k, :] = sentinel
        default_bin[k] = m.default_bin
        if not fg.is_multi:
            for b in range(nb):
                gather[k, b] = gi * group_bin_padded + b
                valid[k, b] = True
        else:
            # bundle member: natural bin b != default lives at
            # lo + b - (b > default); default bin is reconstructed
            for b in range(nb):
                valid[k, b] = True
                if b == dbin:
                    continue
                slot = lo + b - (1 if b > dbin else 0)
                gather[k, b] = gi * group_bin_padded + slot
            efb_omitted[k] = True
    return FeatureMeta(
        gather_index=jnp.asarray(gather, dtype=jnp.int32),
        valid_slot=jnp.asarray(valid, dtype=jnp.bool_),
        default_bin=jnp.asarray(default_bin, dtype=jnp.int32),
        efb_omitted=jnp.asarray(efb_omitted, dtype=jnp.bool_),
        missing_type=jnp.asarray(missing, dtype=jnp.int32),
        nbins=jnp.asarray(nbins, dtype=jnp.int32),
        is_categorical=jnp.asarray(is_cat, dtype=jnp.bool_),
        monotone=jnp.asarray(mono, dtype=jnp.int32),
        real_feature=list(feats),
        max_bins=Bmax,
        hist_rows=G * group_bin_padded,
        has_categorical=bool(is_cat.any()),
    )


class ScanMeta(NamedTuple):
    """The FeatureMeta subset the split scan reads — a plain pytree so
    distributed learners can shard it along the feature axis. efb_omitted
    rides along so sharded learners can run fix_feature_hist on their local
    feature block AFTER the cross-shard histogram reduction."""

    valid_slot: jax.Array  # [F, Bmax] bool
    default_bin: jax.Array  # [F] int32
    missing_type: jax.Array  # [F] int32
    nbins: jax.Array  # [F] int32
    is_categorical: jax.Array  # [F] bool
    monotone: jax.Array  # [F] int32 (-1/0/+1)
    efb_omitted: jax.Array  # [F] bool


def scan_meta_of(meta: FeatureMeta) -> ScanMeta:
    return ScanMeta(meta.valid_slot, meta.default_bin, meta.missing_type,
                    meta.nbins, meta.is_categorical, meta.monotone,
                    meta.efb_omitted)


def pad_feature_meta(meta: FeatureMeta, f_pad: int) -> FeatureMeta:
    """Pad the feature axis to f_pad with inert rows (valid_slot all False,
    gather hitting the zero sentinel) so it divides a mesh axis evenly."""
    F = meta.gather_index.shape[0]
    if f_pad == F:
        return meta
    pad = f_pad - F
    return FeatureMeta(
        gather_index=jnp.concatenate([
            meta.gather_index,
            jnp.full((pad, meta.max_bins), meta.hist_rows, jnp.int32)]),
        valid_slot=jnp.concatenate([
            meta.valid_slot, jnp.zeros((pad, meta.max_bins), bool)]),
        default_bin=jnp.concatenate([meta.default_bin, jnp.zeros(pad, jnp.int32)]),
        efb_omitted=jnp.concatenate([meta.efb_omitted, jnp.zeros(pad, bool)]),
        missing_type=jnp.concatenate([meta.missing_type, jnp.zeros(pad, jnp.int32)]),
        nbins=jnp.concatenate([meta.nbins, jnp.ones(pad, jnp.int32)]),
        is_categorical=jnp.concatenate([meta.is_categorical, jnp.zeros(pad, bool)]),
        monotone=jnp.concatenate([meta.monotone, jnp.zeros(pad, jnp.int32)]),
        real_feature=list(meta.real_feature) + [-1] * pad,
        max_bins=meta.max_bins,
        hist_rows=meta.hist_rows,
        has_categorical=meta.has_categorical,
    )


def _register_barrier_batching() -> None:
    # jaxlib (as of 0.4.37) ships no vmap rule for optimization_barrier, but
    # the device learner vmaps find_best_split over leaves and that path
    # reaches the threshold_l1 barrier below. The barrier is the identity on
    # values, so batching is trivial: bind on the batched operands and keep
    # each operand's batch dim unchanged.
    try:
        from jax._src.lax.lax import optimization_barrier_p
        from jax.interpreters import batching
    except ImportError:  # future jax: internals moved — assume rule exists
        return
    if optimization_barrier_p not in batching.primitive_batchers:
        def _batcher(args, dims):
            return optimization_barrier_p.bind(*args), dims
        batching.primitive_batchers[optimization_barrier_p] = _batcher


_register_barrier_batching()


def threshold_l1(s, l1):
    # The barrier pins the soft-thresholded gradient to a rounded f32 before
    # it feeds the output division and the gain products. Without it, XLA's
    # algebraic rewrite of the fused sign/abs/divide/multiply chain differs
    # between the inlined single-device lowering and the SPMD-partitioned
    # >=2-device lowering, and split gains wiggle by one ULP across mesh
    # sizes — which breaks the shrink-to-fit resume bit-identity contract
    # (docs/ROBUSTNESS.md). Pinning this one value makes every mesh size
    # produce identical records.
    return jax.lax.optimization_barrier(
        jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0))


def leaf_output(sum_grad, sum_hess, l1, l2, max_delta_step):
    """CalculateSplittedLeafOutput (feature_histogram.hpp)."""
    num = -threshold_l1(sum_grad, l1)
    out = num / jnp.maximum(sum_hess + l2, K_EPSILON)
    return jnp.where(max_delta_step > 0,
                     jnp.clip(out, -max_delta_step, max_delta_step), out)


def leaf_gain_given_output(sum_grad, sum_hess, l1, l2, output):
    g = threshold_l1(sum_grad, l1)
    return -(2.0 * g * output + (sum_hess + l2) * output * output)


def leaf_gain(sum_grad, sum_hess, l1, l2, max_delta_step):
    out = leaf_output(sum_grad, sum_hess, l1, l2, max_delta_step)
    return leaf_gain_given_output(sum_grad, sum_hess, l1, l2, out)


# Packed best-split record layout (device -> host, one sync per leaf).
# For categorical splits: threshold_bin holds the one-hot bin (cat_dir=0) or
# the sorted-subset prefix LENGTH (cat_dir=+/-1 giving the scan direction);
# the host re-derives the bin set from the feature's histogram row.
SPLIT_FIELDS = ["gain", "feature", "threshold_bin", "default_left",
                "left_sum_g", "left_sum_h", "left_count",
                "right_sum_g", "right_sum_h", "right_count",
                "left_output", "right_output", "is_cat", "cat_dir"]


@dataclass
class SplitInfo:
    """Host-side split record (counterpart of split_info.hpp SplitInfo)."""

    gain: float = -np.inf
    feature: int = -1  # dense (used-feature) index
    threshold_bin: int = 0
    default_left: bool = False
    left_sum_g: float = 0.0
    left_sum_h: float = 0.0
    left_count: int = 0
    right_sum_g: float = 0.0
    right_sum_h: float = 0.0
    right_count: int = 0
    left_output: float = 0.0
    right_output: float = 0.0
    is_categorical: bool = False
    cat_dir: int = 0  # 0 = one-hot; +/-1 = sorted-subset scan direction
    cat_bitset_bins: Optional[List[int]] = None  # bin-space bitset words

    @property
    def valid(self) -> bool:
        return self.feature >= 0 and np.isfinite(self.gain) and self.gain > 0

    @classmethod
    def from_packed(cls, vec: np.ndarray) -> "SplitInfo":
        out = cls(gain=float(vec[0]), feature=int(vec[1]),
                  threshold_bin=int(vec[2]), default_left=bool(vec[3] > 0.5),
                  left_sum_g=float(vec[4]), left_sum_h=float(vec[5]),
                  left_count=int(round(vec[6])), right_sum_g=float(vec[7]),
                  right_sum_h=float(vec[8]), right_count=int(round(vec[9])),
                  left_output=float(vec[10]), right_output=float(vec[11]))
        if len(vec) > 13:
            out.is_categorical = bool(vec[12] > 0.5)
            out.cat_dir = int(round(vec[13]))
        return out


def gather_feature_hist_raw(hist: jax.Array, gather_index: jax.Array,
                            valid_slot: jax.Array) -> jax.Array:
    """[G, Bg, CH] group hist -> [F, Bmax, CH] by pure index gather, NO EFB
    reconstruction. Selection commutes bit-exactly with sum reductions
    (integer or float, any summation order), so sharded learners gather
    their raw local histograms, reduce across shards, and apply
    fix_feature_hist on the reduced blocks with GLOBAL totals — matching
    the single-device op order exactly."""
    flat = hist.reshape(-1, hist.shape[-1])
    flat = jnp.concatenate(
        [flat, jnp.zeros((1, hist.shape[-1]), flat.dtype)], axis=0)
    fh = flat[gather_index]  # [F, Bmax, CH]
    return fh * valid_slot[:, :, None]


def fix_feature_hist(fh: jax.Array, totals: jax.Array,
                     efb_omitted: jax.Array,
                     default_bin: jax.Array) -> jax.Array:
    """EFB default-bin reconstruction: default = leaf totals - sum(other
    bins), added at the default bin of bundle members only (FixHistogram,
    include/LightGBM/dataset.h:770). Works on the full [F, Bmax, CH] tensor
    or a sharded feature block — totals must be the LEAF totals matching
    fh's aggregation scope.

    (dtype-preserving multiply, not jnp.where with a float 0: quantized
    histograms flow through here as exact int32)"""
    missing_mass = totals[None, :].astype(fh.dtype) - fh.sum(axis=1)  # [F, CH]
    add = missing_mass * efb_omitted[:, None]
    return fh.at[jnp.arange(fh.shape[0], dtype=jnp.int32),
                 default_bin].add(add)


@partial(jax.jit, static_argnames=())
def gather_feature_hist(hist: jax.Array, meta: FeatureMeta,
                        totals: jax.Array) -> jax.Array:
    """[G, Bg, 3] group hist -> [F, Bmax, 3] feature hist with EFB default
    reconstruction (FixHistogram)."""
    fh = gather_feature_hist_raw(hist, meta.gather_index, meta.valid_slot)
    return fix_feature_hist(fh, totals, meta.efb_omitted, meta.default_bin)


def per_feature_best(fh: jax.Array, totals: jax.Array, meta: FeatureMeta,
                     params: jax.Array,
                     feature_mask: Optional[jax.Array] = None,
                     constraint: Optional[jax.Array] = None,
                     penalty: Optional[jax.Array] = None) -> jax.Array:
    """Best split per feature: [F, len(SPLIT_FIELDS)] records.

    fh:     [F, Bmax, 3] feature histograms (after gather_feature_hist)
    totals: [3] leaf (sum_grad, sum_hess, count)
    params: [lambda_l1, lambda_l2, min_data_in_leaf, min_sum_hessian_in_leaf,
             min_gain_to_split, max_delta_step] as a device vector
    constraint: optional [2] (min, max) leaf output bounds — basic-mode
             monotone constraints (monotone_constraints.hpp BasicLeafConstraints):
             candidate outputs are clamped, and splits on a monotone feature
             whose clamped outputs violate the direction are discarded
             (GetSplitGains, feature_histogram.hpp:788-792).
    penalty: optional [F] gain penalty subtracted per feature (CEGB DeltaGain,
             cost_effective_gradient_boosting.hpp:80-98).

    The `feature` field is the LOCAL row index into fh (invalid rows get -1);
    distributed feature shards offset it by their block start. This is the
    core scan shared by the serial learner and the data/feature/voting
    parallel learners (the reference runs FindBestThresholdSequentially per
    rank feature block, data_parallel_tree_learner.cpp:305+).

    On TPU backends the numeric lanes route to the fused Pallas kernel
    (ops/scan_pallas.py, bit-identical; LGBM_TPU_SCAN_PALLAS=0 restores this
    XLA body byte-for-byte). Monotone-constrained scans — the clamped-output
    gain variant below — always take the XLA body.
    """
    from . import scan_pallas  # local import: scan_pallas has no split dep
    if (constraint is None and fh.dtype == jnp.float32
            and scan_pallas.use_scan_pallas()):
        return scan_pallas.per_feature_best_fused(
            fh, totals, meta, params, feature_mask, penalty,
            interpret=scan_pallas.interpret_mode())
    l1, l2, min_data, min_hess, min_gain, max_delta = (
        params[0], params[1], params[2], params[3], params[4], params[5])
    F, Bmax, _ = fh.shape

    total_g, total_h, total_cnt = totals[0], totals[1], totals[2]

    # pull the missing bin out of the ordered scan: the NaN bin is the last
    # bin for MissingType::NaN, the zero/default bin for MissingType::Zero
    missing_pos = jnp.where(meta.missing_type == MISSING_NAN,
                            meta.nbins - 1, meta.default_bin)
    has_missing = meta.missing_type != MISSING_NONE
    rows = jnp.arange(F, dtype=jnp.int32)
    missing_vals = jnp.where(has_missing[:, None],
                             fh[rows, missing_pos], 0.0)  # [F, 3]
    scan_hist = jnp.where(
        (has_missing[:, None] & (jnp.arange(Bmax, dtype=jnp.int32)[None, :] == missing_pos[:, None]))[:, :, None],
        0.0, fh)

    cum = jnp.cumsum(scan_hist, axis=1)  # [F, Bmax, 3]

    # lane 0: missing goes right (natural);  lane 1: missing goes left
    left0 = cum
    left1 = cum + missing_vals[:, None, :]
    results = []
    for lane, left in enumerate((left0, left1)):
        lg, lh, lc = left[..., 0], left[..., 1], left[..., 2]
        rg, rh, rc = total_g - lg, total_h - lh, total_cnt - lc
        ok = (lc >= min_data) & (rc >= min_data) & \
             (lh >= min_hess) & (rh >= min_hess)
        # threshold t must leave at least one real bin on the right
        tpos = jnp.arange(Bmax, dtype=jnp.int32)[None, :]
        ok &= tpos < (meta.nbins[:, None] - 1)
        ok &= meta.valid_slot
        ok &= ~meta.is_categorical[:, None]
        if feature_mask is not None:
            ok &= feature_mask[:, None]
        if lane == 1:
            ok &= has_missing[:, None]
        if constraint is not None:
            lo_ = leaf_output(lg, lh, l1, l2, max_delta)
            ro_ = leaf_output(rg, rh, l1, l2, max_delta)
            lo_ = jnp.clip(lo_, constraint[0], constraint[1])
            ro_ = jnp.clip(ro_, constraint[0], constraint[1])
            mono = meta.monotone[:, None]
            ok &= ~(((mono > 0) & (lo_ > ro_)) | ((mono < 0) & (lo_ < ro_)))
            gain = (leaf_gain_given_output(lg, lh, l1, l2, lo_)
                    + leaf_gain_given_output(rg, rh, l1, l2, ro_))
        else:
            gain = (leaf_gain(lg, lh, l1, l2, max_delta)
                    + leaf_gain(rg, rh, l1, l2, max_delta))
        gain = jnp.where(ok, gain, -jnp.inf)
        results.append((gain, lg, lh, lc, rg, rh, rc))

    gain_shift = leaf_gain(total_g, total_h, l1, l2, max_delta) + min_gain
    g0, g1 = results[0][0], results[1][0]
    per_f = jnp.stack([g0, g1], axis=1).reshape(F, 2 * Bmax)  # lane-major
    best_flat = jnp.argmax(per_f, axis=1)  # [F]
    lane_b = best_flat // Bmax
    t_b = best_flat % Bmax
    best_gain = jnp.take_along_axis(per_f, best_flat[:, None], axis=1)[:, 0]

    def pick(a0, a1):
        stack = jnp.stack([a0, a1])  # [2, F, Bmax]
        return stack[lane_b, rows, t_b]

    lg = pick(results[0][1], results[1][1])
    lh = pick(results[0][2], results[1][2])
    lc = pick(results[0][3], results[1][3])
    rg = pick(results[0][4], results[1][4])
    rh = pick(results[0][5], results[1][5])
    rc = pick(results[0][6], results[1][6])

    is_valid = jnp.isfinite(best_gain) & (best_gain > gain_shift)
    out_gain = jnp.where(is_valid, best_gain - gain_shift, -jnp.inf)
    if penalty is not None:
        out_gain = jnp.where(is_valid, out_gain - penalty, -jnp.inf)
    lout = leaf_output(lg, lh, l1, l2, max_delta)
    rout = leaf_output(rg, rh, l1, l2, max_delta)
    if constraint is not None:
        lout = jnp.clip(lout, constraint[0], constraint[1])
        rout = jnp.clip(rout, constraint[0], constraint[1])
    zeros = jnp.zeros_like(out_gain)
    # default_left lane semantics: lane 1 sends the missing bin left
    return jnp.stack([
        out_gain,
        jnp.where(is_valid, rows.astype(jnp.float32), -1.0),
        t_b.astype(jnp.float32),
        lane_b.astype(jnp.float32),
        lg, lh, lc, rg, rh, rc, lout, rout, zeros, zeros,
    ], axis=1)


def per_feature_best_categorical(fh: jax.Array, totals: jax.Array,
                                 meta: FeatureMeta, params: jax.Array,
                                 feature_mask: Optional[jax.Array] = None,
                                 constraint: Optional[jax.Array] = None,
                                 penalty: Optional[jax.Array] = None
                                 ) -> jax.Array:
    """Best categorical split per feature: [F, len(SPLIT_FIELDS)] records.

    Counterpart of FindBestThresholdCategoricalInner
    (src/treelearner/feature_histogram.cpp:147-241):

      * one-hot when num_bin <= max_cat_to_onehot: every single bin is a
        left-set candidate (plain lambda_l2);
      * sorted-subset otherwise: bins with count >= cat_smooth, ordered by
        grad/(hess + cat_smooth), scanned as prefixes from both ends up to
        min(max_cat_threshold, (used+1)/2) categories, with lambda_l2+cat_l2
        and min_data_per_group throttling.

    Bin counts come from the histogram's exact count channel (the reference
    reconstructs them as RoundInt(hess * num_data / sum_hessian)). Only the
    prefix length + direction are recorded; the host re-derives the bin set
    from the same f32 ctr ordering (stable argsort on identical values).
    """
    l1, l2, min_data, min_hess, min_gain, max_delta = (
        params[0], params[1], params[2], params[3], params[4], params[5])
    max_onehot, max_cat_thresh = params[6], params[7]
    cat_l2, cat_smooth, min_group = params[8], params[9], params[10]
    F, Bmax, _ = fh.shape
    rows = jnp.arange(F, dtype=jnp.int32)
    total_g, total_h, total_cnt = totals[0], totals[1], totals[2]
    gain_shift = leaf_gain(total_g, total_h, l1, l2, max_delta) + min_gain
    neg_inf = jnp.float32(-jnp.inf)
    eps = jnp.float32(K_EPSILON)

    g, h, c = fh[..., 0], fh[..., 1], fh[..., 2]
    bin_valid = meta.valid_slot & (jnp.arange(Bmax, dtype=jnp.int32)[None, :]
                                   < meta.nbins[:, None])

    # ---- one-hot lane (each bin alone goes left)
    other_h = total_h - h - eps
    other_c = total_cnt - c
    ok1 = bin_valid & (c >= min_data) & (h >= min_hess) & \
        (other_c >= min_data) & (other_h >= min_hess)
    gain1 = (leaf_gain(total_g - g, other_h, l1, l2, max_delta)
             + leaf_gain(g, h + eps, l1, l2, max_delta))
    gain1 = jnp.where(ok1, gain1, neg_inf)
    onehot_t = jnp.argmax(gain1, axis=1)
    onehot_gain = jnp.take_along_axis(gain1, onehot_t[:, None], axis=1)[:, 0]
    onehot_lg = g[rows, onehot_t]
    onehot_lh = h[rows, onehot_t] + eps
    onehot_lc = c[rows, onehot_t]

    # ---- sorted-subset lane
    l2c = l2 + cat_l2
    eligible = bin_valid & (c >= cat_smooth)
    ctr = jnp.where(eligible, g / (h + cat_smooth), jnp.inf)
    order = jnp.argsort(ctr, axis=1, stable=True)  # eligible first (asc)
    used = eligible.sum(axis=1)  # [F]
    sg = jnp.take_along_axis(g, order, axis=1)
    sh = jnp.take_along_axis(h, order, axis=1)
    sc = jnp.take_along_axis(c, order, axis=1)
    max_num_cat = jnp.minimum(max_cat_thresh, (used + 1) // 2)  # [F]

    def direction_scan(sgd, shd, scd):
        """Prefix scan in sorted order; returns (best_gain, best_len, best
        left stats) per feature. sgd/shd/scd: [F, Bmax] stats in scan order."""
        clg = jnp.cumsum(sgd, axis=1)
        clh = jnp.cumsum(shd, axis=1) + eps
        clc = jnp.cumsum(scd, axis=1)
        pos = jnp.arange(Bmax, dtype=jnp.float32)[None, :]
        in_range = (pos < used[:, None]) & (pos < max_num_cat[:, None])
        rh = total_h - clh
        rc = total_cnt - clc
        ok = in_range & (clc >= min_data) & (clh >= min_hess) & \
            (rc >= min_data) & (rc >= min_group) & (rh >= min_hess)
        # min_data_per_group throttling: the reference requires >= min_group
        # rows accumulated since the last evaluated prefix; approximated
        # here as cumulative count >= min_group (vector-friendly and equal
        # for the common leading-prefix case)
        ok &= clc >= min_group
        gains = (leaf_gain(clg, clh, l1, l2c, max_delta)
                 + leaf_gain(total_g - clg, rh, l1, l2c, max_delta))
        gains = jnp.where(ok, gains, neg_inf)
        best_i = jnp.argmax(gains, axis=1)
        best_gain = jnp.take_along_axis(gains, best_i[:, None], axis=1)[:, 0]
        blg = clg[rows, best_i]
        blh = clh[rows, best_i]
        blc = clc[rows, best_i]
        return best_gain, best_i + 1, blg, blh, blc

    fwd = direction_scan(sg, sh, sc)
    # backward lane: reversal puts the ineligible (inf-keyed) padding first,
    # so roll each row back by (Bmax - used) to start at the LAST eligible bin
    shift = (Bmax - used)[:, None]
    idx = (jnp.arange(Bmax, dtype=jnp.int32)[None, :] + shift) % Bmax
    bwd_stats = tuple(jnp.take_along_axis(a, idx, axis=1)
                      for a in (sg[:, ::-1], sh[:, ::-1], sc[:, ::-1]))
    bwd = direction_scan(*bwd_stats)

    use_onehot = meta.nbins <= max_onehot
    lanes_gain = jnp.stack([
        jnp.where(use_onehot, onehot_gain, neg_inf),
        jnp.where(use_onehot, neg_inf, fwd[0]),
        jnp.where(use_onehot, neg_inf, bwd[0]),
    ], axis=1)  # [F, 3]
    lane = jnp.argmax(lanes_gain, axis=1)
    best_gain = jnp.take_along_axis(lanes_gain, lane[:, None], axis=1)[:, 0]

    def pick(a_one, a_fwd, a_bwd):
        stack = jnp.stack([a_one, a_fwd, a_bwd], axis=1)
        return stack[rows, lane]

    thresh = pick(onehot_t.astype(jnp.float32),
                  fwd[1].astype(jnp.float32), bwd[1].astype(jnp.float32))
    lg = pick(onehot_lg, fwd[2], bwd[2])
    lh = pick(onehot_lh, fwd[3], bwd[3])
    lc = pick(onehot_lc, fwd[4], bwd[4])
    cat_dir = pick(jnp.zeros(F, dtype=jnp.float32), jnp.ones(F, dtype=jnp.float32),
                   -jnp.ones(F, dtype=jnp.float32))
    l2_eff = jnp.where(lane == 0, l2, l2c)

    rg, rh, rc = total_g - lg, total_h - lh, total_cnt - lc
    is_valid = (meta.is_categorical & jnp.isfinite(best_gain)
                & (best_gain > gain_shift))
    if feature_mask is not None:
        is_valid &= feature_mask
    out_gain = jnp.where(is_valid, best_gain - gain_shift, neg_inf)
    if penalty is not None:
        out_gain = jnp.where(is_valid, out_gain - penalty, neg_inf)
    lout = leaf_output(lg, lh, l1, l2_eff, max_delta)
    rout = leaf_output(rg, rh, l1, l2_eff, max_delta)
    if constraint is not None:
        lout = jnp.clip(lout, constraint[0], constraint[1])
        rout = jnp.clip(rout, constraint[0], constraint[1])
    return jnp.stack([
        out_gain,
        jnp.where(is_valid, rows.astype(jnp.float32), -1.0),
        thresh,
        jnp.zeros(F, dtype=jnp.float32),  # default_left = false (CategoricalDecision)
        lg, lh, lc, rg, rh, rc, lout, rout,
        jnp.ones(F, dtype=jnp.float32), cat_dir,
    ], axis=1)


def derive_cat_left_bins(bin_stats: np.ndarray, nbins: int, split: SplitInfo,
                         cat_smooth: float) -> List[int]:
    """Re-derive the winning categorical left-bin set on host from the
    feature's histogram row.

    Replays the device scan's f32 ctr computation and stable argsort on the
    SAME values, so the permutation matches bit-for-bit; only the prefix
    length + direction travel in the packed record.
    """
    if split.cat_dir == 0:
        return [int(split.threshold_bin)]
    g = np.asarray(bin_stats[:nbins, 0], dtype=np.float32)
    h = np.asarray(bin_stats[:nbins, 1], dtype=np.float32)
    c = np.asarray(bin_stats[:nbins, 2], dtype=np.float32)
    smooth = np.float32(cat_smooth)
    eligible = c >= smooth
    ctr = np.where(eligible, g / (h + smooth), np.float32(np.inf))
    order = np.argsort(ctr, kind="stable")
    used = int(eligible.sum())
    k = min(int(split.threshold_bin), used)
    chosen = order[:k] if split.cat_dir > 0 else order[used - k: used]
    return [int(b) for b in chosen]


def bins_to_bitset(values: List[int]) -> List[int]:
    """Pack non-negative ints into 32-bit bitset words (Common::ConstructBitset)."""
    vals = [v for v in values if v >= 0]
    if not vals:
        return [0]
    words = [0] * (max(vals) // 32 + 1)
    for v in vals:
        words[v // 32] |= 1 << (v % 32)
    return words


def reduce_best_record(recs: jax.Array) -> jax.Array:
    """[K, len(SPLIT_FIELDS)] -> [len(SPLIT_FIELDS)] by max gain (ties: first,
    matching the reference's SplitInfo operator> sweep order)."""
    return recs[jnp.argmax(recs[:, 0])]


@partial(jax.jit, static_argnames=())
def find_best_split(hist: jax.Array, totals: jax.Array, meta: FeatureMeta,
                    params: jax.Array,
                    feature_mask: Optional[jax.Array] = None,
                    constraint: Optional[jax.Array] = None,
                    penalty: Optional[jax.Array] = None) -> jax.Array:
    """Best split across all features for one leaf.

    hist:   [G, Bg, 3] group histogram for the leaf
    totals: [3] leaf (sum_grad, sum_hess, count)
    feature_mask: optional [F] bool (ColSampler / interaction constraints)
    constraint: optional [2] (min, max) output bounds (monotone constraints)
    penalty: optional [F] per-feature gain penalty (CEGB)
    Returns packed split record [len(SPLIT_FIELDS)] float32.
    """
    fh = gather_feature_hist(hist, meta, totals)  # [F, Bmax, 3]
    recs = per_feature_best(fh, totals, meta, params, feature_mask,
                            constraint, penalty)
    if meta.has_categorical:  # static flag: skip the scan entirely otherwise
        cat_recs = per_feature_best_categorical(fh, totals, meta, params,
                                                feature_mask, constraint,
                                                penalty)
        recs = jnp.concatenate([recs, cat_recs])
    return reduce_best_record(recs)
