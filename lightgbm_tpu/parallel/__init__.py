"""Distributed tree learners over JAX device meshes.

TPU-native replacement for the reference's distributed layer: the socket/MPI
collective stack (src/network/, include/LightGBM/network.h:89-275) collapses
into XLA collectives (`psum`, `psum_scatter`, `all_gather`) inside
`jax.shard_map` over a mesh axis; the Bruck/recursive-halving schedules and
linker plumbing are XLA's job. The three reference parallel learners map to:

  tree_learner=data    -> DataParallelTreeLearner   (rows sharded, histogram
                          psum_scatter per feature block; the ReduceScatter +
                          per-rank split search of
                          src/treelearner/data_parallel_tree_learner.cpp)
  tree_learner=feature -> FeatureParallelTreeLearner (data replicated, split
                          scan sharded over features;
                          src/treelearner/feature_parallel_tree_learner.cpp)
  tree_learner=voting  -> VotingParallelTreeLearner  (PV-Tree two-phase vote;
                          src/treelearner/voting_parallel_tree_learner.cpp)
"""
from .learners import (DataParallelTreeLearner, FeatureParallelTreeLearner,
                       VotingParallelTreeLearner, create_parallel_learner)
from .mesh import data_mesh
from .predict import predict_raw_sharded, sharded_predict_enabled

__all__ = [
    "DataParallelTreeLearner", "FeatureParallelTreeLearner",
    "VotingParallelTreeLearner", "create_parallel_learner", "data_mesh",
    "predict_raw_sharded", "sharded_predict_enabled",
]
