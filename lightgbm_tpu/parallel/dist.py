"""Multi-host distributed runtime.

TPU-native replacement for the reference's socket/MPI linkers
(src/network/linkers_socket.cpp:188-215, linkers_mpi.cpp): instead of a
hand-rolled TCP ring, multi-host training runs as one JAX process per host
joined through `jax.distributed.initialize`; the device mesh then spans all
hosts and the SAME shard_map collectives that ride ICI within a host ride
DCN across hosts — XLA picks the transport.

`init_distributed` maps the reference's conf surface (num_machines +
machine_list_file + local_listen_port, docs/Features.rst:119-141) onto the
JAX coordinator model: the FIRST machine in the list is the coordinator,
process_id is this host's line index. Standard JAX env vars
(JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID) override.

Array placement and host readback helpers paper over the single- vs multi-
process difference: in one process `jax.device_put` suffices; across
processes globally-sharded arrays are assembled from per-process data via
`jax.make_array_from_callback`, and host syncs read the replicated
addressable shard.
"""
from __future__ import annotations

import os
import socket
from typing import Optional

import jax
import numpy as np
from jax.sharding import NamedSharding

from ..utils.log import Log
from ..utils.timer import global_timer

_initialized = False


def _local_addresses() -> set:
    names = {"localhost", "127.0.0.1", socket.gethostname()}
    try:
        names.add(socket.gethostbyname(socket.gethostname()))
    except OSError:
        pass
    return names


def init_distributed(config=None,
                     coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> bool:
    """Join (or skip joining) the multi-host world. Idempotent.

    Resolution order: explicit args > JAX_* env vars > reference-style conf
    (machine_list_file + local_listen_port + num_machines). Returns True
    when a multi-process runtime is active after the call.
    """
    global _initialized
    if _initialized:
        return jax.process_count() > 1
    try:  # user may have initialized jax.distributed themselves
        from jax._src import distributed as _dist_state

        if getattr(_dist_state.global_state, "client", None) is not None:
            _initialized = True
            return jax.process_count() > 1
    except Exception:  # noqa: BLE001 - internal layout changed: fall through
        pass

    env_addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    env_pid = os.environ.get("JAX_PROCESS_ID")
    if coordinator_address is None and env_addr:
        coordinator_address = env_addr
        num_processes = num_processes or int(env_np) if env_np else num_processes
        process_id = process_id if process_id is not None else (
            int(env_pid) if env_pid else None)

    if coordinator_address is None and config is not None:
        machines = []
        mlist = getattr(config, "machine_list_filename", "") or ""
        if mlist and os.path.isfile(mlist):
            with open(mlist) as f:
                machines = [ln.strip() for ln in f if ln.strip()]
        elif getattr(config, "machines", ""):
            machines = [m.strip() for m in config.machines.split(",")
                        if m.strip()]
        if len(machines) > 1:
            port = int(getattr(config, "local_listen_port", 12400))
            host0 = machines[0].split(":")[0].split(" ")[0]
            coordinator_address = f"{host0}:{port}"
            num_processes = num_processes or len(machines)
            if process_id is None:
                local = _local_addresses()
                for i, m in enumerate(machines):
                    if m.split(":")[0].split(" ")[0] in local:
                        process_id = i
                        break

    if coordinator_address is None:
        # no multi-host config: don't touch JAX at all (process_count would
        # initialize the backend, breaking a later explicit initialize())
        return False
    if num_processes is None or process_id is None:
        Log.fatal("Multi-host init needs num_processes and process_id "
                  "(set JAX_NUM_PROCESSES / JAX_PROCESS_ID or a machine "
                  "list containing this host)")
    Log.info("Joining distributed world: coordinator=%s process %d/%d",
             coordinator_address, process_id, num_processes)
    on_cpu = (os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu"
              or getattr(config, "device_type", "") == "cpu")
    if int(num_processes) > 1 and on_cpu:
        # the default CPU client has no cross-process collectives ("Multi-
        # process computations aren't implemented on the CPU backend");
        # gloo gives the CPU gang real psums — essential for the chaos
        # harness, harmless for the TPU path (knob only affects CPU)
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 - older jaxlib: knob absent
            pass
    try:
        # the coordinator join can block for the whole cluster spin-up;
        # make that visible in perf reports
        with global_timer.scope("dist_init"):
            jax.distributed.initialize(coordinator_address=coordinator_address,
                                       num_processes=int(num_processes),
                                       process_id=int(process_id))
    except RuntimeError as e:
        # "should only be called once" / "already initialized": fine
        if "once" not in str(e) and "already" not in str(e):
            raise
    _initialized = True
    return jax.process_count() > 1


def put_global(arr, mesh: jax.sharding.Mesh, spec) -> jax.Array:
    """Place a host array onto the mesh with the given PartitionSpec, working
    both single-process (plain device_put) and multi-process (each process
    materializes its addressable shards from the same full host array)."""
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    # graftlint: disable=R1 -- multi-process placement must materialize one host copy: make_array_from_callback's callback slices a host array per addressable shard; the single-process path above stays a pure device_put
    arr = np.asarray(arr)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def put_global_tree(tree, mesh: jax.sharding.Mesh, spec):
    """put_global over every leaf of a pytree (same spec)."""
    return jax.tree_util.tree_map(lambda a: put_global(a, mesh, spec), tree)


def put_replicated(tree, mesh: jax.sharding.Mesh):
    """Replicate a pytree of host/device arrays onto every mesh device."""
    return put_global_tree(tree, mesh, jax.sharding.PartitionSpec())


def host_value(arr) -> np.ndarray:
    """Read a (possibly replicated multi-process) device array on host.
    Replicated out_specs=P() results are not fully addressable across
    processes; their first addressable shard IS the full value."""
    if isinstance(arr, jax.Array) and not arr.is_fully_addressable:
        return np.asarray(arr.addressable_data(0))  # graftlint: disable=R1 -- host_value IS the deliberate commit-point device->host read: every caller sits where the host needs the value (split records, narrow/miss counters), so the sync is the contract, not a hidden stall
    return np.asarray(arr)  # graftlint: disable=R1 -- same contract as the multi-process branch above
