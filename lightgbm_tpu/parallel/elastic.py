"""Elastic multi-process training: gang supervision, collective heartbeats,
and watchdog conversion of indefinite collective blocks into typed errors.

The reference's network layer is built around failure — socket linkers retry
connects with timeouts and the collective algorithms assume a machine can
drop (src/network/linkers_socket.cpp:188-215). The jax.distributed analog
has the opposite default: a worker that dies mid-wave leaves every sibling
blocked in a `psum_scatter` forever. This module supplies the three missing
pieces (docs/ROBUSTNESS.md, "Distributed fault domain"):

* **CollectiveWatchdog** — a daemon thread fed one cheap ``beat()`` per
  iteration. When no beat lands for ``LGBM_TPU_COLLECTIVE_TIMEOUT_S`` the
  block is converted into a typed :class:`WorkerLostError` carrying this
  rank and the last-good iteration, dumped through the PR 11 flight
  recorder. Escalation is staged: cooperative raise at the next injection
  point, then a best-effort async raise into the blocked thread, then — only
  under gang supervision — a hard ``os._exit`` so the launcher can reap the
  gang instead of hanging with it.
* **collective heartbeat** — a tiny ``psum`` token over the ``data`` mesh.
  It rides the HealthMonitor's existing per-``check_every`` sync slot
  (health.py ``admit``), NOT a new hot-path host sync; without a monitor it
  self-windows at ``LGBM_TPU_HEARTBEAT_EVERY``. A completed-but-short token
  means the mesh lost cardinality mid-run and raises WorkerLostError; a
  dead sibling usually manifests as the psum blocking, which the watchdog
  owns.
* **GangSupervisor** — the launcher-side policy: watch the worker gang,
  reap every sibling the moment one exits nonzero or misses its liveness
  deadline (no orphaned hangs), and under ``--elastic`` relaunch the gang —
  at the same world size by default (the lost rank is respawned, keeping
  resume bit-identical), or at the surviving world size with
  ``--allow-shrink`` (shrink-to-fit; see the checkpoint world fingerprint).

Module import stays jax-free: launch.py and bench.py drive GangSupervisor
without paying a backend init; jax loads lazily on the first heartbeat.
"""
from __future__ import annotations

import os
import subprocess
import threading
import time
from typing import Any, Callable, List, Optional, Tuple

from .. import telemetry, tracing
from ..utils import sanitize
from ..utils.log import Log
from ..utils.timer import global_timer

# exit code a worker uses when its watchdog hard-exits out of a dead
# collective (distinct from crash codes so the supervisor log names it)
EXIT_WORKER_LOST = 117

ENV_TIMEOUT = "LGBM_TPU_COLLECTIVE_TIMEOUT_S"
ENV_HEARTBEAT_EVERY = "LGBM_TPU_HEARTBEAT_EVERY"
ENV_ELASTIC = "LGBM_TPU_ELASTIC"
ENV_GANG = "LGBM_TPU_GANG"          # set by the launcher: under supervision
ENV_GANG_DIR = "LGBM_TPU_GANG_DIR"  # per-rank liveness files live here
ENV_GANG_ATTEMPT = "LGBM_TPU_GANG_ATTEMPT"

_DEF_HEARTBEAT_EVERY = 10
_LIVENESS_MIN_INTERVAL_S = 0.5


class WorkerLostError(RuntimeError):
    """A collective peer stopped participating: the watchdog expired (the
    collective blocked past the deadline) or the heartbeat token came back
    short. Carries the observing rank and its last-good iteration count
    (finished iterations — the checkpoint a restart resumes from)."""

    def __init__(self, message: str = "a gang peer stopped participating",
                 rank: int = -1, last_good_iteration: int = -1) -> None:
        # message MUST default: the watchdog's async-raise escalation can
        # only deliver the bare class, which Python instantiates with no
        # arguments at the interrupt point
        super().__init__(message)
        self.rank = int(rank)
        self.last_good_iteration = int(last_good_iteration)


def _rank() -> int:
    try:
        return int(os.environ.get("JAX_PROCESS_ID", "0") or 0)
    except ValueError:
        return 0


class CollectiveWatchdog:
    """Deadline watchdog over the training thread's iteration beats.

    ``beat()`` is O(1) attribute stores — no lock, no syscall — so the hot
    loop pays nothing. The daemon thread fires when the gap since the last
    beat exceeds ``timeout_s``, records a fully-populated WorkerLostError,
    dumps a flight postmortem, and escalates (async raise, then gang hard
    exit) until the error is consumed by a cooperative checkpoint."""

    def __init__(self, timeout_s: float, rank: Optional[int] = None) -> None:
        self.timeout_s = float(timeout_s)
        self.rank = _rank() if rank is None else int(rank)
        self.error: Optional[WorkerLostError] = None
        self._last: Optional[Tuple[float, int, int]] = None  # (t, iters, tid)
        self._armed = False
        self._fired_at: Optional[float] = None
        self._async_raised = False
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._poll_s = max(0.02, min(self.timeout_s / 4.0, 0.25))

    # ------------------------------------------------------------ hot path

    def beat(self, finished_iterations: int) -> None:
        """One call per iteration from the training thread: records 'alive
        at N finished iterations' plus the thread to interrupt on expiry."""
        self._last = (time.monotonic(), int(finished_iterations),
                      threading.get_ident())
        self._armed = True
        if self._thread is None:
            self._start()

    def raise_if_expired(self) -> None:
        """Cooperative checkpoint: surface the watchdog's verdict in the
        training thread with the full typed error (the async-raise fallback
        can only deliver a bare class)."""
        err = self.error
        if err is not None:
            self.error = None
            self._armed = False
            self._fired_at = None
            raise err

    def disarm(self) -> None:
        """Training finished (or aborted): beats stop legitimately."""
        self._armed = False
        self.error = None
        self._fired_at = None
        self._async_raised = False

    def stop(self) -> None:
        self.disarm()
        self._stop = True

    # ------------------------------------------------------------- thread

    def _start(self) -> None:
        t = threading.Thread(target=self._run, name="lgbm-collective-watchdog",
                             daemon=True)
        self._thread = t
        t.start()

    def _run(self) -> None:
        while not self._stop:
            time.sleep(self._poll_s)
            last = self._last
            if not self._armed or last is None:
                continue
            now = time.monotonic()
            if self._fired_at is None:
                if now - last[0] > self.timeout_s:
                    self._fire(last)
                continue
            self._escalate(now, last)

    def _fire(self, last: Tuple[float, int, int]) -> None:
        t_beat, iters, _tid = last
        err = WorkerLostError(
            f"collective blocked for more than {self.timeout_s:.1f}s on "
            f"rank {self.rank} (last good iteration: {iters}) — a gang "
            "peer stopped participating", rank=self.rank,
            last_good_iteration=iters)
        self.error = err
        self._fired_at = time.monotonic()
        self._async_raised = False
        Log.warning("%s", err)
        tracing.note("worker_lost", rank=self.rank, last_good_iteration=iters,
                     timeout_s=self.timeout_s)
        if telemetry.enabled():
            telemetry.emit("worker_lost", rank=self.rank,
                           last_good_iteration=iters,
                           timeout_s=self.timeout_s)
        global_timer.add_count("elastic_worker_lost", 1)
        tracing.dump_flight("worker_lost", extra={
            "rank": self.rank, "last_good_iteration": iters,
            "timeout_s": self.timeout_s}, force=True)

    def _escalate(self, now: float, last: Tuple[float, int, int]) -> None:
        """After firing: if no cooperative checkpoint consumed the error,
        try an async raise into the training thread (lands at its next
        bytecode — enough for Python-level blocks); if the block is at the
        C level and we run under a gang, hard-exit so the supervisor reaps
        the gang instead of inheriting the hang."""
        assert self._fired_at is not None
        if not self._async_raised and now - self._fired_at > 2 * self._poll_s:
            self._async_raised = True
            try:
                import ctypes

                ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_ulong(last[2]), ctypes.py_object(WorkerLostError))
            except Exception:  # noqa: BLE001 - escalation is best-effort
                pass
        grace = max(1.0, self.timeout_s)
        if os.environ.get(ENV_GANG) and now - self._fired_at > grace:
            Log.warning("watchdog: rank %d still blocked %.1fs after the "
                        "deadline; exiting %d for the gang supervisor",
                        self.rank, now - self._fired_at, EXIT_WORKER_LOST)
            os._exit(EXIT_WORKER_LOST)


class ElasticRuntime:
    """Per-process elastic state: the watchdog, the heartbeat collective,
    and the liveness file the gang supervisor reads. Obtained via
    :func:`active` (env-configured) or :func:`install` (tests/bench)."""

    def __init__(self, timeout_s: Optional[float] = None,
                 heartbeat_every: int = _DEF_HEARTBEAT_EVERY,
                 rank: Optional[int] = None,
                 gang_dir: Optional[str] = None) -> None:
        self.rank = _rank() if rank is None else int(rank)
        self.heartbeat_every = max(1, int(heartbeat_every))
        self.watchdog = (CollectiveWatchdog(timeout_s, rank=self.rank)
                         if timeout_s else None)
        self.gang_dir = gang_dir
        self._since_hb = 0
        self._hb: Optional[tuple] = None  # lazily built (fn, token_in, world)
        self._liveness_t = 0.0

    # ------------------------------------------------------------ hot path

    def on_iteration_start(self, finished_iterations: int,
                           piggyback: bool = False) -> None:
        """Called at the top of every training iteration. Beats the
        watchdog, surfaces a pending expiry, touches the liveness file, and
        — only when no HealthMonitor window exists to piggyback on
        (``piggyback=False``) — runs the self-windowed heartbeat."""
        if self.watchdog is not None:
            self.watchdog.raise_if_expired()
            self.watchdog.beat(finished_iterations)
        if self.gang_dir:
            self._touch_liveness(finished_iterations)
        if not piggyback:
            self._since_hb += 1
            if self._since_hb >= self.heartbeat_every:
                self._since_hb = 0
                self.heartbeat_sync(finished_iterations)

    def poll_raise(self) -> None:
        """Cooperative poll for code that blocks on purpose (the injected
        worker_hang loop): raises the watchdog's typed error when set."""
        if self.watchdog is not None:
            self.watchdog.raise_if_expired()

    # ----------------------------------------------------------- heartbeat

    def heartbeat_sync(self, iteration: int) -> bool:
        """All-reduce one health token over the data mesh and verify its
        cardinality. This is the method health.py calls inside its existing
        per-``check_every`` sync window — the token pull rides a slot that
        is already serialized, so no new hot-path host sync is introduced.
        Returns True when the full world answered; a short token raises."""
        hb = self._ensure_collective()
        if hb is None:
            return True
        fn, token_in, world = hb
        token = fn(token_in)
        from .dist import host_value

        # graftlint: disable=R1 -- the windowed heartbeat pull: rides the health.py check_every sync slot (or self-windows at LGBM_TPU_HEARTBEAT_EVERY), never per-iteration
        got = int(host_value(token))
        global_timer.add_count("elastic_heartbeats", 1)
        if telemetry.enabled():
            telemetry.emit("heartbeat", iteration=int(iteration),
                           token=got, world=world, rank=self.rank)
        if got == world:
            # the heartbeat slot doubles as the sanitizer's collective-
            # order sync point: every rank is here in lockstep, so the
            # allgathered fingerprints compare like-for-like
            if sanitize.enabled():
                sanitize.check_collective_order()
            return True
        last_good = int(iteration) if self.watchdog is None else max(
            0, int(iteration))
        err = WorkerLostError(
            f"heartbeat token came back {got}/{world} at iteration "
            f"{iteration}: the mesh lost cardinality mid-run",
            rank=self.rank, last_good_iteration=last_good)
        tracing.note("heartbeat_mismatch", token=got, world=world,
                     iteration=int(iteration), rank=self.rank)
        tracing.dump_flight("heartbeat_mismatch", extra={
            "token": got, "world": world, "iteration": int(iteration),
            "rank": self.rank}, force=True)
        raise err

    def _ensure_collective(self) -> Optional[tuple]:
        """Build (once) the jitted psum token over the data mesh. A
        single-device world has nobody to hear from — the heartbeat
        degrades to the watchdog beat alone."""
        if self._hb is not None:
            return self._hb or None
        import jax

        # graftlint: disable=collective-order -- the windowed heartbeat pull, the one sanctioned rank-dependent gate: process_count()/device count are uniform across the gang, so every rank takes the same arm — single-process runs skip the psum by construction, multi-process gangs all build it
        if len(jax.devices()) <= 1 and jax.process_count() <= 1:
            self._hb = ()
            return None
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import PartitionSpec as P

        from ..utils.compat import shard_map
        from .dist import put_global
        from .mesh import data_mesh

        mesh = data_mesh(0)
        world = int(mesh.devices.size)
        token_in = put_global(np.ones((world,), np.float32), mesh, P("data"))

        def _token_sum(x):
            return jax.lax.psum(jnp.sum(x), "data")

        fn = jax.jit(shard_map(_token_sum, mesh=mesh,
                               in_specs=P("data"), out_specs=P()))
        self._hb = (fn, token_in, world)
        return self._hb

    # ------------------------------------------------------------ liveness

    def _touch_liveness(self, finished_iterations: int) -> None:
        now = time.monotonic()
        if now - self._liveness_t < _LIVENESS_MIN_INTERVAL_S:
            return
        self._liveness_t = now
        try:
            os.makedirs(self.gang_dir, exist_ok=True)
            with open(os.path.join(self.gang_dir, f"hb_{self.rank}"),
                      "w") as fh:
                fh.write(f"{int(finished_iterations)}\n")
        except OSError:
            pass  # liveness is advisory; the heartbeat/watchdog still cover

    def notify_train_end(self) -> None:
        if self.watchdog is not None:
            self.watchdog.disarm()


# -------------------------------------------------------- runtime registry

_runtime: Optional[ElasticRuntime] = None
_runtime_key: Optional[tuple] = None
_installed = False


def active() -> Optional[ElasticRuntime]:
    """The process's elastic runtime, or None when elastic mode is off.
    Env-configured (LGBM_TPU_ELASTIC / LGBM_TPU_COLLECTIVE_TIMEOUT_S) unless
    a runtime was installed programmatically; the off-path costs two dict
    lookups, matching the faults-hook budget."""
    global _runtime, _runtime_key
    if _installed:
        return _runtime
    timeout = os.environ.get(ENV_TIMEOUT, "")
    elastic_on = os.environ.get(ENV_ELASTIC, "") not in ("", "0", "false")
    if not timeout and not elastic_on:
        return None
    key = (timeout, elastic_on, os.environ.get(ENV_HEARTBEAT_EVERY, ""),
           os.environ.get(ENV_GANG_DIR, ""))
    if _runtime is None or _runtime_key != key:
        try:
            timeout_s = float(timeout) if timeout else None
        except ValueError:
            Log.warning("Ignoring unparseable %s=%r", ENV_TIMEOUT, timeout)
            timeout_s = None
        every = os.environ.get(ENV_HEARTBEAT_EVERY, "")
        _runtime = ElasticRuntime(
            timeout_s=timeout_s,
            heartbeat_every=int(every) if every else _DEF_HEARTBEAT_EVERY,
            gang_dir=os.environ.get(ENV_GANG_DIR) or None)
        _runtime_key = key
    return _runtime


def install(timeout_s: Optional[float] = None,
            heartbeat_every: int = _DEF_HEARTBEAT_EVERY,
            rank: Optional[int] = None,
            gang_dir: Optional[str] = None) -> ElasticRuntime:
    """Arm an elastic runtime programmatically (tests, bench)."""
    global _runtime, _runtime_key, _installed
    clear()
    _runtime = ElasticRuntime(timeout_s=timeout_s,
                              heartbeat_every=heartbeat_every,
                              rank=rank, gang_dir=gang_dir)
    _runtime_key = None
    _installed = True
    return _runtime


def clear() -> None:
    """Disarm; the next active() re-reads the environment."""
    global _runtime, _runtime_key, _installed
    if _runtime is not None and _runtime.watchdog is not None:
        _runtime.watchdog.stop()
    _runtime = None
    _runtime_key = None
    _installed = False


def notify_train_end() -> None:
    """engine.train's finally hook: legitimate end of beats — the watchdog
    must not convert post-training silence into a worker loss."""
    if _runtime is not None:
        _runtime.notify_train_end()


# ------------------------------------------------------- gang supervision

def latest_snapshot(output_model: str) -> Optional[str]:
    """Newest ``<output_model>.snapshot_iter_<k>`` with a VALID sidecar —
    what a relaunched gang resumes from. Validation runs the sidecar
    checksum (checkpoint.read_sidecar_manifest); a snapshot whose write was
    torn by the dying worker is skipped, not resumed."""
    import glob

    best: Optional[Tuple[int, str]] = None
    for path in glob.glob(output_model + ".snapshot_iter_*"):
        if path.endswith(".ckpt"):
            continue
        try:
            it = int(path.rsplit("_", 1)[1])
        except ValueError:
            continue
        if best is not None and it <= best[0]:
            continue
        try:
            from ..checkpoint import read_sidecar_manifest

            if read_sidecar_manifest(path) is None:
                continue
        except Exception:  # noqa: BLE001 - damaged snapshot: skip it
            continue
        best = (it, path)
    return best[1] if best else None


class GangSupervisor:
    """Watch a gang of worker processes; reap on first loss; optionally
    relaunch. ``spawn(world_size, rank, attempt)`` -> subprocess.Popen is
    supplied by the caller (launch.py builds CLI workers; bench.py drives
    stub commands to measure detect->reap->respawn latency in isolation).

    Loss detection: any nonzero exit, or — when ``liveness_timeout_s`` is
    set — a rank whose liveness file under ``gang_dir`` goes stale (the
    hung-not-dead case). Either way every sibling is torn down before the
    supervisor returns or relaunches: no orphaned hangs (the launch.py
    pre-elastic bug, where one dead worker left the rest blocked in
    jax.distributed barriers forever)."""

    def __init__(self, spawn: Callable[[int, int, int], subprocess.Popen],
                 nproc: int, *, elastic: bool = False, max_restarts: int = 2,
                 allow_shrink: bool = False, liveness_timeout_s: float = 0.0,
                 gang_dir: Optional[str] = None, poll_s: float = 0.1,
                 reap_grace_s: float = 5.0) -> None:
        self.spawn = spawn
        self.nproc = int(nproc)
        self.elastic = bool(elastic)
        self.max_restarts = int(max_restarts)
        self.allow_shrink = bool(allow_shrink)
        self.liveness_timeout_s = float(liveness_timeout_s)
        self.gang_dir = gang_dir
        self.poll_s = float(poll_s)
        self.reap_grace_s = float(reap_grace_s)
        self.attempts_used = 0
        self.last_recovery_ms: Optional[float] = None
        self._loss_t: Optional[float] = None

    def run(self) -> int:
        world, attempt = self.nproc, 0
        while True:
            self._clear_liveness()
            procs = [self.spawn(world, rank, attempt) for rank in range(world)]
            if self._loss_t is not None:
                # detect -> reap -> respawn latency of THIS recovery
                self.last_recovery_ms = (time.monotonic()
                                         - self._loss_t) * 1e3
                global_timer.set_count("gang_recovery_ms",
                                       int(self.last_recovery_ms))
            lost = self._watch(procs)
            if lost is None:
                return 0
            rank, rc, why = lost
            reaped = self._reap(procs)
            Log.warning("gang: worker %d lost (%s, rc=%s) at attempt %d; "
                        "reaped %d sibling(s)", rank, why, rc, attempt,
                        reaped)
            tracing.note("gang_worker_lost", rank=rank, exit_code=rc,
                         attempt=attempt, why=why, world_size=world)
            if telemetry.enabled():
                telemetry.emit("gang_worker_lost", rank=rank, exit_code=rc,
                               attempt=attempt, why=why, world_size=world)
            global_timer.add_count("gang_workers_lost", 1)
            tracing.dump_flight("gang_worker_lost", extra={
                "rank": rank, "exit_code": rc, "attempt": attempt,
                "why": why, "world_size": world}, force=True)
            if not self.elastic or attempt >= self.max_restarts:
                return rc if rc else 1
            attempt += 1
            self.attempts_used = attempt
            if self.allow_shrink and world > 1:
                world -= 1
            Log.warning("gang: elastic restart %d/%d at world size %d",
                        attempt, self.max_restarts, world)

    # ------------------------------------------------------------ watching

    def _watch(self, procs: List[subprocess.Popen]
               ) -> Optional[Tuple[int, Optional[int], str]]:
        """Block until the gang finishes cleanly (None) or a worker is
        lost: (rank, exit_code_or_None, "exit"|"liveness")."""
        while True:
            running = 0
            for rank, p in enumerate(procs):
                rc = p.poll()
                if rc is None:
                    running += 1
                elif rc != 0:
                    self._loss_t = time.monotonic()
                    return (rank, rc, "exit")
            if running == 0:
                return None
            stale = self._stale_liveness(procs)
            if stale is not None:
                self._loss_t = time.monotonic()
                return (stale, None, "liveness")
            time.sleep(self.poll_s)

    def _stale_liveness(self, procs: List[subprocess.Popen]
                        ) -> Optional[int]:
        if not self.liveness_timeout_s or not self.gang_dir:
            return None
        now = time.time()
        for rank, p in enumerate(procs):
            if p.poll() is not None:
                continue
            path = os.path.join(self.gang_dir, f"hb_{rank}")
            try:
                age = now - os.stat(path).st_mtime
            except OSError:
                continue  # never beat yet: still in startup, not stale
            if age > self.liveness_timeout_s:
                return rank
        return None

    def _clear_liveness(self) -> None:
        if not self.gang_dir:
            return
        for rank in range(self.nproc):
            try:
                os.unlink(os.path.join(self.gang_dir, f"hb_{rank}"))
            except OSError:
                pass

    def _reap(self, procs: List[subprocess.Popen]) -> int:
        """terminate -> bounded wait -> kill every survivor. Returns the
        number of processes that had to be reaped."""
        alive = [p for p in procs if p.poll() is None]
        for p in alive:
            try:
                p.terminate()
            except OSError:
                pass
        deadline = time.monotonic() + self.reap_grace_s
        for p in alive:
            try:
                p.wait(timeout=max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                try:
                    p.kill()
                except OSError:
                    pass
                p.wait()
        return len(alive)


def worker_env(base: Optional[dict] = None, *, port: int, world: int,
               rank: int, attempt: int, gang_dir: Optional[str] = None,
               elastic: bool = False, devices_per_proc: int = 0) -> dict:
    """Environment block for one gang worker: the jax.distributed triple
    plus the gang markers faults.py / the watchdog key off."""
    env = dict(os.environ if base is None else base)
    env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
    env["JAX_NUM_PROCESSES"] = str(world)
    env["JAX_PROCESS_ID"] = str(rank)
    env[ENV_GANG] = "1"
    env[ENV_GANG_ATTEMPT] = str(attempt)
    if gang_dir:
        env[ENV_GANG_DIR] = gang_dir
    if elastic:
        env[ENV_ELASTIC] = "1"
    if devices_per_proc:
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count="
            f"{devices_per_proc}").strip()
    return env
