"""Distributed tree learners: data-, feature-, and voting-parallel.

All three reuse the leaf-wise control flow of SerialTreeLearner and override
its device-execution hooks; collectives run inside `jax.shard_map` over the
``data`` mesh axis, replacing the reference's Network::ReduceScatter /
Allreduce stack (src/network/network.cpp:71-331).

Data-parallel (src/treelearner/data_parallel_tree_learner.cpp):
  * rows sharded across devices; a device-resident per-shard leaf-id vector
    replaces index permutation (the CUDADataPartition design, kept local —
    partitioning needs NO communication);
  * per-leaf histograms are built locally then `psum_scatter` distributes
    aggregated FEATURE blocks (the ReduceScatter with feature-block
    assignment of :252-299);
  * each device scans its feature block, then an `all_gather` + argmax picks
    the global best split (SyncUpGlobalBestSplit, parallel_tree_learner.h:209).

Feature-parallel (feature_parallel_tree_learner.cpp): data replicated, only
the split scan is sharded over the feature axis, best split all_gathered.

Voting-parallel (voting_parallel_tree_learner.cpp, PV-Tree): each device
votes its local top-k features from a local scan; the global top-2k by vote
count are the only histogram columns reduced (`psum` of a [2k, Bmax, 3]
gather), decoupling comm volume from the feature count.
"""
from __future__ import annotations

import os
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config import Config
from ..io.dataset import Dataset
from ..models.sample_strategy import host_bag_indices
from ..models.tree import Tree
from ..ops.histogram import build_histogram
from ..ops.partition import split_decision_bins, split_decision_bins_cat
from ..ops.quantize import int16_reduction_safe
from ..ops.split import (SplitInfo, gather_feature_hist, pad_feature_meta,
                         per_feature_best, per_feature_best_categorical,
                         reduce_best_record, scan_meta_of)
from ..perfmodel import (feature_ici_bytes_per_wave, ici_overlap_pct,
                         voting_ici_bytes_per_wave)
from ..treelearner.device import (REC, DeviceTreeLearner, _PendingTree,
                                  make_sharded_grow_fn)
from ..treelearner.serial import (SerialTreeLearner, _LeafState,
                                  device_growth_applies)
from ..utils import sanitize
from ..utils.compat import shard_map
from ..utils.log import Log
from ..utils.timer import global_timer
from .dist import (host_value, init_distributed, put_global, put_global_tree,
                   put_replicated)
from .mesh import data_mesh, padded_row_count


def _ceil_to(n: int, d: int) -> int:
    return -(-n // d) * d



def _better_record(recs: jax.Array, other: jax.Array) -> jax.Array:
    """Row-wise pick the higher-gain record. Each feature is either numerical
    or categorical, so exactly one of the two scans can be finite per row."""
    return jnp.where((other[:, 0] > recs[:, 0])[:, None], other, recs)


def _make_inbag_count_fn(mesh):
    """jit(shard_map) GLOBAL in-bag row count: psum of each shard's local
    `leaf_id == 0` count. Every dtype decision on the reduction wire (the
    int16 histogram packing) must key off this global count — under skewed
    bagging two shards' LOCAL counts can fall on opposite sides of the
    int16 bound, and shards disagreeing on the wire dtype deadlock or
    garble the psum."""

    def body(leaf_sh):
        return jax.lax.psum((leaf_sh == 0).sum().astype(jnp.int32), "data")

    return jax.jit(shard_map(body, mesh=mesh, in_specs=(P("data"),),
                             out_specs=P(), check_vma=False))


def _make_feature_scan_fn(mesh, f_local, has_cat: bool = False):
    """jit(shard_map) best-split scan over feature blocks: each device scans
    its block (numerical + categorical lanes), offsets local feature indices,
    all_gathers the packed records and reduces to the global best
    (SyncUpGlobalBestSplit, parallel_tree_learner.h:209)."""

    def scan_block(fh_blk, totals, params, scan_meta_sh, mask_sh, constraint):
        recs = per_feature_best(fh_blk, totals, scan_meta_sh, params, mask_sh,
                                constraint)
        if has_cat:
            recs = _better_record(recs, per_feature_best_categorical(
                fh_blk, totals, scan_meta_sh, params, mask_sh, constraint))
        off = (jax.lax.axis_index("data") * f_local).astype(jnp.float32)
        feat = recs[:, 1]
        recs = recs.at[:, 1].set(jnp.where(feat >= 0, feat + off, -1.0))
        all_recs = jax.lax.all_gather(recs, "data", axis=0, tiled=True)
        return reduce_best_record(all_recs)

    return jax.jit(shard_map(
        scan_block, mesh=mesh,
        in_specs=(P("data"), P(), P(), P("data"), P("data"), P()),
        out_specs=P(), check_vma=False))


class LeafIdPartition:
    """Partition view backed by a sharded per-row leaf-id vector.

    Exposes the same indices()/count() surface as ops.partition.RowPartition
    (used by score updates and L1-style leaf refits); index materialization
    pulls the leaf-id vector to host once per tree.
    """

    def __init__(self, learner: "DataParallelTreeLearner") -> None:
        self._learner = learner
        self.counts = {}
        self._host_ids: Optional[np.ndarray] = None

    def count(self, leaf: int) -> int:
        return self.counts[leaf]

    def leaf_ids_dev(self) -> jax.Array:
        """Vectorized score-update fast path (see GBDT._update_train_score)."""
        return self._learner.leaf_id[: self._learner.num_data]

    def indices(self, leaf: int) -> np.ndarray:
        if self._host_ids is None:
            # leaf_ids_dev() is already sliced to the real rows — one pull
            # of exactly num_data ids (the old path pulled the padded
            # vector and sliced on host)
            self._host_ids = np.asarray(self.leaf_ids_dev())
        return np.nonzero(self._host_ids == leaf)[0].astype(np.int32)

    def invalidate(self) -> None:
        self._host_ids = None


class DataParallelTreeLearner(SerialTreeLearner):
    def __init__(self, config: Config, dataset: Dataset) -> None:
        self.mesh = data_mesh(config.num_machines)
        self.D = int(self.mesh.devices.size)
        self.n_pad = _ceil_to(dataset.num_data, self.D)
        super().__init__(config, dataset)
        F = len(self.meta.real_feature)
        self.f_pad = _ceil_to(max(F, self.D), self.D)
        self.f_local = self.f_pad // self.D
        self.meta_pad = pad_feature_meta(self.meta, self.f_pad)
        self.scan_meta_sharded = put_global_tree(
            scan_meta_of(self.meta_pad), self.mesh, P("data"))
        self._row_valid = np.zeros(self.n_pad, dtype=bool)
        self._row_valid[: self.num_data] = True
        self.leaf_id: Optional[jax.Array] = None
        self._inbag_count_fn = _make_inbag_count_fn(self.mesh)
        self._build_step_fns()

    # -------------------------------------------------------- device layout

    def _device_bins(self, dataset: Dataset) -> jax.Array:
        """Rows padded to a multiple of the mesh size and sharded on `data`
        (each device holds its contiguous row block — the pre-partitioned
        load of DatasetLoader::LoadFromFile(rank, num_machines))."""
        bins_pad = np.pad(dataset.bins,
                          ((0, 0), (0, self.n_pad - dataset.num_data)))
        return put_global(bins_pad, self.mesh, P(None, "data"))

    # graftlint: disable=untimed-hot-func -- builder only defines jitted closures; real cost is lazy trace+compile inside the timed train() scopes
    def _build_step_fns(self) -> None:
        mesh = self.mesh
        bpad = self.group_bin_padded
        f_local = self.f_local
        qz = self.quantized
        cd = jnp.int8 if qz else jnp.float32

        def make_fh_block(narrow: bool):
            def fh_block(bins_sh, gh_sh, leaf_id_sh, leaf, meta_full):
                """Local masked histogram -> locally-gathered feature hists ->
                psum_scatter so each device owns an aggregated feature block.
                `narrow` reduces quantized int32 histograms in int16 (half the
                ICI bytes — the int16 reduction of
                data_parallel_tree_learner.cpp:285-297), chosen per leaf when
                leaf_count * num_grad_quant_bins provably fits."""
                mask = leaf_id_sh == leaf
                ghm = jnp.where(mask[:, None], gh_sh,
                                jnp.zeros((), gh_sh.dtype))
                hist = build_histogram(bins_sh, ghm, bpad, compute_dtype=cd)
                local_tot = hist[0].sum(axis=0)
                # EFB FixHistogram runs on local totals: the reconstruction is
                # linear in (hist, totals) so it commutes with the reduction
                fh = gather_feature_hist(hist, meta_full, local_tot)
                if narrow:
                    fh = fh.astype(jnp.int16)
                red = jax.lax.psum_scatter(fh, "data", scatter_dimension=0,
                                           tiled=True)
                return red.astype(jnp.int32) if narrow else red

            return jax.jit(shard_map(
                fh_block, mesh=mesh,
                in_specs=(P(None, "data"), P("data"), P("data"), P(), P()),
                out_specs=P("data")))

        self._fh_block_fn = make_fh_block(False)
        self._fh_block_fn_i16 = make_fh_block(True) if qz else None

        self._scan_fn = _make_feature_scan_fn(
            mesh, f_local, self.meta.has_categorical)

        def totals_fn(gh_sh, leaf_id_sh):
            mask = leaf_id_sh == 0
            vals = jnp.where(mask[:, None], gh_sh, jnp.zeros((), gh_sh.dtype))
            if qz:
                vals = vals.astype(jnp.int32)
            return jax.lax.psum(vals.sum(axis=0), "data")

        self._totals_fn = jax.jit(shard_map(
            totals_fn, mesh=mesh,
            in_specs=(P("data"), P("data")), out_specs=P()))

        def partition_fn(bins_sh, leaf_id_sh, decision, gi, leaf, new_leaf,
                         cat_mask, use_cat):
            gb = jnp.take(bins_sh, gi, axis=0)
            go_left = jnp.where(use_cat,
                                split_decision_bins_cat(gb, decision, cat_mask),
                                split_decision_bins(gb, decision))
            on_leaf = leaf_id_sh == leaf
            new_ids = jnp.where(on_leaf & go_left, leaf,
                                jnp.where(on_leaf, new_leaf, leaf_id_sh))
            left = jax.lax.psum((on_leaf & go_left).sum(), "data")
            return new_ids, left

        self._partition_fn = jax.jit(shard_map(
            partition_fn, mesh=mesh,
            in_specs=(P(None, "data"), P("data"), P(), P(), P(), P(), P(),
                      P()),
            out_specs=(P("data"), P())))

    # ------------------------------------------------------------------ hooks

    def _begin_tree(self, gh_ext: jax.Array,
                    bag_indices: Optional[np.ndarray]) -> None:
        n, npad = self.num_data, self.n_pad
        # sharded learners address rows host-side; a DeviceBag (device
        # GOSS) materializes its indices once here
        bag_indices = host_bag_indices(bag_indices)
        gh_ext = self._prepare_gh(gh_ext)
        gh = jnp.concatenate(
            [gh_ext[:n], jnp.zeros((npad - n, gh_ext.shape[1]), gh_ext.dtype)])
        self._gh_sh = put_global(gh, self.mesh, P("data"))
        in_bag = self._row_valid
        if bag_indices is not None:
            in_bag = np.zeros(npad, dtype=bool)
            in_bag[np.asarray(bag_indices, dtype=np.int64)] = True
            in_bag &= self._row_valid
        ids = np.where(in_bag, 0, -1).astype(np.int32)
        self.leaf_id = put_global(ids, self.mesh, P("data"))
        self.partition = LeafIdPartition(self)
        # root count from the DEVICE psum, not the host-side in_bag.sum():
        # _int16_reduction_safe keys the reduction dtype off counts[0], and
        # a local/per-process bag view here would let shards pick different
        # wire dtypes under skewed bagging (see _make_inbag_count_fn)
        self.partition.counts[0] = int(host_value(
            self._inbag_count_fn(self.leaf_id)))
        # tree-level column sampling (per-node masks would need a transfer
        # per leaf; the distributed learners sample per tree only)
        F = len(self.meta.real_feature)
        mask = np.ones(self.f_pad, dtype=bool)
        if self.col_sampler.active:
            mask[:F] = self.col_sampler.reset_by_tree()
        self._mask_padded = put_global(mask, self.mesh, P("data"))

    def _leaf_hist(self, leaf: int) -> jax.Array:
        fn = self._fh_block_fn
        if self.quantized and self._int16_reduction_safe(leaf):
            fn = self._fh_block_fn_i16
        return fn(self.bins_dev, self._gh_sh, self.leaf_id,
                  jnp.int32(leaf), self.meta_pad)

    def _int16_reduction_safe(self, leaf: int) -> bool:
        """All channel sums (and every ring partial sum) of a leaf's integer
        histogram are bounded by leaf_count * num_grad_quant_bins."""
        count = self.partition.counts.get(leaf, self.num_data)
        return int16_reduction_safe(count, self.config.num_grad_quant_bins)

    def _root_totals(self, root_hist) -> Tuple[float, float, float]:
        tot = host_value(self._totals_fn(self._gh_sh, self.leaf_id))
        if self.quantized:
            s = np.asarray(self._scale_vec)
            return (float(tot[0]) * float(s[0]),
                    float(tot[1]) * float(s[1]), float(tot[2]))
        return (float(tot[0]), float(tot[1]), float(tot[2]))

    def _search_split(self, state: _LeafState, leaf: int) -> SplitInfo:
        rec = self._scan_fn(self._hist_for_scan(state.hist),
                            jnp.asarray(state.totals, dtype=jnp.float32),
                            self.params_dev, self.scan_meta_sharded,
                            self._mask_padded, self._constraint_dev(state))
        return SplitInfo.from_packed(host_value(rec))

    def _constraint_dev(self, state: _LeafState) -> jax.Array:
        return jnp.asarray(state.bounds, dtype=jnp.float32)

    def _partition_split(self, leaf: int, new_leaf: int, gi: int,
                         decision: jax.Array,
                         cat_mask=None) -> Tuple[int, int]:
        use_cat = cat_mask is not None
        if cat_mask is None:  # static-shape placeholder for the jitted fn
            cat_mask = jnp.zeros(self.group_bin_padded, dtype=bool)
        new_ids, left_dev = self._partition_fn(
            self.bins_dev, self.leaf_id, decision, jnp.int32(gi),
            jnp.int32(leaf), jnp.int32(new_leaf), cat_mask,
            jnp.bool_(use_cat))
        self.leaf_id = new_ids
        left = int(host_value(left_dev))
        parent = self.partition.counts[leaf]
        self.partition.counts[leaf] = left
        self.partition.counts[new_leaf] = parent - left
        self.partition.invalidate()
        return left, parent - left

    def _cat_bin_stats(self, state: _LeafState, gi: int,
                       dense_f: int) -> np.ndarray:
        # state.hist is the psum_scatter'd FEATURE-major [f_pad, Bmax, 3]
        # block array; each row is already globally aggregated
        return host_value(self._hist_for_scan(state.hist)[dense_f])

    def _feature_hist_row(self, state: _LeafState,
                          dense_f: int) -> np.ndarray:
        # feature-major layout: the row IS the aggregated feature histogram
        # (same accessor as the categorical bin stats)
        return self._cat_bin_stats(state, -1, dense_f)


class FeatureParallelTreeLearner(SerialTreeLearner):
    """Full data on every device; only the split scan is feature-sharded."""

    def __init__(self, config: Config, dataset: Dataset) -> None:
        self.mesh = data_mesh(config.num_machines)
        self.D = int(self.mesh.devices.size)
        super().__init__(config, dataset)
        F = len(self.meta.real_feature)
        self.f_pad = _ceil_to(max(F, self.D), self.D)
        self.f_local = self.f_pad // self.D
        self.meta_pad = pad_feature_meta(self.meta, self.f_pad)
        self.scan_meta_sharded = put_global_tree(
            scan_meta_of(self.meta_pad), self.mesh, P("data"))
        self._scan_fn = _make_feature_scan_fn(self.mesh, self.f_local,
                                              self.meta.has_categorical)
        self._gather_fn = jax.jit(gather_feature_hist)

    def _begin_tree(self, gh_ext, bag_indices) -> None:
        super()._begin_tree(gh_ext, bag_indices)
        F = len(self.meta.real_feature)
        mask = np.ones(self.f_pad, dtype=bool)
        if self._tree_feature_mask is not None:
            mask[:F] = np.asarray(self._tree_feature_mask)
        self._mask_padded = put_global(mask, self.mesh, P("data"))

    def _search_split(self, state: _LeafState, leaf: int) -> SplitInfo:
        totals = jnp.asarray(state.totals, dtype=jnp.float32)
        fh = self._gather_fn(self._hist_for_scan(state.hist), self.meta_pad,
                             totals)
        rec = self._scan_fn(fh, totals, self.params_dev,
                            self.scan_meta_sharded, self._mask_padded,
                            jnp.asarray(state.bounds, dtype=jnp.float32))
        return SplitInfo.from_packed(host_value(rec))


class VotingParallelTreeLearner(DataParallelTreeLearner):
    """PV-Tree: two-phase voting (local top-k -> global top-2k -> reduce only
    the elected columns)."""

    def __init__(self, config: Config, dataset: Dataset) -> None:
        super().__init__(config, dataset)
        F = len(self.meta.real_feature)
        self.k_local = max(1, min(config.top_k, F))
        self.k_global = max(1, min(2 * config.top_k, F))
        # voting replaces the DP psum_scatter hist + feature-block scan with
        # its own local-hist/vote pipeline (only totals/partition are reused)
        self._fh_block_fn = None
        self._scan_fn = None
        self.scan_meta_full = scan_meta_of(self.meta_pad)
        self._build_voting_fns()

    # graftlint: disable=untimed-hot-func -- builder only defines jitted closures; real cost is lazy trace+compile inside the timed train() scopes
    def _build_voting_fns(self) -> None:
        mesh = self.mesh
        bpad = self.group_bin_padded
        k_local, k_global = self.k_local, self.k_global

        def local_hist(bins_sh, gh_sh, leaf_id_sh, leaf):
            mask = leaf_id_sh == leaf
            ghm = jnp.where(mask[:, None], gh_sh, 0.0)
            hist = build_histogram(bins_sh, ghm, bpad)
            return hist[None]  # stacked [1, G, Bpad, 3] per device

        self._local_hist_fn = jax.jit(shard_map(
            local_hist, mesh=mesh,
            in_specs=(P(None, "data"), P("data"), P("data"), P()),
            out_specs=P("data")))

        has_cat = self.meta.has_categorical

        def vote_scan(local_hist_blk, totals, params, meta_full,
                      scan_meta_full, mask_full, constraint):
            lh = local_hist_blk[0]  # this device's [G, Bpad, 3]
            local_tot = lh[0].sum(axis=0)
            fh_local = gather_feature_hist(lh, meta_full, local_tot)
            local_recs = per_feature_best(fh_local, local_tot,
                                          scan_meta_full, params, mask_full,
                                          constraint)
            if has_cat:
                local_recs = _better_record(
                    local_recs, per_feature_best_categorical(
                        fh_local, local_tot, scan_meta_full, params,
                        mask_full, constraint))
            # phase 1: local proposal of top-k features by local gain
            _, topk_idx = jax.lax.top_k(local_recs[:, 0], k_local)
            votes = jax.lax.all_gather(topk_idx, "data", tiled=True)
            counts = jnp.zeros((fh_local.shape[0],), jnp.int32).at[votes].add(1)
            # phase 2: global top-2k by vote count (GlobalVoting,
            # parallel_tree_learner.h:153); replicated + deterministic
            _, selected = jax.lax.top_k(counts, k_global)
            sel_fh = jax.lax.psum(fh_local[selected], "data")  # [K, Bmax, 3]
            sel_meta = jax.tree_util.tree_map(
                lambda a: a[selected], scan_meta_full)
            recs = per_feature_best(sel_fh, totals, sel_meta, params,
                                    None, constraint)
            if has_cat:
                recs = _better_record(recs, per_feature_best_categorical(
                    sel_fh, totals, sel_meta, params, None, constraint))
            valid = recs[:, 1] >= 0
            recs = recs.at[:, 1].set(
                jnp.where(valid, selected.astype(jnp.float32), -1.0))
            return reduce_best_record(recs)

        self._vote_scan_fn = jax.jit(shard_map(
            vote_scan, mesh=mesh,
            in_specs=(P("data"), P(), P(), P(), P(), P(), P()), out_specs=P(),
            check_vma=False))

    def _leaf_hist(self, leaf: int) -> jax.Array:
        return self._local_hist_fn(self.bins_dev, self._gh_sh, self.leaf_id,
                                   jnp.int32(leaf))

    def _cat_bin_stats(self, state: _LeafState, gi: int,
                       dense_f: int) -> np.ndarray:
        # state.hist is the per-device local-hist stack [D, G, Bpad, 3];
        # sum over the device axis to aggregate the winning feature's row
        return host_value(self._hist_for_scan(state.hist.sum(axis=0))[gi])

    def _feature_hist_row(self, state: _LeafState,
                          dense_f: int) -> np.ndarray:
        from ..ops.split import gather_feature_hist

        agg = self._hist_for_scan(state.hist.sum(axis=0))  # [G, Bpad, 3]
        fh = gather_feature_hist(agg, self.meta_pad,
                                 jnp.asarray(state.totals, jnp.float32))
        return host_value(fh[dense_f])

    def _search_split(self, state: _LeafState, leaf: int) -> SplitInfo:
        mask_full = jnp.ones(self.f_pad, dtype=bool)
        if self.col_sampler.active:
            mask_full = mask_full.at[: len(self.meta.real_feature)].set(
                jnp.asarray(np.asarray(self.col_sampler._tree_mask)))
        rec = self._vote_scan_fn(state.hist,
                                 jnp.asarray(state.totals, dtype=jnp.float32),
                                 self.params_dev, self.meta_pad,
                                 self.scan_meta_full, mask_full,
                                 jnp.asarray(state.bounds, dtype=jnp.float32))
        return SplitInfo.from_packed(host_value(rec))


class DeviceDataParallelTreeLearner(DeviceTreeLearner):
    """tree_learner=data + device growth: the whole-tree wave learner
    sharded data-parallel over the ICI mesh — ONE dispatch per tree across
    every device (see treelearner/device.py make_sharded_grow_fn). The
    host-driven DataParallelTreeLearner below stays the fallback for
    configs the device grower cannot serve (categorical, per-node masks,
    monotone, CEGB, linear trees — device_growth_applies)."""

    # the feature-parallel subclass replicates the rows (and skips the
    # per-shard row padding — the grower pads internally, single-device
    # style); everything else about the dispatch shell is shared
    _replicate_rows = False

    def __init__(self, config: Config, dataset: Dataset) -> None:
        from ..ops.compact_pallas import COMPACT_TILE
        from ..ops.hist_pallas import DEFAULT_TILE_ROWS

        self.mesh = data_mesh(config.num_machines)
        self.D = int(self.mesh.devices.size)
        # every shard must be a multiple of the wave tile unit so the
        # shard_map body needs no per-device re-padding
        self._row_unit = max(DEFAULT_TILE_ROWS, COMPACT_TILE)
        if self._replicate_rows:
            self.n_pad = dataset.num_data
            self._row_spec = P()
        else:
            self.n_pad = padded_row_count(dataset.num_data, self.D,
                                          self._row_unit)
            self._row_spec = P("data")
        super().__init__(config, dataset)
        F = len(self.meta.real_feature)
        self.f_pad = _ceil_to(max(F, self.D), self.D)
        self.f_local = self.f_pad // self.D
        self.meta_pad = pad_feature_meta(self.meta, self.f_pad)
        self.scan_meta_sharded = put_global_tree(
            scan_meta_of(self.meta_pad), self.mesh, P("data"))
        # full-feature raw gather tables ride replicated: every device
        # gathers ALL features locally before the psum_scatter hands it
        # its reduced feature block
        self._gidx_rep = put_replicated(self.meta_pad.gather_index,
                                        self.mesh)
        self._vslot_rep = put_replicated(self.meta_pad.valid_slot, self.mesh)
        self._tables_rep = put_replicated(self.tables, self.mesh)
        self._params_rep = put_replicated(self.params_dev, self.mesh)
        self._grow_fns = {}
        self._inbag_count_fn = (None if self._replicate_rows
                                else _make_inbag_count_fn(self.mesh))
        self._scan_args()

    # --------------------------------------------------- per-mode hooks
    # (overridden by the voting / feature-parallel subclasses below)

    def _scan_args(self) -> None:
        """Placement of the scan tables + the feature-mask spec for this
        mode: data-parallel scans feature-SHARDED blocks after the
        psum_scatter, so scan_meta/mask shard and the raw gather tables
        replicate."""
        self._scan_meta_arg = self.scan_meta_sharded
        self._gidx_arg = self._gidx_rep
        self._vslot_arg = self._vslot_rep
        self._fmask_spec = P("data")

    def _grow_fn_extra(self) -> dict:
        return {}

    def _extra_grow_args(self) -> tuple:
        return ()

    def _note_grow_extras(self, extra: tuple) -> None:
        pass

    def _narrow(self, leaf_sh: jax.Array) -> bool:
        """int16 wire packing decision from the GLOBAL psum'd in-bag count
        (satellite bugfix: a local/per-process bag view can fall on
        opposite sides of the int16 bound under skewed bagging, and shards
        disagreeing on the reduction dtype deadlock or garble the wire).
        The scalar pull only syncs on the quantized path."""
        if not self.quantized:
            return False
        n_g = int(host_value(self._inbag_count_fn(leaf_sh)))
        return int16_reduction_safe(n_g, self.config.num_grad_quant_bins)

    def snapshot_state(self) -> dict:
        st = super().snapshot_state()
        st["n_devices"] = int(self.D)
        return st

    def restore_snapshot_state(self, st: dict) -> None:
        n = int(st.get("n_devices", self.D))
        if n != self.D:
            Log.warning("Checkpoint was captured on a %d-device mesh; "
                        "resuming on %d devices. Committed trees are "
                        "replicated so training stays bit-identical, but "
                        "per-wave comm volume will differ", n, self.D)
        super().restore_snapshot_state(st)

    def _device_bins(self, dataset: Dataset) -> jax.Array:
        """Rows padded to the sharded tile unit and split on `data` (each
        device holds its contiguous row block); same native-width rules as
        the single-device learner. The feature-parallel subclass places
        them replicated instead (n_pad == num_data, so the pad is empty)."""
        bins_pad = np.pad(dataset.bins,
                          ((0, 0), (0, self.n_pad - dataset.num_data)))
        if (bins_pad.dtype.itemsize == 1
                and os.environ.get("LGBM_TPU_BINS_I32", "") == "1"):
            bins_pad = bins_pad.astype(np.int32)
        spec = P() if self._replicate_rows else P(None, "data")
        return put_global(bins_pad, self.mesh, spec)

    def _grow_fn(self, bagged: bool, narrow: bool):
        key = (bagged, narrow)
        if key not in self._grow_fns:
            self._grow_fns[key] = make_sharded_grow_fn(
                self.mesh, num_leaves=self.config.num_leaves,
                num_bins=self.group_bin_padded,
                max_depth=self.config.max_depth, quantized=self.quantized,
                batch=self.wave, bagged=bagged, narrow=narrow,
                **self._grow_fn_extra())
        return self._grow_fns[key]

    def _record_ici_bytes(self, narrow: bool) -> None:
        """Gauge: ICI bytes per wave — the psum_scatter'd [K, F_pad, Bmax,
        CH] raw feature histograms plus the all_gathered [2K, F_pad, REC]
        records. O(K*F*Bmax*CH): independent of the row count
        (docs/PERF_NOTES.md comm-volume model); tests assert the
        N-independence."""
        K = max(1, min(self.wave, self.config.num_leaves))
        pool_bytes = 2 if narrow else 4
        global_timer.set_count(
            "device_ici_bytes_per_wave",
            K * self.f_pad * self.meta.max_bins * 3 * pool_bytes
            + 2 * K * self.f_pad * REC * 4)

    def train_async(self, gh_ext: jax.Array,
                    bag_indices: Optional[np.ndarray] = None) -> _PendingTree:
        cfg = self.config
        n, npad = self.num_data, self.n_pad
        bag_indices = host_bag_indices(bag_indices)
        if self.quantized:
            gh_ext = self._prepare_gh(gh_ext)  # int8 rows + scales
        gh = gh_ext[:-1]
        if bag_indices is not None:
            in_bag = np.zeros(n, dtype=bool)
            # graftlint: disable=R1 -- bag_indices is a host ndarray from the bagging sampler (see the parameter annotation); asarray only normalizes dtype, nothing crosses the device boundary
            in_bag[np.asarray(bag_indices, dtype=np.int64)] = True
            gh = jnp.where(jnp.asarray(in_bag, dtype=jnp.bool_)[:, None], gh,
                           jnp.zeros((), gh.dtype))
            ids = np.where(in_bag, 0, -1).astype(np.int32)
            n_bag = len(bag_indices)
        else:
            ids = np.zeros(n, dtype=np.int32)
            n_bag = n
        ids_pad = np.full(npad, -1, dtype=np.int32)
        ids_pad[:n] = ids
        gh_pad = jnp.concatenate(
            [gh, jnp.zeros((npad - n, gh.shape[1]), gh.dtype)])
        gh_sh = put_global(gh_pad, self.mesh, self._row_spec)
        leaf_sh = put_global(ids_pad, self.mesh, self._row_spec)

        F = len(self.meta.real_feature)
        mask = np.ones(self.f_pad, dtype=bool)
        if self.col_sampler.active:
            mask[:F] = self.col_sampler.reset_by_tree()
        fmask_sh = put_global(mask, self.mesh, self._fmask_spec)
        scale = (self._scale_vec if self.quantized
                 else jnp.ones(3, jnp.float32))
        scale_rep = put_global(scale, self.mesh, P())

        narrow = self._narrow(leaf_sh)
        self._record_carry_bytes()
        self._record_ici_bytes(narrow)
        grow = sanitize.guard(
            self._grow_fn(bag_indices is not None, narrow), (0, 1, 2),
            "the sharded grow dispatch (parallel/learners.py train_async)")
        with global_timer.scope("tree_device"):
            out = grow(
                jnp.copy(self.bins_dev), gh_sh, leaf_sh, self._gidx_arg,
                self._vslot_arg, self._scan_meta_arg, self._tables_rep,
                self._params_rep, fmask_sh, scale_rep,
                *self._extra_grow_args())
        rec_store, leaf_id, _, hist_rows, n_waves = out[:5]
        self._note_grow_extras(out[5:])
        leaf_id = leaf_id[:n]
        for arr in (rec_store, leaf_id, hist_rows, n_waves):
            start = getattr(arr, "copy_to_host_async", None)
            if start is not None:
                start()
        return _PendingTree(Tree(cfg.num_leaves), rec_store, leaf_id,
                            hist_rows, n_waves, n_bag)

    def _renew_quantized_leaves_device(self, tree: Tree,
                                       leaf_id: jax.Array) -> None:
        # densify onto one device first: the parent's single scatter-add
        # then sums in the SAME order as the single-device learner
        # (sharded scatter-adds may reorder the f32 accumulation)
        super()._renew_quantized_leaves_device(
            tree, jnp.asarray(np.asarray(leaf_id)))


class VotingDataParallelTreeLearner(DeviceDataParallelTreeLearner):
    """tree_learner=voting + device growth: the whole-tree wave learner
    with PV-Tree two-phase voting (voting_parallel_tree_learner.cpp) on
    the reduction. Rows shard like the data-parallel learner, but every
    device keeps the full LOCAL group-histogram pool and scans ALL
    features locally; a [2K, D*top_k] nomination all_gather elects <=
    2*top_k global candidates per child, and ONLY the elected [Bmax, CH]
    slices are psum'd before a replicated rescan commits the split — per-
    wave ICI volume is O(K * top_k * Bmax), independent of F
    (perfmodel.voting_ici_bytes_per_wave). With top_k >= F every feature
    is elected and the trees are bit-identical to the data-parallel
    learner. LGBM_TPU_VOTING_EXACT_CHECK=1 also runs the full reduction
    and counts committed-split disagreements (voting_miss_total)."""

    def __init__(self, config: Config, dataset: Dataset) -> None:
        super().__init__(config, dataset)
        self._exact_check = os.environ.get(
            "LGBM_TPU_VOTING_EXACT_CHECK", "").lower() in ("1", "true",
                                                           "on")
        self._k_local = max(1, min(int(config.top_k), self.f_pad))
        self._k_global = max(1, min(2 * int(config.top_k), self.f_pad))
        self._pending_miss = []

    def _scan_args(self) -> None:
        # the local scan covers the FULL padded feature axis on every
        # device: scan meta, gather tables and mask all ride replicated
        self._scan_meta_arg = put_replicated(scan_meta_of(self.meta_pad),
                                             self.mesh)
        self._gidx_arg = self._gidx_rep
        self._vslot_arg = self._vslot_rep
        self._fmask_spec = P()

    def _grow_fn_extra(self) -> dict:
        return {"mode": "voting", "top_k": int(self.config.top_k),
                "exact_check": self._exact_check}

    def _extra_grow_args(self) -> tuple:
        from ..utils import faults
        skew = faults.vote_skew_params()
        r, w = skew if skew is not None else (-1, -1)
        return (put_replicated(jnp.int32(r), self.mesh),
                put_replicated(jnp.int32(w), self.mesh))

    def _note_grow_extras(self, extra: tuple) -> None:
        self._pending_miss.append(extra[0])

    def _record_ici_bytes(self, narrow: bool) -> None:
        """Gauge: the nomination all_gather + the ELECTED slice psum only
        — no term scales with F (tests assert F-independence at two
        widths). The smaller-child half of each wave is dispatched before
        the larger-child subtraction it overlaps, so half the wave's ICI
        bytes hide behind local compute by construction."""
        K = max(1, min(self.wave, self.config.num_leaves))
        pool_bytes = 2 if narrow else 4
        bytes_w = voting_ici_bytes_per_wave(
            K, self._k_local, self._k_global, self.meta.max_bins, self.D,
            pool_bytes=pool_bytes)
        global_timer.set_count("device_ici_bytes_per_wave", bytes_w)
        global_timer.set_count("voting_ici_bytes_per_wave", bytes_w)
        global_timer.set_count(
            "device_ici_overlap_pct",
            int(ici_overlap_pct(bytes_w // 2, bytes_w)))

    def finalize(self, pending: _PendingTree) -> Tree:
        tree = super().finalize(pending)
        if self._pending_miss:
            from ..utils import faults
            miss = int(host_value(self._pending_miss.pop(0)))
            global_timer.add_count("voting_miss_total", miss)
            faults.check_vote_skew_surfaced(miss, self._exact_check)
        return tree


class DeviceFeatureParallelTreeLearner(DeviceDataParallelTreeLearner):
    """tree_learner=feature + device growth: rows REPLICATED, each device
    owns a disjoint block of the padded feature axis and scans only it;
    the single collective is the [2K, D, REC] best-record all_gather
    (feature_parallel_tree_learner.cpp semantics — comm independent of
    both N and F, the right regime for wide-sparse data). The lowest
    device owns the lowest feature range and reduce_best_record breaks
    ties toward the first record, so the gathered argmax equals the
    serial learner's full-scan argmax."""

    _replicate_rows = True

    def _scan_args(self) -> None:
        # rows replicate; the gather tables + scan meta + mask shard on
        # the feature axis instead
        self._scan_meta_arg = self.scan_meta_sharded
        self._gidx_arg = put_global(self.meta_pad.gather_index, self.mesh,
                                    P("data"))
        self._vslot_arg = put_global(self.meta_pad.valid_slot, self.mesh,
                                     P("data"))
        self._fmask_spec = P("data")

    def _grow_fn_extra(self) -> dict:
        return {"mode": "feature"}

    def _narrow(self, leaf_sh: jax.Array) -> bool:
        # nothing histogram-shaped crosses the wire — no packing decision
        return False

    def _record_ici_bytes(self, narrow: bool) -> None:
        """Gauge: the best-record all_gather is the ONLY collective —
        O(2K*D*REC), independent of N and F (tests assert the
        N-independence)."""
        K = max(1, min(self.wave, self.config.num_leaves))
        bytes_w = feature_ici_bytes_per_wave(K, self.D)
        global_timer.set_count("device_ici_bytes_per_wave", bytes_w)
        global_timer.set_count("feature_ici_bytes_per_wave", bytes_w)


def _streamed_learner_or_none(learner_type: str, config: Config,
                              dataset: Dataset):
    from ..streaming.learner import streaming_requested

    if not streaming_requested():
        return None
    # LGBM_TPU_HBM_BUDGET + a parallel learner: the plane must stay
    # host-resident, so route to the gang-sharded streamed learner
    # (streaming/sharded.py) instead of the resident device mesh
    if learner_type != "data":
        Log.fatal("LGBM_TPU_HBM_BUDGET streaming supports "
                  "tree_learner=serial or data only (got %s): feature/"
                  "voting learners need the full plane device-resident",
                  learner_type)
    from ..streaming.sharded import ShardedStreamedTreeLearner

    return ShardedStreamedTreeLearner(config, dataset)


def create_parallel_learner(learner_type: str, config: Config,
                            dataset: Dataset):
    from ..treelearner.cegb import CEGB

    # join the multi-host world first when a machine list / env is present,
    # so the mesh below spans every process's devices
    if init_distributed(config) and config.pre_partition:
        Log.warning(
            "pre_partition=true is not yet honored: every process must load "
            "the full dataset (device memory IS stripe-partitioned; host "
            "memory is replicated)")
    if CEGB.enabled(config):
        Log.fatal("cegb_* parameters are not supported with distributed "
                  "tree learners (use tree_learner=serial)")
    streamed = _streamed_learner_or_none(learner_type, config, dataset)
    if streamed is not None:
        return streamed
    # device growth shards the whole-tree wave learner over the mesh (one
    # dispatch per tree); host-driven leaf-wise growth stays the fallback
    # for configs the device grower cannot serve
    on_device = device_growth_applies(getattr(config, "device_type", "cpu"),
                                      config, dataset)
    if (config.use_quantized_grad and learner_type == "voting"
            and not on_device):
        # the DEVICE voting learner reduces raw integer slices exactly
        # like the data-parallel path; only the host-driven PV-Tree
        # fallback keeps the restriction
        Log.fatal("use_quantized_grad is not supported with the host "
                  "tree_learner=voting fallback (use data or feature)")
    if learner_type == "data":
        if on_device:
            return DeviceDataParallelTreeLearner(config, dataset)
        return DataParallelTreeLearner(config, dataset)
    if learner_type == "feature":
        if on_device:
            return DeviceFeatureParallelTreeLearner(config, dataset)
        return FeatureParallelTreeLearner(config, dataset)
    if learner_type == "voting":
        if on_device:
            return VotingDataParallelTreeLearner(config, dataset)
        return VotingParallelTreeLearner(config, dataset)
    Log.fatal("Unknown parallel tree learner type: %s", learner_type)
