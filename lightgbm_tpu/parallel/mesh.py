"""Device-mesh construction for distributed training.

The reference sizes its world from `num_machines` + a machine list
(src/network/linkers_socket.cpp); here the world is the JAX device set —
all local TPU cores by default, every process's devices under
`jax.distributed.initialize` for multi-host. `num_machines` (kept for config
compatibility) caps the mesh when > 1.
"""
from __future__ import annotations

import os

import jax
import numpy as np

from ..utils.log import Log


def padded_row_count(num_rows: int, n_devices: int, unit: int = 1) -> int:
    """Global row count padded so every device's shard is a multiple of
    `unit` (the wave learner's tile unit): rows are first rounded up to a
    per-device multiple of unit, then multiplied back out."""
    per = -(-num_rows // (n_devices * unit)) * unit
    return per * n_devices


def data_mesh(num_machines: int = 0) -> jax.sharding.Mesh:
    """1-D mesh over the row-sharding axis ``data``.

    num_machines <= 1 means "use every visible device" (the reference's
    num_machines=1 is non-distributed; on TPU a single host already exposes
    the full slice, so defaulting to all cores is the native analog).

    Under `jax.distributed` (multi-host), the mesh always spans every
    process's devices; num_machines is validated against the process count.
    In a single process, num_machines > 1 selects a sub-mesh of that many
    devices when available (local simulation of a num_machines cluster) and
    falls back to all devices with a warning otherwise.

    ``LGBM_TPU_FORCE_MESH_DEVICES=N`` caps the mesh as a final override —
    num_machines cannot express the 1-device leg of a shrink-to-fit resume
    chain (<=1 means "all devices"), so the elastic tests/docs use the env
    knob to replay a shrunk world inside one process.
    """
    devices = jax.devices()
    n = len(devices)
    if num_machines and num_machines > 1:
        if jax.process_count() > 1:
            if num_machines != jax.process_count():
                Log.warning(
                    "num_machines=%d does not match the distributed world "
                    "(%d processes); the mesh uses all %d devices",
                    num_machines, jax.process_count(), n)
        elif num_machines <= n:
            n = num_machines
        else:
            Log.warning(
                "num_machines=%d exceeds the %d visible devices; using a "
                "%d-device mesh (start one process per machine with "
                "jax.distributed for a real multi-host run)",
                num_machines, n, n)
    forced = os.environ.get("LGBM_TPU_FORCE_MESH_DEVICES", "")
    if forced:
        try:
            n = max(1, min(int(forced), n))
        except ValueError:
            Log.warning("Ignoring unparseable LGBM_TPU_FORCE_MESH_DEVICES=%r",
                        forced)
    # graftlint: disable=R1 -- np.array over jax.Device handles lays out the mesh grid; no array data moves, and the mesh is built once per learner, not per iteration
    return jax.sharding.Mesh(np.array(devices[:n]), ("data",))
