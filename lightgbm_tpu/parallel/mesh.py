"""Device-mesh construction for distributed training.

The reference sizes its world from `num_machines` + a machine list
(src/network/linkers_socket.cpp); here the world is the JAX device set —
all local TPU cores by default, every process's devices under
`jax.distributed.initialize` for multi-host. `num_machines` (kept for config
compatibility) caps the mesh when > 1.
"""
from __future__ import annotations

import jax
import numpy as np


def data_mesh(num_machines: int = 0) -> jax.sharding.Mesh:
    """1-D mesh over the row-sharding axis ``data``.

    num_machines <= 1 means "use every visible device" (the reference's
    num_machines=1 is non-distributed; on TPU a single host already exposes
    the full slice, so defaulting to all cores is the native analog).
    """
    devices = jax.devices()
    n = len(devices)
    if num_machines and num_machines > 1:
        n = min(num_machines, n)
    return jax.sharding.Mesh(np.array(devices[:n]), ("data",))
