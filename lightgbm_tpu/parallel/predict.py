"""Row-sharded multi-chip inference over the ``data`` mesh axis.

Prediction is embarrassingly parallel over rows, so the Meng et al.
communication model that PR 4 applied to training degenerates to its best
case for serving: the packed ensemble (O(T*I) node words) replicates onto
every device ONCE per PredictorCache entry, X scatters as [N/n_dev, F]
row shards, every device traverses its shard with zero cross-device
traffic, and the only collective output is the [N/n_dev, C] per-shard
score gather — per-row ICI is O(C) out, 0 in. Contrast training
(PERF_NOTES Round-6), which pays a K*F_pad*Bmax*CH histogram scatter per
wave; serving pays nothing per tree.

Gated by LGBM_TPU_PREDICT_SHARD (1/0 force on/off); by default engages
only for batches large enough that per-device dispatch overhead amortizes.
"""
from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.predict import PackedEnsemble, _predict_raw_fused, validate_tree_count
from ..utils.compat import shard_map
from ..utils.timer import global_timer
from .dist import put_global, put_replicated
from .mesh import data_mesh, padded_row_count

_SHARD_ENV = "LGBM_TPU_PREDICT_SHARD"
_AUTO_MIN_ROWS = 1 << 16  # below this, single-chip dispatch is cheaper

_fn_cache: dict = {}


def sharded_predict_enabled(n_rows: int,
                            min_rows: Optional[int] = None) -> bool:
    """Row-sharding policy: env force-off/on, else auto for large batches
    on multi-device platforms. `min_rows` (the pred_shard_rows param —
    the serving fleet sets it per model entry) replaces the auto
    threshold: batches at or above it shard, smaller ones stay
    single-chip."""
    env = os.environ.get(_SHARD_ENV, "").lower()
    if env in ("0", "false", "off"):
        return False
    if jax.device_count() <= 1:
        return False
    if env in ("1", "true", "on"):
        return True
    return n_rows >= (_AUTO_MIN_ROWS if min_rows is None else max(1, min_rows))


def _sharded_predict_fn(mesh: jax.sharding.Mesh, num_tree_per_iteration: int):
    """jit(shard_map) closure per (device set, C): packed replicates,
    X and the output shard over ``data``."""
    key = (tuple(int(d.id) for d in mesh.devices.flat), num_tree_per_iteration)
    fn = _fn_cache.get(key)
    if fn is not None:
        return fn
    P = jax.sharding.PartitionSpec

    def body(packed, x):
        return _predict_raw_fused(packed, x, num_tree_per_iteration)

    fn = jax.jit(shard_map(body, mesh=mesh, in_specs=(P(), P("data")),
                           out_specs=P("data"), check_vma=False))
    _fn_cache[key] = fn
    return fn


def predict_raw_sharded(packed: PackedEnsemble, X: np.ndarray,
                        num_tree_per_iteration: int,
                        mesh: Optional[jax.sharding.Mesh] = None) -> np.ndarray:
    """Raw scores [N, C] with rows sharded across the mesh."""
    validate_tree_count(packed, num_tree_per_iteration)
    if mesh is None:
        mesh = data_mesh()
    n_dev = mesh.devices.size
    n = X.shape[0]
    with global_timer.scope("predict_shard"):
        n_pad = padded_row_count(n, n_dev)
        if n_pad > n:
            X = np.concatenate(
                [X, np.zeros((n_pad - n, X.shape[1]), dtype=X.dtype)])
        P = jax.sharding.PartitionSpec
        x_dev = put_global(X, mesh, P("data"))
        packed_rep = put_replicated(packed, mesh)
        out = _sharded_predict_fn(mesh, num_tree_per_iteration)(
            packed_rep, x_dev)
        global_timer.add_count("predict_sharded_rows", n)
        return np.asarray(out)[:n]
