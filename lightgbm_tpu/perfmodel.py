"""Cost-model-attributed profiling: expected vs measured, per stage.

docs/PERF_NOTES.md derives an analytic model for every hot stage of the
wave learner — carry bytes dragged through HBM per wave, rows-in-leaf
histogram traffic, the gain-scan read volume, the ICI merge — but until
now the model lived only in prose, and the telemetry stack (telemetry.py)
recorded only measured walls. This module connects the two so a bench
capture can say *which* stage is eating the gap to the reference baseline
instead of just restating the end-to-end number:

  * **Formulas as code** — `carry_bytes_per_wave`, `hist_bytes_per_row`,
    `scan_bytes_per_wave`, `ici_bytes_per_wave` are the executable form of
    the PERF_NOTES models. The device/sharded learners publish their
    gauges through these functions (one source of truth; the doc
    cross-links here), and `attribution()` reads them back from
    `global_timer` counters.
  * **Static compile-time costs** — `note_dispatch()` captures the jitted
    callable plus abstract arg shapes the first time each instrumented
    stage dispatches (growth, compaction, scan, predict);
    `static_costs()` later AOT-lowers each capture and reads XLA's own
    `cost_analysis()` / `memory_analysis()` — flops, bytes accessed, peak
    temp bytes — for the actual compiled program, no estimate drift.
  * **Attribution** — `attribution()` merges measured per-stage walls
    (timer totals, captured by any telemetry session), the analytic byte
    model, and a per-device-kind peak-bandwidth table into a report:
    stage fraction of the covered wall (fractions sum to 1, the residual
    is an explicit "other" stage), model-implied seconds, model-vs-
    measured drift, and the roofline fraction actually achieved.

bench.py embeds the report in every capture record (the ledger schema in
docs/OBSERVABILITY.md); `tools/perfreport.py` renders it for humans.

Hot-path cost: `note_dispatch` is a dict-membership check after the first
capture of a stage, and call sites guard on `telemetry.enabled()` — the
disabled path stays a no-op (graftlint R9 polices this file's scope too).
"""
from __future__ import annotations

from typing import Any, Dict, List, Mapping, NamedTuple, Optional, Tuple

# ---------------------------------------------------------------------------
# Analytic formulas — the executable docs/PERF_NOTES.md model
# ---------------------------------------------------------------------------

# per-wave loop-carry payload: gh channels + position + leaf id, 4 B each
PAYLOAD_COLS = 5
# packed best-split record length ([2K, F_pad, REC] all_gather, f32)
REC_FIELDS = 14


def padded_rows(n_rows: int, unit: int) -> int:
    """Rows padded to the wave tile unit (compaction/histogram grids)."""
    return -(-int(n_rows) // int(unit)) * int(unit)


def plane_groups_padded(n_groups: int, plane_bytes: int) -> int:
    """Bin-plane group dim after Mosaic tile padding: uint8 planes pad to
    the (32, 128) tile's 32 sublanes, int32 planes to 8."""
    g = int(n_groups)
    return -(-g // 32) * 32 if int(plane_bytes) == 1 else -(-g // 8) * 8


def carry_bytes_per_wave(n_rows: int, n_groups: int, plane_bytes: int,
                         unit: int, payload_cols: int = PAYLOAD_COLS) -> int:
    """HBM bytes of the wave loop carry (PERF_NOTES round-5):
    ``Gp * Np * plane_bytes + Np * payload_cols * 4``."""
    np_rows = padded_rows(n_rows, unit)
    gp = plane_groups_padded(n_groups, plane_bytes)
    return gp * np_rows * int(plane_bytes) + np_rows * int(payload_cols) * 4


def hist_bytes_per_row(n_groups: int, plane_bytes: int, ch: int = 3) -> int:
    """Bytes the ragged histogram kernel streams per histogrammed row: the
    row's bin-plane column plus its gh payload channels."""
    gp = plane_groups_padded(n_groups, plane_bytes)
    return gp * int(plane_bytes) + int(ch) * 4


def stream_block_bytes(block_rows: int, n_groups: int, plane_bytes: int) -> int:
    """H2D bytes of one streamed bin block (streaming/learner.py): the raw
    [G, block_rows] slab — ``G * block_rows * plane_bytes``. Transfers copy
    the unpadded host slab; Mosaic tile padding applies only once the block
    feeds a kernel, so the G here is the true group count, not
    plane_groups_padded."""
    return int(n_groups) * int(block_rows) * int(plane_bytes)


def scan_bytes_per_wave(wave_width: int, f_pad: int, max_bins: int,
                        ch: int = 3, pool_bytes: int = 4,
                        fused: bool = False) -> int:
    """Gain-scan traffic per wave (PERF_NOTES round-4 step 5, round-8):
    both regimes read the [K, F_pad, Bmax, CH] histogram pool block and
    write the [2K, F_pad, REC] best-record store; the unfused XLA path
    additionally materializes the two per-lane gain tensors ([K, F_pad,
    2*Bmax] f32, written then re-read by the argmax) through HBM, which
    the fused Pallas kernel (ops/scan_pallas.py) keeps in VMEM."""
    k = int(wave_width)
    base = (k * int(f_pad) * int(max_bins) * int(ch) * int(pool_bytes)
            + 2 * k * int(f_pad) * REC_FIELDS * 4)
    if not fused:
        base += 2 * k * int(f_pad) * 2 * int(max_bins) * 4
    return base


def ici_bytes_per_wave(wave_width: int, f_pad: int, max_bins: int,
                       ch: int = 3, pool_bytes: int = 4) -> int:
    """Cross-device bytes per wave for the data-parallel learner
    (PERF_NOTES round-6): one psum_scatter of the raw [K, F_pad, Bmax, CH]
    histograms plus the [2K, F_pad, REC] best-record all_gather."""
    k = int(wave_width)
    return (k * int(f_pad) * int(max_bins) * int(ch) * int(pool_bytes)
            + 2 * k * int(f_pad) * REC_FIELDS * 4)


def voting_ici_bytes_per_wave(wave_width: int, k_local: int, k_global: int,
                              max_bins: int, n_shards: int, ch: int = 3,
                              pool_bytes: int = 4) -> int:
    """Cross-device bytes per wave for the voting-parallel learner
    (PERF_NOTES round-9, PV-Tree): the [2K, D*k_local] nomination
    all_gather plus the psum of the [2K, k_global, Bmax, CH] ELECTED
    histogram slices. No term scales with the feature count — that is the
    whole point of the vote."""
    k = int(wave_width)
    return (2 * k * int(n_shards) * int(k_local) * 4
            + 2 * k * int(k_global) * int(max_bins) * int(ch)
            * int(pool_bytes))


def feature_ici_bytes_per_wave(wave_width: int, n_shards: int) -> int:
    """Cross-device bytes per wave for the feature-parallel learner
    (PERF_NOTES round-9): rows are replicated and every histogram stays
    local, so the only traffic is the [2K, D, REC] best-record all_gather
    — independent of the row count AND the feature count."""
    return 2 * int(wave_width) * int(n_shards) * REC_FIELDS * 4


def serve_wire_bytes_per_request(n_rows: int, n_cols: int,
                                 binary: bool = True,
                                 name_len: int = 8,
                                 json_chars_per_value: int = 20) -> int:
    """Request-body bytes on the serving wire (PERF_NOTES round-10).

    Binary (serving/wire.py): a fixed 24-byte header + the model name +
    the raw f32 row block — 4 bytes per value, parsed by one zero-copy
    frombuffer. JSON: each f64 value prints as up to ~20 characters
    (sign, 17 significant digits, exponent, comma), so the same rows cost
    ~5x the bytes AND a per-value float parse. The ratio is the static
    half of the measured serve_wire_binary_rows_per_sec /
    serve_rows_per_sec speedup; the dynamic half is the per-request
    allocation count (one view vs a parsed list-of-lists)."""
    if binary:
        return 24 + int(name_len) + 4 * int(n_rows) * int(n_cols)
    # {"model": ..., "rows": [[...]]} framing plus per-value text
    return (24 + int(name_len)
            + int(n_rows) * int(n_cols) * int(json_chars_per_value)
            + 2 * int(n_rows))


def serve_cold_start_ms(n_buckets: int, compile_ms_per_bucket: float,
                        deserialize_ms_per_bucket: float = 7.0,
                        aot: bool = True) -> float:
    """Replica cold-start model (PERF_NOTES round-10): time from model
    load to the first bucket-shaped answer. Without an AOT bundle every
    warmup bucket pays one XLA compile (O(100ms) each, serialized on the
    main thread); with one (ops/predict.aot_serialize_bundle persisted by
    checkpoint.write_aot_sidecar) each bucket pays only executable
    deserialization, measured at ~7ms on CPU — a ~25x per-bucket ratio
    that the serve_cold_start_ms ledger metric tracks end to end."""
    per = (float(deserialize_ms_per_bucket) if aot
           else float(compile_ms_per_bucket))
    return float(n_buckets) * per


def serve_replica_scaling_efficiency(t1_rows_per_sec: float,
                                     tn_rows_per_sec: float,
                                     n_replicas: int) -> float:
    """Fleet dispatch efficiency: measured N-replica throughput over N x
    the single-replica figure. Below 1.0 the replicas are contending (one
    device queue, GIL-held decode, shared breaker lock); the ledger metric
    of the same name records the 2-replica figure on the smoke bench."""
    if t1_rows_per_sec <= 0 or n_replicas <= 0:
        return 0.0
    return round(float(tn_rows_per_sec)
                 / (float(n_replicas) * float(t1_rows_per_sec)), 4)


def ici_overlap_pct(overlapped_bytes: int, total_bytes: int) -> float:
    """Share of a wave's ICI traffic dispatched while independent local
    compute is still pending (double-buffered dispatch, PERF_NOTES
    round-9) — the fraction of the transfer XLA's async collectives can
    hide behind the Pallas kernels. Byte accounting, so the gauge is
    deterministic; the wall-clock benefit shows up in the tree_device
    stage attribution instead."""
    if int(total_bytes) <= 0:
        return 0.0
    return round(100.0 * int(overlapped_bytes) / int(total_bytes), 2)


# Peak HBM bandwidth per chip by device kind (bytes/s). Matched by
# substring against jax's `device_kind` string; used for the roofline
# fraction in attribution reports. Override with LGBM_TPU_PEAK_BW_GBPS.
PEAK_HBM_BYTES_PER_S: Tuple[Tuple[str, float], ...] = (
    ("v5 lite", 819e9), ("v5e", 819e9), ("v5p", 2765e9),
    ("v4", 1228e9), ("v3", 900e9), ("v2", 700e9),
)


# VMEM capacity per core by device kind (bytes), substring-matched like
# the bandwidth table. graftlint R14 reads this file's AST (no import) to
# bound every pallas_call's worst-case footprint; the runtime helper below
# serves bench attribution. The DEFAULT is the floor every kernel must
# fit: the smallest VMEM of any device the kernels are expected to run on
# (see /opt guide numbers cited in docs/PERF_NOTES.md).
PALLAS_VMEM_BYTES: Tuple[Tuple[str, int], ...] = (
    ("v5 lite", 134217728), ("v5e", 134217728),   # 128 MiB
    ("v7x", 67108864),                            # 64 MiB
)
PALLAS_VMEM_DEFAULT_BYTES: int = 16777216          # 16 MiB conservative floor

# Lint-time worst-case caps for BlockSpec dimensions that are runtime
# values (static argnums, shape components). R14 substitutes these when a
# dimension does not resolve to a literal/module constant; raising a cap
# here widens the envelope the kernels are certified for, so keep each in
# sync with the call sites' actual maxima.
PALLAS_DIM_BOUNDS: Tuple[Tuple[str, int], ...] = (
    ("num_bins", 256), ("n_bins", 256),   # histogram bins cap (uint8 codes)
    ("tile_rows", 2048),                  # row tiles (hist/predict)
    ("GB", 64), ("CH", 8), ("SC", 64),    # hist group block / channels / slots
    ("Gp", 512), ("tile", 1024), ("rc", 16),  # compact planes / row tile / cols
    ("F", 1024), ("C", 32),               # predict feature row / tree outputs
)


def pallas_vmem_bytes(device_kind: str = "") -> int:
    """VMEM capacity in bytes for a device kind (floor default when the
    kind is unknown). $LGBM_TPU_VMEM_MIB overrides for calibration."""
    import os

    env = os.environ.get("LGBM_TPU_VMEM_MIB", "")
    if env:
        try:
            return int(float(env) * 1048576)
        except ValueError:
            pass
    kind = (device_kind or "").lower()
    for marker, cap in PALLAS_VMEM_BYTES:
        if marker in kind:
            return cap
    return PALLAS_VMEM_DEFAULT_BYTES


def peak_bandwidth_bytes_per_s(device_kind: str = "") -> Optional[float]:
    """Peak HBM bytes/s for a device kind, or None when unknown (CPU and
    unrecognized backends report no roofline). $LGBM_TPU_PEAK_BW_GBPS
    overrides — the knob for calibrating against a measured STREAM."""
    import os

    env = os.environ.get("LGBM_TPU_PEAK_BW_GBPS", "")
    if env:
        try:
            return float(env) * 1e9
        except ValueError:
            pass
    kind = (device_kind or "").lower()
    for marker, bw in PEAK_HBM_BYTES_PER_S:
        if marker in kind:
            return bw
    return None


# ---------------------------------------------------------------------------
# Dispatch capture — static flops/bytes from XLA's own cost analysis
# ---------------------------------------------------------------------------


class _Dispatch(NamedTuple):
    fn: Any                     # the jitted callable (has .lower)
    args: Tuple[Any, ...]       # ShapeDtypeStructs / static literals
    kwargs: Dict[str, Any]


_dispatches: Dict[str, _Dispatch] = {}
_static_cache: Dict[str, Dict[str, Any]] = {}


def _abstractify(x: Any) -> Any:
    """Array-like (incl. tracers mid-trace) -> ShapeDtypeStruct; anything
    else (static ints, bools, None) passes through for the AOT re-lower."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None:
        return x
    import jax

    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def note_dispatch(stage: str, fn: Any, *args: Any, **kwargs: Any) -> None:
    """Record one instrumented stage's dispatch signature (first one wins).

    Called from the stage's real call site — eagerly (grow, scan, predict)
    or at trace time (the compaction pallas_call inside the fused growth
    jit): tracers carry shape/dtype, which is all the AOT lower needs.
    After the first capture this is a dict-membership check, so per-tree /
    per-predict call sites stay O(1)."""
    if stage in _dispatches:
        return
    try:
        import jax

        spec_args = tuple(jax.tree_util.tree_map(_abstractify, a)
                          for a in args)
        spec_kwargs = {k: jax.tree_util.tree_map(_abstractify, v)
                       for k, v in kwargs.items()}
    except Exception:  # never let instrumentation break a dispatch
        return
    _dispatches[stage] = _Dispatch(fn, spec_args, spec_kwargs)
    _static_cache.pop(stage, None)


def captured_stages() -> List[str]:
    return sorted(_dispatches)


def reset_dispatches() -> None:
    """Test hook: forget captured dispatches (and their cached analyses)."""
    _dispatches.clear()
    _static_cache.clear()


def static_costs(stages: Optional[List[str]] = None) -> Dict[str, Dict[str, Any]]:
    """AOT-lower every captured dispatch and read the compiled program's
    own cost figures. Per stage: ``flops``, ``bytes_accessed`` (from
    ``cost_analysis()``), ``argument_bytes`` / ``output_bytes`` /
    ``temp_bytes`` (from ``memory_analysis()``). A stage that fails to
    lower degrades to an ``error`` entry — never an exception (a capture
    run must not die on an analysis)."""
    out: Dict[str, Dict[str, Any]] = {}
    for stage in (stages or captured_stages()):
        if stage in _static_cache:
            out[stage] = _static_cache[stage]
            continue
        d = _dispatches.get(stage)
        if d is None:
            continue
        try:
            compiled = d.fn.lower(*d.args, **d.kwargs).compile()
            entry = _read_compiled(compiled)
        except Exception as e:  # noqa: BLE001 - structured degradation
            entry = {"error": repr(e)[:300]}
        _static_cache[stage] = entry
        out[stage] = entry
    return out


def _read_compiled(compiled: Any) -> Dict[str, Any]:
    entry: Dict[str, Any] = {}
    try:
        ca = compiled.cost_analysis()
    except Exception:
        ca = None
    # jax returns one properties dict per computation on this version
    # (older/newer return the dict directly) — normalize both shapes
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if isinstance(ca, Mapping):
        entry["flops"] = float(ca.get("flops", 0.0))
        entry["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is not None:
        for key, attr in (("argument_bytes", "argument_size_in_bytes"),
                          ("output_bytes", "output_size_in_bytes"),
                          ("temp_bytes", "temp_size_in_bytes"),
                          ("code_bytes", "generated_code_size_in_bytes")):
            val = getattr(ma, attr, None)
            if val is not None:
                entry[key] = int(val)
    if not entry:
        entry["error"] = "backend reported no cost/memory analysis"
    return entry


# ---------------------------------------------------------------------------
# Attribution — measured walls x analytic bytes x roofline
# ---------------------------------------------------------------------------

# stage name -> timer labels whose totals it owns. These are the LEAF
# scopes of the training loop (never a scope that nests another listed
# one, so stage walls are disjoint and the fractions can sum to 1).
STAGE_LABELS: Dict[str, Tuple[str, ...]] = {
    "grow_fused": ("tree_device",),
    "histogram": ("hist_root", "hist_children", "hist_recompute"),
    "scan": ("find_best_split",),
    "partition": ("partition",),
    "replay": ("tree_replay",),
    "score_update": ("update_score",),
    "bagging": ("bagging",),
    "linear_fit": ("linear_fit",),
}

ATTRIBUTION_SCHEMA_VERSION = 1


def model_bytes_from_counters(counters: Mapping[str, int]) -> Dict[str, int]:
    """Total analytic HBM/ICI bytes per stage for one run, reconstructed
    from the gauges/counters the learners publish (PERF_NOTES models):

      compaction  2 x carry x waves   (the kernel reads AND writes the carry)
      histogram   hist_rows x bytes/row  (the rows-in-leaf ragged kernel)
      scan        scan_bytes x waves
      ici         ici_bytes x waves

    Missing counters contribute nothing — a serial-learner run (no device
    gauges) yields an empty model and the attribution falls back to pure
    measured fractions."""
    waves = int(counters.get("device_waves", 0))
    out: Dict[str, int] = {}
    carry = int(counters.get("device_carry_bytes_per_wave", 0))
    if carry and waves:
        out["compact"] = 2 * carry * waves
    hist_rows = int(counters.get("device_hist_rows", 0))
    row_bytes = int(counters.get("device_hist_bytes_per_row", 0))
    if hist_rows and row_bytes:
        out["histogram"] = hist_rows * row_bytes
    scan = int(counters.get("device_scan_bytes_per_wave", 0))
    if scan and waves:
        out["scan"] = scan * waves
    ici = int(counters.get("device_ici_bytes_per_wave", 0))
    if ici and waves:
        out["ici"] = ici * waves
    # out-of-core H2D traffic: the block cache counts every upload's bytes
    # directly (blocks x stream_block_bytes + per-split group rows), so the
    # counter IS the model — no waves multiplier
    h2d = int(counters.get("stream_h2d_bytes", 0))
    if h2d:
        out["stream_h2d"] = h2d
    return out


def attribution(totals: Mapping[str, float], counters: Mapping[str, int],
                total_s: Optional[float] = None,
                device_kind: str = "",
                include_static: bool = False) -> Dict[str, Any]:
    """Per-stage attribution report.

    totals:   timer label -> accumulated seconds (global_timer.totals or a
              snapshot / a telemetry session_end's ``timer_totals``)
    counters: global_timer counters (for the analytic byte model)
    total_s:  the wall to attribute against; defaults to the ``boosting``
              scope total (the whole training loop)
    Returns ``{"stages": {name: {...}}, "fractions_sum": ~1.0, ...}``;
    every stage carries ``wall_s`` and ``fraction``, device stages add
    ``model_bytes`` / ``model_s`` / ``drift_pct`` / ``roofline_frac``
    when the analytic model and bandwidth table cover them."""
    if total_s is None:
        total_s = float(totals.get("boosting", 0.0))
    walls: Dict[str, float] = {}
    for stage, labels in STAGE_LABELS.items():
        w = sum(float(totals.get(lbl, 0.0)) for lbl in labels)
        if w > 0.0:
            walls[stage] = w
    covered = sum(walls.values())
    if total_s <= 0.0:
        total_s = covered
    # nested scopes cannot overflow their parent, but when no parent scope
    # ran (direct learner drives in tests) covered IS the total
    if covered > total_s:
        total_s = covered
    model = model_bytes_from_counters(counters)
    bw = peak_bandwidth_bytes_per_s(device_kind)
    stages: Dict[str, Dict[str, Any]] = {}
    for stage, wall in sorted(walls.items(), key=lambda kv: -kv[1]):
        entry: Dict[str, Any] = {
            "wall_s": round(wall, 6),
            "fraction": round(wall / total_s, 6) if total_s else 0.0,
        }
        # the fused device stage owns every analytic component; host-driven
        # stages map 1:1 by name
        if stage == "grow_fused":
            comp = dict(model)
            comp.pop("stream_h2d", None)  # H2D is its own (overlapped) stage
            if comp:
                entry["model_components_bytes"] = comp
                m_bytes = sum(comp.values())
                entry["model_bytes"] = m_bytes
                _add_model_seconds(entry, m_bytes, wall, bw)
        elif stage in model:
            entry["model_bytes"] = model[stage]
            _add_model_seconds(entry, model[stage], wall, bw)
        stages[stage] = entry
    other = max(total_s - covered, 0.0)
    if total_s > 0.0 and other > 0.0:
        stages["other"] = {"wall_s": round(other, 6),
                           "fraction": round(other / total_s, 6)}
    if "stream_h2d" in model:
        # out-of-core block transfer: dispatched behind histogram compute
        # (streaming/learner.py double buffer), so its wall rides inside
        # stages already counted — fraction stays 0 and the stage is
        # excluded from the ~1.0 closure by construction
        h2d_wall = float(counters.get("stream_h2d_us", 0)) / 1e6
        entry = {"wall_s": round(h2d_wall, 6), "fraction": 0.0,
                 "overlapped": True, "model_bytes": model["stream_h2d"]}
        _add_model_seconds(entry, model["stream_h2d"], h2d_wall, bw)
        stages["stream_h2d"] = entry
    report: Dict[str, Any] = {
        "schema_version": ATTRIBUTION_SCHEMA_VERSION,
        "total_s": round(total_s, 6),
        "covered_s": round(covered, 6),
        "stages": stages,
        "fractions_sum": round(sum(s["fraction"] for s in stages.values()),
                               6) if stages else 0.0,
    }
    if bw is not None:
        report["peak_bw_bytes_per_s"] = bw
    if include_static:
        static = static_costs()
        if static:
            report["static"] = static
    return report


def _add_model_seconds(entry: Dict[str, Any], model_bytes: int,
                       wall_s: float, bw: Optional[float]) -> None:
    """Model-implied seconds at peak bandwidth, measured-vs-model drift,
    and the roofline fraction the stage actually achieved."""
    if not bw or model_bytes <= 0:
        return
    model_s = model_bytes / bw
    entry["model_s"] = round(model_s, 6)
    if wall_s > 0.0:
        entry["drift_pct"] = round((wall_s / model_s - 1.0) * 100.0, 1)
        entry["roofline_frac"] = round(model_s / wall_s, 4)
