"""Plotting utilities (matplotlib-based).

Counterpart of python-package/lightgbm/plotting.py: feature-importance bars,
recorded-metric curves, split-value histograms, and tree diagrams. Tree
plotting renders with matplotlib annotations instead of graphviz (not in the
image); dump_model's JSON structure is the shared input.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .basic import Booster
from .utils.log import LightGBMError


def _check_not_tuple_of_2_elements(obj: Any, obj_name: str) -> None:
    if not isinstance(obj, (list, tuple)) or len(obj) != 2:
        raise TypeError(f"{obj_name} must be a list/tuple of length 2.")


def _get_booster(booster) -> Booster:
    if isinstance(booster, Booster):
        return booster
    if hasattr(booster, "booster_"):
        return booster.booster_
    raise TypeError("booster must be Booster or LGBMModel.")


def plot_importance(booster, ax=None, height: float = 0.2,
                    xlim: Optional[Tuple] = None, ylim: Optional[Tuple] = None,
                    title: str = "Feature importance",
                    xlabel: str = "Feature importance",
                    ylabel: str = "Features",
                    importance_type: str = "auto",
                    max_num_features: Optional[int] = None,
                    ignore_zero: bool = True, figsize=None, dpi=None,
                    grid: bool = True, precision: int = 3, **kwargs):
    """Horizontal bar chart of split/gain importances (plotting.py:36)."""
    import matplotlib.pyplot as plt

    bst = _get_booster(booster)
    if importance_type == "auto":
        importance_type = "split"
    importance = bst.feature_importance(importance_type=importance_type)
    feature_name = bst.feature_name()
    if not len(importance):
        raise ValueError("Booster's feature_importance is empty.")
    tuples = sorted(zip(feature_name, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [x for x in tuples if x[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    labels, values = zip(*tuples) if tuples else ((), ())

    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y,
                f"{x:.{precision}f}" if importance_type == "gain" else str(x),
                va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
        ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
        ax.set_ylim(ylim)
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster: Union[Dict, Any], metric: Optional[str] = None,
                dataset_names: Optional[List[str]] = None, ax=None,
                xlim=None, ylim=None, title: str = "Metric during training",
                xlabel: str = "Iterations", ylabel: str = "@metric@",
                figsize=None, dpi=None, grid: bool = True):
    """Plot metric curves from record_evaluation results (plotting.py:193)."""
    import matplotlib.pyplot as plt

    if isinstance(booster, dict):
        eval_results = booster
    elif hasattr(booster, "evals_result_"):
        eval_results = booster.evals_result_
    else:
        raise TypeError(
            "booster must be dict (from record_evaluation) or LGBMModel.")
    if not eval_results:
        raise ValueError("eval results cannot be empty.")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    names = dataset_names or list(eval_results.keys())
    first = eval_results[names[0]]
    if metric is None:
        metric = list(first.keys())[0]
    for name in names:
        results = eval_results[name][metric]
        ax.plot(range(len(results)), results, label=name)
    ax.legend(loc="best")
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel.replace("@metric@", metric))
    ax.grid(grid)
    return ax


def plot_split_value_histogram(booster, feature: Union[int, str], bins=None,
                               ax=None, width_coef: float = 0.8, xlim=None,
                               ylim=None,
                               title: str = "Split value histogram for "
                                            "feature with @index/name@ @feature@",
                               xlabel: str = "Feature split value",
                               ylabel: str = "Count", figsize=None, dpi=None,
                               grid: bool = True):
    """Histogram of a feature's split thresholds (plotting.py:299)."""
    import matplotlib.pyplot as plt

    bst = _get_booster(booster)
    model = bst.dump_model()
    feature_name = bst.feature_name()
    if isinstance(feature, str):
        fidx = feature_name.index(feature)
        ftag = "name"
    else:
        fidx = int(feature)
        ftag = "index"
    values: List[float] = []

    def collect(node: Dict) -> None:
        if "split_feature" in node:
            if node["split_feature"] == fidx and node.get(
                    "decision_type") == "<=":
                values.append(node["threshold"])
            collect(node["left_child"])
            collect(node["right_child"])

    for tree in model["tree_info"]:
        collect(tree["tree_structure"])
    if not values:
        raise ValueError(
            f"Cannot plot split value histogram, "
            f"as feature {feature} was not used in splitting.")
    hist, bin_edges = np.histogram(values, bins=bins if bins else "auto")
    centers = (bin_edges[:-1] + bin_edges[1:]) / 2
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ax.bar(centers, hist,
           width=width_coef * (bin_edges[1] - bin_edges[0]))
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    title = title.replace("@index/name@", ftag).replace(
        "@feature@", str(feature))
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_tree(booster, tree_index: int = 0, ax=None, figsize=None, dpi=None,
              show_info: Optional[List[str]] = None,
              precision: int = 3, orientation: str = "horizontal", **kwargs):
    """Render one tree as a matplotlib annotation diagram (the reference
    renders via graphviz, plotting.py:606; same node content)."""
    import matplotlib.pyplot as plt

    bst = _get_booster(booster)
    model = bst.dump_model()
    if tree_index >= len(model["tree_info"]):
        raise IndexError("tree_index is out of range.")
    tree = model["tree_info"][tree_index]["tree_structure"]
    feature_name = bst.feature_name()

    # lay out leaves on one axis, depth on the other
    positions: Dict[int, Tuple[float, float]] = {}
    labels: Dict[int, str] = {}
    edges: List[Tuple[int, int, str]] = []
    counter = [0, 0.0]

    def walk(node: Dict, depth: int) -> int:
        nid = counter[0]
        counter[0] += 1
        if "split_feature" in node:
            lid = walk(node["left_child"], depth + 1)
            rid = walk(node["right_child"], depth + 1)
            x = (positions[lid][0] + positions[rid][0]) / 2
            positions[nid] = (x, -depth)
            f = feature_name[node["split_feature"]]
            labels[nid] = (f"{f}\n<= {node['threshold']:.{precision}g}\n"
                           f"gain: {node.get('split_gain', 0):.{precision}g}")
            edges.append((nid, lid, "yes"))
            edges.append((nid, rid, "no"))
        else:
            positions[nid] = (counter[1], -depth)
            counter[1] += 1.0
            labels[nid] = (f"leaf {node.get('leaf_index', 0)}\n"
                           f"{node.get('leaf_value', 0):.{precision}g}")
        return nid

    walk(tree, 0)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize or (12, 8), dpi=dpi)
    for parent, child, tag in edges:
        x0, y0 = positions[parent]
        x1, y1 = positions[child]
        ax.plot([x0, x1], [y0, y1], "-", color="gray", zorder=1)
        ax.annotate(tag, ((x0 + x1) / 2, (y0 + y1) / 2), fontsize=8,
                    color="blue")
    for nid, (x, y) in positions.items():
        ax.annotate(labels[nid], (x, y), ha="center", va="center",
                    bbox=dict(boxstyle="round", fc="lightyellow", ec="gray"),
                    fontsize=8, zorder=2)
    ax.axis("off")
    return ax
