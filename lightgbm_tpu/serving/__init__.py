"""Hardened prediction service over the device-resident inference engine.

Layers (each importable on its own):

    errors    typed failures with HTTP statuses
    registry  named models, checksum-verified atomic hot-swap, host path
    breaker   CLOSED -> DEGRADED -> OPEN -> HALF_OPEN degradation ladder
    batcher   micro-batching worker: coalesce, admit, shed, pad, dispatch
    service   in-process facade: validation, warmup, health, stats
    http      stdlib ThreadingHTTPServer front (/predict /models /healthz)

See docs/SERVING.md for the batching contract and operational semantics.
"""
from .batcher import MicroBatcher
from .breaker import CircuitBreaker
from .errors import (DeadlineExceeded, InvalidRequest, ModelLoadError,
                     ModelNotFound, Overloaded, ServiceClosed, ServingError)
from .http import ServingHTTPServer, serve
from .registry import ModelEntry, ModelRegistry
from .service import PredictionService

__all__ = [
    "CircuitBreaker", "DeadlineExceeded", "InvalidRequest", "MicroBatcher",
    "ModelEntry", "ModelLoadError", "ModelNotFound", "ModelRegistry",
    "Overloaded", "PredictionService", "ServiceClosed", "ServingError",
    "ServingHTTPServer", "serve",
]
