"""Micro-batching dispatch core: coalesce, bound, shed, degrade.

Concurrent requests for the same (model version, raw_score) are coalesced
by a single worker thread into one device dispatch, padded to the
power-of-two bucket sizes (`ops.partition.bucket_size`) the predict jit
cache already holds — so a steady request mix compiles each bucket once at
warmup and NEVER again under load, no matter how request sizes jitter.

Admission is bounded by total queued ROWS (not request count — one 4096-row
request occupies what 4096 single-row requests would): past the bound,
submit raises Overloaded WITHOUT enqueuing, so a flood cannot grow memory.
Each request may carry a deadline budget; expired requests are shed at
batch-assembly time — before any device dispatch — and a caller whose wait
runs out raises DeadlineExceeded immediately without blocking the batch
its rows ride in.

The breaker (serving/breaker.py) is consulted per batch: DEGRADED caps the
chunk rows, OPEN routes to the host-pinned predict path, and a device
dispatch that throws is retried on the host path in place — the batch's
callers still get bit-identical answers while the failure feeds the
breaker.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import telemetry, tracing
from ..ops.partition import bucket_size
from ..utils import faults
from ..utils.log import Log
from ..utils.timer import global_timer
from .breaker import CircuitBreaker, Decision
from .errors import (DeadlineExceeded, Overloaded, ServiceClosed,
                     ServingError)
from .registry import ModelEntry


class _Request:
    __slots__ = ("entry", "rows", "raw_score", "deadline", "event",
                 "result", "error", "cancelled", "t_submit", "t_submit_pc",
                 "span")

    def __init__(self, entry: ModelEntry, rows: np.ndarray, raw_score: bool,
                 deadline: Optional[float],
                 span: Optional[tracing.Span] = None) -> None:
        self.entry = entry
        self.rows = rows
        self.raw_score = raw_score
        self.deadline = deadline  # absolute monotonic, None = unbounded
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[ServingError] = None
        self.cancelled = False
        self.t_submit = time.monotonic()
        self.t_submit_pc = time.perf_counter()  # stage-mark clock basis
        self.span = span

    def key(self) -> Tuple[int, bool]:
        # entry identity, not name: a hot-swap mid-queue splits the batch,
        # so every response comes from the version it was admitted under
        return (id(self.entry), self.raw_score)


class MicroBatcher:
    def __init__(self, breaker: Optional[CircuitBreaker] = None,
                 max_batch_rows: int = 4096, max_queue_rows: int = 32768,
                 min_bucket: int = 256,
                 batch_window_s: float = 0.001) -> None:
        self.breaker = breaker or CircuitBreaker()
        self.max_batch_rows = bucket_size(max(1, max_batch_rows), 1)
        self.max_queue_rows = max_queue_rows
        self.min_bucket = bucket_size(max(1, min_bucket), 1)
        self.batch_window_s = batch_window_s
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._queued_rows = 0
        self._closed = False
        self._latencies_ms: deque = deque(maxlen=4096)
        # lifetime counters (instance-local: global_timer counters are
        # process-wide and shared across services/tests)
        self.n_requests = 0
        self.n_rows = 0
        self.n_batches = 0
        self.n_overloaded = 0
        self.n_deadline_shed = 0
        self.n_deadline_wait_expired = 0
        self.n_device_failures = 0
        self.n_host_chunks = 0
        self._worker = threading.Thread(
            target=self._run, name="lgbm-serve-batcher", daemon=True)
        self._worker.start()

    # -------------------------------------------------------------- submit

    def submit(self, entry: ModelEntry, rows: np.ndarray, raw_score: bool,
               timeout_s: Optional[float] = None,
               span: Optional[tracing.Span] = None) -> np.ndarray:
        """Enqueue one request and block until its batch answers, its
        deadline expires, or the service closes."""
        n = int(rows.shape[0])
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        req = _Request(entry, rows, raw_score, deadline, span=span)
        with self._lock:
            if self._closed:
                raise ServiceClosed("service is shutting down")
            if self._queued_rows + n > self.max_queue_rows:
                self.n_overloaded += 1
                global_timer.add_count("serve_overloaded", 1)
                if span is not None:
                    span.finish(terminal="rejected")
                raise Overloaded(
                    f"admission queue full ({self._queued_rows} rows "
                    f"queued, request adds {n}, limit "
                    f"{self.max_queue_rows}); retry with backoff")
            self._queue.append(req)
            self._queued_rows += n
            global_timer.set_count("serve_queue_depth", self._queued_rows)
            self._cond.notify_all()
        remaining = None
        if deadline is not None:
            remaining = max(0.0, deadline - time.monotonic())
        if not req.event.wait(remaining):
            req.cancelled = True  # worker skips it at assembly time
            self.n_deadline_wait_expired += 1
            global_timer.add_count("serve_deadline_expired", 1)
            if span is not None:
                span.add_stage("shed",
                               time.perf_counter() - req.t_submit_pc)
                span.finish(terminal="shed")
            raise DeadlineExceeded(
                f"deadline of {timeout_s:.3f}s expired while "
                f"{'queued' if req.result is None else 'in flight'}")
        if req.error is not None:
            raise req.error
        lat_ms = (time.monotonic() - req.t_submit) * 1000.0
        with self._lock:
            self._latencies_ms.append(lat_ms)
            self.n_requests += 1
            self.n_rows += n
        return req.result

    # -------------------------------------------------------------- worker

    def _shed_locked(self, now: float) -> None:
        """Drop cancelled/expired requests before they cost a dispatch."""
        live: deque = deque()
        for req in self._queue:
            expired = req.deadline is not None and now >= req.deadline
            if req.cancelled or expired:
                self._queued_rows -= int(req.rows.shape[0])
                if req.span is not None:
                    # terminal stage: shed requests account their whole
                    # queued life to `shed` (extends the PR-9 exact-
                    # accounting invariant to the trace layer)
                    req.span.add_stage(
                        "shed", time.perf_counter() - req.t_submit_pc)
                    req.span.finish(terminal="shed")
                if not req.cancelled:
                    req.error = DeadlineExceeded(
                        "deadline expired before dispatch; request shed "
                        "from the queue")
                    req.event.set()
                self.n_deadline_shed += 1
                global_timer.add_count("serve_deadline_shed", 1)
            else:
                live.append(req)
        self._queue = live
        global_timer.set_count("serve_queue_depth", self._queued_rows)

    def _collect(self) -> List[_Request]:
        """Pull one batch of same-key requests; [] means 'loop again'."""
        with self._lock:
            if not self._queue:
                if self._closed:
                    return []
                self._cond.wait(0.05)
            self._shed_locked(time.monotonic())
            if not self._queue:
                return []
            if (self.batch_window_s > 0
                    and self._queued_rows < self.min_bucket):
                # one coalescing beat: let concurrent submitters land so
                # they share the dispatch instead of each paying their own
                self._cond.wait(self.batch_window_s)
                self._shed_locked(time.monotonic())
                if not self._queue:
                    return []
            key = self._queue[0].key()
            taken: List[_Request] = []
            rows = 0
            keep: deque = deque()
            for req in self._queue:
                n = int(req.rows.shape[0])
                # the head is always taken — even oversized (the dispatch
                # loop chunks it) — so assembly can never spin on it
                if req.key() == key and (not taken
                                         or rows + n <= self.max_batch_rows):
                    taken.append(req)
                    rows += n
                else:
                    keep.append(req)
            self._queue = keep
            self._queued_rows -= rows
            global_timer.set_count("serve_queue_depth", self._queued_rows)
            return taken

    def _run(self) -> None:
        while True:
            with self._lock:
                if self._closed:
                    return
            batch = self._collect()
            if not batch:
                continue
            try:
                self._dispatch(batch)
            except BaseException as exc:  # worker must outlive any batch
                for req in batch:
                    req.error = ServingError(f"prediction failed: {exc}")
                    req.event.set()
                Log.warning("serving: batch dispatch error: %s", exc)
                tracing.note("batcher_exception", error=repr(exc)[:400],
                             requests=len(batch))
                tracing.dump_flight("batcher_exception")

    def _pad(self, chunk: np.ndarray, cap: int) -> np.ndarray:
        """Pad to the power-of-two bucket the jit cache already holds.

        Exact bucket fit is ZERO-COPY: a float32 C-contiguous chunk whose
        row count already equals its bucket (a full max-size chunk, or a
        request sized to a warmed bucket — the binary wire path decodes
        straight into such views) is dispatched as-is. Only a ragged tail
        pays the pad allocation."""
        n = chunk.shape[0]
        target = min(bucket_size(n, min(self.min_bucket, cap)), cap)
        if target <= n:
            if chunk.dtype == np.float32 and chunk.flags["C_CONTIGUOUS"]:
                return chunk
            return np.ascontiguousarray(chunk, dtype=np.float32)
        padded = np.zeros((target, chunk.shape[1]), dtype=np.float32)
        padded[:n] = chunk
        return padded

    def _predict_chunk(self, entry: ModelEntry, chunk: np.ndarray,
                       raw_score: bool, decision: Decision, cap: int,
                       stages: Dict[str, float]) -> np.ndarray:
        t = time.perf_counter()
        padded = self._pad(chunk, cap)
        t_dev = time.perf_counter()
        stages["assembly"] += t_dev - t
        if decision.use_host:
            out = entry.predict_host(padded, raw_score)
            self.breaker.on_success(was_host=True, entry=entry.name)
            self.n_host_chunks += 1
        else:
            try:
                faults.on_serve_dispatch()
                out = entry.predict_device(padded, raw_score)
                self.breaker.on_success(entry=entry.name)
            except Exception as exc:
                self.breaker.on_failure(exc, entry=entry.name)
                self.n_device_failures += 1
                global_timer.add_count("serve_dispatch_failures", 1)
                Log.warning("serving: device dispatch failed (%s); "
                            "retrying this chunk on the host path", exc)
                if telemetry.enabled():
                    telemetry.emit("serve_dispatch_failed", error=str(exc),
                                   rows=int(chunk.shape[0]))
                out = entry.predict_host(padded, raw_score)
                self.n_host_chunks += 1
        # `device` covers the model compute wherever it ran (host path
        # when the breaker is open); `d2h` is the materialize + unpad —
        # on engines whose predict already returns host arrays it reads
        # near zero, which is itself a finding the gauge makes visible
        t_d2h = time.perf_counter()
        stages["device"] += t_d2h - t_dev
        res = np.asarray(out)[: chunk.shape[0]]
        stages["d2h"] += time.perf_counter() - t_d2h
        return res

    def _dispatch(self, batch: List[_Request]) -> None:
        entry = batch[0].entry
        raw_score = batch[0].raw_score
        t_asm = time.perf_counter()
        batch_span = tracing.start_span("serve_batch", record_stats=False)
        for req in batch:
            if req.span is not None:
                # queue_wait: submit to the moment its batch starts work
                req.span.add_stage("queue_wait", t_asm - req.t_submit_pc)
                batch_span.link(req.span.span_id)
        stages = {"assembly": 0.0, "device": 0.0, "d2h": 0.0}
        X = (batch[0].rows if len(batch) == 1
             else np.concatenate([r.rows for r in batch], axis=0))
        stages["assembly"] += time.perf_counter() - t_asm
        n = int(X.shape[0])
        # per-entry breaker shard: one tenant's faulting model sheds ITS
        # load to the host path without opening the breaker for the fleet
        decision = self.breaker.decide(entry.name)
        cap = self.max_batch_rows
        if decision.max_rows is not None:
            cap = min(cap, bucket_size(max(1, decision.max_rows), 1))
        outs = []
        with global_timer.scope("serve_batch"):
            for start in range(0, n, cap):
                outs.append(self._predict_chunk(
                    entry, X[start:start + cap], raw_score, decision, cap,
                    stages))
        t = time.perf_counter()
        out = outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)
        stages["d2h"] += time.perf_counter() - t
        self.n_batches += 1
        global_timer.add_count("serve_batches", 1)
        if telemetry.enabled():
            telemetry.emit("serve_batch", model=entry.name,
                           version=entry.version, rows=n,
                           requests=len(batch), host=decision.use_host)
        pos = 0
        for req in batch:
            k = int(req.rows.shape[0])
            # a member rides the whole batch, so the batch's stage walls
            # ARE its stage walls (the cost of coalescing is queue_wait)
            if req.span is not None:
                for stage, dur in stages.items():
                    req.span.add_stage(stage, dur)
            req.result = out[pos:pos + k]
            pos += k
            req.event.set()
        for stage, dur in stages.items():
            batch_span.add_stage(stage, dur)
        batch_span.attrs.update(rows=n, requests=len(batch),
                                model=entry.name, version=entry.version,
                                host=decision.use_host)
        batch_span.finish()

    # --------------------------------------------------------------- stats

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            lats = sorted(self._latencies_ms)
            stats = {
                "queue_rows": self._queued_rows,
                "requests": self.n_requests,
                "rows": self.n_rows,
                "batches": self.n_batches,
                "overloaded": self.n_overloaded,
                "deadline_shed": self.n_deadline_shed,
                "deadline_wait_expired": self.n_deadline_wait_expired,
                "device_failures": self.n_device_failures,
                "host_chunks": self.n_host_chunks,
            }
        if lats:
            stats["p50_ms"] = lats[len(lats) // 2]
            stats["p99_ms"] = lats[min(len(lats) - 1,
                                       int(len(lats) * 0.99))]
        return stats

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._cond.notify_all()
        self._worker.join(timeout=5.0)
        with self._lock:
            for req in self._queue:
                req.error = ServiceClosed("service is shutting down")
                req.event.set()
            self._queue.clear()
            self._queued_rows = 0
            global_timer.set_count("serve_queue_depth", 0)
