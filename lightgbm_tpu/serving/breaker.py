"""Graceful-degradation circuit breaker for the serving dispatch path.

Four states, strictly ordered by how much they trust the accelerator:

    CLOSED     normal dispatch, full-size chunks
    DEGRADED   device dispatch with a reduced chunk cap — entered on
               pressure SIGNALS (jit recompile churn, HBM high-water from
               the telemetry watchers) rather than hard failures; smaller
               chunks reuse the coldest part of the jit cache and shrink
               per-dispatch HBM footprint
    OPEN       repeated dispatch failures: every request runs the
               host-pinned predict path (registry.ModelEntry.predict_host)
               — bit-identical results, no accelerator contact
    HALF_OPEN  cooldown expired: the next dispatch is a device PROBE; one
               failure re-opens, `probe_successes` straight successes close

All transitions are driven by the single batcher worker calling
`decide()` / `on_success()` / `on_failure()` around each dispatch, plus
`note_signals()` fed from telemetry.signals(); every method is locked so
health endpoints can read state from other threads. The state code is
published as the `serve_breaker_state` gauge (0=closed 1=degraded 2=open
3=half-open).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

from .. import telemetry, tracing
from ..utils.log import Log
from ..utils.timer import global_timer

CLOSED = "closed"
DEGRADED = "degraded"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_CODE = {CLOSED: 0, DEGRADED: 1, OPEN: 2, HALF_OPEN: 3}


class Decision:
    """What the worker should do with the next dispatch."""

    __slots__ = ("use_host", "max_rows", "probe")

    def __init__(self, use_host: bool, max_rows: Optional[int],
                 probe: bool) -> None:
        self.use_host = use_host
        self.max_rows = max_rows  # chunk-row cap, None = no extra cap
        self.probe = probe


class CircuitBreaker:
    def __init__(self, fail_threshold: int = 3, recovery_successes: int = 8,
                 probe_successes: int = 3, cooldown_s: float = 2.0,
                 degraded_rows: int = 256,
                 compile_churn_limit: int = 8,
                 hbm_limit_bytes: int = 0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.fail_threshold = max(1, fail_threshold)
        self.recovery_successes = max(1, recovery_successes)
        self.probe_successes = max(1, probe_successes)
        self.cooldown_s = cooldown_s
        self.degraded_rows = degraded_rows
        self.compile_churn_limit = compile_churn_limit
        self.hbm_limit_bytes = hbm_limit_bytes
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._fail_streak = 0
        self._success_streak = 0
        self._opened_at = 0.0
        self._last_compiles: Optional[int] = None
        self.transitions = 0
        # unconditional transition history: a breaker flap must leave a
        # trace even with telemetry off (surfaced in info() -> /statz,
        # mirrored into the flight recorder)
        self.last_transitions: deque = deque(maxlen=16)
        self._pending_dump: Optional[Dict[str, Any]] = None
        global_timer.set_count("serve_breaker_state", 0)

    # --------------------------------------------------------------- state

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _move(self, new_state: str, why: str) -> None:
        # callers hold self._lock
        if new_state == self._state:
            return
        old = self._state
        self._state = new_state
        self._fail_streak = 0
        self._success_streak = 0
        self.transitions += 1
        if new_state == OPEN:
            self._opened_at = self._clock()
        global_timer.set_count("serve_breaker_state", _STATE_CODE[new_state])
        Log.warning("serving: breaker %s -> %s (%s)", old, new_state, why)
        self.last_transitions.append({
            "old": old, "new": new_state, "reason": why,
            "wall_time": time.time(), "transition": self.transitions})
        tracing.note("breaker_transition", old=old, new=new_state, reason=why)
        if new_state == OPEN:
            # the postmortem dump does I/O — defer it until the caller
            # releases self._lock (see _maybe_dump)
            self._pending_dump = {
                "breaker": {"state": new_state, "reason": why,
                            "fail_streak": self._fail_streak,
                            "transitions": self.transitions}}
        if telemetry.enabled():
            telemetry.emit("breaker_transition", old=old, new=new_state,
                           reason=why)

    def _maybe_dump(self) -> None:
        """Fire the deferred breaker-open flight dump outside the lock."""
        with self._lock:
            pending, self._pending_dump = self._pending_dump, None
        if pending is not None:
            tracing.dump_flight("breaker_open", extra=pending)

    # ------------------------------------------------------------ dispatch

    def decide(self) -> Decision:
        """Routing for the next dispatch. In OPEN, a lapsed cooldown flips
        to HALF_OPEN here so the very next batch is the probe."""
        with self._lock:
            if self._state == OPEN:
                if self._clock() - self._opened_at >= self.cooldown_s:
                    self._move(HALF_OPEN, "cooldown elapsed, probing device")
                else:
                    return Decision(True, None, False)
            if self._state == HALF_OPEN:
                return Decision(False, self.degraded_rows, True)
            if self._state == DEGRADED:
                return Decision(False, self.degraded_rows, False)
            return Decision(False, None, False)

    def on_success(self, was_host: bool = False) -> None:
        if was_host:
            return  # host fallback says nothing about device health
        with self._lock:
            self._fail_streak = 0
            self._success_streak += 1
            if (self._state == HALF_OPEN
                    and self._success_streak >= self.probe_successes):
                self._move(CLOSED, f"{self.probe_successes} probe "
                           "dispatches succeeded")
            elif (self._state == DEGRADED
                    and self._success_streak >= self.recovery_successes):
                self._move(CLOSED, f"{self.recovery_successes} clean "
                           "dispatches at reduced chunk size")

    def on_failure(self, exc: BaseException) -> None:
        with self._lock:
            self._success_streak = 0
            self._fail_streak += 1
            if self._state == HALF_OPEN:
                self._move(OPEN, f"probe dispatch failed: {exc}")
            elif self._fail_streak >= self.fail_threshold:
                self._move(OPEN, f"{self._fail_streak} consecutive "
                           f"dispatch failures (last: {exc})")
        self._maybe_dump()

    # ------------------------------------------------------------- signals

    def note_signals(self, signals: Dict[str, int]) -> None:
        """Pressure signals from telemetry.signals(): a recompile burst or
        an HBM high-water breach degrades a CLOSED breaker (smaller chunks)
        without waiting for an outright failure."""
        compiles = int(signals.get("compiles", 0))
        hbm = int(signals.get("hbm_high_water_bytes", 0))
        with self._lock:
            prev = self._last_compiles
            self._last_compiles = compiles
            if self._state != CLOSED:
                return
            if prev is not None and compiles - prev >= self.compile_churn_limit:
                self._move(DEGRADED, f"jit recompile churn: {compiles - prev} "
                           "compiles since last check")
            elif self.hbm_limit_bytes and hbm >= self.hbm_limit_bytes:
                self._move(DEGRADED, f"HBM high-water {hbm} >= limit "
                           f"{self.hbm_limit_bytes}")

    def rebaseline(self, signals: Dict[str, int]) -> None:
        """Reset the compile-churn baseline — called after a model load,
        whose warmup compiles are expected and must not read as churn."""
        with self._lock:
            self._last_compiles = int(signals.get("compiles", 0))

    def info(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self._state,
                "fail_streak": self._fail_streak,
                "success_streak": self._success_streak,
                "transitions": self.transitions,
                "degraded_rows": self.degraded_rows,
                "last_transitions": list(self.last_transitions),
            }
