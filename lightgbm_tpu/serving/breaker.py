"""Graceful-degradation circuit breaker for the serving dispatch path.

Four states, strictly ordered by how much they trust the accelerator:

    CLOSED     normal dispatch, full-size chunks
    DEGRADED   device dispatch with a reduced chunk cap — entered on
               pressure SIGNALS (jit recompile churn, HBM high-water from
               the telemetry watchers) rather than hard failures; smaller
               chunks reuse the coldest part of the jit cache and shrink
               per-dispatch HBM footprint
    OPEN       repeated dispatch failures: every request runs the
               host-pinned predict path (registry.ModelEntry.predict_host)
               — bit-identical results, no accelerator contact
    HALF_OPEN  cooldown expired: the next dispatch is a device PROBE; one
               failure re-opens, `probe_successes` straight successes close

State is sharded PER MODEL ENTRY: `decide` / `on_success` / `on_failure`
take the entry name, and each name walks the state machine independently,
so one tenant whose model keeps faulting sheds ITS OWN load to the host
path while every other entry stays on full-size device dispatch. The
bare-name default shard ("") keeps the original single-breaker behaviour
for direct callers that never pass an entry. Aggregate views — the
`state` property, the top of `info()`, the `serve_breaker_state` gauge —
report the WORST shard, so health endpoints stay one-glance.

All transitions are driven by the batcher workers calling
`decide()` / `on_success()` / `on_failure()` around each dispatch, plus
`note_signals()` fed from telemetry.signals(); every method is locked so
health endpoints can read state from other threads. The worst-shard state
code is published as the `serve_breaker_state` gauge (0=closed 1=degraded
2=open 3=half-open).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

from .. import telemetry, tracing
from ..utils.log import Log
from ..utils.timer import global_timer

CLOSED = "closed"
DEGRADED = "degraded"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_CODE = {CLOSED: 0, DEGRADED: 1, OPEN: 2, HALF_OPEN: 3}
# ordering for the aggregate worst-shard view: how little the state
# trusts the device (half-open outranks degraded: it is mid-outage)
_SEVERITY = {CLOSED: 0, DEGRADED: 1, HALF_OPEN: 2, OPEN: 3}

DEFAULT_ENTRY = ""


class Decision:
    """What the worker should do with the next dispatch."""

    __slots__ = ("use_host", "max_rows", "probe")

    def __init__(self, use_host: bool, max_rows: Optional[int],
                 probe: bool) -> None:
        self.use_host = use_host
        self.max_rows = max_rows  # chunk-row cap, None = no extra cap
        self.probe = probe


class _Shard:
    """Per-entry state-machine variables (all mutated under the breaker
    lock — a shard has no lock of its own)."""

    __slots__ = ("state", "fail_streak", "success_streak", "opened_at")

    def __init__(self) -> None:
        self.state = CLOSED
        self.fail_streak = 0
        self.success_streak = 0
        self.opened_at = 0.0


class CircuitBreaker:
    def __init__(self, fail_threshold: int = 3, recovery_successes: int = 8,
                 probe_successes: int = 3, cooldown_s: float = 2.0,
                 degraded_rows: int = 256,
                 compile_churn_limit: int = 8,
                 hbm_limit_bytes: int = 0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.fail_threshold = max(1, fail_threshold)
        self.recovery_successes = max(1, recovery_successes)
        self.probe_successes = max(1, probe_successes)
        self.cooldown_s = cooldown_s
        self.degraded_rows = degraded_rows
        self.compile_churn_limit = compile_churn_limit
        self.hbm_limit_bytes = hbm_limit_bytes
        self._clock = clock
        self._lock = threading.Lock()
        self._shards: Dict[str, _Shard] = {DEFAULT_ENTRY: _Shard()}
        self._last_compiles: Optional[int] = None
        self.transitions = 0
        # unconditional transition history: a breaker flap must leave a
        # trace even with telemetry off (surfaced in info() -> /statz,
        # mirrored into the flight recorder)
        self.last_transitions: deque = deque(maxlen=16)
        self._pending_dump: Optional[Dict[str, Any]] = None
        global_timer.set_count("serve_breaker_state", 0)

    # --------------------------------------------------------------- state

    def _shard(self, entry: str) -> _Shard:
        # callers hold self._lock
        sh = self._shards.get(entry)
        if sh is None:
            sh = self._shards[entry] = _Shard()
        return sh

    def _worst(self) -> _Shard:
        # callers hold self._lock
        return max(self._shards.values(), key=lambda s: _SEVERITY[s.state])

    @property
    def state(self) -> str:
        """Aggregate: the worst shard's state."""
        with self._lock:
            return self._worst().state

    def register_entry(self, entry: str) -> None:
        """Create the entry's shard (no-op if present) — the service calls
        this at model load so pressure signals observed before the first
        request still land on the entry."""
        with self._lock:
            self._shard(entry)

    def forget_entry(self, entry: str) -> None:
        """Drop an unloaded entry's shard so its terminal state cannot pin
        the aggregate view (the default shard is never dropped)."""
        if entry == DEFAULT_ENTRY:
            return
        with self._lock:
            self._shards.pop(entry, None)
            code = _STATE_CODE[self._worst().state]
        global_timer.set_count("serve_breaker_state", code)

    def _move(self, entry: str, sh: _Shard, new_state: str, why: str) -> None:
        # callers hold self._lock
        if new_state == sh.state:
            return
        old = sh.state
        sh.state = new_state
        sh.fail_streak = 0
        sh.success_streak = 0
        self.transitions += 1
        if new_state == OPEN:
            sh.opened_at = self._clock()
        global_timer.set_count("serve_breaker_state",
                               _STATE_CODE[self._worst().state])
        label = f"entry {entry!r}" if entry else "default entry"
        Log.warning("serving: breaker[%s] %s -> %s (%s)",
                    entry or "-", old, new_state, why)
        self.last_transitions.append({
            "old": old, "new": new_state, "reason": why, "entry": entry,
            "wall_time": time.time(), "transition": self.transitions})
        tracing.note("breaker_transition", old=old, new=new_state,
                     reason=why, entry=entry)
        if new_state == OPEN:
            # the postmortem dump does I/O — defer it until the caller
            # releases self._lock (see _maybe_dump)
            self._pending_dump = {
                "breaker": {"state": new_state, "reason": why,
                            "entry": entry, "label": label,
                            "transitions": self.transitions}}
        if telemetry.enabled():
            telemetry.emit("breaker_transition", old=old, new=new_state,
                           reason=why, entry=entry)

    def _maybe_dump(self) -> None:
        """Fire the deferred breaker-open flight dump outside the lock."""
        with self._lock:
            pending, self._pending_dump = self._pending_dump, None
        if pending is not None:
            tracing.dump_flight("breaker_open", extra=pending)

    # ------------------------------------------------------------ dispatch

    def decide(self, entry: str = DEFAULT_ENTRY) -> Decision:
        """Routing for the entry's next dispatch. In OPEN, a lapsed
        cooldown flips the shard to HALF_OPEN here so the very next batch
        is the probe."""
        with self._lock:
            sh = self._shard(entry)
            if sh.state == OPEN:
                if self._clock() - sh.opened_at >= self.cooldown_s:
                    self._move(entry, sh, HALF_OPEN,
                               "cooldown elapsed, probing device")
                else:
                    return Decision(True, None, False)
            if sh.state == HALF_OPEN:
                return Decision(False, self.degraded_rows, True)
            if sh.state == DEGRADED:
                return Decision(False, self.degraded_rows, False)
            return Decision(False, None, False)

    def on_success(self, was_host: bool = False,
                   entry: str = DEFAULT_ENTRY) -> None:
        if was_host:
            return  # host fallback says nothing about device health
        with self._lock:
            sh = self._shard(entry)
            sh.fail_streak = 0
            sh.success_streak += 1
            if (sh.state == HALF_OPEN
                    and sh.success_streak >= self.probe_successes):
                self._move(entry, sh, CLOSED, f"{self.probe_successes} "
                           "probe dispatches succeeded")
            elif (sh.state == DEGRADED
                    and sh.success_streak >= self.recovery_successes):
                self._move(entry, sh, CLOSED, f"{self.recovery_successes} "
                           "clean dispatches at reduced chunk size")

    def on_failure(self, exc: BaseException,
                   entry: str = DEFAULT_ENTRY) -> None:
        with self._lock:
            sh = self._shard(entry)
            sh.success_streak = 0
            sh.fail_streak += 1
            if sh.state == HALF_OPEN:
                self._move(entry, sh, OPEN, f"probe dispatch failed: {exc}")
            elif sh.fail_streak >= self.fail_threshold:
                self._move(entry, sh, OPEN, f"{sh.fail_streak} consecutive "
                           f"dispatch failures (last: {exc})")
        self._maybe_dump()

    # ------------------------------------------------------------- signals

    def note_signals(self, signals: Dict[str, int]) -> None:
        """Pressure signals from telemetry.signals(): a recompile burst or
        an HBM high-water breach degrades every CLOSED shard (smaller
        chunks) without waiting for an outright failure — the signals are
        process-wide, so no single entry can be blamed. When named shards
        exist the default shard is left alone: it carries no traffic to
        recover through, and the aggregate view must not stay pinned at
        DEGRADED after every live entry has recovered."""
        compiles = int(signals.get("compiles", 0))
        hbm = int(signals.get("hbm_high_water_bytes", 0))
        with self._lock:
            prev = self._last_compiles
            self._last_compiles = compiles
            churn = (prev is not None
                     and compiles - prev >= self.compile_churn_limit)
            pressure = (self.hbm_limit_bytes
                        and hbm >= self.hbm_limit_bytes)
            if not churn and not pressure:
                return
            named = [e for e in self._shards if e != DEFAULT_ENTRY]
            for entry in (named or [DEFAULT_ENTRY]):
                sh = self._shards[entry]
                if sh.state != CLOSED:
                    continue
                if churn:
                    self._move(entry, sh, DEGRADED, "jit recompile churn: "
                               f"{compiles - prev} compiles since last check")
                else:
                    self._move(entry, sh, DEGRADED,
                               f"HBM high-water {hbm} >= limit "
                               f"{self.hbm_limit_bytes}")

    def rebaseline(self, signals: Dict[str, int]) -> None:
        """Reset the compile-churn baseline — called after a model load,
        whose warmup compiles are expected and must not read as churn."""
        with self._lock:
            self._last_compiles = int(signals.get("compiles", 0))

    def info(self) -> Dict[str, Any]:
        with self._lock:
            worst = self._worst()
            out = {
                "state": worst.state,
                "fail_streak": worst.fail_streak,
                "success_streak": worst.success_streak,
                "transitions": self.transitions,
                "degraded_rows": self.degraded_rows,
                "last_transitions": list(self.last_transitions),
            }
            entries = {e: {"state": sh.state,
                           "fail_streak": sh.fail_streak,
                           "success_streak": sh.success_streak}
                       for e, sh in self._shards.items() if e != DEFAULT_ENTRY}
            if entries:
                out["entries"] = entries
            return out
