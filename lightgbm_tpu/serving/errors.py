"""Typed serving errors, each carrying its HTTP status.

The reference's C API reports every failure as -1 + a thread-local string
(LGBM_GetLastError); a long-lived prediction service needs callers to
distinguish "back off" (Overloaded) from "your request is malformed"
(InvalidRequest) from "you waited too long" (DeadlineExceeded) without
string-matching. Every error maps to one HTTP status in serving/http.py and
is importable for in-process callers; nothing here subclasses
LightGBMError, so a service embedded in a training process can catch
serving failures without swallowing training fatals.
"""
from __future__ import annotations


class ServingError(Exception):
    """Base of every typed serving failure."""

    status = 500
    code = "internal_error"


class InvalidRequest(ServingError):
    """Malformed payload: ragged rows, wrong feature count, oversize batch,
    or (opt-in per model) non-finite values — named column included. Always
    raised at the service boundary, never after a device dispatch."""

    status = 400
    code = "invalid_request"


class ModelNotFound(ServingError):
    """No model registered under the requested name."""

    status = 404
    code = "model_not_found"


class ModelLoadError(ServingError):
    """A staged upload failed verification (checksum mismatch, damaged
    sidecar, unparseable model text). The previously serving version — if
    any — is untouched."""

    status = 400
    code = "model_load_error"


class Overloaded(ServingError):
    """Admission queue full: the request was rejected WITHOUT being
    enqueued (bounded memory under flood). HTTP surface: 429 + Retry-After."""

    status = 429
    code = "overloaded"


class DeadlineExceeded(ServingError):
    """The request's deadline budget expired — either shed from the queue
    before device dispatch, or still in flight when the caller's wait ran
    out. The batch it rode in is never blocked on it."""

    status = 504
    code = "deadline_exceeded"


class ServiceClosed(ServingError):
    """The service is shutting down; pending and new requests fail fast."""

    status = 503
    code = "service_closed"
