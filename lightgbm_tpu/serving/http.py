"""Stdlib HTTP front for PredictionService.

ThreadingHTTPServer (one thread per connection) is deliberate: request
threads only validate + enqueue + wait, so the thread-per-connection model
costs idle waiters, not device contention — every dispatch still funnels
through the batcher's single worker. No framework, no new dependency.

    POST   /predict          {"model", "rows", "raw_score"?, "timeout_ms"?}
                             Content-Type negotiated: application/json (the
                             compatibility path, bit-identical to before) or
                             application/x-lgbm-wire (serving/wire.py binary
                             framing — zero-copy numpy decode, raw float32
                             response block)
    GET    /models           registered models + versions
    POST   /models           {"name", "path"|"model_str", "expected_sha256"?,
                              "reject_nonfinite"?}  -> staged verified swap
    DELETE /models/<name>    unload
    GET    /healthz          liveness + breaker/queue detail (always 200)
    GET    /readyz           200 once a model is loaded, else 503
    GET    /statz            batcher/breaker/registry counters + the
                             per-stage request-path quantiles
    GET    /metrics          Prometheus text exposition (exposition.py):
                             telemetry signals + global_timer counters +
                             the numeric /statz figures as serve_* gauges
    GET    /debug/flight     on-demand flight-recorder dump (JSON; also
                             written to the flight dir when one resolves)

Every error is JSON `{"error": <code>, "detail": <msg>}` with the typed
status from serving/errors.py; Overloaded responses carry Retry-After.

Trace context: /predict honors an inbound W3C ``traceparent`` header
(malformed ones start a fresh trace, per spec), threads the request span
through the batcher stage marks, and echoes a ``traceparent`` naming the
request's own span id on the success response.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

from .. import tracing
from ..utils.log import Log
from . import wire
from .errors import InvalidRequest, Overloaded, ServingError
from .service import PredictionService

MAX_BODY_BYTES = 64 * 1024 * 1024


class ServingHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    # socketserver's default listen backlog is 5: a fleet of clients
    # connecting at once gets connection RESETS, not queueing. Size the
    # backlog for a connection storm instead.
    request_queue_size = 128

    def __init__(self, service: PredictionService, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        super().__init__((host, port), _Handler)
        self.service = service

    @property
    def port(self) -> int:
        return self.server_address[1]


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # Nagle + delayed-ACK interact badly with the header/body write pair
    # on keep-alive connections: a closed-loop client sees ~40 ms stalls
    # per response. Predictions are latency-sensitive; flush immediately.
    disable_nagle_algorithm = True

    # BaseHTTPRequestHandler logs every request to stderr by default;
    # route through the package logger at debug level instead
    def log_message(self, fmt: str, *args: Any) -> None:
        Log.debug("serving-http: " + fmt % args)

    @property
    def service(self) -> PredictionService:
        return self.server.service  # type: ignore[attr-defined]

    # ---------------------------------------------------------------- io

    def _send_json(self, status: int, payload: Dict[str, Any],
                   headers: Optional[Dict[str, str]] = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, status: int, body: str, content_type: str) -> None:
        raw = body.encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _send_error(self, exc: Exception) -> None:
        if isinstance(exc, ServingError):
            headers = {"Retry-After": "1"} if isinstance(exc, Overloaded) \
                else None
            self._send_json(exc.status,
                            {"error": exc.code, "detail": str(exc)}, headers)
        else:
            self._send_json(500, {"error": "internal_error",
                                  "detail": str(exc)})

    def _send_wire(self, status: int, body: bytes,
                   headers: Optional[Dict[str, str]] = None) -> None:
        self.send_response(status)
        self.send_header("Content-Type", wire.CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> bytes:
        length = int(self.headers.get("Content-Length") or 0)
        if length <= 0:
            raise InvalidRequest("missing request body")
        if length > MAX_BODY_BYTES:
            raise InvalidRequest(
                f"request body of {length} bytes exceeds the "
                f"{MAX_BODY_BYTES}-byte limit")
        return self.rfile.read(length)

    def _read_json(self) -> Dict[str, Any]:
        try:
            payload = json.loads(self._read_body())
        except (ValueError, UnicodeDecodeError) as exc:
            raise InvalidRequest(f"body is not valid JSON: {exc}")
        if not isinstance(payload, dict):
            raise InvalidRequest("body must be a JSON object")
        return payload

    # ------------------------------------------------------------ routing

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler API)
        try:
            if self.path == "/healthz":
                self._send_json(200, self.service.healthz())
            elif self.path == "/readyz":
                ready = self.service.readyz()
                self._send_json(200 if ready["ready"] else 503, ready)
            elif self.path == "/statz":
                self._send_json(200, self.service.stats())
            elif self.path == "/models":
                self._send_json(200, {"models": self.service.models()})
            elif self.path == "/metrics":
                self._metrics()
            elif self.path == "/debug/flight":
                self._send_json(200, tracing.build_dump("debug_endpoint"))
                tracing.dump_flight("debug_endpoint", force=True)
            else:
                self._send_json(404, {"error": "not_found",
                                      "detail": self.path})
        except Exception as exc:
            self._send_error(exc)

    def do_POST(self) -> None:  # noqa: N802
        try:
            if self.path == "/predict":
                self._predict()
            elif self.path == "/models":
                self._load_model()
            else:
                self._send_json(404, {"error": "not_found",
                                      "detail": self.path})
        except Exception as exc:
            self._send_error(exc)

    def do_DELETE(self) -> None:  # noqa: N802
        try:
            if self.path.startswith("/models/"):
                name = self.path[len("/models/"):]
                if self.service.unload_model(name):
                    self._send_json(200, {"unloaded": name})
                else:
                    self._send_json(404, {"error": "model_not_found",
                                          "detail": name})
            else:
                self._send_json(404, {"error": "not_found",
                                      "detail": self.path})
        except Exception as exc:
            self._send_error(exc)

    # ----------------------------------------------------------- handlers

    def _predict(self) -> None:
        ctype = (self.headers.get("Content-Type") or "").split(";")[0].strip()
        if ctype == wire.CONTENT_TYPE:
            self._predict_wire()
        else:
            self._predict_json()

    def _predict_wire(self) -> None:
        """Binary fast path: one frombuffer decode, no float text on either
        leg. Error responses stay JSON (typed status + error body) so the
        client branches on the response Content-Type."""
        t_parse = time.perf_counter()
        body = self._read_body()
        dec = wire.decode_request(body)
        # the in-frame traceparent wins over the HTTP header: the frame is
        # the unit a wire client retries/forwards, so its context travels
        # with it through any proxy that re-writes headers
        span = tracing.start_span(
            "serve_request",
            traceparent=dec.traceparent or self.headers.get("traceparent"))
        try:
            timeout_s = (dec.timeout_ms / 1000.0
                         if dec.timeout_ms is not None else None)
            span.add_stage("parse", time.perf_counter() - t_parse)
            version = self.service.registry.get(dec.model).version
            t0 = time.monotonic()
            preds = self.service.predict(
                dec.model, dec.rows, raw_score=dec.raw_score,
                timeout_s=timeout_s, span=span)
            t_ser = time.perf_counter()
            self._send_wire(200, wire.encode_response(
                preds, version, (time.monotonic() - t0) * 1000.0),
                headers={"traceparent": span.traceparent()})
            span.add_stage("serialize", time.perf_counter() - t_ser)
        finally:
            span.finish()

    def _predict_json(self) -> None:
        t_parse = time.perf_counter()
        span = tracing.start_span(
            "serve_request", traceparent=self.headers.get("traceparent"))
        try:
            payload = self._read_json()
            model = payload.get("model")
            if not isinstance(model, str) or not model:
                raise InvalidRequest("missing 'model' (string) field")
            if "rows" not in payload:
                raise InvalidRequest("missing 'rows' field")
            timeout_ms = payload.get("timeout_ms")
            timeout_s = float(timeout_ms) / 1000.0 \
                if timeout_ms is not None else None
            span.add_stage("parse", time.perf_counter() - t_parse)
            version = self.service.registry.get(model).version
            t0 = time.monotonic()
            preds = self.service.predict(
                model, payload["rows"],
                raw_score=bool(payload.get("raw_score", False)),
                timeout_s=timeout_s, span=span)
            t_ser = time.perf_counter()
            self._send_json(200, {
                "model": model,
                "version": version,
                "predictions": preds.tolist(),
                "latency_ms": round((time.monotonic() - t0) * 1000.0, 3),
                "trace_id": span.trace_id,
            }, headers={"traceparent": span.traceparent()})
            span.add_stage("serialize", time.perf_counter() - t_ser)
        finally:
            # idempotent: a shed request was already finished (terminal
            # `shed`) inside the batcher — this records everyone else
            span.finish()

    def _metrics(self) -> None:
        from ..exposition import CONTENT_TYPE, render_metrics

        # flatten the numeric /statz figures into serve_* gauges so one
        # scrape carries the batcher/breaker state next to the telemetry
        # counter namespace (same names documented in docs/OBSERVABILITY.md)
        extra: Dict[str, Any] = {}

        def flatten(prefix: str, obj: Any) -> None:
            if isinstance(obj, dict):
                for k, v in obj.items():
                    flatten(f"{prefix}_{k}", v)
            elif isinstance(obj, bool):
                extra[prefix] = int(obj)
            elif isinstance(obj, (int, float)):
                extra[prefix] = obj

        flatten("serve", self.service.stats())
        self._send_text(200, render_metrics(extra), CONTENT_TYPE)

    def _load_model(self) -> None:
        payload = self._read_json()
        name = payload.get("name")
        if not isinstance(name, str) or not name:
            raise InvalidRequest("missing 'name' (string) field")
        shard_rows = payload.get("shard_rows")
        info = self.service.load_model(
            name, path=payload.get("path"),
            model_str=payload.get("model_str"),
            expected_sha256=payload.get("expected_sha256"),
            reject_nonfinite=bool(payload.get("reject_nonfinite", False)),
            shard_rows=int(shard_rows) if shard_rows is not None else None)
        self._send_json(200, info)


def serve(service: PredictionService, host: str = "127.0.0.1",
          port: int = 0) -> Tuple[ServingHTTPServer, threading.Thread]:
    """Start the HTTP front on a daemon thread; returns (server, thread).
    port=0 binds an ephemeral port (read it back from server.port)."""
    server = ServingHTTPServer(service, host=host, port=port)
    thread = threading.Thread(target=server.serve_forever,
                              name="lgbm-serve-http", daemon=True)
    thread.start()
    Log.info("serving: HTTP front listening on %s:%d",
             server.server_address[0], server.port)
    return server, thread
