"""Multi-model registry with checksum-verified, atomic hot-swap.

Every load stages a COMPLETE candidate — raw text read (or exported),
content-hashed, verified against the writer's ``.ckpt`` sidecar manifest
(checkpoint.py) and/or an explicit expected sha256, and parsed into a fresh
Booster — before a single dict assignment under the registry lock publishes
it. A corrupt upload therefore can never replace a serving model: it fails
the hash or the parse while the previous version keeps answering traffic.
Loads are idempotent (same bytes already serving -> the live entry is
returned unchanged), so a client retrying a timed-out upload cannot
double-bump the version.

Each entry also carries a host-pinned predict path for the circuit
breaker's OPEN state: the SAME packed-ensemble fused traversal the device
path runs, executed on the JAX CPU backend (``jax.default_device``) with a
CPU-resident pack. Same kernel, same summation order — bit-identical
outputs — without touching the accelerator that is misbehaving.
"""
from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import checkpoint, telemetry, tracing
from ..basic import Booster
from ..ops.predict import pack_ensemble, predict_raw
from ..utils import faults
from ..utils.log import Log
from ..utils.timer import global_timer
from .errors import ModelLoadError, ModelNotFound


class ModelEntry:
    """One immutable serving version of one named model."""

    def __init__(self, name: str, booster: Booster, sha256: str,
                 verified: bool, reject_nonfinite: bool,
                 shard_rows: Optional[int] = None,
                 source_path: Optional[str] = None) -> None:
        self.name = name
        self.booster = booster
        self.sha256 = sha256
        self.verified = verified
        self.reject_nonfinite = reject_nonfinite
        self.shard_rows = shard_rows  # row-shard threshold for this entry
        self.source_path = source_path  # where the model text was read from
        self.version = 0  # assigned at publish time
        self.loaded_unix = time.time()
        self.n_features = booster.num_feature()
        self._host_lock = threading.Lock()
        self._host_pack = None

    # ------------------------------------------------------------- predict

    def predict_device(self, X: np.ndarray, raw_score: bool) -> np.ndarray:
        """Normal path: the engine's own dispatch (jit cache, streaming).
        Entries with a `shard_rows` threshold route big micro-batches onto
        the row-sharded multi-chip path (parallel/predict.py)."""
        if self.shard_rows is not None:
            return self.booster.predict(X, raw_score=raw_score,
                                        pred_shard_rows=self.shard_rows)
        return self.booster.predict(X, raw_score=raw_score)

    def _tree_slice_end(self) -> int:
        g = self.booster._gbdt
        n_trees = len(g.models)
        best = self.booster.best_iteration
        if best > 0:
            n_trees = min(n_trees, best * g.num_tree_per_iteration)
        return n_trees

    def predict_host(self, X: np.ndarray, raw_score: bool) -> np.ndarray:
        """Breaker-OPEN path: the same fused traversal pinned to the JAX
        CPU backend. The pack is rebuilt once, CPU-resident, and cached on
        the entry (the PredictorCache keys don't include a device, so the
        device pack cannot be reused here)."""
        import jax
        import jax.numpy as jnp

        g = self.booster._gbdt
        C = g.num_tree_per_iteration
        cpu = jax.local_devices(backend="cpu")[0]
        with jax.default_device(cpu):
            # double-checked pack build: the device transfers inside
            # pack_ensemble must not run under _host_lock (R13) — a slow
            # pack would stall every concurrent breaker-OPEN request at
            # the lock instead of at the (idempotent) build
            with self._host_lock:
                packed = self._host_pack
            if packed is None:
                packed = pack_ensemble(
                    g.models[: self._tree_slice_end()], dtype=jnp.float32)
                with self._host_lock:
                    if self._host_pack is None:
                        self._host_pack = packed
                    packed = self._host_pack
            Xd = jax.device_put(
                np.ascontiguousarray(X, dtype=np.float32), cpu)
            if packed.num_trees > 0:
                out = predict_raw(packed, Xd, C)
            else:
                out = jnp.zeros((X.shape[0], C), dtype=jnp.float32)
            if g.average_output and packed.num_trees > 0:
                out = out / (packed.num_trees // C)
            if not raw_score and g.objective is not None:
                out = g.objective.convert_output(out)
            res = np.asarray(out)
        return res[:, 0] if res.shape[1] == 1 else res

    def predict(self, X: np.ndarray, raw_score: bool,
                host: bool = False) -> np.ndarray:
        if host:
            return self.predict_host(X, raw_score)
        return self.predict_device(X, raw_score)

    def info(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "version": self.version,
            "sha256": self.sha256,
            "verified": self.verified,
            "n_features": self.n_features,
            "num_trees": self.booster.num_trees(),
            "reject_nonfinite": self.reject_nonfinite,
            "shard_rows": self.shard_rows,
            "loaded_unix": self.loaded_unix,
        }


class ModelRegistry:
    """Thread-safe name -> ModelEntry map; swap is one assignment."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._models: Dict[str, ModelEntry] = {}
        self.rejected_uploads = 0
        self.swaps = 0

    # ---------------------------------------------------------------- load

    def load(self, name: str, path: Optional[str] = None,
             model_str: Optional[str] = None,
             booster: Optional[Booster] = None,
             reject_nonfinite: bool = False,
             expected_sha256: Optional[str] = None,
             shard_rows: Optional[int] = None) -> ModelEntry:
        """Stage + verify + parse + publish. Exactly one source among
        `path` / `model_str` / `booster`; an in-process Booster is
        snapshotted through its text export so the served version stays
        immutable even if training continues on the original object."""
        if sum(x is not None for x in (path, model_str, booster)) != 1:
            raise ModelLoadError(
                "exactly one of path / model_str / booster must be given")
        if path is not None:
            try:
                with open(path) as fh:
                    text = fh.read()
            except OSError as exc:
                self._reject(name, f"unreadable model file: {exc}")
        elif booster is not None:
            text = booster.model_to_string()
        else:
            text = model_str or ""
        # transit-corruption fault point: BEFORE verification, so an armed
        # model_corrupt_upload plan exercises the reject path for real
        text = faults.maybe_corrupt_upload(text)
        sha = hashlib.sha256(text.encode()).hexdigest()

        verified = False
        if path is not None:
            try:
                manifest = checkpoint.read_sidecar_manifest(path)
            except checkpoint.CheckpointError as exc:
                self._reject(name, f"damaged checkpoint sidecar: {exc}")
            if manifest is not None:
                want = manifest.get("model_sha256")
                if want and want != sha:
                    self._reject(
                        name, "upload does not match the sidecar's content "
                        f"hash (sidecar {str(want)[:12]}.., staged "
                        f"{sha[:12]}..)")
                verified = True
        if expected_sha256 is not None:
            if expected_sha256.lower() != sha:
                self._reject(
                    name, f"upload hash {sha[:12]}.. does not match "
                    f"expected {expected_sha256[:12]}..")
            verified = True

        with self._lock:
            cur = self._models.get(name)
            if cur is not None and cur.sha256 == sha:
                Log.info("serving: model '%s' v%d already serving these "
                         "bytes; load is a no-op", name, cur.version)
                return cur

        # parse OUTSIDE the lock: a big model text should not stall predicts
        try:
            staged = Booster(model_str=text)
        except Exception as exc:
            self._reject(name, f"unparseable model text: {exc}")
        entry = ModelEntry(name, staged, sha, verified, reject_nonfinite,
                           shard_rows=shard_rows, source_path=path)

        with self._lock:
            cur = self._models.get(name)
            if cur is not None and cur.sha256 == sha:
                return cur  # racing identical upload won
            entry.version = cur.version + 1 if cur is not None else 1
            self._models[name] = entry
            self.swaps += 1
        Log.info("serving: model '%s' -> v%d (%d trees, sha %s%s)",
                 name, entry.version, entry.booster.num_trees(), sha[:12],
                 ", verified" if verified else "")
        tracing.note("model_swap", model=name, version=entry.version,
                     sha256=sha[:12], verified=verified)
        if telemetry.enabled():
            telemetry.emit("model_swap", model=name, version=entry.version,
                           sha256=sha[:12], verified=verified,
                           num_trees=entry.booster.num_trees())
        return entry

    def _reject(self, name: str, why: str) -> None:
        with self._lock:
            self.rejected_uploads += 1
        global_timer.add_count("serve_rejected_uploads", 1)
        Log.warning("serving: REJECTED upload for model '%s': %s", name, why)
        tracing.note("model_upload_rejected", model=name, reason=why)
        if telemetry.enabled():
            telemetry.emit("model_upload_rejected", model=name, reason=why)
        raise ModelLoadError(f"model '{name}': {why}")

    # -------------------------------------------------------------- lookup

    def get(self, name: str) -> ModelEntry:
        with self._lock:
            entry = self._models.get(name)
        if entry is None:
            raise ModelNotFound(f"no model registered under '{name}'")
        return entry

    def unload(self, name: str) -> bool:
        with self._lock:
            return self._models.pop(name, None) is not None

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def info(self) -> List[Dict[str, Any]]:
        with self._lock:
            entries = list(self._models.values())
        return [e.info() for e in sorted(entries, key=lambda e: e.name)]
