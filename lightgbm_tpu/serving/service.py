"""PredictionService: the in-process serving facade.

Wires the registry, breaker, and micro-batcher into one object with the
same surface the HTTP front exposes: predict with a deadline, load/unload
with checksum verification, health and stats. Request validation happens
HERE — at the service boundary, before any row is enqueued — so a
malformed payload (ragged rows, wrong feature count, oversize batch,
opt-in non-finite values) costs a typed InvalidRequest naming the problem
and never a device dispatch.

The service polls telemetry.signals() (rate-limited) and feeds the breaker
so recompile churn or HBM pressure observed by the PR-7 watchers degrades
chunk sizes before anything actually fails.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import telemetry, tracing
from ..health import first_nonfinite_column
from ..utils.log import Log
from .batcher import MicroBatcher
from .breaker import CircuitBreaker
from .errors import InvalidRequest, ModelNotFound, ServiceClosed
from .registry import ModelRegistry


class PredictionService:
    def __init__(self, registry: Optional[ModelRegistry] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 max_batch_rows: int = 4096, max_queue_rows: int = 32768,
                 min_bucket: int = 256, batch_window_s: float = 0.001,
                 max_request_rows: Optional[int] = None,
                 default_timeout_s: Optional[float] = None,
                 signal_poll_s: float = 0.25) -> None:
        self.registry = registry or ModelRegistry()
        self.breaker = breaker or CircuitBreaker()
        self.batcher = MicroBatcher(
            self.breaker, max_batch_rows=max_batch_rows,
            max_queue_rows=max_queue_rows, min_bucket=min_bucket,
            batch_window_s=batch_window_s)
        self.max_request_rows = max_request_rows or self.batcher.max_batch_rows
        self.default_timeout_s = default_timeout_s
        self.signal_poll_s = signal_poll_s
        self._last_signal_poll = 0.0
        self._started = time.monotonic()
        self._closed = False
        # staged canary rollout (streaming/continuous.py gated publish):
        # at most one candidate at a time, None when inactive — the predict
        # hot path pays a single is-None check
        self._canary: Optional[Dict[str, Any]] = None
        self._canary_lock = threading.Lock()
        self._canary_promotions = 0
        self._canary_rollbacks = 0
        # rollback flight-dump recorded under _canary_lock, written after
        # release (the breaker's _maybe_dump convention, checked by R13)
        self._pending_dump: Optional[str] = None

    # -------------------------------------------------------------- models

    def load_model(self, name: str, **kwargs: Any) -> Dict[str, Any]:
        """Registry load + jit warmup of every serving bucket, so the new
        version's first live request never pays a compile."""
        entry = self.registry.load(name, **kwargs)
        self.warmup(name)
        # warmup compiles are expected, not churn — don't let them trip the
        # breaker's recompile signal on the next poll
        self.breaker.rebaseline(telemetry.signals())
        return entry.info()

    def unload_model(self, name: str) -> bool:
        return self.registry.unload(name)

    def models(self) -> List[Dict[str, Any]]:
        return self.registry.info()

    def warmup(self, name: str, max_rows: Optional[int] = None) -> List[int]:
        """Dispatch zeros at each power-of-two bucket (both raw and
        transformed outputs) so the jit cache holds every shape the batcher
        can produce — the 'zero new compiles under load' contract."""
        entry = self.registry.get(name)
        cap = min(max_rows or self.batcher.max_batch_rows,
                  self.batcher.max_batch_rows)
        buckets: List[int] = []
        b = self.batcher.min_bucket
        while b <= cap:
            zeros = np.zeros((b, max(entry.n_features, 1)), dtype=np.float32)
            for raw in (False, True):
                entry.predict_device(zeros, raw)
            buckets.append(b)
            b <<= 1
        return buckets

    # -------------------------------------------------------------- canary

    def start_canary(self, name: str, *, fraction: float = 0.1,
                     promote_after: int = 32, **kwargs: Any) -> Dict[str, Any]:
        """Stage a candidate model for `name` behind a traffic split: every
        ~1/fraction-th predict routes to the candidate until it either
        serves `promote_after` requests with the breaker closed (full
        swap) or shows pressure (auto-rollback). `kwargs` is the same
        payload load_model takes (booster= / model_str= / path=); it is
        kept so promotion replays the exact load. One canary at a time —
        a newer candidate supersedes (rolls back) the current one."""
        if not 0.0 < fraction <= 1.0:
            raise ValueError(f"canary fraction must be in (0, 1], "
                             f"got {fraction}")
        canary_name = f"{name}!canary"
        with self._canary_lock:
            if self._canary is not None:
                self._resolve_canary_locked(False, "superseded by a newer "
                                            "candidate")
            entry = self.registry.load(canary_name, **kwargs)
            self.warmup(canary_name)
            self.breaker.rebaseline(telemetry.signals())
            self._canary = {
                "model": name,
                "canary": canary_name,
                "fraction": float(fraction),
                "every": max(1, int(round(1.0 / float(fraction)))),
                "promote_after": int(promote_after),
                "served": 0,
                "seen": 0,
                "payload": dict(kwargs),
                "version": entry.version,
            }
        self._maybe_dump()
        tracing.note("canary_started", model=name, fraction=float(fraction),
                     promote_after=int(promote_after))
        if telemetry.enabled():
            telemetry.emit("canary_started", model=name,
                           fraction=float(fraction),
                           promote_after=int(promote_after))
        return entry.info()

    def _canary_route(self, model: str):
        """The canary registry entry when THIS request is the candidate's
        turn, else None. Breaker pressure observed here rolls the canary
        back before any further traffic reaches it."""
        with self._canary_lock:
            entry = self._canary_route_locked(model)
        self._maybe_dump()
        return entry

    def _canary_route_locked(self, model: str):
        c = self._canary
        if c is None or c["model"] != model:
            return None
        if self.breaker.info()["state"] != "closed":
            self._resolve_canary_locked(
                False, "breaker pressure during canary window")
            return None
        c["seen"] += 1
        if c["seen"] % c["every"] != 0:
            return None
        try:
            return self.registry.get(c["canary"])
        except ModelNotFound:
            self._canary = None
            return None

    def _canary_served(self, model: str) -> None:
        with self._canary_lock:
            c = self._canary
            if c is not None and c["model"] == model:
                c["served"] += 1
                if c["served"] >= c["promote_after"] \
                        and self.breaker.info()["state"] == "closed":
                    self._resolve_canary_locked(True,
                                                "served its window clean")
        self._maybe_dump()

    def resolve_canary(self, promote: bool, reason: str = "") -> bool:
        """Finish the canary now: promote the candidate to the primary
        slot, or roll it back and keep serving the current model. Returns
        False when no canary is active."""
        with self._canary_lock:
            out = self._resolve_canary_locked(promote, reason)
        self._maybe_dump()
        return out

    def _maybe_dump(self) -> None:
        """Write the flight dump a locked canary transition recorded.
        MUST be called with _canary_lock released: dump_flight does file
        I/O (R13 polices this)."""
        tag, self._pending_dump = self._pending_dump, None
        if tag is not None:
            tracing.dump_flight(tag)

    def _resolve_canary_locked(self, promote: bool, reason: str) -> bool:
        c = self._canary
        if c is None:
            return False
        self._canary = None
        if promote:
            self.registry.load(c["model"], **c["payload"])
            self.warmup(c["model"])
            self.breaker.rebaseline(telemetry.signals())
            self.registry.unload(c["canary"])
            self._canary_promotions += 1
            Log.info("serving: canary for %r promoted after %d canary "
                     "requests (%s)", c["model"], c["served"], reason)
            tracing.note("canary_promoted", model=c["model"],
                         served=c["served"])
            if telemetry.enabled():
                telemetry.emit("canary_promoted", model=c["model"],
                               served=c["served"])
        else:
            self.registry.unload(c["canary"])
            self._canary_rollbacks += 1
            Log.warning("serving: canary for %r rolled back after %d canary "
                        "requests: %s; primary keeps serving", c["model"],
                        c["served"], reason or "unspecified")
            tracing.note("canary_rolled_back", model=c["model"],
                         served=c["served"], reason=reason)
            if telemetry.enabled():
                telemetry.emit("canary_rolled_back", model=c["model"],
                               served=c["served"], reason=reason)
            # recorded here, written by the caller after the lock drops
            self._pending_dump = "canary_rollback"
        return True

    def canary_info(self) -> Dict[str, Any]:
        with self._canary_lock:
            c = self._canary
            out = {"active": c is not None,
                   "promoted": self._canary_promotions,
                   "rolled_back": self._canary_rollbacks}
            if c is not None:
                out.update(model=c["model"], fraction=c["fraction"],
                           served=c["served"],
                           promote_after=c["promote_after"])
            return out

    # ------------------------------------------------------------- predict

    def predict(self, model: str, rows: Any, raw_score: bool = False,
                timeout_s: Optional[float] = None,
                span: Optional[tracing.Span] = None) -> np.ndarray:
        """`span` is the request-scoped trace span; the HTTP front passes
        one carrying the inbound traceparent context (and finishes it
        after serialize), an in-process caller gets one made — and
        finished — here, so every admitted request is traced either way."""
        own_span = span is None
        if own_span:
            span = tracing.start_span("serve_request")
        t_parse = time.perf_counter()
        try:
            if self._closed:
                raise ServiceClosed("service is shutting down")
            self._poll_signals()
            entry = self.registry.get(model)
            X = self._validate(entry, rows)
            span.add_stage("parse", time.perf_counter() - t_parse)
            timeout = (timeout_s if timeout_s is not None
                       else self.default_timeout_s)
            if self._canary is not None:
                canary_entry = self._canary_route(model)
                if canary_entry is not None:
                    try:
                        out = self.batcher.submit(canary_entry, X, raw_score,
                                                  timeout, span=span)
                    except Exception as exc:
                        # the candidate failed a live request: roll it back
                        # and answer from the primary — the caller must
                        # never see a canary-induced failure
                        self.resolve_canary(
                            False, f"candidate request failed: {exc}")
                        return self.batcher.submit(entry, X, raw_score,
                                                   timeout, span=span)
                    self._canary_served(model)
                    return out
            return self.batcher.submit(entry, X, raw_score, timeout,
                                       span=span)
        finally:
            if own_span:
                span.finish()

    def _validate(self, entry, rows: Any) -> np.ndarray:
        try:
            X = np.asarray(rows, dtype=np.float64)
        except (ValueError, TypeError) as exc:
            raise InvalidRequest(f"rows are not a numeric matrix: {exc}")
        if X.ndim == 1:
            X = X.reshape(1, -1)
        if X.ndim != 2:
            raise InvalidRequest(
                f"rows must be a 2-D matrix, got {X.ndim}-D")
        if X.shape[0] == 0:
            raise InvalidRequest("empty request: no rows")
        if X.shape[0] > self.max_request_rows:
            raise InvalidRequest(
                f"request has {X.shape[0]} rows, per-request limit is "
                f"{self.max_request_rows}; split the request")
        if entry.n_features > 0 and X.shape[1] != entry.n_features:
            raise InvalidRequest(
                f"request rows have {X.shape[1]} features, model "
                f"'{entry.name}' v{entry.version} expects {entry.n_features}")
        if entry.reject_nonfinite:
            col = first_nonfinite_column(X)
            if col is not None:
                raise InvalidRequest(
                    f"non-finite value in feature column {col}; model "
                    f"'{entry.name}' was registered with reject_nonfinite "
                    "(NaN-as-missing disabled)")
        return np.ascontiguousarray(X, dtype=np.float32)

    # ------------------------------------------------------------- signals

    def _poll_signals(self) -> None:
        now = time.monotonic()
        if now - self._last_signal_poll < self.signal_poll_s:
            return
        self._last_signal_poll = now
        self.breaker.note_signals(telemetry.signals())

    # -------------------------------------------------------------- health

    def healthz(self) -> Dict[str, Any]:
        stats = self.batcher.stats()
        breaker = self.breaker.info()
        status = "ok"
        if breaker["state"] != "closed":
            status = "degraded"
        if self._closed:
            status = "closing"
        return {
            "status": status,
            "uptime_s": round(time.monotonic() - self._started, 3),
            "models": len(self.registry.names()),
            "rejected_uploads": self.registry.rejected_uploads,
            "breaker": breaker,
            "queue": stats,
        }

    def readyz(self) -> Dict[str, Any]:
        ready = not self._closed and bool(self.registry.names())
        return {"ready": ready, "models": self.registry.names()}

    def stats(self) -> Dict[str, Any]:
        # lazy import: stats() is a cold path and the streaming package
        # must not load just because a serving facade was constructed
        from ..streaming import drift as _drift

        return {
            "canary": self.canary_info(),
            "drift": _drift.latest(),
            "batcher": self.batcher.stats(),
            "breaker": self.breaker.info(),
            "models": self.registry.info(),
            "swaps": self.registry.swaps,
            "rejected_uploads": self.registry.rejected_uploads,
            # per-stage request-path quantiles (tracing histograms); the
            # /metrics flatten turns these into serve_stages_* gauges
            "stages": tracing.stage_summary("serve_request"),
            "flight": {
                "enabled": tracing.enabled(),
                "records": tracing.recorder().total,
                "dropped": tracing.recorder().dropped,
                "last_dump_path": tracing.last_dump_path(),
            },
        }

    def close(self) -> None:
        self._closed = True
        self.batcher.close()
