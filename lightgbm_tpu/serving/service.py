"""PredictionService: the in-process serving facade.

Wires the registry, breaker, and micro-batcher into one object with the
same surface the HTTP front exposes: predict with a deadline, load/unload
with checksum verification, health and stats. Request validation happens
HERE — at the service boundary, before any row is enqueued — so a
malformed payload (ragged rows, wrong feature count, oversize batch,
opt-in non-finite values) costs a typed InvalidRequest naming the problem
and never a device dispatch.

The service polls telemetry.signals() (rate-limited) and feeds the breaker
so recompile churn or HBM pressure observed by the PR-7 watchers degrades
chunk sizes before anything actually fails.

Fleet dispatch: `replicas=N` runs N independent MicroBatcher workers in
one process; each model entry is pinned to a replica at load time
(round-robin placement), so two hot models coalesce and dispatch
concurrently instead of serializing through one worker thread. The
breaker is shared but sharded per entry (breaker.py), so one tenant's
faulting model sheds only its own load.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import checkpoint, telemetry, tracing
from ..health import first_nonfinite_column
from ..utils.log import Log
from .batcher import MicroBatcher
from .breaker import CircuitBreaker
from .errors import InvalidRequest, ModelNotFound, ServiceClosed
from .registry import ModelRegistry


class PredictionService:
    def __init__(self, registry: Optional[ModelRegistry] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 max_batch_rows: int = 4096, max_queue_rows: int = 32768,
                 min_bucket: int = 256, batch_window_s: float = 0.001,
                 max_request_rows: Optional[int] = None,
                 default_timeout_s: Optional[float] = None,
                 signal_poll_s: float = 0.25, replicas: int = 1) -> None:
        self.registry = registry or ModelRegistry()
        self.breaker = breaker or CircuitBreaker()
        self._batchers = [
            MicroBatcher(self.breaker, max_batch_rows=max_batch_rows,
                         max_queue_rows=max_queue_rows, min_bucket=min_bucket,
                         batch_window_s=batch_window_s)
            for _ in range(max(1, int(replicas)))]
        # replica 0 keeps the historical single-batcher attribute so
        # existing callers (tests, tools) read the same surface
        self.batcher = self._batchers[0]
        # model entry name -> replica index; assigned round-robin at first
        # sight and dropped at unload, so a reloaded fleet rebalances
        self._placement: Dict[str, int] = {}
        self._placement_next = 0
        self._placement_lock = threading.Lock()
        self.max_request_rows = max_request_rows or self.batcher.max_batch_rows
        self.default_timeout_s = default_timeout_s
        self.signal_poll_s = signal_poll_s
        self._last_signal_poll = 0.0
        self._started = time.monotonic()
        self._closed = False
        # staged canary rollout (streaming/continuous.py gated publish):
        # at most one candidate at a time, None when inactive — the predict
        # hot path pays a single is-None check
        self._canary: Optional[Dict[str, Any]] = None
        self._canary_lock = threading.Lock()
        self._canary_promotions = 0
        self._canary_rollbacks = 0
        # rollback flight-dump recorded under _canary_lock, written after
        # release (the breaker's _maybe_dump convention, checked by R13)
        self._pending_dump: Optional[str] = None

    # ------------------------------------------------------------ placement

    def _batcher_for(self, name: str) -> MicroBatcher:
        """The replica batcher this entry is pinned to (round-robin
        assignment at first sight; stable until unload)."""
        if len(self._batchers) == 1:
            return self.batcher
        with self._placement_lock:
            idx = self._placement.get(name)
            if idx is None:
                idx = self._placement_next % len(self._batchers)
                self._placement[name] = idx
                self._placement_next += 1
        return self._batchers[idx]

    def _forget_placement(self, name: str) -> None:
        with self._placement_lock:
            self._placement.pop(name, None)

    # -------------------------------------------------------------- models

    def load_model(self, name: str, **kwargs: Any) -> Dict[str, Any]:
        """Registry load + AOT warm-start install (when a ``.aot`` sidecar
        rides next to the model file) + jit warmup of every serving bucket,
        so the new version's first live request never pays a compile."""
        entry = self.registry.load(name, **kwargs)
        self.breaker.register_entry(name)
        self._batcher_for(name)  # pin the replica before any traffic
        aot = self._install_aot(entry)
        self.warmup(name)
        # warmup compiles are expected, not churn — don't let them trip the
        # breaker's recompile signal on the next poll
        self.breaker.rebaseline(telemetry.signals())
        info = entry.info()
        info["aot_buckets"] = aot
        return info

    def unload_model(self, name: str) -> bool:
        self._forget_placement(name)
        self.breaker.forget_entry(name)
        return self.registry.unload(name)

    def models(self) -> List[Dict[str, Any]]:
        return self.registry.info()

    # ------------------------------------------------------------ AOT warm

    def _install_aot(self, entry) -> int:
        """Install the model's serialized predict executables (if a valid
        ``.aot`` sidecar rides next to its file) into its predictor cache.
        Every failure mode — absent, damaged, stale environment, wrong
        model hash — falls back to fresh compilation with a warning; a
        bundle can cost a compile, never a wrong answer. Returns the
        number of bucket executables installed."""
        from ..ops.predict import aot_load_bundle

        if entry.source_path is None:
            return 0
        try:
            blob = checkpoint.read_aot_sidecar(entry.source_path)
        except checkpoint.CheckpointError as exc:
            Log.warning("serving: damaged AOT sidecar for model '%s' (%s); "
                        "falling back to fresh compiles", entry.name, exc)
            return 0
        if blob is None:
            return 0
        executables, problems = aot_load_bundle(blob,
                                                model_sha256=entry.sha256)
        if problems:
            Log.warning("serving: AOT bundle for model '%s' refused (%s); "
                        "falling back to fresh compiles", entry.name,
                        "; ".join(problems))
            return 0
        n = entry.booster._gbdt._predictor.install_aot(executables)
        Log.info("serving: model '%s' warm-started with %d AOT bucket "
                 "executable(s)", entry.name, n)
        tracing.note("aot_installed", model=entry.name, buckets=n)
        if telemetry.enabled():
            telemetry.emit("aot_installed", model=entry.name, buckets=n)
        return n

    def export_aot(self, name: str, path: Optional[str] = None) -> str:
        """Compile + serialize this entry's per-bucket predict executables
        and persist them as ``<model path>.aot`` (or next to an explicit
        `path`). A warm writer calls this once; every cold replica that
        loads the same model file then skips its per-bucket XLA compiles."""
        from ..ops.predict import aot_serialize_bundle

        entry = self.registry.get(name)
        target = path or entry.source_path
        if target is None:
            raise ValueError(
                f"model '{name}' was not loaded from a file; pass an "
                "explicit path to export its AOT bundle")
        g = entry.booster._gbdt
        best = entry.booster.best_iteration
        packed = g._packed(best if best > 0 else 0, 0)
        buckets: List[int] = []
        b = self.batcher.min_bucket
        while b <= self.batcher.max_batch_rows:
            buckets.append(b)
            b <<= 1
        bundle = aot_serialize_bundle(
            packed, max(entry.n_features, 1), g.num_tree_per_iteration,
            buckets, model_sha256=entry.sha256)
        sidecar = checkpoint.write_aot_sidecar(target, bundle)
        Log.info("serving: exported AOT bundle for model '%s' (%d buckets, "
                 "%d bytes) -> %s", name, len(buckets), len(bundle), sidecar)
        return sidecar

    def warmup(self, name: str, max_rows: Optional[int] = None) -> List[int]:
        """Dispatch zeros at each power-of-two bucket (both raw and
        transformed outputs) so the jit cache holds every shape the batcher
        can produce — the 'zero new compiles under load' contract.

        Buckets covered by an installed AOT executable dispatch raw-score
        only: the single dispatch smoke-tests the deserialized executable
        (bit-identical traversal, no XLA compile) while the raw=False
        transform rides the same executable plus one tiny convert_output
        jit — so an AOT cold start stays milliseconds. If a deserialized
        executable fails at dispatch, the bundle is dropped and the full
        compile warmup runs instead."""
        entry = self.registry.get(name)
        cap = min(max_rows or self.batcher.max_batch_rows,
                  self.batcher.max_batch_rows)
        predictor = entry.booster._gbdt._predictor
        aot_covered = set(predictor.aot_rows())
        buckets: List[int] = []
        b = self.batcher.min_bucket
        while b <= cap:
            zeros = np.zeros((b, max(entry.n_features, 1)), dtype=np.float32)
            if b in aot_covered:
                try:
                    entry.predict_device(zeros, True)
                except Exception as exc:  # noqa: BLE001 - drop AOT, recover
                    Log.warning(
                        "serving: AOT executable for %d rows failed at "
                        "warmup (%s); dropping the bundle and compiling "
                        "fresh", b, exc)
                    predictor.invalidate()
                    return self.warmup(name, max_rows)
            else:
                for raw in (False, True):
                    entry.predict_device(zeros, raw)
            buckets.append(b)
            b <<= 1
        return buckets

    # -------------------------------------------------------------- canary

    def start_canary(self, name: str, *, fraction: float = 0.1,
                     promote_after: int = 32, **kwargs: Any) -> Dict[str, Any]:
        """Stage a candidate model for `name` behind a traffic split: every
        ~1/fraction-th predict routes to the candidate until it either
        serves `promote_after` requests with the breaker closed (full
        swap) or shows pressure (auto-rollback). `kwargs` is the same
        payload load_model takes (booster= / model_str= / path=); it is
        kept so promotion replays the exact load. One canary at a time —
        a newer candidate supersedes (rolls back) the current one."""
        if not 0.0 < fraction <= 1.0:
            raise ValueError(f"canary fraction must be in (0, 1], "
                             f"got {fraction}")
        canary_name = f"{name}!canary"
        with self._canary_lock:
            if self._canary is not None:
                self._resolve_canary_locked(False, "superseded by a newer "
                                            "candidate")
            entry = self.registry.load(canary_name, **kwargs)
            self.breaker.register_entry(canary_name)
            self.warmup(canary_name)
            self.breaker.rebaseline(telemetry.signals())
            self._canary = {
                "model": name,
                "canary": canary_name,
                "fraction": float(fraction),
                "every": max(1, int(round(1.0 / float(fraction)))),
                "promote_after": int(promote_after),
                "served": 0,
                "seen": 0,
                "payload": dict(kwargs),
                "version": entry.version,
            }
        self._maybe_dump()
        tracing.note("canary_started", model=name, fraction=float(fraction),
                     promote_after=int(promote_after))
        if telemetry.enabled():
            telemetry.emit("canary_started", model=name,
                           fraction=float(fraction),
                           promote_after=int(promote_after))
        return entry.info()

    def _canary_route(self, model: str):
        """The canary registry entry when THIS request is the candidate's
        turn, else None. Breaker pressure observed here rolls the canary
        back before any further traffic reaches it."""
        with self._canary_lock:
            entry = self._canary_route_locked(model)
        self._maybe_dump()
        return entry

    def _canary_route_locked(self, model: str):
        c = self._canary
        if c is None or c["model"] != model:
            return None
        if self.breaker.info()["state"] != "closed":
            self._resolve_canary_locked(
                False, "breaker pressure during canary window")
            return None
        c["seen"] += 1
        if c["seen"] % c["every"] != 0:
            return None
        try:
            return self.registry.get(c["canary"])
        except ModelNotFound:
            self._canary = None
            return None

    def _canary_served(self, model: str) -> None:
        with self._canary_lock:
            c = self._canary
            if c is not None and c["model"] == model:
                c["served"] += 1
                if c["served"] >= c["promote_after"] \
                        and self.breaker.info()["state"] == "closed":
                    self._resolve_canary_locked(True,
                                                "served its window clean")
        self._maybe_dump()

    def resolve_canary(self, promote: bool, reason: str = "") -> bool:
        """Finish the canary now: promote the candidate to the primary
        slot, or roll it back and keep serving the current model. Returns
        False when no canary is active."""
        with self._canary_lock:
            out = self._resolve_canary_locked(promote, reason)
        self._maybe_dump()
        return out

    def _maybe_dump(self) -> None:
        """Write the flight dump a locked canary transition recorded.
        MUST be called with _canary_lock released: dump_flight does file
        I/O (R13 polices this)."""
        tag, self._pending_dump = self._pending_dump, None
        if tag is not None:
            tracing.dump_flight(tag)

    def _resolve_canary_locked(self, promote: bool, reason: str) -> bool:
        c = self._canary
        if c is None:
            return False
        self._canary = None
        if promote:
            self.registry.load(c["model"], **c["payload"])
            self.warmup(c["model"])
            self.breaker.rebaseline(telemetry.signals())
            self.registry.unload(c["canary"])
            self._forget_placement(c["canary"])
            self.breaker.forget_entry(c["canary"])
            self._canary_promotions += 1
            Log.info("serving: canary for %r promoted after %d canary "
                     "requests (%s)", c["model"], c["served"], reason)
            tracing.note("canary_promoted", model=c["model"],
                         served=c["served"])
            if telemetry.enabled():
                telemetry.emit("canary_promoted", model=c["model"],
                               served=c["served"])
        else:
            self.registry.unload(c["canary"])
            self._forget_placement(c["canary"])
            self.breaker.forget_entry(c["canary"])
            self._canary_rollbacks += 1
            Log.warning("serving: canary for %r rolled back after %d canary "
                        "requests: %s; primary keeps serving", c["model"],
                        c["served"], reason or "unspecified")
            tracing.note("canary_rolled_back", model=c["model"],
                         served=c["served"], reason=reason)
            if telemetry.enabled():
                telemetry.emit("canary_rolled_back", model=c["model"],
                               served=c["served"], reason=reason)
            # recorded here, written by the caller after the lock drops
            self._pending_dump = "canary_rollback"
        return True

    def canary_info(self) -> Dict[str, Any]:
        with self._canary_lock:
            c = self._canary
            out = {"active": c is not None,
                   "promoted": self._canary_promotions,
                   "rolled_back": self._canary_rollbacks}
            if c is not None:
                out.update(model=c["model"], fraction=c["fraction"],
                           served=c["served"],
                           promote_after=c["promote_after"])
            return out

    # ------------------------------------------------------------- predict

    def predict(self, model: str, rows: Any, raw_score: bool = False,
                timeout_s: Optional[float] = None,
                span: Optional[tracing.Span] = None) -> np.ndarray:
        """`span` is the request-scoped trace span; the HTTP front passes
        one carrying the inbound traceparent context (and finishes it
        after serialize), an in-process caller gets one made — and
        finished — here, so every admitted request is traced either way."""
        own_span = span is None
        if own_span:
            span = tracing.start_span("serve_request")
        t_parse = time.perf_counter()
        try:
            if self._closed:
                raise ServiceClosed("service is shutting down")
            self._poll_signals()
            entry = self.registry.get(model)
            X = self._validate(entry, rows)
            span.add_stage("parse", time.perf_counter() - t_parse)
            timeout = (timeout_s if timeout_s is not None
                       else self.default_timeout_s)
            batcher = self._batcher_for(entry.name)
            if self._canary is not None:
                canary_entry = self._canary_route(model)
                if canary_entry is not None:
                    try:
                        out = self._batcher_for(canary_entry.name).submit(
                            canary_entry, X, raw_score, timeout, span=span)
                    except Exception as exc:
                        # the candidate failed a live request: roll it back
                        # and answer from the primary — the caller must
                        # never see a canary-induced failure
                        self.resolve_canary(
                            False, f"candidate request failed: {exc}")
                        return batcher.submit(entry, X, raw_score,
                                              timeout, span=span)
                    self._canary_served(model)
                    return out
            return batcher.submit(entry, X, raw_score, timeout, span=span)
        finally:
            if own_span:
                span.finish()

    def _validate(self, entry, rows: Any) -> np.ndarray:
        if isinstance(rows, np.ndarray) and rows.dtype == np.float32 \
                and rows.ndim == 2 and rows.flags["C_CONTIGUOUS"]:
            # binary-wire fast path: the decoder already produced exactly
            # the dtype/layout the batcher dispatches, so the f64 round
            # trip below (a full copy per request) is skipped; the shape
            # and finiteness checks still run on the view
            X = rows
        else:
            try:
                X = np.asarray(rows, dtype=np.float64)
            except (ValueError, TypeError) as exc:
                raise InvalidRequest(f"rows are not a numeric matrix: {exc}")
        if X.ndim == 1:
            X = X.reshape(1, -1)
        if X.ndim != 2:
            raise InvalidRequest(
                f"rows must be a 2-D matrix, got {X.ndim}-D")
        if X.shape[0] == 0:
            raise InvalidRequest("empty request: no rows")
        if X.shape[0] > self.max_request_rows:
            raise InvalidRequest(
                f"request has {X.shape[0]} rows, per-request limit is "
                f"{self.max_request_rows}; split the request")
        if entry.n_features > 0 and X.shape[1] != entry.n_features:
            raise InvalidRequest(
                f"request rows have {X.shape[1]} features, model "
                f"'{entry.name}' v{entry.version} expects {entry.n_features}")
        if entry.reject_nonfinite:
            col = first_nonfinite_column(X)
            if col is not None:
                raise InvalidRequest(
                    f"non-finite value in feature column {col}; model "
                    f"'{entry.name}' was registered with reject_nonfinite "
                    "(NaN-as-missing disabled)")
        if X.dtype == np.float32 and X.flags["C_CONTIGUOUS"]:
            return X
        return np.ascontiguousarray(X, dtype=np.float32)

    # ------------------------------------------------------------- signals

    def _poll_signals(self) -> None:
        now = time.monotonic()
        if now - self._last_signal_poll < self.signal_poll_s:
            return
        self._last_signal_poll = now
        self.breaker.note_signals(telemetry.signals())

    # -------------------------------------------------------------- health

    def _batcher_stats(self) -> Dict[str, Any]:
        """Fleet-aggregate batcher counters: sums for counts, worst-case
        for the latency quantiles (a replica's tail is the fleet's tail)."""
        per = [b.stats() for b in self._batchers]
        if len(per) == 1:
            return per[0]
        agg: Dict[str, Any] = {}
        for st in per:
            for k, v in st.items():
                if k in ("p50_ms", "p99_ms"):
                    agg[k] = max(agg.get(k, 0.0), v)
                else:
                    agg[k] = agg.get(k, 0) + v
        return agg

    def healthz(self) -> Dict[str, Any]:
        stats = self._batcher_stats()
        breaker = self.breaker.info()
        status = "ok"
        if breaker["state"] != "closed":
            status = "degraded"
        if self._closed:
            status = "closing"
        return {
            "status": status,
            "uptime_s": round(time.monotonic() - self._started, 3),
            "models": len(self.registry.names()),
            "rejected_uploads": self.registry.rejected_uploads,
            "breaker": breaker,
            "queue": stats,
        }

    def readyz(self) -> Dict[str, Any]:
        ready = not self._closed and bool(self.registry.names())
        return {"ready": ready, "models": self.registry.names()}

    def stats(self) -> Dict[str, Any]:
        # lazy import: stats() is a cold path and the streaming package
        # must not load just because a serving facade was constructed
        from ..streaming import drift as _drift

        with self._placement_lock:
            placement = dict(self._placement)
        return {
            "canary": self.canary_info(),
            "drift": _drift.latest(),
            "batcher": self._batcher_stats(),
            "replicas": {"count": len(self._batchers),
                         "placement": placement},
            "breaker": self.breaker.info(),
            "models": self.registry.info(),
            "swaps": self.registry.swaps,
            "rejected_uploads": self.registry.rejected_uploads,
            # per-stage request-path quantiles (tracing histograms); the
            # /metrics flatten turns these into serve_stages_* gauges
            "stages": tracing.stage_summary("serve_request"),
            "flight": {
                "enabled": tracing.enabled(),
                "records": tracing.recorder().total,
                "dropped": tracing.recorder().dropped,
                "last_dump_path": tracing.last_dump_path(),
            },
        }

    def close(self) -> None:
        self._closed = True
        for b in self._batchers:
            b.close()
