"""Binary wire protocol for the /predict fast path.

The JSON front (serving/http.py) spends most of a small request's budget
on text: the client renders every float as decimal, the server parses it
back through `json.loads` + `np.asarray(..., float64)`, and the response
re-renders the predictions as text. This module defines the sibling
binary framing negotiated by Content-Type — a length-delimited, versioned
little-endian format whose row block IS the IEEE-754 array, so the server
decodes a request with one `np.frombuffer` view (zero copy) and answers
with the raw float32 prediction bytes.

Request frame (Content-Type: ``application/x-lgbm-wire``)::

    0   4  magic        b"LGBW"
    4   1  version      1
    5   1  kind         1 = predict request
    6   1  dtype        0 = float32, 1 = float64 (row block element type)
    7   1  flags        bit 0: raw_score
    8   4  n_rows       uint32
    12  4  n_cols       uint32
    16  2  name_len     uint16, UTF-8 model name follows the header
    18  2  trace_len    uint16, optional W3C traceparent (ASCII) after name
    20  4  timeout_ms   uint32, 0 = server default
    24      name bytes | traceparent bytes | row block
               (n_rows * n_cols elements, C order)

Response frame (same Content-Type on the 200)::

    0   4  magic        b"LGBW"
    4   1  version      1
    5   1  kind         2 = predict response
    6   1  dtype        0 = float32 (prediction element type)
    7   1  flags        reserved, 0
    8   4  n_rows       uint32
    12  4  n_cols       uint32 (1 for binary/regression, C for multiclass)
    16  4  model_version uint32
    20  4  latency_ms   float32
    24      prediction block (n_rows * n_cols float32, C order)

Errors are NOT framed: any failed request keeps the JSON error body
``{"error", "detail"}`` with the typed status from serving/errors.py, so
a client can always branch on the response Content-Type. Every frame
fault (bad magic, unknown version, truncated or oversized row block)
raises InvalidRequest -> typed 400 naming the offset that disagreed.

The frame length is validated EXACTLY: header + name + trace + row block
must equal the body length, so truncation and trailing garbage are both
typed 400s instead of a silently short matrix.

Stdlib + numpy only, same as the rest of the serving stack.
"""
from __future__ import annotations

import struct
from typing import NamedTuple, Optional, Tuple

import numpy as np

from .errors import InvalidRequest

CONTENT_TYPE = "application/x-lgbm-wire"

MAGIC = b"LGBW"
VERSION = 1
KIND_PREDICT = 1
KIND_RESPONSE = 2

DTYPE_F32 = 0
DTYPE_F64 = 1
_DTYPES = {DTYPE_F32: np.dtype(np.float32), DTYPE_F64: np.dtype(np.float64)}
_DTYPE_CODES = {np.dtype(np.float32): DTYPE_F32,
                np.dtype(np.float64): DTYPE_F64}

FLAG_RAW_SCORE = 1

_REQ = struct.Struct("<4sBBBBIIHHI")   # 24 bytes
_RESP = struct.Struct("<4sBBBBIIIf")   # 24 bytes

HEADER_BYTES = _REQ.size
RESPONSE_HEADER_BYTES = _RESP.size


class WireRequest(NamedTuple):
    model: str
    rows: np.ndarray            # [n_rows, n_cols] zero-copy view of the frame
    raw_score: bool
    timeout_ms: Optional[int]   # None = server default
    traceparent: Optional[str]


def encode_request(model: str, rows: np.ndarray, raw_score: bool = False,
                   timeout_ms: Optional[int] = None,
                   traceparent: Optional[str] = None) -> bytes:
    """One request frame. `rows` must be a 2-D float32/float64 matrix;
    float32 C-contiguous input is framed without a copy of the row block
    conversion (tobytes still materializes the frame itself)."""
    X = np.asarray(rows)
    if X.ndim == 1:
        X = X.reshape(1, -1)
    if X.ndim != 2:
        raise ValueError(f"rows must be 2-D, got {X.ndim}-D")
    code = _DTYPE_CODES.get(X.dtype)
    if code is None:
        X = np.ascontiguousarray(X, dtype=np.float32)
        code = DTYPE_F32
    name = model.encode("utf-8")
    trace = (traceparent or "").encode("ascii")
    flags = FLAG_RAW_SCORE if raw_score else 0
    header = _REQ.pack(MAGIC, VERSION, KIND_PREDICT, code, flags,
                       X.shape[0], X.shape[1], len(name), len(trace),
                       int(timeout_ms or 0))
    return b"".join((header, name, trace,
                     np.ascontiguousarray(X).tobytes()))


def decode_request(buf: bytes) -> WireRequest:
    """Parse one request frame; the returned row matrix is a zero-copy
    (read-only) view into `buf`."""
    if len(buf) < HEADER_BYTES:
        raise InvalidRequest(
            f"wire frame of {len(buf)} bytes is shorter than the "
            f"{HEADER_BYTES}-byte header")
    (magic, version, kind, dtype_code, flags, n_rows, n_cols,
     name_len, trace_len, timeout_ms) = _REQ.unpack_from(buf)
    if magic != MAGIC:
        raise InvalidRequest(
            f"bad wire magic {magic!r} at offset 0 (expected {MAGIC!r})")
    if version != VERSION:
        raise InvalidRequest(
            f"unsupported wire version {version} (this server speaks "
            f"version {VERSION})")
    if kind != KIND_PREDICT:
        raise InvalidRequest(
            f"unexpected frame kind {kind} (expected predict request "
            f"{KIND_PREDICT})")
    dtype = _DTYPES.get(dtype_code)
    if dtype is None:
        raise InvalidRequest(
            f"unknown row-block dtype code {dtype_code} "
            f"(known: {sorted(_DTYPES)})")
    off = HEADER_BYTES
    block = n_rows * n_cols * dtype.itemsize
    expected = off + name_len + trace_len + block
    if len(buf) != expected:
        raise InvalidRequest(
            f"wire frame length {len(buf)} does not match the header "
            f"({n_rows}x{n_cols} {dtype.name} rows after a {name_len}-byte "
            f"name and {trace_len}-byte traceparent = {expected} bytes)")
    if n_rows == 0 or n_cols == 0:
        raise InvalidRequest("empty request: zero-size row block")
    try:
        model = bytes(buf[off:off + name_len]).decode("utf-8")
    except UnicodeDecodeError as exc:
        raise InvalidRequest(f"model name is not valid UTF-8: {exc}")
    if not model:
        raise InvalidRequest("missing model name in wire frame")
    off += name_len
    trace = bytes(buf[off:off + trace_len]).decode("ascii", "replace") \
        if trace_len else None
    off += trace_len
    rows = np.frombuffer(buf, dtype=dtype, count=n_rows * n_cols,
                         offset=off).reshape(n_rows, n_cols)
    return WireRequest(model=model, rows=rows,
                       raw_score=bool(flags & FLAG_RAW_SCORE),
                       timeout_ms=int(timeout_ms) or None,
                       traceparent=trace)


def encode_response(preds: np.ndarray, model_version: int,
                    latency_ms: float) -> bytes:
    """One response frame around the float32 prediction block. A 1-D
    prediction vector frames as n_cols=1 — the shape the JSON path's
    `predictions` list carries for single-output models."""
    P = np.asarray(preds, dtype=np.float32)
    n_cols = 1 if P.ndim == 1 else P.shape[1]
    header = _RESP.pack(MAGIC, VERSION, KIND_RESPONSE, DTYPE_F32, 0,
                        P.shape[0], n_cols, int(model_version),
                        float(latency_ms))
    return header + np.ascontiguousarray(P).tobytes()


def decode_response(buf: bytes) -> Tuple[np.ndarray, int, float]:
    """(predictions, model_version, latency_ms) from one response frame.
    1-column blocks come back 1-D, matching PredictionService.predict."""
    if len(buf) < RESPONSE_HEADER_BYTES:
        raise InvalidRequest(
            f"wire response of {len(buf)} bytes is shorter than the "
            f"{RESPONSE_HEADER_BYTES}-byte header")
    (magic, version, kind, dtype_code, _flags, n_rows, n_cols,
     model_version, latency_ms) = _RESP.unpack_from(buf)
    if magic != MAGIC or version != VERSION or kind != KIND_RESPONSE:
        raise InvalidRequest(
            f"bad wire response header (magic {magic!r}, version {version}, "
            f"kind {kind})")
    dtype = _DTYPES.get(dtype_code)
    if dtype is None:
        raise InvalidRequest(f"unknown response dtype code {dtype_code}")
    expected = RESPONSE_HEADER_BYTES + n_rows * n_cols * dtype.itemsize
    if len(buf) != expected:
        raise InvalidRequest(
            f"wire response length {len(buf)} does not match its header "
            f"({n_rows}x{n_cols} {dtype.name} = {expected} bytes)")
    P = np.frombuffer(buf, dtype=dtype, count=n_rows * n_cols,
                      offset=RESPONSE_HEADER_BYTES).reshape(n_rows, n_cols)
    if n_cols == 1:
        P = P.reshape(n_rows)
    return P, int(model_version), float(latency_ms)
