"""SHAP feature contributions (TreeSHAP).

Counterpart of the reference's PredictContrib path (gbdt_prediction.cpp:99,
Tree SHAP recursion in src/io/tree.cpp). Full polynomial-time TreeSHAP is
implemented on host over the tree arrays; output layout matches the reference:
[N, F+1] with the expected value in the last column (per class blocks for
multiclass).
"""
from __future__ import annotations

from typing import List

import numpy as np

from .models.tree import Tree


def _tree_shap(tree: Tree, row: np.ndarray, phi: np.ndarray) -> None:
    """Exact TreeSHAP (Lundberg et al. 2018 'Consistent Individualized
    Feature Attribution for Tree Ensembles') over one tree."""
    if tree.num_leaves <= 1:
        return

    class PathElem:
        __slots__ = ("d", "zero", "one", "pweight")

        def __init__(self, d, zero, one, pweight):
            self.d = d
            self.zero = zero
            self.one = one
            self.pweight = pweight

    def extend(path: List[PathElem], zero: float, one: float, d: int):
        path.append(PathElem(d, zero, one, 1.0 if len(path) == 0 else 0.0))
        n = len(path)
        for i in range(n - 2, -1, -1):
            path[i + 1].pweight += one * path[i].pweight * (i + 1) / n
            path[i].pweight = zero * path[i].pweight * (n - 1 - i) / n

    def unwind(path: List[PathElem], i: int):
        n = len(path)
        one = path[i].one
        zero = path[i].zero
        nxt = path[n - 1].pweight
        for j in range(n - 2, -1, -1):
            if one != 0:
                tmp = path[j].pweight
                path[j].pweight = nxt * n / ((j + 1) * one)
                nxt = tmp - path[j].pweight * zero * (n - 1 - j) / n
            else:
                path[j].pweight = path[j].pweight * n / (zero * (n - 1 - j))
        for j in range(i, n - 1):
            path[j].d = path[j + 1].d
            path[j].zero = path[j + 1].zero
            path[j].one = path[j + 1].one
        path.pop()

    def unwound_sum(path: List[PathElem], i: int) -> float:
        n = len(path)
        one = path[i].one
        zero = path[i].zero
        total = 0.0
        nxt = path[n - 1].pweight
        for j in range(n - 2, -1, -1):
            if one != 0:
                tmp = nxt * n / ((j + 1) * one)
                total += tmp
                nxt = path[j].pweight - tmp * zero * ((n - 1 - j) / n)
            else:
                total += path[j].pweight / (zero * ((n - 1 - j) / n))
        return total

    def node_weight(node: int) -> float:
        if node < 0:
            return float(tree.leaf_count[~node])
        return float(tree.internal_count[node])

    def recurse(node: int, path: List[PathElem], zero: float, one: float, pfeat: int):
        path = [PathElem(p.d, p.zero, p.one, p.pweight) for p in path]
        extend(path, zero, one, pfeat)
        if node < 0:
            leaf = ~node
            for i in range(1, len(path)):
                w = unwound_sum(path, i)
                phi[path[i].d] += w * (path[i].one - path[i].zero) * tree.leaf_value[leaf]
            return
        feat = int(tree.split_feature[node])
        # hot/cold child by the decision
        nxt = tree._decide_categorical(float(row[feat]), node) \
            if int(tree.decision_type[node]) & 1 else \
            tree._decide_numerical(float(row[feat]), node)
        hot = nxt
        cold = int(tree.right_child[node]) if hot == int(tree.left_child[node]) \
            else int(tree.left_child[node])
        w = node_weight(node)
        hot_frac = node_weight(hot) / w if w > 0 else 0.0
        cold_frac = node_weight(cold) / w if w > 0 else 0.0
        incoming_zero, incoming_one = 1.0, 1.0
        path_index = next((i for i in range(len(path)) if path[i].d == feat), -1)
        if path_index >= 0:
            incoming_zero = path[path_index].zero
            incoming_one = path[path_index].one
            unwind(path, path_index)
        recurse(hot, path, incoming_zero * hot_frac, incoming_one, feat)
        recurse(cold, path, incoming_zero * cold_frac, 0.0, feat)

    recurse(0, [], 1.0, 1.0, -1)


def predict_contrib(trees: List[Tree], X: np.ndarray,
                    num_tree_per_iteration: int = 1,
                    num_iteration: int = 0) -> np.ndarray:
    if any(t.is_linear for t in trees):
        # matches the reference: TreeSHAP is undefined over linear leaf
        # models (gbdt.cpp PredictContrib path CHECKs !linear_tree_)
        raise ValueError(
            "pred_contrib (SHAP) is not supported for linear-tree models")
    n, f = X.shape
    n_trees = len(trees)
    if num_iteration > 0:
        n_trees = min(n_trees, num_iteration * num_tree_per_iteration)
    C = num_tree_per_iteration
    out = np.zeros((n, C * (f + 1)), dtype=np.float64)
    for t_idx in range(n_trees):
        tree = trees[t_idx]
        c = t_idx % C
        base = c * (f + 1)
        expected = tree.expected_value()
        for i in range(n):
            phi = np.zeros(f + 1)
            phi_feat = np.zeros(f + 1)

            class _Phi:
                pass

            arr = np.zeros(f)
            _tree_shap(tree, X[i], arr)
            out[i, base: base + f] += arr
            out[i, base + f] += expected
    if C == 1:
        return out
    return out
