"""scikit-learn estimator API.

Counterpart of python-package/lightgbm/sklearn.py: LGBMModel base +
LGBMClassifier / LGBMRegressor / LGBMRanker wrapping engine.train with the
standard sklearn fit/predict surface, eval sets, early stopping via
callbacks, label encoding for classifiers, and fitted attributes
(best_iteration_, best_score_, feature_importances_, classes_).
"""
from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset, _is_dataframe
from .engine import train as train_fn
from .utils.log import LightGBMError

try:  # sklearn is optional at runtime, mirrored from the reference's guard
    from sklearn.base import BaseEstimator, ClassifierMixin, RegressorMixin

    _SKLEARN_INSTALLED = True
except ImportError:  # pragma: no cover
    _SKLEARN_INSTALLED = False

    class BaseEstimator:  # type: ignore
        pass

    class ClassifierMixin:  # type: ignore
        pass

    class RegressorMixin:  # type: ignore
        pass


class LGBMModel(BaseEstimator):
    """Base estimator (sklearn.py LGBMModel)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[str] = None,
                 class_weight: Optional[Union[Dict, str]] = None,
                 min_split_gain: float = 0.0, min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 0, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 random_state: Optional[int] = None, n_jobs: int = -1,
                 importance_type: str = "split", **kwargs: Any) -> None:
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self._other_params: Dict[str, Any] = kwargs
        self._Booster: Optional[Booster] = None
        self._evals_result: Dict = {}
        self._best_iteration = 0
        self._best_score: Dict = {}
        self._n_features = 0
        self._objective = objective
        self.set_params(**kwargs)

    # --------------------------------------------------------------- params

    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = super().get_params(deep=deep) if _SKLEARN_INSTALLED else {
            k: getattr(self, k) for k in (
                "boosting_type", "num_leaves", "max_depth", "learning_rate",
                "n_estimators", "subsample_for_bin", "objective",
                "class_weight", "min_split_gain", "min_child_weight",
                "min_child_samples", "subsample", "subsample_freq",
                "colsample_bytree", "reg_alpha", "reg_lambda", "random_state",
                "n_jobs", "importance_type")}
        params.update(self._other_params)
        return params

    def set_params(self, **params: Any) -> "LGBMModel":
        for key, value in params.items():
            setattr(self, key, value)
            if not hasattr(type(self), key):
                self._other_params[key] = value
        return self

    def _lgb_params(self) -> Dict[str, Any]:
        params = {
            "boosting": self.boosting_type,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "bin_construct_sample_cnt": self.subsample_for_bin,
            "min_gain_to_split": self.min_split_gain,
            "min_sum_hessian_in_leaf": self.min_child_weight,
            "min_data_in_leaf": self.min_child_samples,
            "bagging_fraction": self.subsample,
            "bagging_freq": self.subsample_freq,
            "feature_fraction": self.colsample_bytree,
            "lambda_l1": self.reg_alpha,
            "lambda_l2": self.reg_lambda,
            "verbosity": -1,
        }
        if self.random_state is not None:
            params["seed"] = (self.random_state
                              if isinstance(self.random_state, int)
                              else self.random_state.randint(2**31 - 1))
        if self._objective is not None:
            params["objective"] = self._objective
        params.update(self._other_params)
        return params

    # ------------------------------------------------------------------ fit

    def _fit(self, X, y, sample_weight=None, init_score=None, group=None,
             eval_set=None, eval_names=None, eval_sample_weight=None,
             eval_group=None, eval_metric=None, callbacks=None) -> "LGBMModel":
        if not _is_dataframe(X):  # DataFrames pass through to Dataset's
            X = np.asarray(X, dtype=np.float64)  # pandas-categorical handling
        y = np.asarray(y, dtype=np.float64).ravel()
        self._n_features = X.shape[1]
        params = self._lgb_params()
        if eval_metric is not None:
            params["metric"] = eval_metric
        train_set = Dataset(X, label=y, weight=sample_weight,
                            group=group, init_score=init_score)
        valid_sets: List[Dataset] = []
        valid_names: List[str] = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                vw = eval_sample_weight[i] if eval_sample_weight else None
                vg = eval_group[i] if eval_group else None
                if not _is_dataframe(vx):
                    vx = np.asarray(vx, dtype=np.float64)
                vy = np.asarray(vy, dtype=np.float64).ravel()

                def _opt_equal(a, b):
                    if a is None or b is None:
                        return a is b
                    return np.array_equal(np.asarray(a), np.asarray(b))

                if (np.array_equal(vy, y) and np.array_equal(vx, X)
                        and _opt_equal(vw, sample_weight)
                        and _opt_equal(vg, group)):
                    valid_sets.append(train_set)
                else:
                    valid_sets.append(Dataset(
                        vx, label=self._encode_eval_label(vy), weight=vw,
                        group=vg, reference=train_set))
                valid_names.append(eval_names[i] if eval_names
                                   else f"valid_{i}")
        self._evals_result = {}
        callbacks = list(callbacks) if callbacks else []
        callbacks.append(callback_mod.record_evaluation(self._evals_result))
        self._Booster = train_fn(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets or None,
            valid_names=valid_names or None, callbacks=callbacks)
        self._best_iteration = self._Booster.best_iteration
        self._best_score = copy.deepcopy(self._evals_result)
        return self

    def _encode_eval_label(self, y: np.ndarray) -> np.ndarray:
        return y

    fit = _fit

    # -------------------------------------------------------------- predict

    def predict(self, X, raw_score: bool = False, num_iteration: Optional[int] = None,
                **kwargs: Any) -> np.ndarray:
        if self._Booster is None:
            raise _not_fitted_error(self)
        if not _is_dataframe(X):  # frames map through pandas_categorical
            # keep f32/f64 inputs as-is: Booster.predict routes the device
            # dtype, and an f32 matrix forced through f64 would pay a 2x
            # host copy just to be downcast again at upload
            X = np.asarray(X)
            if X.dtype not in (np.float32, np.float64):
                X = X.astype(np.float64)
        if X.shape[1] != self._n_features:
            raise ValueError(
                "Number of features of the model must match the input. "
                f"Model n_features_ is {self._n_features} and input "
                f"n_features is {X.shape[1]}")
        return self._Booster.predict(
            X, raw_score=raw_score,
            num_iteration=num_iteration if num_iteration is not None else 0,
            **kwargs)

    # ---------------------------------------------------------- attributes

    @property
    def n_features_(self) -> int:
        if self._Booster is None:
            raise _not_fitted_error(self)
        return self._n_features

    @property
    def n_features_in_(self) -> int:
        return self.n_features_

    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise _not_fitted_error(self)
        return self._Booster

    @property
    def best_iteration_(self) -> int:
        if self._Booster is None:
            raise _not_fitted_error(self)
        return self._best_iteration

    @property
    def best_score_(self) -> Dict:
        if self._Booster is None:
            raise _not_fitted_error(self)
        return self._best_score

    @property
    def evals_result_(self) -> Dict:
        if self._Booster is None:
            raise _not_fitted_error(self)
        return self._evals_result

    @property
    def feature_importances_(self) -> np.ndarray:
        if self._Booster is None:
            raise _not_fitted_error(self)
        return self._Booster.feature_importance(
            importance_type=self.importance_type)

    @property
    def feature_name_(self) -> List[str]:
        if self._Booster is None:
            raise _not_fitted_error(self)
        return self._Booster.feature_name()


def _not_fitted_error(est) -> Exception:
    if _SKLEARN_INSTALLED:
        from sklearn.exceptions import NotFittedError

        return NotFittedError(
            f"This {type(est).__name__} instance is not fitted yet.")
    return LightGBMError(
        f"This {type(est).__name__} instance is not fitted yet.")


class LGBMRegressor(RegressorMixin, LGBMModel):
    def fit(self, X, y, sample_weight=None, init_score=None, eval_set=None,
            eval_names=None, eval_sample_weight=None, eval_metric=None,
            callbacks=None) -> "LGBMRegressor":
        if self._objective is None:
            self._objective = "regression"
        return self._fit(X, y, sample_weight=sample_weight,
                         init_score=init_score, eval_set=eval_set,
                         eval_names=eval_names,
                         eval_sample_weight=eval_sample_weight,
                         eval_metric=eval_metric, callbacks=callbacks)


class LGBMClassifier(ClassifierMixin, LGBMModel):
    def fit(self, X, y, sample_weight=None, init_score=None, eval_set=None,
            eval_names=None, eval_sample_weight=None, eval_metric=None,
            callbacks=None) -> "LGBMClassifier":
        y = np.asarray(y).ravel()
        self._classes, y_enc = np.unique(y, return_inverse=True)
        self._n_classes = len(self._classes)
        if self._n_classes > 2:
            if self._objective is None or self._objective in (
                    "binary", "multiclass"):
                self._objective = "multiclass"
            self._other_params["num_class"] = self._n_classes
        else:
            if self._objective is None:
                self._objective = "binary"
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            eval_set = [(vx, np.searchsorted(self._classes, np.asarray(vy).ravel()))
                        for vx, vy in eval_set]
        return self._fit(X, y_enc.astype(np.float64),
                         sample_weight=sample_weight, init_score=init_score,
                         eval_set=eval_set, eval_names=eval_names,
                         eval_sample_weight=eval_sample_weight,
                         eval_metric=eval_metric, callbacks=callbacks)

    def predict(self, X, raw_score: bool = False,
                num_iteration: Optional[int] = None, **kwargs: Any) -> np.ndarray:
        proba = self.predict_proba(X, raw_score=raw_score,
                                   num_iteration=num_iteration, **kwargs)
        if raw_score:
            return proba
        if proba.ndim == 1:
            idx = (proba > 0.5).astype(int)
        else:
            idx = np.argmax(proba, axis=1)
        return self._classes[idx]

    def predict_proba(self, X, raw_score: bool = False,
                      num_iteration: Optional[int] = None,
                      **kwargs: Any) -> np.ndarray:
        out = super().predict(X, raw_score=raw_score,
                              num_iteration=num_iteration, **kwargs)
        if raw_score:
            return out
        if out.ndim == 1 and self._n_classes <= 2:
            return np.vstack([1.0 - out, out]).T
        return out

    @property
    def classes_(self) -> np.ndarray:
        if self._Booster is None:
            raise _not_fitted_error(self)
        return self._classes

    @property
    def n_classes_(self) -> int:
        if self._Booster is None:
            raise _not_fitted_error(self)
        return self._n_classes


class LGBMRanker(LGBMModel):
    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_group=None, eval_metric=None, eval_at=(1, 2, 3, 4, 5),
            callbacks=None) -> "LGBMRanker":
        if group is None:
            raise ValueError("Should set group for ranking task")
        if eval_set is not None and eval_group is None:
            raise ValueError("Eval_group cannot be None when eval_set is not None")
        if self._objective is None:
            self._objective = "lambdarank"
        self._other_params.setdefault("eval_at", list(eval_at))
        return self._fit(X, y, sample_weight=sample_weight,
                         init_score=init_score, group=group,
                         eval_set=eval_set, eval_names=eval_names,
                         eval_sample_weight=eval_sample_weight,
                         eval_group=eval_group, eval_metric=eval_metric,
                         callbacks=callbacks)
