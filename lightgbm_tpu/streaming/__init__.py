"""Out-of-core streaming data engine + continuous-training flywheel.

Three pieces (docs/STREAMING.md):

  * ingest.py     — RowBlockStore: incremental row pushes (numpy blocks,
                    CSR chunks, chunked CSV/iterator sources, and the
                    LGBM_DatasetPushRows* C-API shims in capi/impl.py),
                    binned block-by-block against a BinMapper layout
                    fitted on a buffered sample prefix.
  * learner.py    — StreamedTreeLearner: trains with only
                    LGBM_TPU_HBM_BUDGET bytes of the bin plane device-
                    resident, double-buffering H2D block transfer against
                    per-chunk histogram accumulation; bit-identical to
                    the resident learner on the XLA histogram path.
  * continuous.py — ContinuousTrainer: periodic refits on freshly pushed
                    blocks, crash-consistent checkpoints (checkpoint.py),
                    zero-downtime hot-swap into the serving ModelRegistry.
"""
from .continuous import ContinuousTrainer
from .ingest import RowBlockStore, wrap_dataset
from .learner import (StreamedTreeLearner, stream_budget_bytes,
                      streaming_requested)

__all__ = [
    "ContinuousTrainer",
    "RowBlockStore",
    "StreamedTreeLearner",
    "stream_budget_bytes",
    "streaming_requested",
    "wrap_dataset",
]
