"""Out-of-core streaming data engine + continuous-training flywheel.

Four pieces (docs/STREAMING.md):

  * ingest.py     — RowBlockStore: incremental row pushes (numpy blocks,
                    CSR chunks, chunked CSV/iterator sources, and the
                    LGBM_DatasetPushRows* C-API shims in capi/impl.py),
                    binned block-by-block against a BinMapper layout
                    fitted on a buffered sample prefix.
  * learner.py    — StreamedTreeLearner: trains with only
                    LGBM_TPU_HBM_BUDGET bytes of the bin plane device-
                    resident, double-buffering H2D block transfer against
                    per-chunk histogram accumulation; bit-identical to
                    the resident learner on the XLA histogram path.
  * continuous.py — ContinuousTrainer: periodic refits on freshly pushed
                    blocks, crash-consistent checkpoints (checkpoint.py),
                    a holdout quality gate with generation rollback, and
                    zero-downtime (optionally canaried) hot-swap into the
                    serving ModelRegistry.
  * drift.py      — DriftMonitor: per-feature streaming quantile sketches
                    + bin-occupancy PSI scoring against the binning-time
                    reference, driving alarms and the scheduled bin-mapper
                    refresh (LGBM_TPU_DRIFT / LGBM_TPU_BIN_REFRESH_EVERY).
  * sharded.py    — pod-scale composition: ShardedRowBlockStore (round-
                    robin block placement + rank-merged sketch binning),
                    PodDriftMonitor (gang-merged drift state), and
                    ShardedStreamedTreeLearner (gang-sharded block cache
                    + psum-merged quantized histograms) behind
                    tree_learner=data + LGBM_TPU_HBM_BUDGET.
"""
from .continuous import ContinuousTrainer, GenerationRejected
from .drift import DriftMonitor, QuantileSketch, merge_ranked
from .ingest import RowBlockStore, wrap_dataset
from .learner import (StreamedTreeLearner, stream_budget_bytes,
                      streaming_requested)
from .sharded import (PodDriftMonitor, ShardedRowBlockStore,
                      ShardedStreamedTreeLearner)

__all__ = [
    "ContinuousTrainer",
    "DriftMonitor",
    "GenerationRejected",
    "PodDriftMonitor",
    "QuantileSketch",
    "RowBlockStore",
    "ShardedRowBlockStore",
    "ShardedStreamedTreeLearner",
    "StreamedTreeLearner",
    "merge_ranked",
    "stream_budget_bytes",
    "streaming_requested",
    "wrap_dataset",
]
