"""Continuous-training flywheel: push -> refit -> checkpoint -> hot-swap.

ContinuousTrainer closes the train->serve loop over a live RowBlockStore:

  * `step()` refits when at least `min_new_rows` rows have landed since
    the last published model (always on the first call).
  * Each refit pins a ROW WATERMARK before snapshotting the store, and
    the watermark survives a mid-refit crash: a retried `refit()` for the
    same generation finalizes the identical row range even if pushes kept
    arriving, so the checkpoint-resumed run trains on the exact dataset
    the crashed run saw — the precondition for bit-identical resume.
  * Training runs through engine.train with a per-generation
    checkpoint_callback (checkpoint.py's crash-consistent atomic writer).
    If the generation's checkpoint file already exists when refit starts,
    it is handed to engine.train as init_model — the same-command resume
    path, which subtracts the finished iterations and replays the rest
    bit-identically.
  * On success the booster is published into the PR 9 serving
    ModelRegistry (or a PredictionService, which also re-warms and
    re-baselines its breaker) — an atomic pointer swap, so concurrent
    predicts never observe a half-loaded model.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

from .. import engine, health, tracing
from ..checkpoint import CheckpointError, checkpoint_callback, \
    read_sidecar_manifest
from ..parallel.elastic import WorkerLostError
from ..utils import faults
from ..utils.timer import global_timer
from .. import telemetry
from ..utils.log import Log
from . import drift
from .ingest import RowBlockStore, wrap_dataset


class GenerationRejected(Exception):
    """Typed marker for a candidate generation the publish quality gate
    refused (never raised across the refit() boundary — refit() converts
    it into the same rolled-back None return as a lost worker — but
    carried in telemetry/tracing so dashboards can key on it)."""


class ContinuousTrainer:
    def __init__(self, params: Dict[str, Any], store: RowBlockStore, *,
                 num_boost_round: int = 20,
                 min_new_rows: int = 1,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_period: int = 1,
                 registry=None, service=None,
                 model_name: str = "live",
                 holdout_rows: int = 0,
                 gate_tolerance: float = 0.1,
                 canary_fraction: float = 0.0,
                 canary_promote_after: int = 32,
                 refresh_every: Optional[int] = None) -> None:
        self.params = dict(params)
        self.store = store
        self.num_boost_round = int(num_boost_round)
        self.min_new_rows = int(min_new_rows)
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_period = int(checkpoint_period)
        self.registry = registry
        self.service = service
        self.model_name = model_name
        self.generation = 0
        self.booster = None
        self._trained_rows = 0
        # crash-consistency watermark: rows pinned by an unfinished refit
        self._inflight_rows: Optional[int] = None
        # publish quality gate: holdout_rows > 0 arms it (the store must be
        # built with a matching holdout ring); the candidate must score
        # within (1 + gate_tolerance) of the serving model's holdout loss
        self.holdout_rows = int(holdout_rows)
        self.gate_tolerance = float(gate_tolerance)
        self._inflight_holdout = None  # pinned with the row watermark
        # optional canary: route a traffic fraction at the candidate first
        self.canary_fraction = float(canary_fraction)
        self.canary_promote_after = int(canary_promote_after)
        # scheduled bin refresh cadence in generations (0/None = drift-only)
        if refresh_every is None:
            refresh_every = int(os.environ.get(
                drift.REFRESH_EVERY_ENV, "0") or 0)
        self.refresh_every = int(refresh_every)
        if self.holdout_rows > 0 and self.store.holdout_rows <= 0:
            # arm the store's raw tail ring so holdout_snapshot() works
            self.store.holdout_rows = self.holdout_rows

    # ------------------------------------------------------------- refit

    def checkpoint_path(self, generation: Optional[int] = None) -> Optional[str]:
        if not self.checkpoint_dir:
            return None
        gen = self.generation if generation is None else generation
        return os.path.join(self.checkpoint_dir, f"refit_gen{gen:04d}.txt")

    def step(self):
        """Refit if enough fresh rows landed; returns the new Booster or
        None when below the threshold."""
        fresh = self.store.total_rows - self._trained_rows
        if self.booster is not None and fresh < self.min_new_rows \
                and self._inflight_rows is None:
            return None
        return self.refit()

    def refit(self):
        """One generation: snapshot -> train (checkpointed) -> gate ->
        publish."""
        if self._inflight_rows is None:
            # fresh generation boundary: the ONLY place a bin refresh may
            # run. A crash-resumed refit skips this branch (the watermark
            # is still pinned), so the resume replays against the exact
            # mapper generation the crashed attempt trained under — the
            # sidecar's bin_mapper_generation verifies it below.
            due = (self.refresh_every > 0 and self.generation > 0
                   and self.generation % self.refresh_every == 0)
            self.store.maybe_refresh_bins(force=due)
            self._inflight_rows = self.store.total_rows
            if self.holdout_rows > 0 and self.booster is not None:
                # pin the holdout with the watermark: the gate must score
                # candidate and serving model on the same frozen window
                self._inflight_holdout = self.store.holdout_snapshot()
        rows = self._inflight_rows
        holdout = self._inflight_holdout
        train_rows = rows
        if holdout is not None:
            # recent rows are held out of training so the gate is honest
            train_rows = max(1, rows - len(holdout[1]))
        core = self.store.finalize(train_rows)
        train_set = wrap_dataset(core, params=self.params)
        callbacks = []
        init_model = None
        ckpt = self.checkpoint_path()
        if ckpt:
            os.makedirs(self.checkpoint_dir, exist_ok=True)
            callbacks.append(checkpoint_callback(
                ckpt, period=self.checkpoint_period,
                extra_manifest={
                    "stream_generation": self.generation,
                    "bin_mapper_generation": self.store.layout_generation,
                }))
            if os.path.exists(ckpt):
                # a crashed refit of THIS generation left a snapshot:
                # resume it (engine.train subtracts finished iterations
                # and replays the remainder bit-identically)
                init_model = ckpt
                Log.info("continuous: resuming generation %d from %s",
                         self.generation, ckpt)
                self._check_resume_mapper_generation(ckpt)
        try:
            with global_timer.scope("stream_refit"):
                booster = engine.train(
                    self.params, train_set,
                    num_boost_round=self.num_boost_round,
                    init_model=init_model, callbacks=callbacks)
        except WorkerLostError as exc:
            # a gang peer died mid-refit: roll this generation back to its
            # pinned checkpoint. The watermark stays pinned and the
            # generation counter does NOT advance, so the next refit()
            # resumes the SAME row range from the same-generation snapshot;
            # serving keeps answering from the last published model the
            # whole time (nothing was swapped).
            Log.warning("continuous: worker lost mid-refit of generation "
                        "%d (rank %d, last good iteration %d); generation "
                        "rolled back to its pinned checkpoint, serving "
                        "keeps the last published model", self.generation,
                        exc.rank, exc.last_good_iteration)
            tracing.note("stream_refit_worker_lost",
                         generation=self.generation, rank=exc.rank,
                         last_good_iteration=exc.last_good_iteration)
            if telemetry.enabled():
                telemetry.emit("stream_refit_worker_lost",
                               generation=self.generation, rank=exc.rank,
                               last_good_iteration=exc.last_good_iteration)
            global_timer.add_count("stream_refit_worker_lost", 1)
            return None
        booster = faults.maybe_poison_generation(booster, self.generation)
        if holdout is not None and not self._gate_accepts(booster, holdout):
            # quality gate rejected the candidate: roll the generation back
            # exactly like the lost-worker path. The watermark AND holdout
            # stay pinned (the retry scores the same frozen window), the
            # generation counter does not advance, and serving keeps the
            # last published model — the rejected candidate never answers
            # a single predict. The generation checkpoint on disk holds the
            # trained state, so the retry resumes instead of retraining.
            return None
        self._publish(booster)
        self.booster = booster
        # full watermark: held-out rows roll into the NEXT generation's
        # training window (they were only excluded from this one)
        self._trained_rows = rows
        self._inflight_rows = None
        self._inflight_holdout = None
        # emit first, bump after: the event and gauge must name the
        # generation this model was checkpointed and published as
        global_timer.set_count("stream_generation", self.generation)
        if telemetry.enabled():
            telemetry.emit("stream_refit", generation=self.generation,
                           rows=rows)
        self.generation += 1
        return booster

    def _check_resume_mapper_generation(self, ckpt: str) -> None:
        """Resume-path invariant: the sidecar's recorded bin-mapper
        generation must match the store's live one (refreshes are fenced
        to fresh generation boundaries, so in-process this always holds;
        a mismatch means the checkpoint came from another store lineage)."""
        try:
            manifest = read_sidecar_manifest(ckpt)
        except CheckpointError:
            return  # damaged sidecar: load_checkpoint degrades, not us
        if manifest is None:
            return
        want = manifest.get("bin_mapper_generation")
        if want is None or int(want) == self.store.layout_generation:
            return
        Log.warning("continuous: checkpoint %s was trained under bin-mapper "
                    "generation %s but the store is at %d; resume would "
                    "replay against different cut points", ckpt, want,
                    self.store.layout_generation)
        global_timer.add_count("stream_mapper_generation_mismatch", 1)
        tracing.note("stream_mapper_generation_mismatch",
                     checkpoint=int(want),
                     store=self.store.layout_generation)
        if telemetry.enabled():
            telemetry.emit("stream_mapper_generation_mismatch",
                           checkpoint=int(want),
                           store=self.store.layout_generation)

    def _gate_accepts(self, candidate, holdout) -> bool:
        """Score the candidate against the serving model on the pinned
        holdout window; False (with the full rejection paper trail) when
        it lands outside tolerance."""
        X, y = holdout
        objective = str(self.params.get("objective", ""))
        with global_timer.scope("stream_gate_eval"):
            cand_loss = health.prediction_loss(
                candidate.predict(X), y, objective)
            base_loss = health.prediction_loss(
                self.booster.predict(X), y, objective)
        if cand_loss <= base_loss * (1.0 + self.gate_tolerance) + 1e-12:
            return True
        reject = GenerationRejected(
            f"generation {self.generation}: holdout loss {cand_loss:.6g} "
            f"vs serving {base_loss:.6g} exceeds tolerance "
            f"{self.gate_tolerance:.3g}")
        Log.warning("continuous: %s; generation rolled back, serving keeps "
                    "the last published model", reject)
        tracing.note("stream_generation_rejected",
                     generation=self.generation,
                     candidate_loss=float(cand_loss),
                     serving_loss=float(base_loss),
                     tolerance=self.gate_tolerance)
        if telemetry.enabled():
            telemetry.emit("generation_rejected",
                           generation=self.generation,
                           candidate_loss=float(cand_loss),
                           serving_loss=float(base_loss),
                           tolerance=self.gate_tolerance,
                           holdout_rows=int(len(y)))
        global_timer.add_count("stream_generation_rejected", 1)
        tracing.dump_flight("generation_rejected")
        return False

    def _publish(self, booster) -> None:
        """Atomic hot-swap into the serving front (no-op without one).
        With canary_fraction > 0 and a model already serving, the swap is
        staged: PredictionService routes a traffic fraction to the
        candidate and promotes (or auto-rolls-back) on its own evidence."""
        if self.service is not None:
            if self.canary_fraction > 0.0 and self.booster is not None:
                self.service.start_canary(
                    self.model_name, booster=booster,
                    fraction=self.canary_fraction,
                    promote_after=self.canary_promote_after)
            else:
                self.service.load_model(self.model_name, booster=booster)
        elif self.registry is not None:
            self.registry.load(self.model_name, booster=booster)
