"""Continuous-training flywheel: push -> refit -> checkpoint -> hot-swap.

ContinuousTrainer closes the train->serve loop over a live RowBlockStore:

  * `step()` refits when at least `min_new_rows` rows have landed since
    the last published model (always on the first call).
  * Each refit pins a ROW WATERMARK before snapshotting the store, and
    the watermark survives a mid-refit crash: a retried `refit()` for the
    same generation finalizes the identical row range even if pushes kept
    arriving, so the checkpoint-resumed run trains on the exact dataset
    the crashed run saw — the precondition for bit-identical resume.
  * Training runs through engine.train with a per-generation
    checkpoint_callback (checkpoint.py's crash-consistent atomic writer).
    If the generation's checkpoint file already exists when refit starts,
    it is handed to engine.train as init_model — the same-command resume
    path, which subtracts the finished iterations and replays the rest
    bit-identically.
  * On success the booster is published into the PR 9 serving
    ModelRegistry (or a PredictionService, which also re-warms and
    re-baselines its breaker) — an atomic pointer swap, so concurrent
    predicts never observe a half-loaded model.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

from .. import engine, tracing
from ..checkpoint import checkpoint_callback
from ..parallel.elastic import WorkerLostError
from ..utils.timer import global_timer
from .. import telemetry
from ..utils.log import Log
from .ingest import RowBlockStore, wrap_dataset


class ContinuousTrainer:
    def __init__(self, params: Dict[str, Any], store: RowBlockStore, *,
                 num_boost_round: int = 20,
                 min_new_rows: int = 1,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_period: int = 1,
                 registry=None, service=None,
                 model_name: str = "live") -> None:
        self.params = dict(params)
        self.store = store
        self.num_boost_round = int(num_boost_round)
        self.min_new_rows = int(min_new_rows)
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_period = int(checkpoint_period)
        self.registry = registry
        self.service = service
        self.model_name = model_name
        self.generation = 0
        self.booster = None
        self._trained_rows = 0
        # crash-consistency watermark: rows pinned by an unfinished refit
        self._inflight_rows: Optional[int] = None

    # ------------------------------------------------------------- refit

    def checkpoint_path(self, generation: Optional[int] = None) -> Optional[str]:
        if not self.checkpoint_dir:
            return None
        gen = self.generation if generation is None else generation
        return os.path.join(self.checkpoint_dir, f"refit_gen{gen:04d}.txt")

    def step(self):
        """Refit if enough fresh rows landed; returns the new Booster or
        None when below the threshold."""
        fresh = self.store.total_rows - self._trained_rows
        if self.booster is not None and fresh < self.min_new_rows \
                and self._inflight_rows is None:
            return None
        return self.refit()

    def refit(self):
        """One generation: snapshot -> train (checkpointed) -> publish."""
        if self._inflight_rows is None:
            self._inflight_rows = self.store.total_rows
        rows = self._inflight_rows
        core = self.store.finalize(rows)
        train_set = wrap_dataset(core, params=self.params)
        callbacks = []
        init_model = None
        ckpt = self.checkpoint_path()
        if ckpt:
            os.makedirs(self.checkpoint_dir, exist_ok=True)
            callbacks.append(checkpoint_callback(
                ckpt, period=self.checkpoint_period))
            if os.path.exists(ckpt):
                # a crashed refit of THIS generation left a snapshot:
                # resume it (engine.train subtracts finished iterations
                # and replays the remainder bit-identically)
                init_model = ckpt
                Log.info("continuous: resuming generation %d from %s",
                         self.generation, ckpt)
        try:
            with global_timer.scope("stream_refit"):
                booster = engine.train(
                    self.params, train_set,
                    num_boost_round=self.num_boost_round,
                    init_model=init_model, callbacks=callbacks)
        except WorkerLostError as exc:
            # a gang peer died mid-refit: roll this generation back to its
            # pinned checkpoint. The watermark stays pinned and the
            # generation counter does NOT advance, so the next refit()
            # resumes the SAME row range from the same-generation snapshot;
            # serving keeps answering from the last published model the
            # whole time (nothing was swapped).
            Log.warning("continuous: worker lost mid-refit of generation "
                        "%d (rank %d, last good iteration %d); generation "
                        "rolled back to its pinned checkpoint, serving "
                        "keeps the last published model", self.generation,
                        exc.rank, exc.last_good_iteration)
            tracing.note("stream_refit_worker_lost",
                         generation=self.generation, rank=exc.rank,
                         last_good_iteration=exc.last_good_iteration)
            if telemetry.enabled():
                telemetry.emit("stream_refit_worker_lost",
                               generation=self.generation, rank=exc.rank,
                               last_good_iteration=exc.last_good_iteration)
            global_timer.add_count("stream_refit_worker_lost", 1)
            return None
        self._publish(booster)
        self.booster = booster
        self._trained_rows = rows
        self._inflight_rows = None
        self.generation += 1
        global_timer.set_count("stream_generation", self.generation)
        if telemetry.enabled():
            telemetry.emit("stream_refit", generation=self.generation,
                           rows=rows)
        return booster

    def _publish(self, booster) -> None:
        """Atomic hot-swap into the serving front (no-op without one)."""
        if self.service is not None:
            self.service.load_model(self.model_name, booster=booster)
        elif self.registry is not None:
            self.registry.load(self.model_name, booster=booster)
