"""Drift detection + bin-mapper refresh for the streaming flywheel.

ROADMAP item 3(c): bin cut points are fitted once on the sampled prefix
and pinned forever, so a drifting feature distribution silently degrades
bin resolution — every out-of-support value piles into one edge bin —
until a model-breaking refit-from-scratch. This module closes that gap
with three pieces, all numpy-only and all optional (``LGBM_TPU_DRIFT``):

* **QuantileSketch** — a mergeable multi-level compacting sketch (the
  Manku/KLL shape): values buffer at level 0; a full level sorts and
  keeps every other element at doubled weight, cascading upward. O(1)
  amortized per row, O(k log(n/k)) retained values, and deterministic —
  compaction parity alternates instead of flipping coins, so two runs
  over the same pushes hold byte-identical sketches (the chaos tests
  replay on this). Zeros and NaNs are counted, not stored, mirroring
  the sparse sample convention ``BinMapper.find_bin`` expects.
* **DriftMonitor** — per-feature sketches plus bin-occupancy counters
  against the binning-time reference distribution. Every
  ``check_rows`` ingested rows it computes a PSI-style drift score and
  an edge-bin overflow fraction per feature; scores land in the gauge
  namespace (``drift_psi_milli_max`` → /metrics), the /statz ``drift``
  section (``latest()``), and — above ``LGBM_TPU_DRIFT_THRESHOLD`` —
  a latched alarm with a ``flight-drift_alarm`` postmortem dump.
* **Mapper refresh** — ``refit_mapper_from_sketch`` reconstructs a
  sampled-prefix-shaped value array from the sketch (rank-uniform
  quantile sample + scaled zero/NaN counts) and runs the one true
  ``find_bin`` over it, so refreshed cut points come from the same
  binning code the original layout used. RowBlockStore applies the
  result as a measured event (``maybe_refresh_bins``); previously
  published models are untouched by construction — tree thresholds are
  real-valued at the model surface (``Dataset.real_threshold`` /
  ``BinMapper.bin_to_value``), so a mapper swap cannot move a single
  published prediction bit.

When ``LGBM_TPU_DRIFT`` is unset/0 nothing here is constructed: ingest
pays one ``is None`` check per push and trains bit-identical models.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .. import telemetry, tracing
from ..io.binning import BIN_TYPE_NUMERICAL, MISSING_NAN, BinMapper
from ..utils import faults
from ..utils.log import Log
from ..utils.timer import global_timer

DRIFT_ENV = "LGBM_TPU_DRIFT"
THRESHOLD_ENV = "LGBM_TPU_DRIFT_THRESHOLD"      # PSI alarm level (0.25)
CHECK_ROWS_ENV = "LGBM_TPU_DRIFT_CHECK_ROWS"    # score cadence in rows (1024)
REFRESH_EVERY_ENV = "LGBM_TPU_BIN_REFRESH_EVERY"  # scheduled refresh (gens)

DEFAULT_THRESHOLD = 0.25
DEFAULT_CHECK_ROWS = 1024
_PSI_EPS = 1e-6


def enabled() -> bool:
    """Drift detection opt-in. Off means ZERO overhead: RowBlockStore
    constructs no monitor and push_rows pays one None check."""
    return os.environ.get(DRIFT_ENV, "0").lower() not in (
        "0", "", "false", "off")


# --------------------------------------------------------------- sketch

class QuantileSketch:
    """Deterministic mergeable streaming quantile sketch.

    Level i holds values of weight 2**i. update() appends to the level-0
    buffer; a level reaching ``k`` items is sorted and every other item
    survives at double weight (alternating parity — no RNG), cascading
    into the next level. Total retained values stay O(k * levels).
    """

    __slots__ = ("k", "levels", "nonzero_n", "zero_n", "nan_n", "_parity")

    def __init__(self, k: int = 256) -> None:
        self.k = max(8, int(k))
        self.levels: List[np.ndarray] = [np.empty(0, dtype=np.float64)]
        self.nonzero_n = 0
        self.zero_n = 0
        self.nan_n = 0
        self._parity = 0

    def update(self, col: np.ndarray) -> None:
        """Fold one column block in. Zeros/NaNs are counted, not stored
        (the find_bin sparse-sample convention)."""
        col = np.asarray(col, dtype=np.float64).ravel()
        nan_mask = np.isnan(col)
        nz = col[(col != 0.0) & ~nan_mask]
        self.nan_n += int(nan_mask.sum())
        self.zero_n += int(len(col) - len(nz) - nan_mask.sum())
        if len(nz) == 0:
            return
        self.nonzero_n += len(nz)
        self.levels[0] = np.concatenate([self.levels[0], nz])
        self._compact()

    def _compact(self) -> None:
        lvl = 0
        while lvl < len(self.levels) and len(self.levels[lvl]) >= self.k:
            survivors = np.sort(self.levels[lvl],
                                kind="stable")[self._parity::2]
            self._parity ^= 1
            self.levels[lvl] = np.empty(0, dtype=np.float64)
            if lvl + 1 == len(self.levels):
                self.levels.append(np.empty(0, dtype=np.float64))
            self.levels[lvl + 1] = np.concatenate(
                [self.levels[lvl + 1], survivors])
            lvl += 1

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Mergeable-sketch contract: fold `other` in level-by-level
        (weights align), then re-compact. Counts are additive."""
        while len(self.levels) < len(other.levels):
            self.levels.append(np.empty(0, dtype=np.float64))
        for i, lv in enumerate(other.levels):
            if len(lv):
                self.levels[i] = np.concatenate([self.levels[i], lv])
        self.nonzero_n += other.nonzero_n
        self.zero_n += other.zero_n
        self.nan_n += other.nan_n
        self._compact()
        return self

    def copy(self) -> "QuantileSketch":
        dup = QuantileSketch(self.k)
        dup.levels = [lv.copy() for lv in self.levels]
        dup.nonzero_n = self.nonzero_n
        dup.zero_n = self.zero_n
        dup.nan_n = self.nan_n
        dup._parity = self._parity
        return dup

    def healthy(self) -> bool:
        """update() strips NaN before storing, so a NaN inside a level is
        impossible organically — it is the ``sketch_corrupt`` signature
        (torn memory, a buggy merge). Detect it instead of refitting cut
        points from garbage."""
        return not any(np.isnan(lv).any() for lv in self.levels if len(lv))

    def weighted(self):
        """(values, weights) over every retained item, sorted by value."""
        vals = np.concatenate([lv for lv in self.levels])
        wts = np.concatenate([
            np.full(len(lv), 1 << i, dtype=np.int64)
            for i, lv in enumerate(self.levels)])
        order = np.argsort(vals, kind="stable")
        return vals[order], wts[order]

    def quantile_sample(self, m: int) -> np.ndarray:
        """A deterministic m-value sample at rank-uniform quantiles of the
        sketched (non-zero) distribution — the stand-in for the raw
        sampled prefix that find_bin refits cut points from."""
        if self.nonzero_n == 0 or m <= 0:
            return np.empty(0, dtype=np.float64)
        vals, wts = self.weighted()
        if len(vals) == 0:
            return np.empty(0, dtype=np.float64)
        cum = np.cumsum(wts, dtype=np.float64)
        ranks = (np.arange(m, dtype=np.float64) + 0.5) / m * cum[-1]
        idx = np.searchsorted(cum, ranks, side="left")
        return vals[np.minimum(idx, len(vals) - 1)]


def merge_ranked(pairs) -> QuantileSketch:
    """Order-canonicalized gang merge: fold ``(rank, sketch)`` pairs in
    ascending RANK order into a fresh sketch, leaving the inputs intact.

    ``QuantileSketch.merge`` is order-dependent (concatenation order and
    the alternating compaction parity both depend on the fold sequence),
    so merging shard sketches in arrival order would make the merged
    sketch — and therefore refreshed cut points — differ across reruns
    and across ranks. Canonicalizing on the rank key makes the result a
    pure function of the shard sketches, byte-stable no matter which
    order the gang's payloads landed in.
    """
    items = sorted(pairs, key=lambda rs: int(rs[0]))
    ranks = [int(r) for r, _ in items]
    if len(set(ranks)) != len(ranks):
        raise ValueError("merge_ranked needs distinct ranks, got %r" % ranks)
    if not items:
        return QuantileSketch()
    out = items[0][1].copy()
    for _, sk in items[1:]:
        out.merge(sk)
    return out


# ------------------------------------------------------------ refitting

def refit_mapper_from_sketch(mapper: BinMapper, sketch: QuantileSketch,
                             config, max_bin: int) -> Optional[BinMapper]:
    """Refit one feature's cut points from its sketch, through the same
    ``find_bin`` the original layout used. Returns None (keep the old
    mapper) for categorical/trivial features, starved or unhealthy
    sketches, or a refit that degenerates to a trivial mapper."""
    if mapper.bin_type != BIN_TYPE_NUMERICAL or mapper.is_trivial:
        return None
    if sketch is None or sketch.nonzero_n == 0:
        return None
    if not sketch.healthy():
        return None
    m = int(min(config.bin_construct_sample_cnt, sketch.nonzero_n))
    sample = sketch.quantile_sample(m)
    scale = m / max(sketch.nonzero_n, 1)
    nan_scaled = int(round(sketch.nan_n * scale))
    zero_scaled = int(round(sketch.zero_n * scale))
    values = (np.concatenate([sample, np.full(nan_scaled, np.nan)])
              if nan_scaled else sample)
    new = BinMapper()
    new.find_bin(values, m + nan_scaled + zero_scaled, max_bin,
                 min_data_in_bin=config.min_data_in_bin,
                 min_split_data=config.min_data_in_leaf,
                 pre_filter=False,  # never let a refresh drop a live feature
                 bin_type=BIN_TYPE_NUMERICAL,
                 use_missing=config.use_missing,
                 zero_as_missing=config.zero_as_missing)
    if new.is_trivial or new.num_bin < 2:
        return None
    return new


def feature_bin_lut(old: BinMapper, new: BinMapper) -> np.ndarray:
    """old-bin → new-bin lookup table, via each old bin's representative
    (upper-bound) value re-binned through the new mapper. NaN bins map to
    NaN bins (or bin 0 when the refreshed mapper dropped missing)."""
    nb = old.num_bin
    n_search = nb - 1 if old.missing_type == MISSING_NAN else nb
    reps = np.empty(nb, dtype=np.float64)
    reps[:n_search] = old.bin_upper_bound[:n_search]
    if n_search < nb:
        reps[n_search:] = np.nan
    return new.values_to_bins(reps).astype(np.int64)


def group_bin_lut(old_fg, new_fg) -> np.ndarray:
    """Group-plane old-bin → new-bin LUT for one FeatureGroup, composed
    from the per-member feature LUTs. Group structure (member list and
    order) is preserved across a refresh, only offsets move."""
    if not old_fg.is_multi:
        return feature_bin_lut(old_fg.mappers[0], new_fg.mappers[0])
    lut = np.zeros(old_fg.num_total_bin, dtype=np.int64)
    for mi, m_old in enumerate(old_fg.mappers):
        m_new = new_fg.mappers[mi]
        flut = feature_bin_lut(m_old, m_new)
        off_old = old_fg.bin_offsets[mi]
        off_new = new_fg.bin_offsets[mi]
        for b in range(m_old.num_bin):
            if b == m_old.default_bin:
                continue
            g_old = off_old + b - (1 if b > m_old.default_bin else 0)
            nb = int(flut[b])
            g_new = (0 if nb == m_new.default_bin
                     else off_new + nb - (1 if nb > m_new.default_bin else 0))
            lut[g_old] = g_new
    return lut


# -------------------------------------------------------------- monitor

# last computed scores, for /statz and the serving stats surface;
# written under the owning store's lock, read lock-free (atomic rebind)
_latest: Dict[str, Any] = {}


def latest() -> Dict[str, Any]:
    """Most recent drift summary across monitors ({} when disabled)."""
    return dict(_latest)


class DriftMonitor:
    """Per-feature drift state for one RowBlockStore (constructed only
    when ``LGBM_TPU_DRIFT`` is on — see ``from_env``)."""

    @classmethod
    def from_env(cls, config,
                 categorical_feature: Sequence[int] = ()
                 ) -> Optional["DriftMonitor"]:
        if not enabled():
            return None
        thr = float(os.environ.get(THRESHOLD_ENV, "") or DEFAULT_THRESHOLD)
        rows = int(os.environ.get(CHECK_ROWS_ENV, "") or DEFAULT_CHECK_ROWS)
        return cls(config, categorical_feature, threshold=thr,
                   check_rows=rows)

    def __init__(self, config, categorical_feature: Sequence[int] = (),
                 threshold: float = DEFAULT_THRESHOLD,
                 check_rows: int = DEFAULT_CHECK_ROWS,
                 sketch_k: int = 256) -> None:
        self.config = config
        self.categorical = set(int(c) for c in categorical_feature)
        self.threshold = float(threshold)
        self.check_rows = max(1, int(check_rows))
        self.sketch_k = int(sketch_k)
        self.sketches: List[Optional[QuantileSketch]] = []
        self.alarmed = False
        self.alarm_feature: Optional[int] = None
        self._ref: Dict[int, np.ndarray] = {}    # reference occupancy
        self._cur: Dict[int, np.ndarray] = {}    # current-window occupancy
        self._layout = None
        self._rows_since_check = 0
        self.scores: Dict[int, Dict[str, float]] = {}
        # flight-dump tag recorded under the caller's lock, written by
        # flush_pending() once the lock is released
        self._pending_dump: Optional[str] = None

    def flush_pending(self) -> None:
        """Write the flight dump a locked _check() recorded. Callers MUST
        hold no lock here — dump_flight does file I/O (R13)."""
        tag, self._pending_dump = self._pending_dump, None
        if tag is not None:
            tracing.dump_flight(tag)

    # ---------------------------------------------------------- observe

    def observe(self, block: np.ndarray, layout) -> None:
        """Fold one pushed block into the sketches (always) and the
        bin-occupancy window (once a layout exists). Called under the
        store lock from push_rows."""
        n_feat = block.shape[1]
        while len(self.sketches) < n_feat:
            j = len(self.sketches)
            self.sketches.append(None if j in self.categorical
                                 else QuantileSketch(self.sketch_k))
        for j in range(n_feat):
            sk = self.sketches[j]
            if sk is not None:
                sk.update(block[:, j])
        if layout is not None:
            self._layout = layout
            for j in self._ref:
                mapper = layout.mappers[j]
                bins = mapper.values_to_bins(
                    np.asarray(block[:, j], dtype=np.float64))
                self._cur[j] += np.bincount(
                    bins, minlength=mapper.num_bin)[:mapper.num_bin]
            self._rows_since_check += block.shape[0]
            if self._rows_since_check >= self.check_rows:
                self._check()

    def set_reference(self, layout, prefix: np.ndarray) -> None:
        """Capture the binning-time occupancy baseline from the fitted
        prefix — the distribution every later window scores against."""
        self._layout = layout
        self._ref.clear()
        self._cur.clear()
        for j in layout.used_features:
            mapper = layout.mappers[j]
            if mapper.bin_type != BIN_TYPE_NUMERICAL or mapper.is_trivial:
                continue
            bins = mapper.values_to_bins(
                np.asarray(prefix[:, j], dtype=np.float64))
            self._ref[j] = np.bincount(
                bins, minlength=mapper.num_bin)[:mapper.num_bin].astype(
                    np.float64)
            self._cur[j] = np.zeros(mapper.num_bin, dtype=np.float64)

    # ------------------------------------------------------------ score

    @staticmethod
    def psi(ref: np.ndarray, cur: np.ndarray) -> float:
        """Population-stability index between two occupancy vectors,
        epsilon-smoothed so empty bins stay finite."""
        p = (ref + _PSI_EPS) / (ref.sum() + _PSI_EPS * len(ref))
        q = (cur + _PSI_EPS) / (cur.sum() + _PSI_EPS * len(cur))
        return float(np.sum((q - p) * np.log(q / p)))

    @staticmethod
    def edge_overflow(mapper: BinMapper, ref: np.ndarray,
                      cur: np.ndarray) -> float:
        """Excess share of the current window landing in the extreme
        finite bins vs the reference — the out-of-support signature."""
        top = (mapper.num_bin - 2 if mapper.missing_type == MISSING_NAN
               else mapper.num_bin - 1)
        if top < 0 or cur.sum() <= 0:
            return 0.0
        rs, cs = max(ref.sum(), 1.0), cur.sum()
        hi = max(0.0, cur[top] / cs - ref[top] / rs)
        lo = max(0.0, cur[0] / cs - ref[0] / rs)
        return float(max(hi, lo))

    def _check(self) -> None:
        self._rows_since_check = 0
        k = faults.sketch_corrupt_feature()
        if k is not None and 0 <= k < len(self.sketches) \
                and self.sketches[k] is not None:
            # planted corruption: NaN garbage lands inside a level, which
            # healthy() flags and the next refresh discards
            self.sketches[k].levels[0] = np.concatenate(
                [self.sketches[k].levels[0], np.full(4, np.nan)])
        worst_psi, worst_edge, worst_feat = 0.0, 0.0, None
        for j, ref in self._ref.items():
            cur = self._cur[j]
            if cur.sum() <= 0:
                continue
            mapper = self._layout.mappers[j]
            s_psi = self.psi(ref, cur)
            s_edge = self.edge_overflow(mapper, ref, cur)
            self.scores[j] = {"psi": round(s_psi, 6),
                              "edge_overflow": round(s_edge, 6)}
            if s_psi > worst_psi:
                worst_psi, worst_feat = s_psi, j
            worst_edge = max(worst_edge, s_edge)
        global_timer.add_count("drift_checks", 1)
        global_timer.set_count("drift_psi_milli_max", int(worst_psi * 1000))
        global_timer.set_count("drift_edge_milli_max", int(worst_edge * 1000))
        global_timer.set_count("drift_features_tracked", len(self._ref))
        global _latest
        _latest = {
            "enabled": True,
            "max_psi": round(worst_psi, 6),
            "max_edge_overflow": round(worst_edge, 6),
            "worst_feature": worst_feat,
            "threshold": self.threshold,
            "alarmed": self.alarmed,
            "features": {int(j): dict(s) for j, s in
                         sorted(self.scores.items(),
                                key=lambda kv: -kv[1]["psi"])[:8]},
        }
        if worst_psi >= self.threshold and not self.alarmed:
            self.alarmed = True
            self.alarm_feature = worst_feat
            global_timer.add_count("drift_alarms", 1)
            Log.warning("drift: PSI %.4f on feature %s crossed the %.2f "
                        "alarm threshold (edge overflow %.4f); bin refresh "
                        "pending", worst_psi, worst_feat, self.threshold,
                        worst_edge)
            tracing.note("drift_alarm", feature=worst_feat,
                         psi=round(worst_psi, 6),
                         edge_overflow=round(worst_edge, 6))
            if telemetry.enabled():
                telemetry.emit("drift_alarm", feature=worst_feat,
                               psi=round(worst_psi, 6),
                               edge_overflow=round(worst_edge, 6),
                               threshold=self.threshold)
            # observe() runs under the ingest store's push lock; the
            # postmortem dump does file I/O, so record it here and let the
            # store write it after release (breaker _maybe_dump
            # convention, R13)
            self._pending_dump = "drift_alarm"

    # ---------------------------------------------------------- refresh

    def refit_mapper(self, j: int, mapper: BinMapper) -> Optional[BinMapper]:
        """Refreshed mapper for feature j, or None to keep the old one.
        A corrupt sketch is discarded (and replaced fresh) rather than
        trusted — the ``sketch_corrupt`` containment path."""
        if j >= len(self.sketches):
            return None
        sk = self.sketches[j]
        if sk is None:
            return None
        if not sk.healthy():
            global_timer.add_count("drift_sketch_discarded", 1)
            Log.warning("drift: sketch for feature %d holds non-finite "
                        "garbage; discarding it and keeping the current "
                        "cut points", j)
            tracing.note("drift_sketch_discarded", feature=j)
            if telemetry.enabled():
                telemetry.emit("drift_sketch_discarded", feature=j)
            self.sketches[j] = QuantileSketch(self.sketch_k)
            return None
        mb = self.config.max_bin
        if self.config.max_bin_by_feature \
                and j < len(self.config.max_bin_by_feature):
            mb = self.config.max_bin_by_feature[j]
        return refit_mapper_from_sketch(mapper, sk, self.config, mb)

    def after_refresh(self, layout) -> None:
        """Re-anchor the occupancy baseline on the refreshed mappers: the
        reference becomes the sketch's own distribution binned through
        the new cut points, and the window + alarm reset."""
        self._layout = layout
        self._ref.clear()
        self._cur.clear()
        for j in layout.used_features:
            mapper = layout.mappers[j]
            if mapper.bin_type != BIN_TYPE_NUMERICAL or mapper.is_trivial:
                continue
            sk = self.sketches[j] if j < len(self.sketches) else None
            if sk is None or sk.nonzero_n == 0:
                continue
            m = int(min(self.config.bin_construct_sample_cnt, sk.nonzero_n))
            bins = mapper.values_to_bins(sk.quantile_sample(m))
            ref = np.bincount(bins, minlength=mapper.num_bin)[
                :mapper.num_bin].astype(np.float64)
            scale = m / max(sk.nonzero_n, 1)
            zero_bin = int(mapper.values_to_bins(np.zeros(1))[0])
            ref[zero_bin] += sk.zero_n * scale
            self._ref[j] = ref
            self._cur[j] = np.zeros(mapper.num_bin, dtype=np.float64)
        self.alarmed = False
        self.alarm_feature = None

    def summary(self) -> Dict[str, Any]:
        return dict(_latest) if _latest else {"enabled": True,
                                              "max_psi": 0.0,
                                              "alarmed": False}
