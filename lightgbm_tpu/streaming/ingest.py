"""Streaming ingest: chunked row-block builder behind LGBM_DatasetPushRows*.

RowBlockStore is the counterpart of the reference's streaming dataset
construction (`LGBM_DatasetCreateByReference` + `LGBM_DatasetPushRows` /
`LGBM_DatasetPushRowsByCSR`, c_api.cpp): callers push row blocks
incrementally — numpy matrices, CSR chunks, chunked CSV files, or python
iterators — and the store produces an io/dataset.py core Dataset without
ever materializing the raw feature matrix.

Mechanics:

  * Raw blocks buffer on host until `bin_sample_rows` rows have arrived
    (default: Config.bin_construct_sample_cnt). The bin layout — per-feature
    BinMappers, used features, EFB group lists — is then fitted once on the
    buffered prefix via Dataset._fit_layout, after which every block (the
    buffered ones first, then each new push) is binned immediately through
    Dataset._bin_rows into a C-contiguous [num_groups, block_rows] plane
    slab and the raw block is dropped. Peak host memory is the uint8/uint16
    bin blocks plus one raw block in flight.
  * Binning is per-row independent, so the concatenated block planes are
    byte-identical to a one-shot Dataset.from_matrix over the same layout.
    When total pushed rows stay at or below the sample budget the fitted
    layout itself matches one-shot construction exactly (the "prefix" is
    the whole matrix, and the sampling RNG draws identically), so the
    finalized dataset is indistinguishable from the in-memory build — the
    equivalence tier-1 tests lock. Past the sample budget, layouts are
    fitted from the prefix sample rather than a global sample: same shape,
    slightly different cut points, exactly like the reference's
    sampled-prefix StreamingDataset contract.
  * finalize() snapshots the store into a core Dataset (optionally only the
    first `num_rows` rows — the continuous trainer pins a row watermark so
    a crash-resumed refit sees the identical dataset even while pushes keep
    landing). The store stays open for more pushes afterwards.
"""
from __future__ import annotations

import threading
from typing import Iterable, List, Optional, Sequence

import numpy as np

from ..config import Config
from ..io.dataset import Dataset as CoreDataset
from ..utils import faults
from ..utils.log import Log
from ..utils.timer import global_timer
from .. import telemetry, tracing
from . import drift


def _as_block(data) -> np.ndarray:
    """Normalize one pushed block to a 2-D float matrix, mirroring
    from_matrix's dtype rule (f32/f64 kept, everything else -> f64)."""
    block = np.asarray(data)
    if block.ndim == 1:
        block = block.reshape(1, -1)
    if block.ndim != 2:
        raise ValueError(f"pushed block must be 2-D, got shape {block.shape}")
    if block.dtype not in (np.float32, np.float64):
        block = block.astype(np.float64)
    return block


def _csr_to_dense(indptr: np.ndarray, indices: np.ndarray, values: np.ndarray,
                  num_col: int) -> np.ndarray:
    """Densify one CSR chunk (reference PushRowsByCSR semantics: absent
    entries are 0.0, duplicate column entries keep the last write)."""
    indptr = np.asarray(indptr, dtype=np.int64)
    indices = np.asarray(indices, dtype=np.int64)
    values = np.asarray(values, dtype=np.float64)
    nrow = len(indptr) - 1
    block = np.zeros((nrow, int(num_col)), dtype=np.float64)
    for r in range(nrow):
        lo, hi = indptr[r], indptr[r + 1]
        block[r, indices[lo:hi]] = values[lo:hi]
    return block


class RowBlockStore:
    """Incremental row-block dataset builder (streaming ingest front).

    Thread-safe for one pusher at a time interleaved with finalize() from
    another thread (the continuous-training flywheel's pattern).
    """

    def __init__(self, params: Optional[dict] = None,
                 config: Optional[Config] = None,
                 n_features: Optional[int] = None,
                 categorical_feature: Sequence[int] = (),
                 feature_names: Optional[Sequence[str]] = None,
                 bin_sample_rows: Optional[int] = None,
                 holdout_rows: int = 0) -> None:
        self.config = config or Config(dict(params) if params else {})
        self.n_features = int(n_features) if n_features else None
        self.categorical_feature = tuple(categorical_feature)
        self.feature_names = list(feature_names) if feature_names else None
        self.bin_sample_rows = int(bin_sample_rows
                                   if bin_sample_rows is not None
                                   else self.config.bin_construct_sample_cnt)
        self._lock = threading.RLock()
        self._raw_blocks: List[np.ndarray] = []      # pre-layout buffer
        self._raw_labels: List[Optional[np.ndarray]] = []
        self._bin_blocks: List[np.ndarray] = []      # [G, rows] slabs
        self._labels: List[Optional[np.ndarray]] = []  # aligned with pushes
        self._weights: List[Optional[np.ndarray]] = []
        self._layout: Optional[CoreDataset] = None
        self.total_rows = 0
        # full-array metadata overrides (C-API LGBM_DatasetSetField routing)
        self._field_overrides: dict = {}
        # drift detection (None unless LGBM_TPU_DRIFT is on: the hot push
        # path then pays exactly one is-None check)
        self._drift = drift.DriftMonitor.from_env(
            self.config, self.categorical_feature)
        # group composition pinned at the prefix fit; a bin refresh refits
        # cut points but keeps the EFB bundles (history can't re-conflict)
        self._group_lists: Optional[List[List[int]]] = None
        # bin layout generation: bumped by every maybe_refresh_bins swap,
        # recorded in checkpoint sidecars for resume verification
        self.layout_generation = 0
        # raw tail ring for the publish quality gate's pinned holdout
        self.holdout_rows = int(holdout_rows)
        self._tail: List[tuple] = []   # (raw block, label) most-recent-last
        self._tail_n = 0

    # ------------------------------------------------------------------ push

    def push_rows(self, data, label=None, weight=None) -> "RowBlockStore":
        """Push one row block. Feature count is pinned by the first push
        (or the n_features constructor arg — the C-API contract)."""
        block = _as_block(data)
        if label is not None:
            label = np.asarray(label, dtype=np.float64).ravel()
            if len(label) != block.shape[0]:
                raise ValueError("label length does not match pushed rows")
        if weight is not None:
            weight = np.asarray(weight, dtype=np.float64).ravel()
            if len(weight) != block.shape[0]:
                raise ValueError("weight length does not match pushed rows")
        with self._lock:
            if self.n_features is None:
                self.n_features = block.shape[1]
            elif block.shape[1] != self.n_features:
                raise ValueError(
                    f"pushed block has {block.shape[1]} features, "
                    f"store expects {self.n_features}")
            # graftlint: disable=lock-discipline -- chaos-path only: _emit_fault flight-dumps solely when an injected fault fires; production runs have no fault plan installed, so the steady-state path under this lock never touches the filesystem
            block = faults.maybe_shift_block(block, self.total_rows)
            if self._drift is not None:
                self._drift.observe(block, self._layout)
            if self.holdout_rows > 0:
                self._tail.append((block, label))
                self._tail_n += block.shape[0]
                while self._tail and \
                        self._tail_n - self._tail[0][0].shape[0] \
                        >= self.holdout_rows:
                    self._tail_n -= self._tail.pop(0)[0].shape[0]
            self._labels.append(label)
            self._weights.append(weight)
            if self._layout is None:
                self._raw_blocks.append(block)
                self._buffered = getattr(self, "_buffered", 0) + block.shape[0]
                if self._buffered >= self.bin_sample_rows:
                    # graftlint: disable=lock-discipline -- one-shot layout fit: runs exactly once per stream when the bin sample fills; the forced-bins file read inside Dataset._fit_layout is part of that single fit and must stay atomic with the drain it guards
                    self._fit_and_drain()
            else:
                self._bin_blocks.append(
                    np.ascontiguousarray(self._layout._bin_rows(block)))
            self.total_rows += block.shape[0]
            global_timer.add_count("stream_ingest_rows", block.shape[0])
            global_timer.add_count("stream_ingest_bytes", block.nbytes)
        if self._drift is not None:
            self._drift.flush_pending()  # drift-alarm dump, outside _lock
        return self

    def push_csr(self, indptr, indices, values, num_col: int,
                 label=None, weight=None) -> "RowBlockStore":
        """Push one CSR chunk (LGBM_DatasetPushRowsByCSR parity)."""
        return self.push_rows(_csr_to_dense(indptr, indices, values, num_col),
                              label=label, weight=weight)

    def push_csv(self, path: str, chunk_rows: int = 65536,
                 header: Optional[bool] = None,
                 label_column: Optional[str] = None) -> "RowBlockStore":
        """Parse a CSV/TSV file (io/parser.py dialect) and push it in
        chunk_rows-sized blocks — the file is parsed once, streamed in."""
        from ..io.parser import parse_file

        X, y, names = parse_file(
            path,
            header=self.config.header if header is None else header,
            label_column=(label_column if label_column is not None
                          else (self.config.label_column or "0")))
        if self.feature_names is None and names:
            self.feature_names = list(names)
        for lo in range(0, X.shape[0], int(chunk_rows)):
            hi = min(X.shape[0], lo + int(chunk_rows))
            self.push_rows(X[lo:hi], label=y[lo:hi] if y is not None else None)
        return self

    def push_from_iterator(self, blocks: Iterable) -> "RowBlockStore":
        """Drain an iterator of blocks: each item is either a matrix or an
        (X, y) tuple. The chunked-iterator source for CI's streaming smoke."""
        for item in blocks:
            if isinstance(item, tuple):
                X, y = item
                self.push_rows(X, label=y)
            else:
                self.push_rows(item)
        return self

    # ------------------------------------------------- C-API duck surface
    # (capi/impl.py routes LGBM_Dataset* calls through these so a streaming
    # handle drops into every shim that expects a basic.Dataset)

    def num_data(self) -> int:
        return self.total_rows

    def num_feature(self) -> int:
        return self.n_features or 0

    def set_label(self, label) -> "RowBlockStore":
        self._field_overrides["label"] = np.asarray(label, dtype=np.float64).ravel()
        return self

    def set_weight(self, weight) -> "RowBlockStore":
        self._field_overrides["weight"] = (
            None if weight is None else np.asarray(weight, dtype=np.float64).ravel())
        return self

    def set_group(self, group) -> "RowBlockStore":
        self._field_overrides["group"] = np.asarray(group).ravel()
        return self

    def set_init_score(self, init_score) -> "RowBlockStore":
        self._field_overrides["init_score"] = (
            None if init_score is None else np.asarray(init_score, dtype=np.float64))
        return self

    def set_position(self, position) -> "RowBlockStore":
        self._field_overrides["position"] = np.asarray(position).ravel()
        return self

    # -------------------------------------------------------------- layout

    def _fit_and_drain(self) -> None:
        """Fit the bin layout on the buffered prefix, then bin and drop
        every buffered raw block. Called under self._lock."""
        prefix = (self._raw_blocks[0] if len(self._raw_blocks) == 1
                  else np.concatenate(self._raw_blocks, axis=0))
        # the last block can overshoot the sample budget; fit on EXACTLY
        # bin_sample_rows rows so the cut points depend only on the pushed
        # row sequence, never on how callers chunked it (the overshoot rows
        # still get binned below — only the fit sample is clipped)
        prefix = prefix[:self.bin_sample_rows]
        layout = CoreDataset(self.config)
        with global_timer.scope("stream_fit_layout"):
            group_lists = layout._fit_layout(prefix, self.categorical_feature)
            layout._make_groups(group_lists)
        self._layout = layout
        self._group_lists = group_lists
        if self._drift is not None:
            self._drift.set_reference(layout, prefix)
        for blk in self._raw_blocks:
            self._bin_blocks.append(np.ascontiguousarray(layout._bin_rows(blk)))
        self._raw_blocks = []
        self._buffered = 0
        if telemetry.enabled():
            telemetry.emit("stream_layout_fitted",
                           sample_rows=int(prefix.shape[0]),
                           num_groups=len(layout.groups))

    def _require_layout(self) -> CoreDataset:
        if self._layout is None:
            if not self._raw_blocks:
                raise ValueError("RowBlockStore is empty: push rows first")
            self._fit_and_drain()
        return self._layout

    def _concat_field(self, name: str, blocks: List[Optional[np.ndarray]],
                      num_rows: int) -> Optional[np.ndarray]:
        override = self._field_overrides.get(name)
        if override is not None:
            return override[:num_rows] if override.ndim == 1 else override
        provided = [b for b in blocks if b is not None]
        if not provided:
            return None
        if len(provided) != len(blocks):
            raise ValueError(
                f"{name} was provided on some pushes but not others")
        return np.concatenate(provided)[:num_rows]

    # ------------------------------------------------------------- finalize

    def finalize(self, num_rows: Optional[int] = None) -> CoreDataset:
        """Snapshot the store into a core io/dataset.py Dataset.

        num_rows pins the snapshot to the first N rows (the continuous
        trainer's crash-consistent refit watermark); default is every row
        pushed so far. The store remains open for further pushes."""
        with self._lock:
            # graftlint: disable=lock-discipline -- one-shot layout fit (see push_rows): only a finalize racing the very first sample fill pays it, and it must stay atomic with the snapshot
            layout = self._require_layout()
            n = self.total_rows if num_rows is None else int(num_rows)
            if n > self.total_rows:
                raise ValueError(
                    f"finalize({n}) exceeds pushed rows ({self.total_rows})")
            plane = (self._bin_blocks[0] if len(self._bin_blocks) == 1
                     else np.concatenate(self._bin_blocks, axis=1))
            plane = np.ascontiguousarray(plane[:, :n])
            label = self._concat_field("label", self._labels, n)
            weight = self._concat_field("weight", self._weights, n)
            ds = CoreDataset.from_layout(
                layout, plane, n, label=label, weight=weight,
                group=self._field_overrides.get("group"),
                init_score=self._field_overrides.get("init_score"),
                position=self._field_overrides.get("position"),
                feature_names=self.feature_names)
            global_timer.set_count("stream_finalized_rows", n)
            return ds

    def to_basic_dataset(self, num_rows: Optional[int] = None,
                         params: Optional[dict] = None):
        """finalize() wrapped for Booster/engine consumption."""
        return wrap_dataset(self.finalize(num_rows), params=params)

    # ----------------------------------------------- drift / bin refresh

    def holdout_snapshot(self):
        """(X, y) of the most recent `holdout_rows` pushed rows (raw
        values, not bins) for the publish quality gate, or None when the
        tail ring is empty or any tail push lacked labels."""
        with self._lock:
            if not self._tail:
                return None
            if any(lbl is None for _, lbl in self._tail):
                return None
            X = np.concatenate([b for b, _ in self._tail], axis=0)
            y = np.concatenate([lbl for _, lbl in self._tail])
            if X.shape[0] > self.holdout_rows:
                X = X[-self.holdout_rows:]
                y = y[-self.holdout_rows:]
            return X, y

    def maybe_refresh_bins(self, force: bool = False) -> bool:
        """Refit the bin-mapper cut points from the drift sketches and
        remap every binned slab through old-bin -> new-bin LUTs, as one
        measured event. Runs when the drift monitor has latched an alarm
        (or unconditionally under `force`); returns True when a refresh
        happened.

        The EFB group composition is pinned (binned history cannot be
        re-checked for conflicts), so only cut points move: every group
        plane is rewritten via its LUT, the monitor re-anchors its
        occupancy baseline on the new mappers, and `layout_generation`
        bumps — the value checkpoint sidecars carry so a resumed refit can
        verify it replays against the mapper generation it trained under.
        Published models never notice: tree thresholds are real-valued at
        the model surface (BinMapper.bin_to_value), not bin indices.
        """
        with self._lock:
            mon = self._drift
            if mon is None or self._layout is None \
                    or self._group_lists is None:
                return False
            if not force and not mon.alarmed:
                return False
            with global_timer.scope("stream_bin_refresh"):
                old = self._layout
                new = CoreDataset(self.config)
                new.num_total_features = old.num_total_features
                new.monotone_constraints = list(old.monotone_constraints)
                new.used_features = list(old.used_features)
                refreshed = 0
                new.mappers = []
                for j, mapper in enumerate(old.mappers):
                    nm = mon.refit_mapper(j, mapper)
                    if nm is None:
                        new.mappers.append(mapper)
                    else:
                        new.mappers.append(nm)
                        refreshed += 1
                if refreshed == 0:
                    return False
                new._make_groups(self._group_lists)
                dtype = new.bins_dtype()
                luts = [drift.group_bin_lut(og, ng).astype(dtype)
                        for og, ng in zip(old.groups, new.groups)]
                remapped = []
                for blk in self._bin_blocks:
                    out = np.empty(blk.shape, dtype=dtype)
                    for gi, lut in enumerate(luts):
                        out[gi] = lut[blk[gi]]
                    remapped.append(out)
                self._bin_blocks = remapped
                self._layout = new
                self.layout_generation += 1
                mon.after_refresh(new)
            global_timer.add_count("bin_refresh_total", 1)
            global_timer.set_count("stream_bin_generation",
                                   self.layout_generation)
            Log.info("streaming: bin refresh %d refitted %d/%d mappers "
                     "from drift sketches", self.layout_generation,
                     refreshed, len(new.mappers))
            tracing.note("bin_refresh", generation=self.layout_generation,
                         refreshed=refreshed)
            if telemetry.enabled():
                telemetry.emit("bin_refresh",
                               generation=self.layout_generation,
                               refreshed=refreshed)
            return True


def wrap_dataset(core: CoreDataset, params: Optional[dict] = None):
    """Wrap a core Dataset in the lazy basic.Dataset facade (the subset()
    precedent: hand-set _handle so construct() short-circuits). _raw stays
    None — streamed datasets keep no raw matrix, so refit()/linear_tree
    (which need raw feature values) are out of scope for streaming."""
    from .. import basic

    wrapper = basic.Dataset(None, params=dict(params) if params else None,
                            free_raw_data=True)
    wrapper._handle = core
    wrapper._raw = None
    return wrapper
