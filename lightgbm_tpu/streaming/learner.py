"""Out-of-core tree learner: train with a bounded device-resident plane.

StreamedTreeLearner subclasses the host-driven SerialTreeLearner but never
uploads the full [G, N] bin plane. Instead the plane stays host-side and a
`_BlockCache` keeps at most `LGBM_TPU_HBM_BUDGET` bytes of fixed
[G, block_rows] slices device-resident (LRU), prefetching the next
histogram chunk's blocks while the current chunk's one-hot contraction is
still in flight — PR 5's double-buffered async-copy machinery run in the
H2D direction (jax.device_put/jnp.asarray dispatches are async; the python
driver runs ahead of the device queue).

Bit-identity with the resident learner (the acceptance bar):

  * `_leaf_hist` mirrors ops/histogram.py `_build_histogram_rows_xla`'s
    bracketing exactly — one `_hist_chunk` when the padded leaf index set
    fits DEFAULT_ROW_CHUNK, otherwise a zero-seeded accumulation over the
    same chunk boundaries in the same order. Chunk bin buffers are
    assembled from cached blocks (per-block gather + inverse-permutation
    scatter) and carry the identical integer bin values the resident
    gather would produce; padded positions carry bin 0 with gh == 0, a
    contribution of exactly 0.0 to the same accumulator cells. The chunk
    sums therefore reassociate nothing and the histogram is bitwise equal
    on the XLA path. On TPU (or under LGBM_TPU_STREAM_RAGGED) the per-
    block path routes through pallas_histogram_slots_ragged instead —
    `_leaf_hist_ragged` — which is bit-identical for quantized training
    (int32 accumulation) and carries the resident Pallas path's per-tile
    reassociation caveat for float training.
  * `_partition_split` uploads the chosen group's host plane row — the
    same values `bins_dev[gi]` would hold — so RowPartition's stable
    3-way-key argsort compaction sees identical inputs.
  * Train-score updates traverse trees block-by-block
    (`add_tree_to_score_blocked`): each valid row is scattered exactly
    once with the identical leaf value, so the score vector matches the
    resident single-scatter path bit for bit.

When the budget covers the whole plane the cache simply pins every block
(hbm_resident_fraction == 1.0) and the same code path is exercised — there
is no separate resident branch to drift.
"""
from __future__ import annotations

import os
import threading
from collections import OrderedDict
from functools import partial
from time import perf_counter
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..io.dataset import Dataset
from ..ops.hist_pallas import (DEFAULT_TILE_ROWS, active_tile_table,
                               hist_force_f32,
                               pallas_histogram_slots_ragged)
from ..ops.histogram import (DEFAULT_ROW_CHUNK, _acc_dtype, _hist_chunk,
                             _use_pallas)
from ..ops.partition import pad_indices
from ..ops.score import binned_leaf_index, binned_tree_arrays
from ..treelearner.serial import SerialTreeLearner
from ..utils.timer import global_timer

BUDGET_ENV = "LGBM_TPU_HBM_BUDGET"
BLOCK_ROWS_ENV = "LGBM_TPU_STREAM_BLOCK_ROWS"
# per-block histogram kernel routing: "" auto (ragged Pallas wherever the
# resident learner would take Pallas, i.e. TPU), "0" force XLA scatter,
# "1" force the compiled ragged kernel, "interpret" force the kernel in
# Pallas interpret mode (CPU-testable bit-exactness harness)
RAGGED_ENV = "LGBM_TPU_STREAM_RAGGED"
DEFAULT_BLOCK_ROWS = 65536
# per-split group-row uploads kept warm for repeated splits on one group
_ROW_CACHE_SLOTS = 4


def parse_budget_bytes(text: Optional[str]) -> Optional[int]:
    """'64m' / '1g' / '512k' / plain bytes -> int bytes; None/empty/0 ->
    None (streaming off)."""
    if not text:
        return None
    text = text.strip().lower()
    mult = 1
    if text and text[-1] in "kmg":
        mult = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30}[text[-1]]
        text = text[:-1]
    try:
        val = int(float(text) * mult)
    except ValueError:
        return None
    return val if val > 0 else None


def stream_budget_bytes() -> Optional[int]:
    return parse_budget_bytes(os.environ.get(BUDGET_ENV))


def streaming_requested() -> bool:
    """Whether LGBM_TPU_HBM_BUDGET asks for out-of-core training — the
    factory seam create_tree_learner checks (before device growth: a plane
    that needs a budget by definition should not be uploaded whole)."""
    return stream_budget_bytes() is not None


# graftlint: disable=R6 -- no input matches the [G, B, 3] output shape/dtype, nothing is aliasable; the chunk temps free at dispatch end
@partial(jax.jit, static_argnames=("num_bins", "compute_dtype"))
def _hist_chunk_seed(bins_c: jax.Array, gh_c: jax.Array, num_bins: int,
                     compute_dtype=jnp.float32) -> jax.Array:
    """Single-chunk leaf histogram over an assembled chunk buffer —
    mirrors _build_histogram_rows_xla's P <= row_chunk branch."""
    return _hist_chunk(bins_c.astype(jnp.int32), gh_c, num_bins,
                       compute_dtype)


@partial(jax.jit, static_argnames=("num_bins", "compute_dtype"),
         donate_argnums=(0,))
def _hist_chunk_accum(acc: jax.Array, bins_c: jax.Array, gh_c: jax.Array,
                      num_bins: int, compute_dtype=jnp.float32) -> jax.Array:
    """acc + one chunk — the body of _build_histogram_rows_xla's scan,
    with the accumulator donated so the rotating partial sums never
    double-buffer (the chunk bin/gh temps cannot alias the output)."""
    return acc + _hist_chunk(bins_c.astype(jnp.int32), gh_c, num_bins,
                             compute_dtype)


# reservation marker for a prefetch whose H2D dispatch is still outside the
# lock — distinguishable from any real jax.Array
_PENDING = object()


class _BlockCache:
    """LRU device cache over fixed-width column blocks of the host plane.

    `prefetch(b)` dispatches the H2D copy without blocking; a later
    `get(b)` promotes the in-flight array into the resident set. The
    prefetched/cold split feeds `stream_h2d_overlap_pct`.

    Thread safety: map mutation (resident/inflight insert, LRU eviction)
    happens only under `_lock`; the jitted `jnp.asarray` upload dispatch
    always runs OUTSIDE it (R13 discipline — a compile under the lock
    would stall every concurrent reader). A prefetch first parks a
    `_PENDING` reservation under the lock, uploads, then fills the
    reservation only if a racing `get` has not claimed the key; a `get`
    that pops a still-pending reservation simply takes the cold path and
    the prefetcher's late fill is dropped.
    """

    def __init__(self, plane: np.ndarray, block_rows: int, capacity: int,
                 upload_dtype) -> None:
        self.plane = plane
        self.block_rows = int(block_rows)
        self.num_rows = int(plane.shape[1])
        self.n_blocks = max(1, -(-self.num_rows // self.block_rows))
        self.capacity = max(1, int(capacity))
        self.upload_dtype = upload_dtype
        self._resident: "OrderedDict[int, jax.Array]" = OrderedDict()
        self._inflight: Dict[int, object] = {}
        self._lock = threading.Lock()
        self.upload_s = 0.0

    def block_range(self, b: int):
        lo = b * self.block_rows
        return lo, min(self.num_rows, lo + self.block_rows)

    def _upload(self, b: int) -> jax.Array:
        lo, hi = self.block_range(b)
        blk = self.plane[:, lo:hi]
        t0 = perf_counter()
        arr = (jnp.asarray(blk, dtype=self.upload_dtype)
               if self.upload_dtype is not None else jnp.asarray(blk))
        self.upload_s += perf_counter() - t0
        global_timer.add_count("stream_h2d_blocks", 1)
        global_timer.add_count("stream_h2d_bytes", int(arr.nbytes))
        global_timer.set_count("stream_h2d_us", int(self.upload_s * 1e6))
        return arr

    def prefetch(self, b: int) -> None:
        with self._lock:
            if b in self._resident or b in self._inflight:
                return
            if self.capacity < 2:
                return  # one slot: prefetching would evict the working block
            if len(self._resident) + len(self._inflight) >= self.capacity:
                if not self._resident:
                    return
                self._resident.popitem(last=False)
            self._inflight[b] = _PENDING
        arr = self._upload(b)  # jitted dispatch: lock released
        with self._lock:
            if self._inflight.get(b) is _PENDING:
                self._inflight[b] = arr
            # else a racing get() claimed (and cold-loaded) the block while
            # the upload was in flight — drop this copy on the floor

    def get(self, b: int) -> jax.Array:
        with self._lock:
            arr = self._resident.pop(b, None)
            if arr is not None:
                self._resident[b] = arr  # LRU refresh
                global_timer.add_count("stream_cache_hits", 1)
                return arr
            arr = self._inflight.pop(b, None)
            if arr is _PENDING:
                arr = None  # reservation not yet filled: go cold
        if arr is not None:
            global_timer.add_count("stream_h2d_prefetched", 1)
        else:
            global_timer.add_count("stream_h2d_cold", 1)
            arr = self._upload(b)  # jitted dispatch: lock released
        with self._lock:
            self._resident[b] = arr
            while (len(self._resident) + len(self._inflight) > self.capacity
                   and len(self._resident) > 1):
                self._resident.popitem(last=False)
        return arr


class StreamedTreeLearner(SerialTreeLearner):
    """SerialTreeLearner with the bin plane host-resident and block-cached.

    `bins_dev` is None — models/gbdt.py reads that as the signal to route
    train-score tree traversal through add_tree_to_score_blocked. Every
    other hook (split search, colsampler, CEGB, quantized gradients,
    checkpoint snapshot/restore) is inherited unchanged; snapshot state
    never touched the plane, so kill@K resume works as-is.
    """

    def __init__(self, config: Config, dataset: Dataset,
                 budget_bytes: Optional[int] = None,
                 block_rows: Optional[int] = None) -> None:
        self._budget_bytes = (int(budget_bytes) if budget_bytes is not None
                              else (stream_budget_bytes() or 0))
        env_rows = os.environ.get(BLOCK_ROWS_ENV, "")
        self._block_rows_req = (int(block_rows) if block_rows is not None
                                else int(env_rows) if env_rows
                                else DEFAULT_BLOCK_ROWS)
        self._cache: Optional[_BlockCache] = None
        self._row_cache: "OrderedDict[int, jax.Array]" = OrderedDict()
        super().__init__(config, dataset)

    # ------------------------------------------------------------ plane

    def _device_bins(self, dataset: Dataset) -> None:
        plane = dataset.bins
        # mirror the resident upload's LGBM_TPU_BINS_I32 escape hatch so
        # cached blocks hold the same dtype bins_dev would
        upload_dtype = (jnp.int32
                        if (plane.dtype.itemsize == 1
                            and os.environ.get("LGBM_TPU_BINS_I32", "") == "1")
                        else None)
        itemsize = 4 if upload_dtype is not None else plane.dtype.itemsize
        n = max(1, int(plane.shape[1]))
        block_rows = max(256, min(self._block_rows_req, n))
        block_bytes = max(1, plane.shape[0] * block_rows * itemsize)
        if self._budget_bytes > 0:
            capacity = max(1, self._budget_bytes // block_bytes)
        else:
            capacity = -(-n // block_rows)  # no budget: pin everything
        self._cache = _BlockCache(plane, block_rows, capacity, upload_dtype)
        global_timer.set_count("stream_blocks_total", self._cache.n_blocks)
        global_timer.set_count("stream_resident_blocks",
                               min(self._cache.capacity,
                                   self._cache.n_blocks))
        return None

    # ------------------------------------------------------- histograms

    def _ragged_mode(self) -> Optional[str]:
        """Resolve RAGGED_ENV at call time (mirrors _use_pallas's unjitted
        dispatch contract): None = XLA scatter, else 'compiled'|'interpret'."""
        mode = os.environ.get(RAGGED_ENV, "")
        if mode == "0":
            return None
        if mode == "interpret":
            return "interpret"
        if mode == "1":
            return "compiled"
        return "compiled" if _use_pallas() else None

    def _leaf_hist(self, leaf: int) -> jax.Array:
        mode = self._ragged_mode()
        if mode is not None:
            return self._leaf_hist_ragged(leaf, interpret=mode == "interpret")
        # the padded leaf index set is already host-materialized inside
        # RowPartition; this pull does not sync any new device work
        idx = np.asarray(self.partition.indices(leaf))
        return self._hist_over_indices(idx)

    def _hist_over_indices(self, idx: np.ndarray) -> jax.Array:
        """The canonical chunk-order histogram fold over an explicit row
        index set — `_leaf_hist`'s body, split out so the sharded learner
        can fold per-rank subsets through the identical bracketing."""
        compute_dtype = jnp.int8 if self.quantized else jnp.float32
        num_bins = self.group_bin_padded
        chunk = DEFAULT_ROW_CHUNK
        if idx.shape[0] <= chunk:
            self._prefetch_for(idx)
            buf = self._gather_chunk(idx)
            gh_c = jnp.take(self._gh, jnp.asarray(idx), axis=0)
            return _hist_chunk_seed(buf, gh_c, num_bins, compute_dtype)
        n_chunks = -(-idx.shape[0] // chunk)
        pad = n_chunks * chunk - idx.shape[0]
        if pad:
            idx = np.concatenate(
                [idx, np.full(pad, self.num_data, dtype=idx.dtype)])
        chunks = idx.reshape(n_chunks, chunk)
        acc = jnp.zeros((len(self.dataset.groups), num_bins, 3),
                        dtype=_acc_dtype(compute_dtype))
        self._prefetch_for(chunks[0])
        for k in range(n_chunks):
            buf = self._gather_chunk(chunks[k])
            if k + 1 < n_chunks:
                # next chunk's H2D rides behind this chunk's gather in the
                # device queue — the double buffer
                self._prefetch_for(chunks[k + 1])
            gh_c = jnp.take(self._gh, jnp.asarray(chunks[k]), axis=0)
            acc = _hist_chunk_accum(acc, buf, gh_c, num_bins, compute_dtype)
        return acc

    def _leaf_hist_ragged(self, leaf: int, interpret: bool = False
                          ) -> jax.Array:
        """Per-block leaf histogram through the ragged Pallas slots kernel.

        Each cached block slab is fed to pallas_histogram_slots_ragged
        whole (padded to the tile grid) with a 1-slot table: rows of this
        leaf carry slot 0, every other row the dump slot, and the active-
        tile table restricts the grid to the tiles the leaf actually
        touches — per-block cost is O(tiles overlapping the leaf), not
        O(block_rows). The next block's H2D prefetch is dispatched while
        the current block's kernel is in flight (the same double buffer
        as the XLA chunk fold). Quantized histograms accumulate int32 and
        are bit-identical to the scatter path in any block order; float
        histograms reassociate per-tile partial sums, the same caveat the
        resident Pallas path carries.
        """
        idx = np.asarray(self.partition.indices(leaf))
        vi = idx[idx < self.num_data].astype(np.int64)
        return self._ragged_over_indices(vi, interpret=interpret)

    def _ragged_over_indices(self, vi: np.ndarray,
                             interpret: bool = False) -> jax.Array:
        num_bins = self.group_bin_padded
        G = len(self.dataset.groups)
        CH = int(self._gh.shape[1])
        acc_dtype = jnp.int32 if self.quantized else jnp.float32
        acc = jnp.zeros((G, num_bins, CH), dtype=acc_dtype)
        if vi.size == 0:
            return acc
        vi = np.asarray(vi).astype(np.int64)
        cache = self._cache
        tr = DEFAULT_TILE_ROWS
        bid = vi // cache.block_rows
        blocks = np.unique(bid)  # ascending: deterministic fold order
        global_timer.add_count("stream_ragged_leaves", 1)
        for i, b in enumerate(blocks):
            bins_b = cache.get(int(b))
            if i + 1 < len(blocks):
                # next block's H2D rides behind this block's kernel in the
                # device queue — the double buffer
                cache.prefetch(int(blocks[i + 1]))
            sel = vi[bid == b]
            lo, hi = cache.block_range(int(b))
            width = hi - lo
            padded = -(-width // tr) * tr
            if bins_b.shape[1] < padded:
                bins_b = jnp.pad(bins_b,
                                 ((0, 0), (0, padded - bins_b.shape[1])))
            loc = jnp.asarray((sel - lo).astype(np.int32))
            slot = jnp.ones((padded,), jnp.int32).at[loc].set(0)
            gh_rows = jnp.take(self._gh, jnp.asarray(sel),
                               axis=0).astype(jnp.float32)
            gh = jnp.zeros((padded, CH), jnp.float32).at[loc].set(gh_rows)
            tiles, n_act = active_tile_table(
                jnp.asarray([sel[0] - lo], jnp.int32),
                jnp.asarray([sel[-1] - lo + 1], jnp.int32),
                jnp.asarray([True]), padded // tr, tr)
            part = pallas_histogram_slots_ragged(
                bins_b, gh, slot, tiles, n_act, num_bins, 1, tile_rows=tr,
                quantized=self.quantized, f32=hist_force_f32(),
                interpret=interpret)
            acc = acc + part.astype(acc_dtype)
        return acc

    def _prefetch_for(self, idx_chunk: np.ndarray) -> None:
        cache = self._cache
        vi = idx_chunk[idx_chunk < self.num_data]
        if vi.size == 0:
            return
        for b in np.unique(vi // cache.block_rows):
            cache.prefetch(int(b))

    def _gather_chunk(self, idx_chunk: np.ndarray) -> jax.Array:
        """Assemble the [G, C] bin buffer for one chunk of (possibly
        sentinel-padded, possibly unsorted) row indices from cached
        blocks. Valid columns carry the exact plane values; sentinel
        columns stay bin 0 (their gh is the zero row, so they contribute
        exactly nothing to the histogram)."""
        cache = self._cache
        C = idx_chunk.shape[0]
        out_dtype = (jnp.int32 if cache.upload_dtype is not None
                     else cache.plane.dtype)
        valid = idx_chunk < self.num_data
        if not valid.any():
            return jnp.zeros((cache.plane.shape[0], C), dtype=out_dtype)
        vi = idx_chunk[valid]
        bid = vi // cache.block_rows
        order = np.argsort(bid, kind="stable")
        vi_sorted = vi[order]
        bid_sorted = bid[order]
        bounds = np.flatnonzero(np.diff(bid_sorted)) + 1
        starts = np.concatenate([[0], bounds])
        ends = np.concatenate([bounds, [len(vi_sorted)]])
        parts = []
        for s, e in zip(starts, ends):
            b = int(bid_sorted[s])
            lo, _ = cache.block_range(b)
            local = (vi_sorted[s:e] - lo).astype(np.int32)
            parts.append(jnp.take(cache.get(b), jnp.asarray(local), axis=1))
        gathered = parts[0] if len(parts) == 1 else jnp.concatenate(parts,
                                                                    axis=1)
        pos = np.flatnonzero(valid)[order]
        if pos.shape[0] == C and np.array_equal(pos, np.arange(C)):
            return gathered
        buf = jnp.zeros((cache.plane.shape[0], C), dtype=gathered.dtype)
        return buf.at[:, jnp.asarray(pos.astype(np.int32))].set(gathered)

    # ------------------------------------------------------- compaction

    def _partition_split(self, leaf: int, new_leaf: int, gi: int,
                         decision: jax.Array, cat_mask=None):
        return self.partition.split(leaf, new_leaf, self._group_row(gi),
                                    decision, cat_mask)

    def _group_row(self, gi: int) -> jax.Array:
        """One group's full bin row [N] for partition compaction — the
        only per-split whole-dataset transfer (N bytes at uint8), kept in
        a tiny LRU since consecutive splits often reuse a group."""
        row = self._row_cache.pop(gi, None)
        if row is None:
            host = self._cache.plane[gi]
            row = (jnp.asarray(host, dtype=jnp.int32)
                   if self._cache.upload_dtype is not None
                   else jnp.asarray(host))
            global_timer.add_count("stream_h2d_rows", 1)
            global_timer.add_count("stream_h2d_bytes", int(row.nbytes))
        self._row_cache[gi] = row
        while len(self._row_cache) > _ROW_CACHE_SLOTS:
            self._row_cache.popitem(last=False)
        return row

    # ------------------------------------------------------ score update

    def add_tree_to_score_blocked(self, tree, score: jax.Array,
                                  row_idx, max_depth: int = 0) -> jax.Array:
        """Block-sharded ops/score.py add_tree_to_score: traverse each
        cached block with block-local indices, scatter into the global
        score. Each valid row is scattered exactly once with the identical
        leaf value, so the result matches the resident path bitwise."""
        if tree.num_leaves <= 1:
            return score.at[row_idx].add(float(tree.leaf_value[0]),
                                         mode="drop")
        ta = binned_tree_arrays(tree, self.dataset)
        bound = max_depth if max_depth > 0 else int(tree.max_depth)
        cache = self._cache
        rows = np.asarray(row_idx)
        vi = rows[rows < self.num_data].astype(np.int64)
        if vi.size == 0:
            return score
        bid = vi // cache.block_rows
        blocks = np.unique(bid)
        for i, b in enumerate(blocks):
            if i + 1 < len(blocks):
                cache.prefetch(int(blocks[i + 1]))
            sel = vi[bid == b]
            lo, hi = cache.block_range(int(b))
            local_p = pad_indices(
                (sel - lo).astype(np.int32), hi - lo)
            global_p = np.full(local_p.shape[0], self.num_data,
                               dtype=np.int64)
            global_p[: sel.shape[0]] = sel
            leaf = binned_leaf_index(ta, cache.get(int(b)),
                                     jnp.asarray(local_p), hi - lo, bound)
            score = score.at[jnp.asarray(global_p)].add(
                ta.leaf_value[leaf], mode="drop")
        return score
